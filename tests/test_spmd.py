"""SPMD dispatch coverage in two tiers.

1. Real multi-process integration: N OS processes (2 in the normal tier, 4
   in the battletest tier) join one jax.distributed runtime and the
   PRODUCTION CostSolver path replicates solves from rank 0 to the follower
   loops — the local stand-in for a multi-host TPU pod slice. Requires a
   jaxlib whose backend implements cross-process collectives; where it
   doesn't (XLA:CPU in some builds rejects multi-process programs
   outright), the test SKIPS with the backend's own error as the reason —
   a deadlock-shaped failure would say nothing.
2. A single-process CPU-mesh variant that runs in EVERY tier-1 pass on the
   conftest's 8-device virtual mesh: the lead/follower protocol
   (header + device-mask + operand broadcast, shape rebuild, kernel
   mirroring) exercised through an injected loopback transport, so the
   mesh/sharding logic is covered on every run, not only on multi-chip
   hardware."""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from karpenter_tpu.parallel import spmd

_RANK_PROGRAM = textwrap.dedent(
    """
    import sys

    rank, port = int(sys.argv[1]), int(sys.argv[2])
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)

    from karpenter_tpu.parallel.multihost import init_distributed

    num_processes = int(sys.argv[3])
    assert init_distributed(
        {
            "KARPENTER_COORDINATOR": f"127.0.0.1:{port}",
            "KARPENTER_NUM_PROCESSES": str(num_processes),
            "KARPENTER_PROCESS_ID": str(rank),
        }
    )
    assert jax.process_count() == num_processes
    assert jax.device_count() == 2 * num_processes

    if rank > 0:
        from karpenter_tpu.parallel import spmd

        spmd.follower_loop()  # exits on the lead's OP_STOP
        print("follower done", flush=True)
        sys.exit(0)

    # Rank 0: the PRODUCTION entry — CostSolver.solve_encoded — whose
    # cost_solve_dispatch must take the multi-process lead_dispatch branch.
    from karpenter_tpu.api.provisioner import Constraints
    from karpenter_tpu.models.solver import CostSolver, solve_mesh
    from karpenter_tpu.ops.encode import build_fleet, group_pods
    from karpenter_tpu.parallel import spmd
    import tests.fixtures as fixtures

    assert solve_mesh() is not None
    assert spmd.is_multiprocess()
    catalog = fixtures.size_ladder(8)
    pods = fixtures.pods(120, cpu="500m", memory="1Gi") + fixtures.pods(
        60, cpu="1", memory="2Gi"
    )
    groups = group_pods(pods)
    fleet = build_fleet(catalog, Constraints(), pods)
    result = CostSolver(lp_steps=12).solve_encoded(groups, fleet)
    packed = sum(sum(len(n) for n in p.pods_per_node) for p in result.packings)
    assert packed == len(pods), f"{packed}/{len(pods)} packed"
    assert not result.unschedulable
    # A second solve at a different shape exercises a fresh broadcast round.
    pods2 = fixtures.pods(40, cpu="2", memory="1Gi")
    result2 = CostSolver(lp_steps=12).solve_encoded(
        group_pods(pods2), build_fleet(catalog, Constraints(), pods2)
    )
    assert not result2.unschedulable
    spmd.lead_stop()
    print(f"lead done: packed {packed} pods on {result.node_count} nodes", flush=True)
    """
)


def _free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


class TestSpmdMultiProcess:
    @pytest.mark.parametrize(
        "num_processes",
        [
            pytest.param(
                2,
                marks=pytest.mark.skipif(
                    os.environ.get("KARPENTER_BATTLETEST") == "1",
                    reason="2-rank case already ran in the normal tier",
                ),
            ),
            pytest.param(
                4,
                marks=pytest.mark.skipif(
                    os.environ.get("KARPENTER_BATTLETEST") != "1",
                    reason="4-rank SPMD slice runs in the battletest tier",
                ),
            ),
        ],
    )
    def test_production_solve_spans_processes(self, num_processes):
        port = _free_port()
        env = {
            "PATH": "/usr/bin:/bin",
            "PYTHONPATH": ".",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "JAX_PLATFORMS": "cpu",
        }
        procs = [
            subprocess.Popen(
                [
                    sys.executable, "-c", _RANK_PROGRAM,
                    str(rank), str(port), str(num_processes),
                ],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=".",
            )
            for rank in range(num_processes)
        ]
        import time

        deadline = time.monotonic() + 300.0
        outputs = [""] * len(procs)
        timed_out = False
        for index, proc in enumerate(procs):
            remaining = max(deadline - time.monotonic(), 0.1)
            try:
                outputs[index], _ = proc.communicate(timeout=remaining)
            except subprocess.TimeoutExpired:
                timed_out = True
                proc.kill()
                # Drain what the killed process DID write — that's the
                # diagnostic showing where the collective mismatched.
                outputs[index], _ = proc.communicate()
        if timed_out:
            pytest.fail(
                "SPMD processes deadlocked (collective mismatch?):\n"
                + "\n---\n".join(o[-2000:] for o in outputs)
            )
        if any(
            spmd.COLLECTIVES_UNSUPPORTED_MSG in out for out in outputs
        ):
            # The runtime came up (jax.distributed joined, device counts
            # checked) but this jaxlib's backend rejects multi-process
            # programs — the environment cannot host the test. The
            # single-process protocol coverage lives in TestSpmdCpuMesh,
            # which runs in every tier-1 pass.
            pytest.skip(
                "jaxlib backend lacks cross-process collectives "
                f"({spmd.COLLECTIVES_UNSUPPORTED_MSG!r}); "
                "protocol covered by TestSpmdCpuMesh"
            )
        for rank, (proc, out) in enumerate(zip(procs, outputs)):
            assert proc.returncode == 0, (
                f"rank {rank} failed (rc={proc.returncode}):\n{out[-3000:]}"
            )
        assert "lead done" in outputs[0]
        for follower_output in outputs[1:]:
            assert "follower done" in follower_output


class TestSpmdCpuMesh:
    """Tier-1 SPMD protocol coverage on the conftest's single-process
    8-device virtual mesh: the REAL lead and follower code paths wired
    back-to-back through an injected loopback transport. What multi-chip
    hardware would exercise over ICI/DCN — header broadcast, device-mask
    mesh replication (including a DEGRADED shrunk mesh), operand shape
    rebuild, identical kernel dispatch — runs here on every tier-1 pass."""

    def _example(self, mesh):
        import __graft_entry__
        from karpenter_tpu.models.solver import (
            _sharded_fused_kernel,
            pad_kernel_args,
        )

        kernel, (g_mult, t_mult), shards = _sharded_fused_kernel(mesh)
        vectors, counts, capacity, total, valid, prices = (
            __graft_entry__._example_problem(num_groups=8, num_types=16)
        )
        padded = pad_kernel_args(
            vectors, counts, capacity, total, prices,
            g_mult=g_mult, t_mult=t_mult,
        )
        return kernel, padded, shards

    def test_lead_follower_loopback(self, monkeypatch):
        from karpenter_tpu.api import wellknown
        from karpenter_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()
        kernel, padded, shards = self._example(mesh)
        assert shards == 8

        wire = []
        monkeypatch.setattr(
            spmd, "_broadcast", lambda value: (wire.append(value), value)[1]
        )
        dispatcher = spmd.SpmdDispatcher()
        lead_out = dispatcher.lead_dispatch(kernel, padded, 6, mesh=mesh)

        # Replay the recorded wire as the follower: same header, same mask,
        # same operands must rebuild the same mesh and dispatch the same
        # kernel to a bit-identical compact payload.
        replay = list(wire)
        monkeypatch.setattr(spmd, "_broadcast", lambda _: replay.pop(0))
        follower_out = spmd.follower_step(wellknown.NUM_RESOURCE_DIMS)
        assert follower_out is not None
        np.testing.assert_array_equal(
            np.asarray(lead_out[0]), np.asarray(follower_out[0])
        )
        assert not replay, "follower consumed a different number of legs"

    def test_trace_id_rides_the_header_to_follower_spans(self, monkeypatch):
        """The SPMD leg of trace stitching: a trace id current on the lead
        rides the fixed-shape header as two int32 words, and the follower's
        step span carries the SAME id."""
        from karpenter_tpu.parallel.mesh import make_mesh
        from karpenter_tpu.api import wellknown
        from karpenter_tpu.utils import tracing

        tracer = tracing.Tracer(enabled=True)
        monkeypatch.setattr(spmd, "TRACER", tracer)
        mesh = make_mesh()
        kernel, padded, _ = self._example(mesh)

        wire = []
        monkeypatch.setattr(
            spmd, "_broadcast", lambda value: (wire.append(value), value)[1]
        )
        trace_id = tracing.new_trace_id()
        with tracer.trace(trace_id):
            spmd.SpmdDispatcher().lead_dispatch(kernel, padded, 6, mesh=mesh)
        header = np.asarray(wire[0])
        assert header.shape == (spmd.HEADER_WORDS,)
        assert tracing.words_to_trace_id(header[4], header[5]) == trace_id

        replay = list(wire)
        monkeypatch.setattr(spmd, "_broadcast", lambda _: replay.pop(0))
        assert spmd.follower_step(wellknown.NUM_RESOURCE_DIMS) is not None
        [step] = tracer.spans("spmd.follower.step")
        assert step.trace == trace_id

    def test_device_mask_replicates_shrunk_mesh(self, monkeypatch):
        import jax

        from karpenter_tpu.parallel.mesh import make_mesh

        # A lead whose mesh lost chip 7 must hand followers a mask that
        # rebuilds the identical 7-device mesh.
        devices = jax.devices()[:7]
        mesh = make_mesh(devices)
        mask = spmd._device_mask(mesh)
        assert mask.tolist() == [1] * 7 + [0]
        rebuilt = spmd._mesh_from_mask(mask)
        assert rebuilt.devices.size == 7
        assert [d.id for d in rebuilt.devices.flat] == [
            d.id for d in mesh.devices.flat
        ]

    def test_stop_header_ends_follower(self, monkeypatch):
        from karpenter_tpu.api import wellknown

        monkeypatch.setattr(
            spmd, "_broadcast", lambda _: np.zeros(spmd.HEADER_WORDS, np.int32)
        )
        assert spmd.follower_step(wellknown.NUM_RESOURCE_DIMS) is None

    def test_lead_stop_idempotent(self, monkeypatch):
        sent = []
        monkeypatch.setattr(spmd, "is_multiprocess", lambda: True)
        monkeypatch.setattr(
            spmd, "_broadcast", lambda value: (sent.append(value), value)[1]
        )
        dispatcher = spmd.SpmdDispatcher()
        dispatcher.lead_stop()
        dispatcher.lead_stop()
        assert len(sent) == 1, "second stop must not issue a collective"
        padded = (np.zeros((1, 8), np.float32),) * 6
        with pytest.raises(RuntimeError, match="stopped"):
            dispatcher.lead_dispatch(None, padded, 1)

    def test_unsupported_backend_classified(self):
        class FakeXlaError(Exception):
            pass

        error = FakeXlaError(
            "INVALID_ARGUMENT: " + spmd.COLLECTIVES_UNSUPPORTED_MSG + "."
        )
        assert spmd.collectives_unsupported(error)
        assert not spmd.collectives_unsupported(ValueError("other"))
