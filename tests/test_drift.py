"""Drift battletest: spec-hash drift, provider-side drift, and expiration —
all rolled through the budgeted voluntary replacement path — plus the hash
stability properties the whole subsystem rests on, the shared
DisruptionLedger, provisioner weight selection, and the drift crash matrix.

`make drift-smoke` wraps the live churn + spec-flip chaos harness
(tools/drift_smoke.py) around the same subsystem; this module is the
deterministic matrix. test_backend_parity re-runs the classes against the
fake apiserver.
"""

from __future__ import annotations

import pytest

from karpenter_tpu import drift as driftlib
from karpenter_tpu.api import wellknown
from karpenter_tpu.api.provisioner import Constraints, Provisioner, ProvisionerSpec
from karpenter_tpu.api.requirements import Requirement, Requirements
from karpenter_tpu.api.serialization import provisioner_from_dict, provisioner_to_dict
from karpenter_tpu.api.taints import Taint
from karpenter_tpu.api.validation import ValidationError, validate_provisioner
from karpenter_tpu.controllers import eligibility
from karpenter_tpu.controllers.drift import DriftController
from karpenter_tpu.controllers.eligibility import DisruptionLedger
from karpenter_tpu.controllers.instancegc import (
    LAUNCH_GRACE_SECONDS,
    InstanceGcController,
)
from karpenter_tpu.controllers.node import NodeController
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.selection import SelectionController
from karpenter_tpu.controllers.termination import TerminationController
from karpenter_tpu.utils import crashpoints
from karpenter_tpu.utils.crashpoints import SimulatedCrash

from tests import fixtures
from tests.harness import Harness
from tests.test_interruption import BindRecorder

HASH_ANNOTATION = wellknown.PROVISIONER_HASH_ANNOTATION
ACTION_ANNOTATION = wellknown.DRIFT_ACTION_ANNOTATION


# --- harness helpers ---------------------------------------------------------


def drift_harness(pods, **spec_kwargs):
    """Default-catalog harness: provisioner + pods provisioned, every node
    marked ready (drift only disrupts joined nodes)."""
    h = Harness()
    recorder = BindRecorder(h.cluster)
    h.apply_provisioner(
        Provisioner(name="default", spec=ProvisionerSpec(**spec_kwargs))
    )
    h.provision(*pods)
    ready_all(h)
    return h, recorder


def ready_all(h: Harness) -> None:
    for node in h.cluster.list_nodes():
        if not node.ready:
            node.ready = True
            node.status_reported_at = h.clock.now()
            h.cluster.update_node(node)
        if node.deletion_timestamp is None:
            h.node.reconcile(node.name)


def flip_spec(h: Harness, name: str = "default") -> str:
    """Change the provisioner's constraint envelope (a new label) — the
    rolling-upgrade trigger — and return the NEW spec hash."""
    provisioner = h.cluster.try_get_provisioner(name)
    provisioner.spec.constraints.labels["generation"] = "v2"
    h.apply_provisioner(provisioner)
    return driftlib.spec_hash(h.cluster.try_get_provisioner(name))


def converge(h: Harness, rounds: int = 8) -> None:
    """Drive drift sweeps + provisioning + terminations to a fixpoint."""
    for _ in range(rounds):
        h.drift.reconcile()
        for worker in list(h.provisioning.workers.values()):
            worker.provision()
        ready_all(h)
        h.reconcile_terminations(rounds=3)


def restart(h: Harness, ledger: DisruptionLedger = None) -> None:
    """A controller-process restart over the surviving cluster + cloud
    state, plus the boot re-list routing pending pods through selection."""
    h.provisioning = ProvisioningController(h.cluster, h.cloud, None)
    h.selection = SelectionController(h.cluster, h.provisioning)
    h.termination = TerminationController(h.cluster, h.cloud)
    h.instancegc = InstanceGcController(h.cluster, h.cloud)
    h.ledger = ledger or DisruptionLedger(h.cluster)
    h.node = NodeController(h.cluster, ledger=h.ledger)
    h.drift = DriftController(
        h.cluster, h.cloud, h.provisioning, h.termination, ledger=h.ledger
    )
    for provisioner in h.cluster.list_provisioners():
        h.provisioning.reconcile(provisioner.name)
    for pod in h.cluster.list_pods():
        if pod.is_provisionable():
            h.selection.reconcile(pod.namespace, pod.name)


def assert_no_leaks(h: Harness) -> None:
    h.clock.advance(LAUNCH_GRACE_SECONDS + 1)
    h.instancegc.reconcile()
    h.instancegc.reconcile()
    node_ids = {n.provider_id for n in h.cluster.list_nodes()}
    leaked = set(h.cloud.instances) - node_ids
    assert not leaked, f"instances with no Node after GC grace: {sorted(leaked)}"


def claims(h: Harness):
    return [
        n for n in h.cluster.list_nodes() if ACTION_ANNOTATION in n.annotations
    ]


# --- hash stability ----------------------------------------------------------


def _spec(labels=None, taints=None, requirements=None, provider=None, **kwargs):
    return ProvisionerSpec(
        constraints=Constraints(
            labels=dict(labels or {}),
            taints=list(taints or []),
            requirements=Requirements(requirements or []),
            provider=provider,
        ),
        **kwargs,
    )


class TestSpecHashStability:
    """The canonical-form properties the whole subsystem rests on: a hash
    that wobbled under key order or default expansion would roll fleets for
    no reason."""

    def test_label_insertion_order_irrelevant(self):
        a = _spec(labels={"team": "ml", "tier": "prod"})
        b = _spec(labels={"tier": "prod", "team": "ml"})
        assert driftlib.spec_hash(a) == driftlib.spec_hash(b)

    def test_taint_order_irrelevant(self):
        t1 = Taint(key="a", value="1")
        t2 = Taint(key="b", value="2", effect="NoExecute")
        assert driftlib.spec_hash(_spec(taints=[t1, t2])) == driftlib.spec_hash(
            _spec(taints=[t2, t1])
        )

    def test_requirement_order_and_value_order_irrelevant(self):
        r1 = Requirement.in_(wellknown.ZONE_LABEL, ["us-east-1a", "us-east-1b"])
        r2 = Requirement.in_(wellknown.ARCH_LABEL, ["amd64"])
        r1_shuffled = Requirement.in_(
            wellknown.ZONE_LABEL, ["us-east-1b", "us-east-1a"]
        )
        assert driftlib.spec_hash(
            _spec(requirements=[r1, r2])
        ) == driftlib.spec_hash(_spec(requirements=[r2, r1_shuffled]))

    def test_default_equivalent_specs_hash_identically(self):
        assert driftlib.spec_hash(ProvisionerSpec()) == driftlib.spec_hash(
            ProvisionerSpec(
                constraints=Constraints(
                    labels={}, taints=[], requirements=Requirements(), provider=None
                ),
                ttl_seconds_after_empty=None,
                ttl_seconds_until_expired=None,
                limits=None,
                weight=0,
            )
        )

    def test_lifecycle_knobs_excluded(self):
        """TTLs and weight are operational knobs, not the constraint
        envelope: flipping them must not nominate a fleet for replacement."""
        base = driftlib.spec_hash(ProvisionerSpec())
        assert driftlib.spec_hash(ProvisionerSpec(ttl_seconds_after_empty=30)) == base
        assert (
            driftlib.spec_hash(ProvisionerSpec(ttl_seconds_until_expired=3600))
            == base
        )
        assert driftlib.spec_hash(ProvisionerSpec(weight=50)) == base

    def test_envelope_changes_change_the_hash(self):
        base = driftlib.spec_hash(ProvisionerSpec())
        assert driftlib.spec_hash(_spec(labels={"k": "v"})) != base
        assert driftlib.spec_hash(_spec(taints=[Taint(key="t")])) != base
        assert (
            driftlib.spec_hash(
                _spec(requirements=[Requirement.in_(wellknown.ZONE_LABEL, ["z"])])
            )
            != base
        )
        assert driftlib.spec_hash(_spec(provider={"ami": "custom"})) != base

    def test_accepts_provisioner_or_spec(self):
        spec = _spec(labels={"k": "v"})
        assert driftlib.spec_hash(spec) == driftlib.spec_hash(
            Provisioner(name="p", spec=spec)
        )

    def test_hash_survives_serialization_round_trip(self):
        provisioner = Provisioner(
            name="p",
            spec=_spec(
                labels={"team": "ml"},
                taints=[Taint(key="dedicated", value="ml")],
                requirements=[Requirement.in_(wellknown.ZONE_LABEL, ["z1", "z2"])],
                weight=7,
            ),
        )
        revived = provisioner_from_dict(provisioner_to_dict(provisioner))
        assert driftlib.spec_hash(revived) == driftlib.spec_hash(provisioner)
        assert revived.spec.weight == 7

    def test_hash_is_not_python_hash(self):
        """The stamp must be process-stable (PYTHONHASHSEED-independent):
        a fixed-width lowercase hex string, never a salted int."""
        value = driftlib.spec_hash(ProvisionerSpec())
        assert isinstance(value, str)
        assert len(value) == driftlib.HASH_LENGTH
        assert int(value, 16) >= 0


# --- hash stamping -----------------------------------------------------------


class TestHashStamping:
    def test_new_nodes_stamped_at_registration(self):
        h, _ = drift_harness(fixtures.pods(2, cpu="12"))
        expected = driftlib.spec_hash(h.cluster.try_get_provisioner("default"))
        for node in h.cluster.list_nodes():
            assert node.annotations.get(HASH_ANNOTATION) == expected

    def test_legacy_node_backfilled_not_drifted(self):
        """A node with no hash (pre-drift or adopted) is stamped with the
        CURRENT hash by the node reconciler — and the drift sweep must not
        nominate it in the same breath."""
        h, _ = drift_harness(fixtures.pods(1, cpu="12"))
        node = h.cluster.list_nodes()[0]
        h.cluster.remove_node_annotation(node, HASH_ANNOTATION)
        h.drift.reconcile()
        live = h.cluster.get_node(node.name)
        assert ACTION_ANNOTATION not in live.annotations
        assert live.deletion_timestamp is None
        assert live.annotations[HASH_ANNOTATION] == driftlib.spec_hash(
            h.cluster.try_get_provisioner("default")
        )

    def test_node_reconciler_backfills_too(self):
        h, _ = drift_harness(fixtures.pods(1, cpu="12"))
        node = h.cluster.list_nodes()[0]
        h.cluster.remove_node_annotation(node, HASH_ANNOTATION)
        h.node.reconcile(node.name)
        assert HASH_ANNOTATION in h.cluster.get_node(node.name).annotations


# --- detection + rolling replacement ----------------------------------------


class TestDriftReplacement:
    def test_spec_flip_rolls_the_node(self):
        pods = fixtures.pods(2, cpu="6")
        h, recorder = drift_harness(pods)
        victim = h.expect_scheduled(pods[0])
        new_hash = flip_spec(h)
        converge(h)
        assert h.cluster.try_get_node(victim.name) is None, "victim survived"
        for pod in pods:
            live = h.cluster.get_pod(pod.namespace, pod.name)
            assert live.node_name is not None, f"{pod.name} lost in the roll"
            node = h.cluster.get_node(live.node_name)
            assert node.annotations[HASH_ANNOTATION] == new_hash
            assert len(recorder.bound[pod.uid]) <= 2, recorder.bound[pod.uid]
        assert not claims(h)
        assert_no_leaks(h)

    def test_unchanged_spec_never_drifts(self):
        pods = fixtures.pods(2, cpu="6")
        h, _ = drift_harness(pods)
        before = {n.name for n in h.cluster.list_nodes()}
        for _ in range(3):
            h.drift.reconcile()
        assert {n.name for n in h.cluster.list_nodes()} == before
        assert not claims(h)

    def test_provider_drift_rolls_the_node(self):
        pods = fixtures.pods(1, cpu="12")
        h, _ = drift_harness(pods)
        victim = h.expect_scheduled(pods[0])
        h.cloud.inject_drift(victim, reason="launch template moved")
        converge(h)
        assert h.cluster.try_get_node(victim.name) is None
        live = h.cluster.get_pod(pods[0].namespace, pods[0].name)
        assert live.node_name is not None
        assert_no_leaks(h)

    def test_drift_disabled_detects_nothing(self):
        pods = fixtures.pods(1, cpu="12")
        h, _ = drift_harness(pods)
        h.drift.enabled = False
        flip_spec(h)
        h.drift.reconcile()
        assert not claims(h)
        assert all(
            n.deletion_timestamp is None for n in h.cluster.list_nodes()
        )

    def test_do_not_evict_cancels_the_replacement(self):
        pods = fixtures.pods(1, cpu="12")
        h, _ = drift_harness(pods)
        victim = h.expect_scheduled(pods[0])
        live = h.cluster.get_pod(pods[0].namespace, pods[0].name)
        live.annotations[wellknown.DO_NOT_EVICT_ANNOTATION] = "true"
        h.cluster.apply_pod(live)
        flip_spec(h)
        h.drift.reconcile()
        node = h.cluster.get_node(victim.name)
        assert ACTION_ANNOTATION not in node.annotations, "claim not cancelled"
        assert node.deletion_timestamp is None
        assert not node.unschedulable, "cancel must undo the cordon"

    def test_interruption_claimed_node_left_alone(self):
        pods = fixtures.pods(1, cpu="12")
        h, _ = drift_harness(pods)
        victim = h.expect_scheduled(pods[0])
        node = h.cluster.get_node(victim.name)
        node.annotations[wellknown.INTERRUPTION_KIND_ANNOTATION] = "spot-interruption"
        h.cluster.update_node(node)
        flip_spec(h)
        h.drift.reconcile()
        assert ACTION_ANNOTATION not in h.cluster.get_node(victim.name).annotations

    def test_rolling_respects_budget_at_every_instant(self):
        """Flip the spec under a 5-node fleet with drift capped at 2: no
        sweep may ever have more than 2 voluntary disruptions in flight,
        and the fleet still converges to the new hash."""
        pods = fixtures.pods(5, cpu="12")
        h, _ = drift_harness(pods)
        assert len(h.cluster.list_nodes()) == 5
        ledger = DisruptionLedger(
            h.cluster, budget=2, reason_caps={eligibility.REASON_DRIFT: 2}
        )
        h.drift.ledger = ledger
        new_hash = flip_spec(h)
        seen_in_flight = []
        for _ in range(12):
            h.drift.reconcile()
            seen_in_flight.append(len(claims(h)))
            assert sum(ledger.in_flight().values()) <= 2
            for worker in list(h.provisioning.workers.values()):
                worker.provision()
            ready_all(h)
            h.reconcile_terminations(rounds=3)
        assert max(seen_in_flight) <= 2
        assert max(seen_in_flight) > 0, "budget never used"
        for node in h.cluster.list_nodes():
            assert node.annotations[HASH_ANNOTATION] == new_hash
        for pod in pods:
            assert h.cluster.get_pod(pod.namespace, pod.name).node_name
        assert_no_leaks(h)


# --- the shared ledger -------------------------------------------------------


class TestDisruptionLedger:
    def test_reasons_share_one_budget(self):
        h = Harness()
        h.apply_provisioner(Provisioner(name="default", spec=ProvisionerSpec()))
        h.provision(*fixtures.pods(3, cpu="12"))
        nodes = h.cluster.list_nodes()
        ledger = DisruptionLedger(h.cluster, budget=2)
        assert ledger.headroom(eligibility.REASON_DRIFT) == 2
        nodes[0].annotations[wellknown.CONSOLIDATION_ACTION_ANNOTATION] = "delete"
        h.cluster.update_node(nodes[0])
        assert ledger.headroom(eligibility.REASON_DRIFT) == 1
        nodes[1].annotations[ACTION_ANNOTATION] = "spec"
        h.cluster.update_node(nodes[1])
        assert ledger.headroom(eligibility.REASON_DRIFT) == 0
        assert ledger.headroom(eligibility.REASON_CONSOLIDATION) == 0

    def test_waiting_empty_nodes_cost_nothing(self):
        """An emptiness STAMP is scheduled intent, not an in-flight
        disruption: an idle cluster full of stamped-but-waiting empty nodes
        must not starve drift/consolidation of the shared budget."""
        h = Harness()
        h.apply_provisioner(Provisioner(name="default", spec=ProvisionerSpec()))
        h.provision(*fixtures.pods(2, cpu="12"))
        ledger = DisruptionLedger(h.cluster, budget=2)
        for node in h.cluster.list_nodes():
            node.annotations[wellknown.EMPTINESS_TIMESTAMP_ANNOTATION] = "0"
            h.cluster.update_node(node)
        assert ledger.headroom(eligibility.REASON_DRIFT) == 2
        # Deletion begins on one: NOW it counts.
        h.cluster.delete_node(h.cluster.list_nodes()[0].name)
        assert ledger.headroom(eligibility.REASON_DRIFT) == 1

    def test_per_reason_cap_nests_inside_global(self):
        h = Harness()
        ledger = DisruptionLedger(
            h.cluster, budget=10, reason_caps={eligibility.REASON_DRIFT: 2}
        )
        assert ledger.headroom(eligibility.REASON_DRIFT) == 2
        assert ledger.headroom(eligibility.REASON_CONSOLIDATION) == 10
        assert ledger.headroom(eligibility.REASON_EMPTINESS) == 10


# --- expiration through the drift machinery ---------------------------------


class TestExpirationBudget:
    def test_mass_expiry_rolls_budget_at_a_time(self):
        """Satellite regression: N simultaneously-expired nodes are
        replaced at most budget-at-a-time, not all at once — the
        fleet-upgrade-by-TTL scenario that motivated rewiring expiration
        through the shared ledger."""
        pods = fixtures.pods(5, cpu="12")
        h, _ = drift_harness(pods, ttl_seconds_until_expired=300)
        assert len(h.cluster.list_nodes()) == 5
        ledger = DisruptionLedger(h.cluster, budget=2)
        h.node = NodeController(h.cluster, ledger=ledger)
        h.clock.advance(301)
        rounds = 0
        while any(
            n.deletion_timestamp is None for n in h.cluster.list_nodes()
        ) or h.cluster.list_nodes():
            h.reconcile_nodes()
            deleting = [
                n
                for n in h.cluster.list_nodes()
                if n.deletion_timestamp is not None
            ]
            assert len(deleting) <= 2, (
                f"budget overrun: {len(deleting)} nodes deleting at once"
            )
            assert sum(ledger.in_flight().values()) <= 2
            h.reconcile_terminations()
            rounds += 1
            assert rounds < 20, "mass expiry failed to converge"
        assert h.cluster.list_nodes() == []

    def test_expired_claim_is_durable_drift_kind(self):
        h = Harness()
        h.apply_provisioner(
            Provisioner(
                name="default",
                spec=ProvisionerSpec(ttl_seconds_until_expired=300),
            )
        )
        pod = fixtures.pod()
        h.provision(pod)
        node = h.expect_scheduled(pod)
        node.ready = True
        node.status_reported_at = h.clock.now()
        h.cluster.update_node(node)
        h.clock.advance(301)
        h.node.reconcile(node.name)
        live = h.cluster.try_get_node(node.name)
        assert live is None or (
            live.deletion_timestamp is not None
            and live.annotations.get(ACTION_ANNOTATION)
            == driftlib.DRIFT_KIND_EXPIRED
        )

    def test_drift_sweep_detects_expiry_without_double_claim(self):
        pods = fixtures.pods(1, cpu="12")
        h, _ = drift_harness(pods, ttl_seconds_until_expired=300)
        victim = h.expect_scheduled(pods[0])
        h.clock.advance(301)
        h.drift.reconcile()  # the sweep claims it first
        node = h.cluster.try_get_node(victim.name)
        assert node is None or ACTION_ANNOTATION in node.annotations
        # The node reconciler must now leave it alone (no second claim, no
        # headroom consumed twice).
        if node is not None and node.deletion_timestamp is None:
            h.node.reconcile(victim.name)
        converge(h)
        assert h.cluster.try_get_node(victim.name) is None
        assert_no_leaks(h)


# --- crash matrix ------------------------------------------------------------

DRIFT_MATRIX = [(site, 1) for site in crashpoints.DRIFT_SITES] + [
    ("drift.mid-replace", 2)
]


class TestDriftCrashMatrix:
    """The controller killed at every drift commit point, restarted over the
    surviving state, and the roll still converges — every pod bound exactly
    once to a live node, victim gone, zero leaked instances, every claim
    cleared."""

    @pytest.mark.parametrize(
        "site,at", DRIFT_MATRIX, ids=[f"{s}@{a}" for s, a in DRIFT_MATRIX]
    )
    def test_kill_restart_converges(self, site, at):
        pods = fixtures.pods(2, cpu="6")  # both on one 16-cpu node
        h, recorder = drift_harness(pods)
        victim = h.expect_scheduled(pods[0])
        new_hash = flip_spec(h)
        crashpoints.arm(site, at=at)
        with pytest.raises(SimulatedCrash) as crash:
            h.drift.reconcile()
        assert crash.value.site == site
        restart(h)
        converge(h)
        assert h.cluster.try_get_node(victim.name) is None, "victim survived"
        for pod in pods:
            live = h.cluster.get_pod(pod.namespace, pod.name)
            assert live.node_name is not None, f"{pod.name} lost in the crash"
            node = h.cluster.try_get_node(live.node_name)
            assert node is not None and node.deletion_timestamp is None
            assert node.annotations[HASH_ANNOTATION] == new_hash
            assert len(recorder.bound[pod.uid]) <= 2, recorder.bound[pod.uid]
        assert not claims(h), "a drift claim survived convergence"
        assert_no_leaks(h)


# --- provisioner weight ------------------------------------------------------


class TestProvisionerWeight:
    def test_highest_weight_wins_selection(self):
        h = Harness()
        h.apply_provisioner(Provisioner(name="light", spec=ProvisionerSpec()))
        h.apply_provisioner(
            Provisioner(name="heavy", spec=ProvisionerSpec(weight=10))
        )
        pod = fixtures.pod()
        h.provision(pod)
        node = h.expect_scheduled(pod)
        assert node.labels[wellknown.PROVISIONER_NAME_LABEL] == "heavy"

    def test_equal_weight_breaks_ties_alphabetically(self):
        h = Harness()
        h.apply_provisioner(Provisioner(name="bravo", spec=ProvisionerSpec()))
        h.apply_provisioner(Provisioner(name="alpha", spec=ProvisionerSpec()))
        pod = fixtures.pod()
        h.provision(pod)
        node = h.expect_scheduled(pod)
        assert node.labels[wellknown.PROVISIONER_NAME_LABEL] == "alpha"

    def test_weight_validated(self):
        for bad in (-1, 101, 1.5, True):
            with pytest.raises(ValidationError):
                validate_provisioner(
                    Provisioner(name="p", spec=ProvisionerSpec(weight=bad))
                )
        validate_provisioner(
            Provisioner(name="p", spec=ProvisionerSpec(weight=100))
        )

    def test_weight_serialization_round_trip(self):
        provisioner = Provisioner(name="p", spec=ProvisionerSpec(weight=42))
        out = provisioner_to_dict(provisioner)
        assert out["spec"]["weight"] == 42
        assert provisioner_from_dict(out).spec.weight == 42
        # Default weight is omitted from the wire form entirely.
        assert "weight" not in provisioner_to_dict(
            Provisioner(name="p", spec=ProvisionerSpec())
        )["spec"]


# --- observability + flags ---------------------------------------------------


class TestDriftObservability:
    def test_metrics_registered_with_vet_checker(self):
        from tools.vet.checkers import metricsuse
        from tools.vet.framework import production_modules

        by_name, by_var = metricsuse._collect_declarations(production_modules())
        for name in (
            "drift_nodes",
            "drift_replacements_total",
            "disruption_budget_in_use",
        ):
            assert len(set(by_name[name])) == 1, f"{name} declared twice"
        assert by_var["DRIFT_NODES"] == [("gauge", 1)]
        assert by_var["DRIFT_REPLACEMENTS_TOTAL"] == [("counter", 2)]
        assert by_var["DISRUPTION_BUDGET_IN_USE"] == [("gauge", 0)]

    def test_drift_event_flight_recorded(self):
        from karpenter_tpu.utils.obs import RECORDER

        pods = fixtures.pods(1, cpu="12")
        h, _ = drift_harness(pods)
        flip_spec(h)
        h.drift.reconcile()
        events = [
            e
            for e in RECORDER.snapshot()["events"]
            if e.get("kind") == "drift"
        ]
        assert events, "drift decision left no flight-recorder event"
        assert events[-1]["drift_kind"] == driftlib.DRIFT_KIND_SPEC


class TestDriftFlags:
    def test_flags_parse(self):
        from karpenter_tpu.utils.options import parse

        options = parse(
            [
                "--cluster-name", "t",
                "--disruption-budget", "5",
                "--drift-max-disruption", "3",
            ]
        )
        assert options.disruption_budget == 5
        assert options.drift_max_disruption == 3
        assert options.drift_enabled is True
        assert parse(["--cluster-name", "t", "--no-drift"]).drift_enabled is False

    def test_flags_validated(self):
        from karpenter_tpu.utils.options import OptionsError, parse

        with pytest.raises(OptionsError):
            parse(["--cluster-name", "t", "--disruption-budget", "-1"])
        with pytest.raises(OptionsError):
            parse(["--cluster-name", "t", "--drift-max-disruption", "-1"])
        with pytest.raises(OptionsError):
            # A per-reason cap above the global budget can never be spent.
            parse(
                [
                    "--cluster-name", "t",
                    "--disruption-budget", "2",
                    "--drift-max-disruption", "5",
                ]
            )
