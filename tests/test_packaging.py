"""Packaging artifacts: the CRD schema must round-trip the serialization
layer's field names, dashboards must be valid Grafana JSON over metrics that
actually exist, and the chart values must parse (ref: charts/karpenter +
grafana-dashboards/ in the reference)."""

import json
import re
from pathlib import Path

import yaml

from karpenter_tpu.api.provisioner import (
    Constraints,
    Limits,
    Provisioner,
    ProvisionerSpec,
)
from karpenter_tpu.api.requirements import Requirement, Requirements
from karpenter_tpu.api.serialization import provisioner_to_dict
from karpenter_tpu.api.taints import Taint
from karpenter_tpu.api import wellknown
from karpenter_tpu.utils.metrics import REGISTRY

ROOT = Path(__file__).resolve().parent.parent


class TestCRD:
    def _schema(self):
        crd = yaml.safe_load((ROOT / "deploy/crds/provisioner.yaml").read_text())
        assert crd["kind"] == "CustomResourceDefinition"
        version = crd["spec"]["versions"][0]
        return crd, version["schema"]["openAPIV3Schema"]

    def test_group_and_names(self):
        crd, _ = self._schema()
        assert crd["spec"]["group"] == "karpenter.tpu"
        assert crd["spec"]["names"]["kind"] == "Provisioner"
        assert crd["spec"]["scope"] == "Cluster"

    def test_schema_covers_serialized_fields(self):
        _, schema = self._schema()
        spec_props = schema["properties"]["spec"]["properties"]
        status_props = schema["properties"]["status"]["properties"]

        provisioner = Provisioner(
            name="x",
            spec=ProvisionerSpec(
                constraints=Constraints(
                    labels={"a": "b"},
                    taints=[Taint(key="k", value="v")],
                    requirements=Requirements(
                        [Requirement.in_(wellknown.ZONE_LABEL, ["z"])]
                    ),
                    provider={"cloud": "ec2"},
                ),
                ttl_seconds_after_empty=30,
                ttl_seconds_until_expired=300,
                limits=Limits(resources={"cpu": "100"}),
            ),
        )
        serialized = provisioner_to_dict(provisioner)
        for field in serialized["spec"]:
            assert field in spec_props, f"spec.{field} missing from CRD schema"
        for field in serialized["status"]:
            assert field in status_props, f"status.{field} missing from CRD schema"

    def test_requirement_operators_match_validation(self):
        _, schema = self._schema()
        ops = schema["properties"]["spec"]["properties"]["requirements"]["items"][
            "properties"
        ]["operator"]["enum"]
        from karpenter_tpu.api.requirements import SUPPORTED_OPERATORS

        assert set(ops) == set(SUPPORTED_OPERATORS)


class TestDashboards:
    def _metric_names(self):
        # Unobserved metrics render only HELP/TYPE lines; TYPE lists them all.
        return set(re.findall(r"^# TYPE (karpenter_\S+) ", REGISTRY.render(), re.M))

    def test_dashboards_are_valid_json_with_panels(self):
        files = sorted((ROOT / "dashboards").glob("*.json"))
        assert len(files) >= 3
        for path in files:
            dashboard = json.loads(path.read_text())
            assert dashboard["panels"], path.name
            for panel in dashboard["panels"]:
                assert panel["targets"], f"{path.name}: panel without queries"

    def test_dashboard_metrics_exist(self):
        # Every karpenter_* metric referenced by a dashboard must be
        # registered in code (guards against dashboard drift). Exact match
        # after stripping exposition suffixes — a prefix match would let a
        # truncated or removed metric slip through.
        # Touch the histogram/gauge modules so registration runs.
        import karpenter_tpu.controllers.provisioning  # noqa: F401
        import karpenter_tpu.controllers.drift  # noqa: F401 — drift + budget gauges
        import karpenter_tpu.controllers.metrics  # noqa: F401
        import karpenter_tpu.kubeapi.client  # noqa: F401 — lane-wait histogram
        import karpenter_tpu.runtime  # noqa: F401 — reconcile-loop metrics
        import karpenter_tpu.solver_service.client  # noqa: F401

        registered = self._metric_names()
        for path in sorted((ROOT / "dashboards").glob("*.json")):
            text = path.read_text()
            for metric in set(re.findall(r"karpenter_[a-z0-9_]+", text)):
                # Strip histogram exposition suffixes — but gauges may
                # legitimately end in _count (e.g. ready_node_count, matching
                # the reference's names), so accept the exact name too.
                base = re.sub(r"_(bucket|count|sum)$", "", metric)
                assert base in registered or metric in registered, (
                    f"{path.name} references unregistered metric {metric}"
                )


class TestChart:
    def test_values_parse_and_cover_options(self):
        values = yaml.safe_load(
            (ROOT / "deploy/chart/karpenter-tpu/values.yaml").read_text()
        )
        assert values["controller"]["metricsPort"] == 8080
        assert values["controller"]["healthProbePort"] == 8081
        assert values["controller"]["kubeClientQPS"] == 200
        assert values["controller"]["kubeClientBurst"] == 300
        assert values["controller"]["solver"] in (
            "cost", "ffd", "greedy", "native", "remote",
        )
        assert values["solver"]["port"] == 9090

    def test_webhook_registration_matches_served_endpoints(self):
        """The chart's (Mutating|Validating)WebhookConfiguration must point
        at paths the binary serves with AdmissionReview v1, and the TLS
        wiring must exist for the apiserver to call them."""
        templates = ROOT / "deploy/chart/karpenter-tpu/templates"
        config = (templates / "webhook-config.yaml").read_text()
        assert "MutatingWebhookConfiguration" in config
        assert "ValidatingWebhookConfiguration" in config
        assert "path: /default" in config and "path: /validate" in config
        assert "admissionReviewVersions: [v1]" in config
        deployment = (templates / "webhook-deployment.yaml").read_text()
        assert "--tls-cert-file=/certs/tls.crt" in deployment
        assert "--tls-key-file=/certs/tls.key" in deployment
        values = yaml.safe_load(
            (ROOT / "deploy/chart/karpenter-tpu/values.yaml").read_text()
        )
        assert "tlsSecretName" in values["webhook"]

    def test_multihost_statefulset_matches_env_contract(self):
        """The multi-host solver StatefulSet must set exactly the env vars
        parallel/multihost.py consumes, pin the RPC Service to rank 0, and
        provide the headless rendezvous Service."""
        templates = ROOT / "deploy/chart/karpenter-tpu/templates"
        solver = (templates / "solver-deployment.yaml").read_text()
        for var in (
            "KARPENTER_PROCESS_ID",
            "KARPENTER_NUM_PROCESSES",
            "KARPENTER_COORDINATOR",
        ):
            assert var in solver, f"solver template missing {var}"
        assert "kind: StatefulSet" in solver
        assert "podManagementPolicy: Parallel" in solver
        assert "clusterIP: None" in solver  # headless peers service
        assert "statefulset.kubernetes.io/pod-name" in solver  # rank-0 pin
        values = yaml.safe_load(
            (ROOT / "deploy/chart/karpenter-tpu/values.yaml").read_text()
        )
        multihost = values["solver"]["multihost"]
        assert multihost["enabled"] is False  # default stays single-host
        assert multihost["hosts"] >= 2
        assert multihost["coordinatorPort"]

    def test_templates_reference_real_entrypoints(self):
        templates = ROOT / "deploy/chart/karpenter-tpu/templates"
        text = "".join(p.read_text() for p in templates.glob("*.yaml"))
        for module in (
            "karpenter_tpu.cmd.controller",
            "karpenter_tpu.cmd.webhook",
            "karpenter_tpu.solver_service.server",
        ):
            assert module in text, f"chart doesn't wire {module}"
            __import__(module)  # the entrypoint module must exist


class TestComplexityGate:
    """tools/complexity_gate.py — the battletest's gocyclo analogue
    (ref: /root/reference/Makefile:33-38 gates cyclomatic complexity before
    the race-detected suites)."""

    def test_counter_matches_known_complexity(self, tmp_path):
        import sys

        sys.path.insert(0, str(ROOT / "tools"))
        try:
            from complexity_gate import function_complexities
        finally:
            sys.path.pop(0)
        sample = tmp_path / "sample.py"
        sample.write_text(
            "def f(a, b):\n"
            "    if a and b:\n"          # +1 if, +1 and
            "        return 1\n"
            "    for i in range(3):\n"   # +1
            "        while a:\n"         # +1
            "            a -= 1\n"
            "    return [x for x in b if x]\n"  # +1 comp, +1 if
        )
        [(name, _, complexity)] = list(function_complexities(sample))
        assert name == "f" and complexity == 1 + 6

    def test_repo_passes_and_allowlist_is_live(self):
        import subprocess
        import sys

        result = subprocess.run(
            [sys.executable, "tools/complexity_gate.py"],
            capture_output=True,
            text=True,
            timeout=120,
            cwd=ROOT,
        )
        assert result.returncode == 0, result.stdout + result.stderr
