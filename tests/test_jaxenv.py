"""utils/jaxenv: the device-liveness probe and CPU-backend pin that keep
the driver entry points (bench, __graft_entry__) from hanging forever on a
wedged accelerator tunnel."""

import sys

from karpenter_tpu.utils.jaxenv import device_alive, force_cpu_backend


class TestDeviceAlive:
    def test_healthy_probe(self):
        assert device_alive(timeout_s=30.0, _probe_code="pass") is True

    def test_hung_probe_is_killed_at_the_timeout(self):
        """The wedged-tunnel case: the child never returns on its own; the
        probe must declare dead at the deadline instead of hanging with it."""
        assert (
            device_alive(
                timeout_s=1.0, _probe_code="import time; time.sleep(600)"
            )
            is False
        )

    def test_failing_probe_forwards_stderr(self, capfd):
        assert (
            device_alive(
                timeout_s=30.0,
                _probe_code="import sys; sys.stderr.write('no libtpu here'); "
                "raise SystemExit(3)",
            )
            is False
        )
        assert "no libtpu here" in capfd.readouterr().err


class TestForceCpuBackend:
    def test_pins_cpu(self):
        # conftest already pinned cpu for the suite; the helper must be
        # idempotent and return a jax running on the cpu platform.
        jax = force_cpu_backend()
        assert jax.devices()[0].platform == "cpu"
        assert sys.modules["jax"] is jax
