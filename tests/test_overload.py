"""Overload control plane tests: bounded admission with anti-starvation,
critical priority lanes in the kube client, and device-OOM batch survival —
the ISSUE 18 tentpole's regression coverage. The soak smoke
(tools/soak_smoke.py) composes these layers; these tests pin each one in
isolation so a soak failure bisects to a layer, not a rerun."""

import pytest

from karpenter_tpu.api.provisioner import Constraints, Provisioner, ProvisionerSpec
from karpenter_tpu.controllers import provisioning as provisioning_mod
from karpenter_tpu.controllers.provisioning import (
    PROVISION_BACKPRESSURE_TOTAL,
    PROVISION_QUEUE_DEPTH,
)
from karpenter_tpu.controllers.selection import SelectionController
from karpenter_tpu.utils import faultpoints
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.workqueue import BackoffQueue

from tests import fixtures
from tests.harness import Harness


@pytest.fixture(autouse=True)
def _clean_faultpoints():
    faultpoints.disarm_all()
    faultpoints.seed(0)
    yield
    faultpoints.disarm_all()


def default_provisioner(**kwargs) -> Provisioner:
    return Provisioner(name="default", spec=ProvisionerSpec(**kwargs))


# --- bounded admission (tentpole layer 1) ------------------------------------


class TestBoundedAdmission:
    """ProvisionerWorker.add refuses past --provision-queue-max-pods; the
    refusal rides selection's backoff ladder instead of growing an
    unbounded overflow list."""

    def _harness(self, cap: int) -> Harness:
        h = Harness()
        h.provisioning.queue_max_pods = cap
        h.apply_provisioner(default_provisioner())
        return h

    def test_add_refuses_past_cap_and_counts_backpressure(self):
        h = self._harness(cap=10)
        worker = h.provisioning.worker("default")
        before = PROVISION_BACKPRESSURE_TOTAL.get("queue-full")
        pods = fixtures.pods(12, cpu="100m", memory="64Mi")
        accepted = [worker.add(p) for p in pods]
        assert accepted[:10] == [True] * 10
        assert accepted[10:] == [False, False]
        assert worker.queue_depth() == 10
        assert PROVISION_BACKPRESSURE_TOTAL.get("queue-full") == before + 2
        assert PROVISION_QUEUE_DEPTH.get("default") == 10.0

    def test_duplicate_add_still_held_at_cap(self):
        """A re-verify of a pod the worker already holds is not a refusal —
        returning False would bounce an ADMITTED pod onto the backoff
        ladder and double-track it."""
        h = self._harness(cap=5)
        worker = h.provisioning.worker("default")
        pods = fixtures.pods(5, cpu="100m", memory="64Mi")
        for pod in pods:
            assert worker.add(pod)
        assert worker.add(pods[0]) is True  # held, not refused
        assert worker.queue_depth() == 5

    def test_drain_releases_saturation(self):
        h = self._harness(cap=5)
        worker = h.provisioning.worker("default")
        for pod in fixtures.pods(5, cpu="100m", memory="64Mi"):
            worker.add(pod)
        late = fixtures.pod(name="late", cpu="100m", memory="64Mi")
        assert worker.add(late) is False
        worker._drain()
        assert worker.queue_depth() == 0
        assert worker.add(late) is True

    def test_refused_pod_lands_on_selection_backoff_ladder(self):
        h = self._harness(cap=3)
        worker = h.provisioning.worker("default")
        selection = SelectionController(h.cluster, h.provisioning)
        pods = fixtures.pods(4, cpu="100m", memory="64Mi")
        for pod in pods:
            h.cluster.apply_pod(pod)
        delays = [selection.reconcile(p.namespace, p.name) for p in pods]
        # First three accepted (slow-poll requeue), fourth refused with a
        # SHORT backoff — the queue drains on the batch cadence, so the
        # refused cap (30s) stays far under the no-match ceiling.
        assert delays[:3] == [SelectionController.ACCEPTED_REQUEUE_SECONDS] * 3
        assert 0 < delays[3] <= SelectionController.REFUSED_BACKOFF_MAX_SECONDS
        assert worker.queue_depth() == 3
        # After the window drains, the refused pod's retry is accepted.
        worker._drain()
        assert (
            selection.reconcile(pods[3].namespace, pods[3].name)
            == SelectionController.ACCEPTED_REQUEUE_SECONDS
        )

    def test_overflow_refill_is_aging_ordered_across_windows(self, monkeypatch):
        """Anti-starvation: a pod admitted before the cap is solved in
        FIFO-aging order across >=3 batch windows — re-adds arriving out of
        order cannot push an old pending cycle behind fresher waves."""
        monkeypatch.setattr(provisioning_mod, "MAX_PODS_PER_BATCH", 4)
        h = self._harness(cap=100)
        worker = h.provisioning.worker("default")
        pods = fixtures.pods(16, cpu="100m", memory="64Mi")
        # Arrival order is the REVERSE of pending-cycle age: the last-added
        # pods have the oldest anchors (a refused-and-retried wave).
        anchors = {p.uid: 1000.0 - i for i, p in enumerate(pods)}
        monkeypatch.setattr(
            provisioning_mod.OBS, "pending_anchors",
            lambda uids: {u: anchors[u] for u in uids if u in anchors},
        )
        for pod in pods:
            worker.add(pod)
        windows = []
        for _ in range(4):
            windows.append([p.uid for p in worker._drain()])
            h.clock.advance(provisioning_mod.BATCH_IDLE_SECONDS + 0.1)
        assert [len(w) for w in windows] == [4, 4, 4, 4]
        # Window 1 is the already-open batch (arrival order); every refill
        # after it drains oldest-anchor-first: pods 15, 14, ... 4.
        refill_order = [uid for window in windows[1:] for uid in window]
        expected = [p.uid for p in sorted(pods[4:], key=lambda p: anchors[p.uid])]
        assert refill_order == expected

    def test_batch_window_age_histogram_observed(self):
        h = self._harness(cap=100)
        worker = h.provisioning.worker("default")
        before = provisioning_mod.BATCH_WINDOW_AGE.count()
        for pod in fixtures.pods(6, cpu="100m", memory="64Mi"):
            worker.add(pod)
        batch = worker._drain()
        assert len(batch) == 6
        assert provisioning_mod.BATCH_WINDOW_AGE.count() == before + 6


# --- selection BackoffQueue bound (satellite 1) ------------------------------


class TestBackoffQueueBound:
    def test_dedup_holds_at_ten_thousand_keys(self):
        q = BackoffQueue(clock=FakeClock())
        keys = [("default", f"pod-{i}") for i in range(12_000)]
        assert all(q.add(k) for k in keys)
        # A full re-verify storm re-adds every key: nothing grows.
        assert not any(q.add(k) for k in keys)
        assert len(q) == 12_000

    def test_max_items_refuses_new_keys_but_keeps_requeues(self):
        q = BackoffQueue(clock=FakeClock(), max_items=10_000)
        keys = [f"pod-{i}" for i in range(10_000)]
        assert all(q.add(k) for k in keys)
        assert q.add("pod-overflow") is False
        assert len(q) == 10_000
        # Draining frees capacity for new keys.
        done = q.process(lambda item: True)
        assert done == 10_000
        assert q.add("pod-overflow") is True

    def test_failing_items_requeue_within_the_bound(self):
        clock = FakeClock()
        q = BackoffQueue(clock=clock, max_items=2)
        q.add("a")
        q.add("b")
        q.process(lambda item: False)  # both fail -> backoff requeue
        assert len(q) == 2
        assert q.add("c") is False  # bound counts the requeued set
        clock.advance(60.0)
        q.process(lambda item: True)
        assert q.add("c") is True


# --- ReconcileLoop backoff prune (satellite 3) -------------------------------


class TestReconcileBackoffPrune:
    def _loop(self):
        from karpenter_tpu.runtime import ReconcileLoop

        return ReconcileLoop("t", reconcile=lambda key: None)

    def test_forget_drops_streak(self):
        loop = self._loop()
        with loop._cv:
            loop._err_streak[("default", "pod-1")] = 7
            loop._err_streak[("default", "pod-2")] = 3
        loop.forget(("default", "pod-1"))
        assert loop.err_streak_size() == 1
        loop.forget(("default", "pod-1"))  # idempotent
        assert loop.err_streak_size() == 1

    def test_manager_delta_routes_terminal_deletes(self):
        """Manager._on_delta prunes the right loop per kind — the leak was
        one streak entry per churned pod/node for the life of the process."""
        from types import SimpleNamespace

        from karpenter_tpu.runtime import Manager

        loops = {
            name: self._loop()
            for name in (
                "selection", "node", "termination",
                "provisioning", "counter", "metrics",
            )
        }
        for loop in loops.values():
            with loop._cv:
                loop._err_streak["sentinel"] = 1
        with loops["selection"]._cv:
            loops["selection"]._err_streak[("default", "churned")] = 9
        stub = SimpleNamespace(loops=loops)
        pod = SimpleNamespace(namespace="default", name="churned")
        Manager._on_delta(stub, "update", "pod", pod)  # non-terminal: no-op
        assert loops["selection"].err_streak_size() == 2
        Manager._on_delta(stub, "delete", "pod", pod)
        assert loops["selection"].err_streak_size() == 1
        node = SimpleNamespace(name="node-1")
        with loops["node"]._cv:
            loops["node"]._err_streak["node-1"] = 2
        with loops["termination"]._cv:
            loops["termination"]._err_streak["node-1"] = 2
        Manager._on_delta(stub, "delete", "node", node)
        assert loops["node"].err_streak_size() == 1
        assert loops["termination"].err_streak_size() == 1


# --- critical priority lanes (tentpole layer 2) ------------------------------


class TestCriticalLanes:
    def test_wait_never_livelocks_on_sub_ulp_refill(self):
        """Refill arithmetic can leave a token deficit smaller than the
        clock's double-precision ULP; the matching sleep then advances a
        large-valued FakeClock by exactly nothing and wait() spins forever
        (found by the soak's throttled rig at fake_now=1e6). The MIN_SLEEP_S
        floor must keep the refill landing."""
        import threading

        from karpenter_tpu.kubeapi.client import RateLimiter

        clock = FakeClock(start=1_000_000.0)
        limiter = RateLimiter(qps=50.0, burst=20, clock=clock, critical_reserve=2)
        drained = []

        def drain():
            for _ in range(60):  # well past the burst: forces refill waits
                limiter.wait()
            drained.append(True)

        worker = threading.Thread(target=drain, daemon=True)
        worker.start()
        worker.join(timeout=10.0)
        assert drained, "RateLimiter.wait livelocked on a sub-ULP token deficit"

    def test_current_lane_defaults_bulk_and_nests(self):
        from karpenter_tpu.kubeapi.client import critical_lane, current_lane

        assert current_lane() == "bulk"
        with critical_lane():
            assert current_lane() == "critical"
            with critical_lane():
                assert current_lane() == "critical"
            assert current_lane() == "critical"
        assert current_lane() == "bulk"

    def test_bulk_cannot_drain_below_the_reserve(self):
        from karpenter_tpu.kubeapi.client import RateLimiter

        clock = FakeClock()
        limiter = RateLimiter(qps=1.0, burst=10, clock=clock, critical_reserve=2)
        # Bulk takes burst - reserve tokens for free, then must wait.
        for _ in range(8):
            assert limiter.wait() == 0.0
        t0 = clock.now()
        assert limiter.wait() > 0.0  # bulk slept for refill
        assert clock.now() > t0

    def test_critical_lane_passes_through_a_bulk_storm(self):
        """The lease-loss regression: with bulk throttled at the reserve
        floor, a critical call (lease renew) still gets a token with ZERO
        sleep — previously it queued behind the storm and the leader's
        lease expired before the renew's turn came."""
        from karpenter_tpu.kubeapi.client import RateLimiter

        clock = FakeClock()
        limiter = RateLimiter(qps=1.0, burst=10, clock=clock, critical_reserve=2)
        for _ in range(8):
            limiter.wait()  # the bulk storm drains to the floor
        assert limiter.wait(critical=True) == 0.0
        assert limiter.wait(critical=True) == 0.0
        # The reserve is spent: even critical now pays refill, bounded by
        # arithmetic (1 token / qps), not by the storm's queue.
        assert limiter.wait(critical=True) == pytest.approx(1.0)

    def test_client_routes_lane_from_context(self):
        """KubeClient passes the ambient lane to the limiter per request —
        the storm test above only protects callers that actually ride the
        critical flag."""
        from tests.fake_apiserver import DirectTransport, FakeApiServer

        from karpenter_tpu.kubeapi.client import KubeClient, critical_lane

        clock = FakeClock()
        client = KubeClient(
            DirectTransport(FakeApiServer(clock=clock)),
            qps=1.0, burst=10, clock=clock, critical_reserve=2,
        )
        seen = []
        real_wait = client.limiter.wait

        def spy(critical=False):
            seen.append(critical)
            return real_wait(critical=critical)

        client.limiter.wait = spy
        client.get("/api/v1/nodes")
        with critical_lane():
            client.get("/api/v1/nodes")
        assert seen == [False, True]

    def test_lease_renew_survives_a_bulk_storm(self):
        """End-to-end: drain the bucket with bulk reads, then renew the
        lease — the renew must not advance the clock (no throttle sleep),
        i.e. the storm can no longer cost the leader its lease."""
        from tests.fake_apiserver import DirectTransport, FakeApiServer

        from karpenter_tpu.kubeapi import ApiServerCluster, KubeClient

        clock = FakeClock()
        server = FakeApiServer(clock=clock)
        client = KubeClient(
            DirectTransport(server),
            qps=1.0, burst=20, clock=clock, critical_reserve=4,
        )
        cluster = ApiServerCluster(client, clock=clock)
        try:
            assert cluster.acquire_lease("leader", "mgr-1", duration_s=15.0) > 0
            while client.limiter.wait() == 0.0:
                pass  # bulk storm: drain to the reserve floor
            t0 = clock.now()
            assert cluster.acquire_lease("leader", "mgr-1", duration_s=15.0) > 0
            # The whole read-CAS round rode the reserve: zero throttle sleep.
            assert clock.now() == t0
        finally:
            cluster.close()


# --- device-OOM batch survival (tentpole layer 3) ----------------------------


def _canonical(result):
    """Exact (bit-identical) rendering of a PackResult: node layouts, the
    option ladders, the projected cost — float compared with ==, not
    approx, because the bisect re-runs the IDENTICAL per-schedule math."""
    return (
        tuple(
            (
                tuple(opt.name for opt in packing.instance_type_options),
                tuple(
                    tuple(p.name for p in node) for node in packing.pods_per_node
                ),
            )
            for packing in result.packings
        ),
        tuple(p.name for p in result.unschedulable),
        result.projected_cost(),
    )


class TestDeviceOomSurvival:
    """RESOURCE_EXHAUSTED at dispatch bisects the batch and re-dispatches
    halves under the ORIGINAL host-gate flag — plans come out bit-identical
    to the unsplit solve, and only a single schedule that still won't fit
    falls through to the BackendHealth CPU pin."""

    @pytest.fixture(autouse=True)
    def _device_path(self, monkeypatch):
        # Force the device dispatch so the solver.dispatch faultpoint is
        # actually crossed, and keep the single-chip kernel.
        monkeypatch.setenv("KARPENTER_HOST_SOLVE", "0")
        monkeypatch.setenv("KARPENTER_SHARDED_SOLVE", "0")
        monkeypatch.delenv("KARPENTER_HBM_BYTES", raising=False)

    @staticmethod
    def _problems(count=8):
        from karpenter_tpu.ops.encode import build_fleet, group_pods

        problems = []
        for k in range(count):
            pods = fixtures.pods(10 + 5 * k, cpu="1", memory="1Gi")
            catalog = fixtures.size_ladder(3 + (k % 3))
            problems.append(
                (group_pods(pods), build_fleet(catalog, Constraints(), pods))
            )
        return problems

    @pytest.mark.parametrize("failures", [1, 2, 3])
    def test_rotating_split_depths_bit_identical(self, failures):
        from karpenter_tpu.models.solver import CostSolver

        solver = CostSolver(lp_steps=4)
        problems = self._problems(8)
        baseline = [_canonical(r) for r in solver.solve_encoded_many(problems)]
        fault = faultpoints.arm("solver.dispatch", "oom", count=failures)
        survived = solver.solve_encoded_many(problems)
        assert fault.fires == failures  # each depth re-dispatched and re-failed
        assert [_canonical(r) for r in survived] == baseline

    def test_pipelined_path_recovers_mid_stream(self):
        from karpenter_tpu.models.solver import CostSolver

        solver = CostSolver(lp_steps=4)
        problems = self._problems(6)
        baseline = [
            _canonical(r) for r in solver.solve_encoded_pipelined(problems)
        ]
        fault = faultpoints.arm("solver.dispatch", "oom", count=1)
        survived = [
            _canonical(r) for r in solver.solve_encoded_pipelined(problems)
        ]
        assert fault.fires == 1
        assert survived == baseline

    def test_floor_falls_through_to_cpu_pin(self, monkeypatch):
        """A SINGLE schedule that still OOMs is the floor: pin the CPU
        backend (the existing BackendHealth fallback) and answer from the
        host path — never a crash, never a silent drop."""
        from karpenter_tpu.models import solver as S
        from karpenter_tpu.models.solver import CostSolver
        from karpenter_tpu.utils import backend_health

        pinned = []
        monkeypatch.setattr(backend_health, "pin_cpu", lambda: pinned.append(1))
        before = S.SOLVER_BATCH_SPLIT_TOTAL.get("floor")
        faultpoints.arm("solver.dispatch", "oom")  # unlimited: every retry fails
        problems = self._problems(1)
        [result] = CostSolver(lp_steps=4).solve_encoded_many(problems)
        assert pinned == [1]
        assert S.SOLVER_BATCH_SPLIT_TOTAL.get("floor") == before + 1
        # The floor still answers: every pod placed or explicitly left over.
        placed = sum(
            len(node) for p in result.packings for node in p.pods_per_node
        )
        assert placed + len(result.unschedulable) == 10

    def test_whole_batch_never_silently_pinned(self):
        """The acceptance criterion's negative space: a multi-schedule OOM
        must bisect, not dump the entire batch onto the CPU pin — only the
        floor (a lone schedule) may pin."""
        from karpenter_tpu.models import solver as S
        from karpenter_tpu.models.solver import CostSolver

        before_oom = S.SOLVER_BATCH_SPLIT_TOTAL.get("oom")
        before_floor = S.SOLVER_BATCH_SPLIT_TOTAL.get("floor")
        faultpoints.arm("solver.dispatch", "oom", count=1)
        CostSolver(lp_steps=4).solve_encoded_many(self._problems(4))
        assert S.SOLVER_BATCH_SPLIT_TOTAL.get("oom") == before_oom + 1
        assert S.SOLVER_BATCH_SPLIT_TOTAL.get("floor") == before_floor

    def test_hbm_estimator_presplits_oversized_batch(self, monkeypatch):
        from karpenter_tpu.models import solver as S
        from karpenter_tpu.models.solver import CostSolver

        solver = CostSolver(lp_steps=4)
        problems = self._problems(6)
        baseline = [_canonical(r) for r in solver.solve_encoded_many(problems)]
        # Budget sized to hold ~2 schedules per chunk: the batch must be
        # pre-split WITHOUT any injected failure.
        per_item = max(S._estimate_solve_bytes(*p) for p in problems)
        monkeypatch.setenv(
            "KARPENTER_HBM_BYTES", str(per_item * 2 / S.HBM_SAFETY_FACTOR)
        )
        before = S.SOLVER_BATCH_SPLIT_TOTAL.get("estimate")
        split = [_canonical(r) for r in solver.solve_encoded_many(problems)]
        assert S.SOLVER_BATCH_SPLIT_TOTAL.get("estimate") > before
        assert split == baseline

    def test_non_memory_errors_propagate(self, monkeypatch):
        """The bisect must not eat logic errors — retrying those just
        re-fails slower and hides the bug."""
        from karpenter_tpu.models import solver as S
        from karpenter_tpu.models.solver import CostSolver

        def explode(*args, **kwargs):
            raise ValueError("bad plan decode")

        monkeypatch.setattr(S, "fetch_plans", explode)
        with pytest.raises(ValueError, match="bad plan decode"):
            CostSolver(lp_steps=4).solve_encoded_many(self._problems(2))

    def test_classifier_matches_known_phrasings(self):
        from karpenter_tpu.models.solver import _is_resource_exhausted

        assert _is_resource_exhausted(
            RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating 2GiB")
        )
        assert _is_resource_exhausted(
            RuntimeError("Failed to allocate 1073741824 bytes")
        )
        assert not _is_resource_exhausted(ValueError("shape mismatch"))
        assert not _is_resource_exhausted(TimeoutError("deadline"))
