"""Tracing: span nesting, Chrome trace export, pipeline + solver-RPC wiring,
disabled-by-default behavior (the reference has no tracing at all —
SURVEY.md §5 — so everything here is rebuild-added surface)."""

import json

import pytest

from karpenter_tpu.api.provisioner import Provisioner
from karpenter_tpu.models.solver import GreedySolver
from karpenter_tpu.utils import tracing

from tests import fixtures
from tests.harness import Harness


@pytest.fixture()
def tracer(monkeypatch):
    tracer = tracing.Tracer(enabled=True)
    monkeypatch.setattr(tracing, "TRACER", tracer)
    return tracer


class TestSpans:
    def test_span_records_duration_and_attributes(self, tracer):
        with tracer.span("work", items=3):
            pass
        [span] = tracer.spans("work")
        assert span.duration_s >= 0
        assert span.attributes["items"] == 3

    def test_nesting_sets_parent(self, tracer):
        with tracer.span("outer"):
            with tracer.span("inner"):
                pass
        [inner] = tracer.spans("inner")
        [outer] = tracer.spans("outer")
        assert inner.parent == "outer"
        assert outer.parent is None

    def test_set_updates_attributes_mid_span(self, tracer):
        with tracer.span("rpc") as span:
            span.set(outcome="ok")
        [span] = tracer.spans("rpc")
        assert span.attributes["outcome"] == "ok"

    def test_disabled_tracer_records_nothing(self):
        tracer = tracing.Tracer(enabled=False)
        with tracer.span("work"):
            pass
        assert tracer.spans() == []

    def test_ring_buffer_bounded(self, tracer):
        for i in range(tracing._MAX_SPANS + 100):
            tracer.record(tracing.Span(name=f"s{i}", start_s=0.0))
        assert len(tracer.spans()) == tracing._MAX_SPANS


class TestChromeExport:
    def test_events_format(self, tracer, tmp_path):
        with tracer.span("outer"):
            with tracer.span("inner", detail="x"):
                pass
        events = tracer.chrome_trace_events()
        assert {e["name"] for e in events} == {"outer", "inner"}
        for event in events:
            assert event["ph"] == "X"
            assert event["dur"] >= 0
        path = tmp_path / "trace.json"
        flushed = tracer.flush(str(path))
        assert flushed == str(path)
        loaded = json.loads(path.read_text())
        assert len(loaded["traceEvents"]) == 2

    def test_flush_without_target_is_noop(self, tracer, monkeypatch):
        monkeypatch.delenv("KARPENTER_TRACE_FILE", raising=False)
        assert tracer.flush() is None


class TestPipelineWiring:
    def test_provision_emits_stage_spans(self, tracer, monkeypatch):
        # The controllers import TRACER by value; patch their references too.
        from karpenter_tpu.controllers import provisioning as prov_mod

        monkeypatch.setattr(prov_mod, "TRACER", tracer)
        h = Harness(solver=GreedySolver())
        h.apply_provisioner(Provisioner(name="default"))
        h.provision(*fixtures.pods(5))
        assert tracer.spans("provision.schedule")
        [solve] = tracer.spans("provision.solve")
        assert solve.attributes["pods"] == 5
        assert tracer.spans("provision.bind")

    def test_remote_solve_emits_rpc_spans(self, tracer, monkeypatch):
        from karpenter_tpu.solver_service import client as client_mod
        from karpenter_tpu.solver_service import server as server_mod
        from karpenter_tpu.solver_service.client import RemoteSolver
        from karpenter_tpu.solver_service.server import SolverServer
        from karpenter_tpu.api.provisioner import Constraints

        monkeypatch.setattr(client_mod, "TRACER", tracer)
        monkeypatch.setattr(server_mod, "TRACER", tracer)
        server = SolverServer(port=0).start(warmup=False)
        try:
            remote = RemoteSolver(f"127.0.0.1:{server.port}")
            remote.solve(fixtures.pods(6), fixtures.size_ladder(3), Constraints())
            remote.close()
        finally:
            server.stop()
        [rpc] = tracer.spans("solver.rpc")
        assert rpc.attributes["outcome"] == "ok"
        assert rpc.attributes["server_ms"] > 0
        assert tracer.spans("solver.serve")  # server-side span, same process here

    def test_rpc_error_span_marks_outcome(self, tracer, monkeypatch):
        from karpenter_tpu.solver_service import client as client_mod
        from karpenter_tpu.solver_service.client import RemoteSolver
        from karpenter_tpu.api.provisioner import Constraints

        monkeypatch.setattr(client_mod, "TRACER", tracer)
        remote = RemoteSolver("127.0.0.1:1", timeout_s=0.3)
        remote.solve(fixtures.pods(3), fixtures.size_ladder(2), Constraints())
        remote.close()
        [rpc] = tracer.spans("solver.rpc")
        assert rpc.attributes["outcome"] == "error"
