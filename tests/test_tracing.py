"""Tracing: span nesting, Chrome trace export, pipeline + solver-RPC wiring,
disabled-by-default behavior (the reference has no tracing at all —
SURVEY.md §5 — so everything here is rebuild-added surface)."""

import json

import pytest

from karpenter_tpu.api.provisioner import Provisioner
from karpenter_tpu.models.solver import GreedySolver
from karpenter_tpu.utils import tracing

from tests import fixtures
from tests.harness import Harness


@pytest.fixture()
def tracer(monkeypatch):
    tracer = tracing.Tracer(enabled=True)
    monkeypatch.setattr(tracing, "TRACER", tracer)
    return tracer


class TestSpans:
    def test_span_records_duration_and_attributes(self, tracer):
        with tracer.span("work", items=3):
            pass
        [span] = tracer.spans("work")
        assert span.duration_s >= 0
        assert span.attributes["items"] == 3

    def test_nesting_sets_parent(self, tracer):
        with tracer.span("outer"):
            with tracer.span("inner"):
                pass
        [inner] = tracer.spans("inner")
        [outer] = tracer.spans("outer")
        assert inner.parent == "outer"
        assert outer.parent is None

    def test_set_updates_attributes_mid_span(self, tracer):
        with tracer.span("rpc") as span:
            span.set(outcome="ok")
        [span] = tracer.spans("rpc")
        assert span.attributes["outcome"] == "ok"

    def test_disabled_tracer_records_nothing(self):
        tracer = tracing.Tracer(enabled=False)
        with tracer.span("work"):
            pass
        assert tracer.spans() == []

    def test_ring_buffer_bounded(self, tracer):
        for i in range(tracing._MAX_SPANS + 100):
            tracer.record(tracing.Span(name=f"s{i}", start_s=0.0))
        assert len(tracer.spans()) == tracing._MAX_SPANS


class TestChromeExport:
    def test_events_format(self, tracer, tmp_path):
        with tracer.span("outer"):
            with tracer.span("inner", detail="x"):
                pass
        events = tracer.chrome_trace_events()
        assert {e["name"] for e in events} == {"outer", "inner"}
        for event in events:
            assert event["ph"] == "X"
            assert event["dur"] >= 0
        path = tmp_path / "trace.json"
        flushed = tracer.flush(str(path))
        assert flushed == str(path)
        loaded = json.loads(path.read_text())
        # 2 span ('X') events + process_name/thread_name metadata ('M')
        # events — the labels a merged multi-process viewer needs.
        spans = [e for e in loaded["traceEvents"] if e["ph"] == "X"]
        metadata = [e for e in loaded["traceEvents"] if e["ph"] == "M"]
        assert len(spans) == 2
        assert {e["name"] for e in metadata} == {"process_name", "thread_name"}

    def test_export_is_wall_clock_anchored(self, tracer):
        """Satellite: raw perf_counter ts values are incomparable across
        processes — exported ts must be epoch-anchored and the offset
        recorded in the export metadata, so multi-process traces align."""
        import time as _time

        before = _time.time() * 1e6
        with tracer.span("anchored"):
            pass
        [event] = [
            e for e in tracer.chrome_trace_events() if e["name"] == "anchored"
        ]
        after = _time.time() * 1e6
        assert before - 1e6 <= event["ts"] <= after + 1e6
        document = tracer.chrome_trace_document()
        assert document["metadata"]["clock_epoch_offset_s"] == tracer.epoch_offset_s

    def test_full_thread_ids_exported(self, tracer):
        """Satellite: the old `thread_id & 0xFFFF` truncation collided
        lanes; exported tid must be the full ident."""
        import threading

        with tracer.span("here"):
            pass
        [span] = tracer.spans("here")
        assert span.thread_id == threading.get_ident()
        [event] = tracer.chrome_trace_events()
        assert event["tid"] == threading.get_ident()


class TestTraceContext:
    def test_trace_id_rides_spans(self, tracer):
        trace_id = tracing.new_trace_id()
        with tracer.trace(trace_id):
            with tracer.span("inside"):
                pass
        with tracer.span("outside"):
            pass
        [inside] = tracer.spans("inside")
        [outside] = tracer.spans("outside")
        assert inside.trace == trace_id
        assert outside.trace == ""

    def test_trace_context_restores_previous(self, tracer):
        outer, inner = tracing.new_trace_id(), tracing.new_trace_id()
        with tracer.trace(outer):
            with tracer.trace(inner):
                assert tracer.current_trace() == inner
            assert tracer.current_trace() == outer
        assert tracer.current_trace() is None

    def test_none_keeps_outer_trace(self, tracer):
        outer = tracing.new_trace_id()
        with tracer.trace(outer):
            with tracer.trace(None):
                assert tracer.current_trace() == outer

    def test_trace_id_word_round_trip(self):
        """The SPMD header leg carries the id as two non-negative int32
        words; the round trip must be lossless for every minted id."""
        for _ in range(32):
            trace_id = tracing.new_trace_id()
            lo, hi = tracing.trace_id_to_words(trace_id)
            assert 0 <= lo < 2**31 and 0 <= hi < 2**31
            assert tracing.words_to_trace_id(lo, hi) == trace_id
        assert tracing.trace_id_to_words(None) == (0, 0)
        assert tracing.trace_id_to_words("") == (0, 0)
        assert tracing.words_to_trace_id(0, 0) is None

    def test_flush_without_target_is_noop(self, tracer, monkeypatch):
        monkeypatch.delenv("KARPENTER_TRACE_FILE", raising=False)
        assert tracer.flush() is None


class TestPipelineWiring:
    def test_provision_emits_stage_spans(self, tracer, monkeypatch):
        # The controllers import TRACER by value; patch their references too.
        from karpenter_tpu.controllers import provisioning as prov_mod

        monkeypatch.setattr(prov_mod, "TRACER", tracer)
        h = Harness(solver=GreedySolver())
        h.apply_provisioner(Provisioner(name="default"))
        h.provision(*fixtures.pods(5))
        assert tracer.spans("provision.schedule")
        [solve] = tracer.spans("provision.solve")
        assert solve.attributes["pods"] == 5
        assert tracer.spans("provision.bind")

    def test_remote_solve_emits_rpc_spans(self, tracer, monkeypatch):
        from karpenter_tpu.solver_service import client as client_mod
        from karpenter_tpu.solver_service import server as server_mod
        from karpenter_tpu.solver_service.client import RemoteSolver
        from karpenter_tpu.solver_service.server import SolverServer
        from karpenter_tpu.api.provisioner import Constraints

        monkeypatch.setattr(client_mod, "TRACER", tracer)
        monkeypatch.setattr(server_mod, "TRACER", tracer)
        server = SolverServer(port=0).start(warmup=False)
        try:
            remote = RemoteSolver(f"127.0.0.1:{server.port}")
            remote.solve(fixtures.pods(6), fixtures.size_ladder(3), Constraints())
            remote.close()
        finally:
            server.stop()
        [rpc] = tracer.spans("solver.rpc")
        assert rpc.attributes["outcome"] == "ok"
        assert rpc.attributes["server_ms"] > 0
        assert tracer.spans("solver.serve")  # server-side span, same process here

    def test_provision_mints_a_batch_trace_id(self, tracer, monkeypatch):
        """Every provisioning pass runs under a fresh trace id; all its
        stage spans carry it, so one batch filters to one timeline."""
        from karpenter_tpu.controllers import provisioning as prov_mod

        monkeypatch.setattr(prov_mod, "TRACER", tracer)
        h = Harness(solver=GreedySolver())
        h.apply_provisioner(Provisioner(name="default"))
        h.provision(*fixtures.pods(4))
        [schedule] = tracer.spans("provision.schedule")
        [bind] = tracer.spans("provision.bind")
        assert schedule.trace and schedule.trace == bind.trace

    def test_trace_id_rides_rpc_metadata_to_server_spans(
        self, tracer, monkeypatch
    ):
        """The stitching contract: a trace id current on the client rides
        the SolveStream/Solve gRPC metadata, and the sidecar's serve spans
        carry the SAME id — a merged export stitches host + RPC + solve
        lanes under one trace."""
        from karpenter_tpu.solver_service import client as client_mod
        from karpenter_tpu.solver_service import server as server_mod
        from karpenter_tpu.solver_service.client import RemoteSolver
        from karpenter_tpu.solver_service.server import SolverServer
        from karpenter_tpu.api.provisioner import Constraints

        monkeypatch.setattr(client_mod, "TRACER", tracer)
        monkeypatch.setattr(server_mod, "TRACER", tracer)
        trace_id = tracing.new_trace_id()
        server = SolverServer(port=0).start(warmup=False)
        try:
            remote = RemoteSolver(f"127.0.0.1:{server.port}")
            with tracer.trace(trace_id):
                remote.solve(
                    fixtures.pods(6), fixtures.size_ladder(3), Constraints()
                )
            remote.close()
        finally:
            server.stop()
        [rpc] = tracer.spans("solver.rpc")
        [serve] = tracer.spans("solver.serve")
        assert rpc.trace == trace_id
        # The serve span ran on a gRPC worker thread in "another process's"
        # role: its id arrived via the wire metadata, not thread state.
        assert serve.trace == trace_id

    def test_rpc_error_span_marks_outcome(self, tracer, monkeypatch):
        from karpenter_tpu.solver_service import client as client_mod
        from karpenter_tpu.solver_service.client import RemoteSolver
        from karpenter_tpu.api.provisioner import Constraints

        monkeypatch.setattr(client_mod, "TRACER", tracer)
        remote = RemoteSolver("127.0.0.1:1", timeout_s=0.3)
        remote.solve(fixtures.pods(3), fixtures.size_ladder(2), Constraints())
        remote.close()
        [rpc] = tracer.spans("solver.rpc")
        assert rpc.attributes["outcome"] == "error"
