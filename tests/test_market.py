"""Spot-market model + fleet-allocation simulator (cloudprovider/market.py).

The allocation strategies mirror the reference's CreateFleet request
(ref: pkg/cloudprovider/aws/instance.go:116-133): lowest-price for on-demand,
capacity-optimized-prioritized for spot. Both solvers' plans are priced by the
same simulator, so these tests pin the strategy semantics and the fairness of
the comparison.
"""

import numpy as np
import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider import InstanceType, Offering
from karpenter_tpu.cloudprovider.market import (
    PoolOffer,
    SpotMarket,
    allocate,
    capacity_type_for,
    generate_market,
    plan_offers,
    simulate_plan_cost,
)
from karpenter_tpu.models.solver import (
    MAX_POOL_ROWS,
    CostSolver,
    GreedySolver,
    _cheapest_feasible_options,
)
from karpenter_tpu.ops import ffd
from karpenter_tpu.ops.encode import build_fleet, group_pods

ZONES = ("zone-a", "zone-b", "zone-c")


def catalog_with_market(num_types=12, seed=3):
    names = [f"m{i // 4}.{2 ** (i % 4)}x" for i in range(num_types)]
    market = generate_market(names, ZONES, seed=seed)
    catalog = []
    for i, name in enumerate(names):
        size = 2 ** (i % 4)
        od = 0.1 * size * (1 + 0.1 * (i // 4))
        offerings = []
        for z in ZONES:
            offerings.append(Offering(zone=z, capacity_type="on-demand", price=od))
            offerings.append(
                Offering(
                    zone=z,
                    capacity_type="spot",
                    price=market.spot_price((name, z), od),
                )
            )
        catalog.append(
            InstanceType(
                name=name,
                capacity={"cpu": 2 * size, "memory": f"{8 * size}Gi", "pods": 110},
                offerings=offerings,
            )
        )
    return catalog, market


def pods_of(n, cpu="500m", mem="512Mi"):
    return [
        PodSpec(name=f"p-{i}", requests={"cpu": cpu, "memory": mem}, unschedulable=True)
        for i in range(n)
    ]


class TestGenerateMarket:
    def test_deterministic(self):
        a = generate_market(["m1.x", "c1.x"], ZONES, seed=7)
        b = generate_market(["m1.x", "c1.x"], ZONES, seed=7)
        assert a.discount == b.discount and a.depth == b.depth

    def test_discount_bounds(self):
        market = generate_market([f"t{i}.x" for i in range(50)], ZONES, seed=1)
        values = np.array(list(market.discount.values()))
        assert (values >= 0.25).all() and (values <= 0.95).all()
        # Structured, not degenerate: discounts actually vary.
        assert values.std() > 0.02

    def test_depth_price_anticorrelation(self):
        market = generate_market([f"t{i}.x" for i in range(200)], ZONES, seed=2)
        pools = list(market.discount)
        depth = np.array([market.depth[p] for p in pools])
        disc = np.array([market.discount[p] for p in pools])
        rho = np.corrcoef(depth, disc)[0, 1]
        assert rho < -0.2  # deep pools trend cheap


class TestAllocate:
    def offers(self):
        return [
            PoolOffer("a.x", "zone-a", price=1.0, priority=0),
            PoolOffer("b.x", "zone-b", price=0.5, priority=1),
            PoolOffer("c.x", "zone-c", price=0.8, priority=2),
        ]

    def test_on_demand_lowest_price(self):
        chosen = allocate(self.offers(), wellknown.CAPACITY_TYPE_ON_DEMAND)
        assert chosen.instance_type == "b.x"  # cheapest wins regardless of priority

    def test_spot_capacity_optimized_prefers_deep_pool(self):
        market = SpotMarket(
            depth={("a.x", "zone-a"): 10.0, ("b.x", "zone-b"): 1.0, ("c.x", "zone-c"): 1.0}
        )
        chosen = allocate(self.offers(), wellknown.CAPACITY_TYPE_SPOT, market)
        # b.x is cheapest but shallow: capacity wins over price.
        assert chosen.instance_type == "a.x"

    def test_spot_priority_breaks_depth_ties(self):
        market = SpotMarket(
            depth={("a.x", "zone-a"): 5.0, ("b.x", "zone-b"): 4.9, ("c.x", "zone-c"): 1.0}
        )
        chosen = allocate(self.offers(), wellknown.CAPACITY_TYPE_SPOT, market)
        # a and b are capacity-equivalent (within slack); lowest priority wins.
        assert chosen.instance_type == "a.x"

    def test_excluded_pools_skipped(self):
        chosen = allocate(
            self.offers(),
            wellknown.CAPACITY_TYPE_ON_DEMAND,
            excluded=[("b.x", "zone-b")],
        )
        assert chosen.instance_type == "c.x"

    def test_no_usable_pool(self):
        assert (
            allocate(
                self.offers()[:1],
                wellknown.CAPACITY_TYPE_ON_DEMAND,
                excluded=[("a.x", "zone-a")],
            )
            is None
        )


class TestCapacityType:
    def test_spot_when_allowed_and_offered(self):
        catalog, _ = catalog_with_market()
        assert (
            capacity_type_for(Constraints(), catalog) == wellknown.CAPACITY_TYPE_SPOT
        )

    def test_on_demand_when_requirements_forbid_spot(self):
        from karpenter_tpu.api.requirements import Requirement, Requirements

        catalog, _ = catalog_with_market()
        constraints = Constraints(
            requirements=Requirements(
                [
                    Requirement.in_(
                        wellknown.CAPACITY_TYPE_LABEL,
                        [wellknown.CAPACITY_TYPE_ON_DEMAND],
                    )
                ]
            )
        )
        assert (
            capacity_type_for(constraints, catalog)
            == wellknown.CAPACITY_TYPE_ON_DEMAND
        )


class TestPoolOptions:
    def test_cheapest_feasible_pools_hold_demand_and_are_price_sorted(self):
        catalog, _ = catalog_with_market()
        pods = pods_of(40)
        groups = group_pods(pods)
        fleet = build_fleet(catalog, Constraints(), pods)
        fill = np.zeros(groups.num_groups, dtype=np.int64)
        fill[0] = 4
        type_indices, pools = _cheapest_feasible_options(fill, 0, groups, fleet)
        assert pools and len(pools) <= MAX_POOL_ROWS
        prices = [p.price for p in pools]
        assert prices == sorted(prices)
        assert len({p.instance_type.name for p in pools}) <= ffd.MAX_INSTANCE_TYPES
        demand = (fill[:, None] * groups.vectors).sum(axis=0)
        for p in pools:
            idx = fleet.instance_types.index(p.instance_type)
            assert (fleet.capacity[idx] >= demand - 1e-6).all()

    def test_plan_offers_uses_pinned_pools(self):
        catalog, market = catalog_with_market()
        packing = ffd.Packing(
            pods_per_node=[[]],
            instance_type_options=[catalog[0]],
            pool_options=[
                ffd.PoolOption(catalog[0], "zone-b", price=0.04, priority=0),
                ffd.PoolOption(catalog[1], "zone-a", price=0.05, priority=1),
            ],
        )
        offers = plan_offers(
            packing, ZONES, wellknown.CAPACITY_TYPE_SPOT, market
        )
        assert [(o.instance_type, o.zone) for o in offers] == [
            (catalog[0].name, "zone-b"),
            (catalog[1].name, "zone-a"),
        ]
        # Zone filter drops pinned rows outside the envelope.
        offers = plan_offers(
            packing, ["zone-a"], wellknown.CAPACITY_TYPE_SPOT, market
        )
        assert [(o.instance_type, o.zone) for o in offers] == [
            (catalog[1].name, "zone-a")
        ]


class TestSimulatedPlanCost:
    def test_identical_plans_price_identically(self):
        catalog, market = catalog_with_market()
        pods = pods_of(60)
        constraints = Constraints()
        result_a = GreedySolver().solve(pods, catalog, constraints)
        result_b = GreedySolver().solve(pods, catalog, constraints)
        assert simulate_plan_cost(
            result_a, constraints, market, ZONES
        ) == pytest.approx(simulate_plan_cost(result_b, constraints, market, ZONES))

    def test_cost_solver_realized_not_worse_than_greedy(self):
        catalog, market = catalog_with_market()
        pods = pods_of(300, cpu="750m", mem="1Gi")
        constraints = Constraints()
        greedy = GreedySolver().solve(pods, catalog, constraints)
        ours = CostSolver(lp_steps=50).solve(pods, catalog, constraints)
        greedy_cost = simulate_plan_cost(greedy, constraints, market, ZONES)
        ours_cost = simulate_plan_cost(ours, constraints, market, ZONES)
        assert ours_cost <= greedy_cost * 1.001
        # Both plans schedule everything.
        assert not greedy.unschedulable and not ours.unschedulable
        assert sum(len(n) for p in ours.packings for n in p.pods_per_node) == 300

    def test_unbuyable_plan_priced_at_advertised_offering(self):
        instance_type = InstanceType(
            name="od.only",
            capacity={"cpu": 4, "memory": "16Gi", "pods": 110},
            offerings=[Offering(zone="zone-z", capacity_type="on-demand", price=0.5)],
        )
        packing = ffd.Packing(
            pods_per_node=[[]], instance_type_options=[instance_type]
        )
        result = ffd.PackResult(packings=[packing])
        # Zone filter excludes the only offering's zone: falls back to the
        # advertised price instead of silently costing zero.
        cost = simulate_plan_cost(result, Constraints(), None, ["zone-a"])
        assert cost == pytest.approx(0.5)


class TestLaunchEnvelope:
    def test_not_in_zone_constraint_excluded_from_pool_rows(self):
        """NotIn zone requirements must filter the launch envelope: offered
        zones are finite, so the fleet's allowed_zones can always be computed
        even for complement (NotIn) requirement sets."""
        from karpenter_tpu.api.requirements import Requirement, Requirements

        catalog, _ = catalog_with_market()
        constraints = Constraints(
            requirements=Requirements(
                [Requirement("topology.kubernetes.io/zone", "NotIn", ["zone-a"])]
            )
        )
        pods = pods_of(20)
        groups = group_pods(pods)
        fleet = build_fleet(catalog, constraints, pods)
        assert fleet.allowed_zones == ["zone-b", "zone-c"]
        fill = np.zeros(groups.num_groups, dtype=np.int64)
        fill[0] = 4
        _, pools = _cheapest_feasible_options(fill, 0, groups, fleet)
        assert pools and all(p.zone != "zone-a" for p in pools)

    def test_cost_solver_plan_launches_through_pinned_pools(self):
        """End-to-end: the CostSolver's pool rows reach the cloud provider's
        launch call and the fake honors the cheapest pinned pool."""
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider

        catalog, _ = catalog_with_market()
        pods = pods_of(50)
        constraints = Constraints()
        result = CostSolver(lp_steps=50).solve(pods, catalog, constraints)
        packing = result.packings[0]
        assert packing.pool_options, "cost plan should pin pool rows"
        provider = FakeCloudProvider(instance_types=catalog)
        nodes = []
        provider.create(
            constraints,
            packing.instance_type_options,
            packing.node_quantity,
            nodes.append,
            pool_options=packing.pool_options,
        )
        assert len(nodes) == packing.node_quantity
        cheapest = packing.pool_options[0]
        assert nodes[0].instance_type == cheapest.instance_type.name
        assert nodes[0].zone == cheapest.zone
