"""Unhealthy-node battletest: a node that joined and then went dark (or
reports NotReady past the flap hysteresis) must ride the escalation ladder —
re-taint, cordon, PDB-gated displacement, replacement fed ahead of the
drain, finalizer delete — with the stuck-drain breaker and zombie defense
closing the corners, and the same properties must survive a controller
killed at any health crashpoint.

The fake-kubelet fleet (tests/fake_kubelet.py) drives the kubelet side so
the heartbeat plumbing itself is under test, not hand-flipped node fields.
`make lifecycle-smoke` wraps the same subsystem in a 500-node storm; this
module is the deterministic matrix. test_backend_parity re-runs the classes
against the fake apiserver.
"""

from __future__ import annotations

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.provisioner import Provisioner, ProvisionerSpec
from karpenter_tpu.controllers.health import (
    NODE_HEARTBEAT_STALE_SECONDS,
    NODE_UNHEALTHY_TOTAL,
    NODE_ZOMBIE_REJECTIONS_TOTAL,
    HealthController,
)
from karpenter_tpu.controllers.instancegc import (
    LAUNCH_GRACE_SECONDS,
    InstanceGcController,
)
from karpenter_tpu.controllers.node import NodeController
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.selection import SelectionController
from karpenter_tpu.controllers.termination import (
    DRAIN_STALLED_TOTAL,
    TerminationController,
)
from karpenter_tpu.cloudprovider import CloudInstance, NodeSpec
from karpenter_tpu.utils import crashpoints, faultpoints
from karpenter_tpu.utils.crashpoints import SimulatedCrash

from tests import fixtures
from tests.fake_kubelet import FakeKubeletFleet
from tests.harness import Harness


class BindRecorder:
    """Watch-driven record of every node a pod was ever bound to (consecutive
    duplicates collapsed) — the 'rebinds exactly once' oracle."""

    def __init__(self, cluster):
        self.bound = {}
        cluster.watch(self._on)

    def _on(self, kind, obj) -> None:
        if kind != "pod" or getattr(obj, "node_name", None) is None:
            return
        seq = self.bound.setdefault(obj.uid, [])
        if not seq or seq[-1] != obj.node_name:
            seq.append(obj.node_name)


def joined_harness(n_pods=3, pods=None):
    """Harness + provisioner + n pods packed onto one node whose kubelet has
    heartbeated (joined, Ready, not-ready taint stripped); returns
    (harness, recorder, pods, node)."""
    h = Harness()
    recorder = BindRecorder(h.cluster)
    h.apply_provisioner(Provisioner(name="default", spec=ProvisionerSpec()))
    pods = pods if pods is not None else fixtures.pods(n_pods)
    h.provision(*pods)
    node = h.expect_scheduled(pods[0])
    for pod in pods[1:]:
        assert h.expect_scheduled(pod).name == node.name
    h.cluster.heartbeat_node(node.name)
    h.node.reconcile(node.name)  # Ready: strips the not-ready taint
    node = h.cluster.get_node(node.name)
    assert not any(t.key == wellknown.NOT_READY_TAINT_KEY for t in node.taints)
    return h, recorder, pods, node


def sweep_until_confirmed(h: Harness, extra: int = 0) -> None:
    """Advance past the unreachable timeout, then run exactly enough sweeps
    for the hysteresis to pass (+ extra)."""
    h.clock.advance(h.health.unreachable_timeout + 1)
    for _ in range(h.health.stale_observations + extra):
        h.health.reconcile()
        h.clock.advance(2.0)


def converge(h: Harness, rounds: int = 6) -> None:
    """Drive health sweeps + provisioning + terminations to a fixpoint."""
    for _ in range(rounds):
        h.health.reconcile()
        for worker in list(h.provisioning.workers.values()):
            worker.provision()
        h.reconcile_terminations(rounds=3)


def restart(h: Harness) -> None:
    """A controller-process restart over the surviving cluster + cloud state,
    including the health controller, plus the boot re-list routing
    still-pending pods back through selection."""
    h.provisioning = ProvisioningController(h.cluster, h.cloud, None)
    h.selection = SelectionController(h.cluster, h.provisioning)
    h.termination = TerminationController(h.cluster, h.cloud)
    h.instancegc = InstanceGcController(h.cluster, h.cloud)
    h.node = NodeController(h.cluster)
    h.health = HealthController(
        h.cluster, h.cloud, h.provisioning, h.termination
    )
    for provisioner in h.cluster.list_provisioners():
        h.provisioning.reconcile(provisioner.name)
    for pod in h.cluster.list_pods():
        if pod.is_provisionable():
            h.selection.reconcile(pod.namespace, pod.name)


def assert_rebound_exactly_once(h, recorder, pods, old_node) -> None:
    for pod in pods:
        live = h.cluster.get_pod(pod.namespace, pod.name)
        assert live.node_name is not None, f"{pod.name} never rebound"
        assert live.node_name != old_node.name
        assert h.cluster.try_get_node(live.node_name) is not None
        assert recorder.bound[pod.uid] == [old_node.name, live.node_name], (
            f"{pod.name} bind history {recorder.bound[pod.uid]}"
        )


def assert_no_leaks(h: Harness) -> None:
    h.clock.advance(LAUNCH_GRACE_SECONDS + 1)
    h.instancegc.reconcile()
    h.instancegc.reconcile()
    node_ids = {n.provider_id for n in h.cluster.list_nodes()}
    leaked = set(h.cloud.instances) - node_ids
    assert not leaked, f"instances with no Node after GC grace: {sorted(leaked)}"


class TestHealthDetection:
    def test_gone_dark_node_cordoned_drained_replaced_deleted(self):
        """The acceptance scenario and the liveness-gap regression: a node
        that heartbeated ONCE and went dark — which the Liveness guard
        deliberately ignores — is confirmed stale, cordoned, drained,
        replaced, and deleted within the unreachable timeout + drain
        budget, with every pod rebound exactly once and zero leaks."""
        h, recorder, pods, node = joined_harness()
        start = h.clock.now()
        before = NODE_UNHEALTHY_TOTAL.get("stale-heartbeat")

        sweep_until_confirmed(h)
        live = h.cluster.try_get_node(node.name)
        assert live is None or live.deletion_timestamp is not None, (
            "gone-dark node not handed to the finalizer path"
        )
        if live is not None:
            assert live.unschedulable, "victim was not cordoned"
            assert any(
                t.key == wellknown.NOT_READY_TAINT_KEY for t in live.taints
            ), "victim was not re-tainted"
        assert NODE_UNHEALTHY_TOTAL.get("stale-heartbeat") - before == 1

        converge(h)
        assert h.cluster.try_get_node(node.name) is None
        assert node.name in h.cloud.deleted_nodes
        assert_rebound_exactly_once(h, recorder, pods, node)
        elapsed = h.clock.now() - start
        assert elapsed <= (
            h.health.unreachable_timeout + h.health.drain_stuck_timeout
        ), f"convergence took {elapsed}s"
        assert_no_leaks(h)

    def test_flap_is_absorbed_by_hysteresis(self):
        """One NotReady beat (or one missed sweep) must not reach the
        ladder: a fresh healthy heartbeat resets the strike count."""
        h, recorder, pods, node = joined_harness(n_pods=1)
        before = NODE_UNHEALTHY_TOTAL.get("not-ready")
        for _ in range(h.health.stale_observations - 1):
            h.cluster.heartbeat_node(node.name, ready=False)
            h.health.reconcile()
        h.cluster.heartbeat_node(node.name, ready=True)  # recovers
        for _ in range(h.health.stale_observations):
            h.health.reconcile()
        live = h.cluster.get_node(node.name)
        assert live.deletion_timestamp is None
        assert not live.unschedulable
        assert NODE_UNHEALTHY_TOTAL.get("not-ready") == before
        assert h.health._strikes.get(node.name, 0) == 0

    def test_persistent_not_ready_escalates(self):
        """A kubelet that keeps heartbeating but reports NotReady is just as
        dead to the scheduler — same ladder, reason='not-ready'."""
        h, recorder, pods, node = joined_harness()
        before = NODE_UNHEALTHY_TOTAL.get("not-ready")
        for _ in range(h.health.stale_observations):
            h.cluster.heartbeat_node(node.name, ready=False)
            h.health.reconcile()
        assert NODE_UNHEALTHY_TOTAL.get("not-ready") - before == 1
        converge(h)
        assert h.cluster.try_get_node(node.name) is None
        assert_rebound_exactly_once(h, recorder, pods, node)
        assert_no_leaks(h)

    def test_never_joined_node_is_livenesss_case(self):
        """status_reported_at=None is the Liveness guard's jurisdiction —
        health must not double-handle it (two controllers deleting the same
        node would race their replacement launches)."""
        h, recorder, pods, node = joined_harness()
        fresh = h.provision(fixtures.pod(name="late"))
        never_joined = h.expect_scheduled(fresh[0])
        assert never_joined.status_reported_at is None
        sweep_until_confirmed(h, extra=2)
        live = h.cluster.try_get_node(never_joined.name)
        assert live is not None and live.deletion_timestamp is None

    def test_interruption_owned_node_is_skipped(self):
        """A node the interruption drain already owns must not be
        double-driven — one ladder at a time."""
        h, recorder, pods, node = joined_harness(n_pods=1)
        node.annotations[wellknown.INTERRUPTION_KIND_ANNOTATION] = "spot"
        h.cluster.update_node(node)
        before = NODE_UNHEALTHY_TOTAL.get("stale-heartbeat")
        sweep_until_confirmed(h, extra=2)
        assert NODE_UNHEALTHY_TOTAL.get("stale-heartbeat") == before

    def test_staleness_gauge_tracks_worst_node(self):
        h, recorder, pods, node = joined_harness(n_pods=1)
        h.clock.advance(30.0)
        h.health.reconcile()
        assert NODE_HEARTBEAT_STALE_SECONDS.get() == pytest.approx(30.0)
        h.cluster.heartbeat_node(node.name)
        h.health.reconcile()
        assert NODE_HEARTBEAT_STALE_SECONDS.get() == pytest.approx(0.0)


class TestStuckDrain:
    def test_do_not_evict_waits_then_breaker_fires(self):
        """Polite first: a do-not-evict pod pins the drain. Past the
        drain-stuck budget the breaker escalates LOUDLY — leaving pods on an
        unreachable node is strictly worse than any protection."""
        protected = fixtures.pod(
            annotations={wellknown.DO_NOT_EVICT_ANNOTATION: "true"}
        )
        h, recorder, pods, node = joined_harness(pods=[protected, fixtures.pod()])
        stalled_before = DRAIN_STALLED_TOTAL.get("unreachable")

        sweep_until_confirmed(h)
        live = h.cluster.get_node(node.name)
        assert live.deletion_timestamp is None, "polite phase overrode do-not-evict"
        assert live.unschedulable
        assert (
            h.cluster.get_pod(protected.namespace, protected.name).node_name
            == node.name
        )
        assert DRAIN_STALLED_TOTAL.get("unreachable") == stalled_before

        h.clock.advance(h.health.drain_stuck_timeout + 1)
        h.health.reconcile()
        assert DRAIN_STALLED_TOTAL.get("unreachable") - stalled_before == 1
        h.health.reconcile()  # the breaker counts once per episode
        assert DRAIN_STALLED_TOTAL.get("unreachable") - stalled_before == 1

        converge(h)
        assert h.cluster.try_get_node(node.name) is None
        assert_rebound_exactly_once(h, recorder, pods, node)
        assert_no_leaks(h)

    def test_pdb_refusal_waits_then_breaker_overrides(self):
        guarded = [fixtures.pod(labels={"app": "db"}) for _ in range(2)]
        h, recorder, pods, node = joined_harness(pods=guarded)
        h.cluster.apply_pdb("db-pdb", {"app": "db"}, min_available=2)
        stalled_before = DRAIN_STALLED_TOTAL.get("unreachable")

        sweep_until_confirmed(h)
        assert h.cluster.get_node(node.name).deletion_timestamp is None
        for pod in pods:
            assert h.cluster.get_pod(pod.namespace, pod.name).node_name == node.name

        h.clock.advance(h.health.drain_stuck_timeout + 1)
        h.health.reconcile()
        assert DRAIN_STALLED_TOTAL.get("unreachable") - stalled_before == 1
        converge(h)
        assert h.cluster.try_get_node(node.name) is None
        assert_rebound_exactly_once(h, recorder, pods, node)
        assert_no_leaks(h)


class TestZombieDefense:
    def _drain_to_deletion(self):
        h, recorder, pods, node = joined_harness(n_pods=1)
        sweep_until_confirmed(h)
        converge(h)
        assert h.cluster.try_get_node(node.name) is None
        return h, node

    def test_buried_provider_id_rejected_on_reregistration(self):
        """The dead kubelet phoning home: same name, same (dead) provider id
        — rejected, never adopted, counted."""
        h, node = self._drain_to_deletion()
        before = NODE_ZOMBIE_REJECTIONS_TOTAL.get()
        zombie = NodeSpec(
            name=node.name,
            provider_id=node.provider_id,
            labels=dict(node.labels),
            ready=True,
        )
        h.cluster.create_node(zombie)
        h.health.reconcile()
        assert NODE_ZOMBIE_REJECTIONS_TOTAL.get() - before == 1
        live = h.cluster.try_get_node(node.name)
        assert live is None or live.deletion_timestamp is not None

    def test_replacement_with_fresh_provider_id_is_adopted(self):
        """The negative control: a same-name node riding a FRESH launch is a
        legitimate replacement, not a zombie."""
        h, node = self._drain_to_deletion()
        before = NODE_ZOMBIE_REJECTIONS_TOTAL.get()
        fresh = "fake:///z/fi-fresh-launch"
        h.cloud.instances[fresh] = CloudInstance(
            instance_id="fi-fresh-launch", provider_id=fresh
        )
        h.cluster.create_node(
            NodeSpec(
                name=node.name,
                provider_id=fresh,
                labels=dict(node.labels),
                ready=True,
            )
        )
        h.cluster.heartbeat_node(node.name)
        h.health.reconcile()
        h.health.reconcile()
        assert NODE_ZOMBIE_REJECTIONS_TOTAL.get() == before
        assert h.cluster.get_node(node.name).deletion_timestamp is None

    def test_instance_less_ghost_reaped_after_two_sightings(self):
        """The restart-durable layer: a node no provider listing accounts
        for is reaped on the SECOND consecutive sighting (the instancegc
        hysteresis — one sweep of listing lag proves nothing)."""
        h, recorder, pods, node = joined_harness(n_pods=1)  # a real instance
        before = NODE_ZOMBIE_REJECTIONS_TOTAL.get()
        ghost = NodeSpec(
            name="ghost",
            provider_id="fake:///z/fi-ghost",
            labels={wellknown.PROVISIONER_NAME_LABEL: "default"},
            ready=True,
        )
        h.cluster.create_node(ghost)
        h.health.reconcile()
        assert NODE_ZOMBIE_REJECTIONS_TOTAL.get() == before  # first sighting
        assert h.cluster.try_get_node("ghost") is not None
        h.health.reconcile()
        assert NODE_ZOMBIE_REJECTIONS_TOTAL.get() - before == 1
        assert h.cluster.try_get_node("ghost") is None
        # The real node was never collateral damage.
        assert h.cluster.get_node(node.name).deletion_timestamp is None

    def test_empty_provider_listing_disables_ghost_check(self):
        """A backend that enumerates nothing must not get the whole fleet
        reaped as ghosts."""
        h = Harness()
        h.cluster.create_node(
            NodeSpec(
                name="unlisted",
                provider_id="fake:///z/fi-unlisted",
                labels={wellknown.PROVISIONER_NAME_LABEL: "default"},
                ready=True,
            )
        )
        assert h.cloud.list_instances() == []
        before = NODE_ZOMBIE_REJECTIONS_TOTAL.get()
        h.health.reconcile()
        h.health.reconcile()
        h.health.reconcile()
        assert NODE_ZOMBIE_REJECTIONS_TOTAL.get() == before
        assert h.cluster.get_node("unlisted").deletion_timestamp is None


# Every health site at its first passage, plus mid-displace at its second
# (first pod displaced and fed, controller dies before the rest).
HEALTH_MATRIX = [(site, 1) for site in crashpoints.HEALTH_SITES] + [
    ("health.mid-displace", 2)
]


class TestHealthCrashMatrix:
    """The controller killed at every health commit point, restarted over
    the surviving state, and the escalation still converges — pods rebound
    exactly once, victim gone, zero leaked instances."""

    @pytest.mark.parametrize(
        "site,at", HEALTH_MATRIX, ids=[f"{s}@{a}" for s, a in HEALTH_MATRIX]
    )
    def test_kill_restart_converges(self, site, at):
        h, recorder, pods, node = joined_harness()
        h.clock.advance(h.health.unreachable_timeout + 1)
        crashpoints.arm(site, at=at)
        with pytest.raises(SimulatedCrash) as crash:
            for _ in range(h.health.stale_observations + 1):
                h.health.reconcile()
        assert crash.value.site == site
        restart(h)
        converge(h)
        assert h.cluster.try_get_node(node.name) is None
        assert_rebound_exactly_once(h, recorder, pods, node)
        assert_no_leaks(h)


class TestKubeletFleet:
    """The fake-kubelet fleet against the real controllers: heartbeats flow
    through Cluster.heartbeat_node (a status-only write on the apiserver
    backend), behaviors come from the seeded kubelet faultpoints."""

    def test_fleet_joins_nodes_and_strips_taint(self):
        h = Harness()
        h.apply_provisioner(Provisioner(name="default", spec=ProvisionerSpec()))
        h.provision(*fixtures.pods(3))
        fleet = FakeKubeletFleet(h.cluster)
        fleet.step()
        for node in h.cluster.list_nodes():
            assert node.ready and node.status_reported_at is not None
            h.node.reconcile(node.name)
        for node in h.cluster.list_nodes():
            assert not any(
                t.key == wellknown.NOT_READY_TAINT_KEY for t in node.taints
            )

    def test_fleet_acknowledges_pods_running(self):
        h = Harness()
        h.apply_provisioner(Provisioner(name="default", spec=ProvisionerSpec()))
        pods = h.provision(*fixtures.pods(2))
        fleet = FakeKubeletFleet(h.cluster)
        fleet.step()
        running = set()
        for kubelet in fleet.kubelets.values():
            running |= kubelet.running
        assert {(p.namespace, p.name) for p in pods} == running

    def test_never_join_fault_leaves_node_for_liveness(self):
        faultpoints.seed(7)
        faultpoints.arm("kubelet.register", "drop", rate=1.0)
        h = Harness()
        h.apply_provisioner(Provisioner(name="default", spec=ProvisionerSpec()))
        h.provision(fixtures.pod())
        fleet = FakeKubeletFleet(h.cluster)
        for _ in range(5):
            fleet.step()
            h.clock.advance(2.0)
        node = h.cluster.list_nodes()[0]
        assert node.status_reported_at is None  # Liveness will reap it

    def test_heartbeat_drop_goes_dark_and_health_reaps(self):
        """End-to-end tentpole: kubelet joins, loses heartbeats mid-life
        (faultpoint), health confirms staleness and runs the ladder; the
        fleet's eviction handling completes the drain."""
        faultpoints.seed(11)
        h, recorder, pods, node = joined_harness()
        fleet = FakeKubeletFleet(h.cluster)
        fleet.step()  # adopt + heartbeat
        faultpoints.arm("kubelet.heartbeat", "drop", rate=1.0)
        fleet.step()  # the drop latches: kubelet goes dark
        assert fleet.kubelet(node.name).dark
        faultpoints.disarm_all()
        h.clock.advance(h.health.unreachable_timeout + 1)
        for _ in range(h.health.stale_observations):
            h.health.reconcile()
            fleet.step()  # dark kubelet stays silent; others keep beating
        for _ in range(6):
            h.health.reconcile()
            for worker in list(h.provisioning.workers.values()):
                worker.provision()
            fleet.step()  # kubelets complete evictions
            h.reconcile_terminations(rounds=3)
        assert h.cluster.try_get_node(node.name) is None
        assert_rebound_exactly_once(h, recorder, pods, node)
        assert_no_leaks(h)

    def test_eviction_black_hole_sticks_until_breaker(self):
        """A black-holed eviction leaves the pod terminating forever — the
        kubelet-side stall the drain breaker exists for."""
        faultpoints.seed(13)
        h, recorder, pods, node = joined_harness(n_pods=1)
        fleet = FakeKubeletFleet(h.cluster)
        fleet.step()
        faultpoints.arm("kubelet.eviction", "black-hole", rate=1.0)
        h.cluster.evict_pod(pods[0].namespace, pods[0].name)
        fleet.step()
        assert (pods[0].namespace, pods[0].name) in fleet.kubelet(
            node.name
        ).black_holed
        fleet.step()
        assert (
            h.cluster.get_pod(pods[0].namespace, pods[0].name).deletion_timestamp
            is not None
        ), "black-holed pod was completed anyway"

    def test_zombie_kubelet_rejoins_and_is_rejected(self):
        """The full zombie loop: register-zombie fault armed, node deleted
        by health, kubelet re-registers the dead incarnation, health rejects
        it instead of adopting."""
        faultpoints.seed(17)
        faultpoints.arm("kubelet.register", "zombie", rate=1.0)
        h, recorder, pods, node = joined_harness(n_pods=1)
        fleet = FakeKubeletFleet(h.cluster)
        fleet.step()
        assert fleet.kubelet(node.name).zombie
        faultpoints.disarm_all()
        sweep_until_confirmed(h)
        converge(h)
        assert h.cluster.try_get_node(node.name) is None
        before = NODE_ZOMBIE_REJECTIONS_TOTAL.get()
        fleet.step()  # the zombie re-registers under the old name
        assert fleet.kubelet(node.name).rejoined
        assert h.cluster.try_get_node(node.name) is not None
        h.health.reconcile()
        assert NODE_ZOMBIE_REJECTIONS_TOTAL.get() - before == 1
        live = h.cluster.try_get_node(node.name)
        assert live is None or live.deletion_timestamp is not None
        assert_rebound_exactly_once(h, recorder, pods, node)


class TestReadinessRetaint:
    """Satellite regression: readiness must be two-way — a Ready→NotReady
    transition re-adds the not-ready taint, and in-flight schedule receivers
    re-check the live taints before accepting a pod."""

    def test_not_ready_transition_readds_taint(self):
        h, recorder, pods, node = joined_harness(n_pods=1)
        h.cluster.heartbeat_node(node.name, ready=False)
        h.node.reconcile(node.name)
        live = h.cluster.get_node(node.name)
        assert any(
            t.key == wellknown.NOT_READY_TAINT_KEY for t in live.taints
        ), "Ready→NotReady did not restore the taint"
        # And back: recovery strips it again.
        h.cluster.heartbeat_node(node.name, ready=True)
        h.node.reconcile(node.name)
        live = h.cluster.get_node(node.name)
        assert not any(t.key == wellknown.NOT_READY_TAINT_KEY for t in live.taints)

    def test_in_flight_receiver_rechecks_taints(self):
        """A consolidation rebind planned against a then-Ready receiver must
        refuse once the receiver went NotReady — the re-read of the live
        node, not the stale plan, decides."""
        h, recorder, pods, node = joined_harness(n_pods=1)
        orphan = fixtures.pod(cpu="0.01", memory="16Mi", name="displaced")
        h.cluster.apply_pod(orphan)
        assert h.consolidation._rebind(orphan, node.name), (
            "sanity: a Ready receiver accepts"
        )
        h.cluster.reschedule_pod(orphan.namespace, orphan.name)
        h.cluster.heartbeat_node(node.name, ready=False)
        h.node.reconcile(node.name)  # re-taints
        live_pod = h.cluster.get_pod(orphan.namespace, orphan.name)
        assert not h.consolidation._rebind(live_pod, node.name), (
            "NotReady receiver accepted an in-flight pod"
        )


class TestNodeControllerStaleness:
    """Satellite regression: NodeController re-reads the node between
    sub-reconcilers, so a write (or delete) by an earlier sub-reconciler —
    or a rival controller — is visible to the next one."""

    def test_later_subreconcilers_see_earlier_writes(self):
        h, recorder, pods, node = joined_harness(n_pods=1)
        seen = []

        class Mutator:
            def reconcile(self, cluster, provisioner, live):
                live.annotations["probe"] = "written"
                cluster.update_node(live)
                return None

        class Witness:
            def reconcile(self, cluster, provisioner, live):
                seen.append(live.annotations.get("probe"))
                return None

        h.node.reconcilers = [Mutator(), Witness()]
        h.node.reconcile(node.name)
        assert seen == ["written"], (
            "second sub-reconciler saw a stale object (annotation missing)"
        )

    def test_mid_loop_deletion_stops_the_chain(self):
        h, recorder, pods, node = joined_harness(n_pods=1)
        ran = []

        class Deleter:
            def reconcile(self, cluster, provisioner, live):
                cluster.delete_node(live.name)
                return None

        class MustNotRun:
            def reconcile(self, cluster, provisioner, live):
                ran.append(live.name)
                return None

        h.node.reconcilers = [Deleter(), MustNotRun()]
        assert h.node.reconcile(node.name) is None
        assert ran == [], "sub-reconciler ran against a deleting node"
