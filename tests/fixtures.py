"""Shared test fixtures: instance-type catalogs and pod builders, modeled on
the reference's fake cloud provider fixtures (ref: pkg/cloudprovider/fake/
cloudprovider.go:36-116 and instancetype.go:69-80)."""

from typing import List, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.cloudprovider import InstanceType, Offering

ZONES = ("test-zone-1", "test-zone-2", "test-zone-3")


def offerings(price: float, zones=ZONES, spot_discount: float = 0.7) -> List[Offering]:
    out = []
    for zone in zones:
        out.append(Offering(zone=zone, capacity_type="on-demand", price=price))
        out.append(Offering(zone=zone, capacity_type="spot", price=price * spot_discount))
    return out


def cpu_instance(name: str, cpu: float, mem_gib: float, pods: int = 110,
                 price: Optional[float] = None, zones=ZONES, arch="amd64") -> InstanceType:
    return InstanceType(
        name=name,
        capacity={"cpu": cpu, "memory": f"{mem_gib}Gi", "pods": pods},
        architecture=arch,
        offerings=offerings(price if price is not None else cpu * 0.05, zones=zones),
    )


def gpu_instance(name: str, cpu: float, mem_gib: float, gpus: int,
                 price: Optional[float] = None) -> InstanceType:
    return InstanceType(
        name=name,
        capacity={
            "cpu": cpu,
            "memory": f"{mem_gib}Gi",
            "pods": 110,
            wellknown.RESOURCE_NVIDIA_GPU: gpus,
        },
        offerings=offerings(price if price is not None else cpu * 0.15),
    )


def size_ladder(n: int) -> List[InstanceType]:
    """n instance types with linearly growing capacity and price
    (ref: fake.InstanceTypes(n) generates a linear ladder)."""
    return [
        cpu_instance(f"ladder-{i + 1}", cpu=2 * (i + 1), mem_gib=4 * (i + 1),
                     price=0.05 * (i + 1))
        for i in range(n)
    ]


def default_catalog() -> List[InstanceType]:
    return [
        cpu_instance("default-instance-type", cpu=16, mem_gib=64, price=0.8),
        cpu_instance("small-instance-type", cpu=2, mem_gib=4, price=0.1),
        gpu_instance("gpu-instance-type", cpu=16, mem_gib=64, gpus=2, price=2.4),
        cpu_instance("arm-instance-type", cpu=16, mem_gib=64, price=0.7, arch="arm64"),
    ]


_counter = [0]


def pod(cpu="1", memory="512Mi", name=None, extra_requests=None, **kwargs) -> PodSpec:
    """extra_requests merges additional resources (e.g. accelerators) into
    the request set at construction — requests are immutable afterwards."""
    _counter[0] += 1
    requests = {"cpu": cpu, "memory": memory}
    if extra_requests:
        requests.update(extra_requests)
    return PodSpec(
        name=name or f"pod-{_counter[0]}",
        requests=requests,
        unschedulable=True,
        **kwargs,
    )


def pods(n: int, cpu="1", memory="512Mi", **kwargs) -> List[PodSpec]:
    return [pod(cpu=cpu, memory=memory, **kwargs) for _ in range(n)]
