"""Utility-layer tests (ref: pkg/utils — functional/suite_test.go is the
reference's analogue of plain unit coverage for the helper packages)."""

import pytest

from karpenter_tpu.utils.cache import TtlCache
from karpenter_tpu.utils.clock import FakeClock


class TestTtlCache:
    def test_expiry(self):
        clock = FakeClock()
        cache = TtlCache(ttl=10.0, clock=clock)
        cache.set("a", 1)
        assert cache.get("a") == 1
        clock.advance(11.0)
        assert cache.get("a") is None

    def test_set_refreshes_ttl(self):
        clock = FakeClock()
        cache = TtlCache(ttl=10.0, clock=clock)
        cache.set("a", 1)
        clock.advance(8.0)
        cache.set("a", 2)
        clock.advance(8.0)
        assert cache.get("a") == 2

    def test_periodic_sweep_bounds_memory(self):
        """Expired entries for keys never looked up again must not accumulate
        (pod-UID keyspaces churn; go-cache solves this with a janitor)."""
        clock = FakeClock()
        cache = TtlCache(ttl=10.0, clock=clock)
        for i in range(TtlCache.SWEEP_INTERVAL):
            cache.set(f"old-{i}", i)
        clock.advance(11.0)
        # These sets trigger a sweep that purges every expired old-* entry.
        for i in range(TtlCache.SWEEP_INTERVAL):
            cache.set(f"new-{i}", i)
        assert len(cache._entries) <= TtlCache.SWEEP_INTERVAL + 1


class TestExpositionEscaping:
    """Prometheus text-format escaping regression (ISSUE 13 satellite): a
    label value carrying `\\`, `"`, or a newline must render per the spec
    — before the fix one hostile reason string (an exception repr) made the
    whole /metrics page unparseable."""

    def test_gauge_escapes_hostile_label_values(self):
        from karpenter_tpu.utils.metrics import Gauge

        gauge = Gauge("test_escape_gauge", "h", ["reason"])
        gauge.inc('Error("C:\\path")\nline2')
        [line] = [l for l in gauge.render() if not l.startswith("#")]
        assert line == (
            'test_escape_gauge{reason="Error(\\"C:\\\\path\\")\\nline2"} 1.0'
        )

    def test_histogram_escapes_hostile_label_values(self):
        from karpenter_tpu.utils.metrics import Histogram

        histogram = Histogram("test_escape_hist", "h", ["op"], buckets=(1.0,))
        histogram.observe(0.5, 'a"b\\c')
        rendered = "\n".join(histogram.render())
        assert 'op="a\\"b\\\\c"' in rendered
        assert 'a"b\\c"' not in rendered  # no raw quote survives

    def test_plain_values_unchanged(self):
        from karpenter_tpu.utils.metrics import escape_label_value

        assert escape_label_value("spot/us-east-1a") == "spot/us-east-1a"


class TestBackoffQueue:
    """The eviction-queue retry semantics (utils/workqueue.BackoffQueue),
    driven by the FakeClock: set-dedup holds across in-flight processing and
    requeues, and per-item backoff grows exponentially to the 10s cap."""

    def _queue(self):
        from karpenter_tpu.utils.clock import FakeClock
        from karpenter_tpu.utils.workqueue import BackoffQueue

        clock = FakeClock()
        return BackoffQueue(base_delay=0.1, max_delay=10.0, clock=clock), clock

    def test_add_while_in_flight_is_deduped(self):
        """An item being processed is still 'in the queue' for dedup: a
        watch event re-adding it mid-process must not create a second entry
        (it would be processed twice per drain forever)."""
        q, _ = self._queue()
        assert q.add("node-1")
        re_adds = []

        def fail_and_readd(item):
            re_adds.append(q.add(item))  # in-flight re-add
            return False

        q.process(fail_and_readd)
        assert re_adds == [False]
        assert len(q) == 1  # requeued once by the failure, not twice
        assert "node-1" in q

    def test_backoff_doubles_then_caps_at_max_delay(self):
        q, clock = self._queue()
        q.add("node-1")
        attempts = []

        def failing(item):
            attempts.append(clock.now())
            return False

        # Drive enough failures to saturate the cap: 0.1 * 2^(n-1) >= 10
        # from the 8th failure on.
        for _ in range(10):
            q.process(failing)
            clock.advance(10.0)  # always enough to come due again
        delays = [b - a for a, b in zip(attempts, attempts[1:])]
        assert delays[0] == pytest.approx(10.0)  # advance dominated 0.1
        # Saturated: a sweep 9.99s after the 10th failure is NOT due...
        q.process(failing)
        count = len(attempts)
        clock.advance(9.99)
        q.process(failing)
        assert len(attempts) == count  # skipped, still backing off
        # ...and 10.0s after it, it is (the cap, not 0.1 * 2^10 = 102s).
        clock.advance(0.02)
        q.process(failing)
        assert len(attempts) == count + 1

    def test_dedup_holds_across_requeues_and_clears_on_success(self):
        q, clock = self._queue()
        assert q.add("node-1")
        q.process(lambda item: False)  # fail -> requeued with backoff
        assert not q.add("node-1")  # still deduped while backing off
        assert len(q) == 1
        clock.advance(1.0)
        assert q.process(lambda item: True) == 1  # succeeds, leaves the set
        assert len(q) == 0
        assert q.add("node-1")  # a fresh add is accepted again

    def test_success_resets_backoff_history(self):
        q, clock = self._queue()
        q.add("node-1")
        for _ in range(5):  # build up failure history
            q.process(lambda item: False)
            clock.advance(10.0)
        q.process(lambda item: True)
        # Re-added after success: first failure backs off at BASE delay
        # again, not where the old streak left off.
        q.add("node-1")
        q.process(lambda item: False)
        calls = []
        clock.advance(0.11)
        q.process(lambda item: calls.append(item) or True)
        assert calls == ["node-1"]
