"""Utility-layer tests (ref: pkg/utils — functional/suite_test.go is the
reference's analogue of plain unit coverage for the helper packages)."""

from karpenter_tpu.utils.cache import TtlCache
from karpenter_tpu.utils.clock import FakeClock


class TestTtlCache:
    def test_expiry(self):
        clock = FakeClock()
        cache = TtlCache(ttl=10.0, clock=clock)
        cache.set("a", 1)
        assert cache.get("a") == 1
        clock.advance(11.0)
        assert cache.get("a") is None

    def test_set_refreshes_ttl(self):
        clock = FakeClock()
        cache = TtlCache(ttl=10.0, clock=clock)
        cache.set("a", 1)
        clock.advance(8.0)
        cache.set("a", 2)
        clock.advance(8.0)
        assert cache.get("a") == 2

    def test_periodic_sweep_bounds_memory(self):
        """Expired entries for keys never looked up again must not accumulate
        (pod-UID keyspaces churn; go-cache solves this with a janitor)."""
        clock = FakeClock()
        cache = TtlCache(ttl=10.0, clock=clock)
        for i in range(TtlCache.SWEEP_INTERVAL):
            cache.set(f"old-{i}", i)
        clock.advance(11.0)
        # These sets trigger a sweep that purges every expired old-* entry.
        for i in range(TtlCache.SWEEP_INTERVAL):
            cache.set(f"new-{i}", i)
        assert len(cache._entries) <= TtlCache.SWEEP_INTERVAL + 1
