"""Backend parity: the existing controller suites re-run against the
apiserver-backed Cluster (ApiServerCluster + FakeApiServer over the direct
transport). Controllers must not be able to tell the backends apart — this
is the round-2 'done' criterion for the apiserver backend (VERDICT r1 #1:
"the existing controller suites pass against both backends (run them
parameterized)").

Each reused class below inherits every test method from its memory-backed
original; the autouse fixture flips Harness.DEFAULT_BACKEND for the
duration and closes the watch pumps the apiserver harnesses start.
"""

import pytest

from tests import harness as harness_mod
from tests import test_chaos as chaos
from tests import test_consolidation as consolidation
from tests import test_crash_consistency as crash
from tests import test_drift as drift
from tests import test_health as health
from tests import test_interruption as interruption
from tests import test_market_feed as market_feed
from tests import test_node_lifecycle as lifecycle
from tests import test_provisioning as provisioning
from tests import test_scheduling as scheduling
from tests import test_selection as selection
from tests import test_termination as termination


@pytest.fixture(autouse=True)
def _apiserver_backend(monkeypatch):
    monkeypatch.setattr(harness_mod.Harness, "DEFAULT_BACKEND", "apiserver")
    yield
    harness_mod.close_live_harnesses()


class TestProvisioningOnApiserver(provisioning.TestProvisioning):
    pass


class TestProvisionerLifecycleOnApiserver(provisioning.TestProvisionerLifecycle):
    pass


class TestCapacityFeedbackOnApiserver(provisioning.TestCapacityFeedback):
    pass


class TestParallelBindOnApiserver(provisioning.TestParallelBind):
    pass


class TestSelectionOnApiserver(selection.TestSelection):
    pass


class TestPreferencesSideCacheOnApiserver(selection.TestPreferencesSideCache):
    pass


class TestTerminationOnApiserver(termination.TestTermination):
    pass


class TestReadinessOnApiserver(lifecycle.TestReadiness):
    pass


class TestLivenessOnApiserver(lifecycle.TestLiveness):
    pass


class TestEmptinessOnApiserver(lifecycle.TestEmptiness):
    pass


class TestExpirationOnApiserver(lifecycle.TestExpiration):
    pass


class TestFinalizerOnApiserver(lifecycle.TestFinalizer):
    pass


class TestCounterOnApiserver(lifecycle.TestCounter):
    pass


class TestMetricsOnApiserver(lifecycle.TestMetrics):
    pass


class TestZonalTopologyOnApiserver(scheduling.TestZonalTopology):
    pass


class TestHostnameTopologyOnApiserver(scheduling.TestHostnameTopology):
    pass


class TestPreferentialFallbackOnApiserver(scheduling.TestPreferentialFallback):
    pass


class TestWellKnownLabelsOnApiserver(scheduling.TestWellKnownLabels):
    pass


class TestCrashpointMatrixOnApiserver(crash.TestCrashpointMatrix):
    """The crash battletest's 'fake apiserver' half: every kill→restart
    convergence property must hold when the surviving state lives behind
    the apiserver write-through (409-on-duplicate-create is the adoption
    path's real-world shape)."""


class TestInstanceGcOnApiserver(crash.TestInstanceGc):
    pass


class TestDeletionDrainPathOnApiserver(lifecycle.TestDeletionDrainPath):
    """Satellite regression: Liveness/Expiration deletions traverse
    cordon→drain→finalizer on the write-through backend too (the apiserver's
    finalizer protocol is the real-world shape of the held deletion)."""


class TestInterruptionOnApiserver(interruption.TestInterruption):
    """The interruption battletest against the fake apiserver: displacement
    is a real merge-patch (nodeName removed, Unschedulable condition and
    reschedule epoch written through), annotation intent survives as patched
    Node metadata, and the rebind is a fresh Binding POST."""


class TestInterruptionCrashMatrixOnApiserver(interruption.TestInterruptionCrashMatrix):
    pass


class TestConsolidationOnApiserver(consolidation.TestConsolidation):
    """The consolidation battletest against the fake apiserver: the action
    annotation is durable Node metadata, displacement is a real merge-patch,
    and delete-plan rebinds are fresh Binding POSTs."""


class TestConsolidationCrashMatrixOnApiserver(
    consolidation.TestConsolidationCrashMatrix
):
    pass


class TestConsolidationChurnOnApiserver(
    consolidation.TestConsolidationChurnConvergence
):
    pass


class TestMarketCrashRestartOnApiserver(market_feed.TestMarketCrashRestart):
    """The market-fold determinism clause on the apiserver backend: a
    controller killed at market.mid-tick restarts over the write-through
    store, re-folds the provider's replayable tick history from seq 0, and
    reconstructs the identical PriceBook state and generation."""


class TestMarketControllerOnApiserver(market_feed.TestMarketController):
    """The market sweep (feed fold, chaos legs, debounce) must be backend-
    blind: it reads only the provider feed and the store's clock."""


class TestProvisioningUnderApiFaultsOnApiserver(chaos.TestProvisioningUnderApiFaults):
    """The chaos satellite's parity half: on this backend every request
    crosses ChaosTransport, so the armed conflict/timeout/reset storms
    actually fire — the 409-create → GET → retry-once path and the
    committed-timeout re-POST must converge with zero leaked instances,
    indistinguishable (to the controllers) from the quiet in-memory run."""


class TestHealthDetectionOnApiserver(health.TestHealthDetection):
    """The unhealthy-node ladder against the fake apiserver: heartbeats are
    status-only merge-patches (disjoint from the controller's metadata/spec
    writes), the re-taint and cordon are real patches, and the gone-dark
    liveness-gap regression holds on the write-through store."""


class TestStuckDrainOnApiserver(health.TestStuckDrain):
    pass


class TestZombieDefenseOnApiserver(health.TestZombieDefense):
    """Zombie re-registration on this backend is a real POST racing the
    deletion tombstones — the rejection must hold regardless."""


class TestHealthCrashMatrixOnApiserver(health.TestHealthCrashMatrix):
    pass


class TestKubeletFleetOnApiserver(health.TestKubeletFleet):
    """The fake-kubelet fleet speaks to the write-through cluster — every
    heartbeat and eviction completion is a live apiserver request."""


class TestReadinessRetaintOnApiserver(health.TestReadinessRetaint):
    pass


class TestNodeControllerStalenessOnApiserver(health.TestNodeControllerStaleness):
    """The stale-object satellite's real shape: between sub-reconciler
    patches the informer cache has moved — the re-read must pick up the
    merged object, not the pre-write snapshot."""


class TestHashStampingOnApiserver(drift.TestHashStamping):
    pass


class TestDriftReplacementOnApiserver(drift.TestDriftReplacement):
    """The rolling replacement path over real apiserver merge-patches: the
    durable claim, the cordon, and the annotation removal on cancel all go
    through the write-through store."""


class TestDisruptionLedgerOnApiserver(drift.TestDisruptionLedger):
    pass


class TestExpirationBudgetOnApiserver(drift.TestExpirationBudget):
    """ISSUE satellite: N simultaneously-expired nodes roll no more than
    budget-at-a-time on BOTH backends."""


class TestDriftCrashMatrixOnApiserver(drift.TestDriftCrashMatrix):
    pass


class TestLeaseCasUnderChaos:
    """Lease CAS over the REAL apiserver backend under chaos (HA satellite):
    the ``lease.cas`` faultpoint flaps the lease verb itself. The nasty leg
    is ``commit-lost`` — the server write lands but the caller is told it
    lost (timeout after commit). The next campaign by the same holder sees
    itself already holding and must re-acquire with NO transitions bump
    (same fencing generation: it never actually stopped being leader), while
    a rival stays blocked for the remainder of the committed term."""

    def _frontends(self, count=2):
        from karpenter_tpu.kubeapi import ApiServerCluster, KubeClient
        from karpenter_tpu.utils.clock import FakeClock

        from tests.fake_apiserver import DirectTransport, FakeApiServer

        clock = FakeClock()
        server = FakeApiServer(clock=clock)
        clusters = [
            ApiServerCluster(
                KubeClient(DirectTransport(server), qps=1e6, burst=10**6),
                clock=clock,
            )
            for _ in range(count)
        ]
        return clock, server, clusters

    def test_commit_lost_is_absorbed_without_a_generation_bump(self):
        from karpenter_tpu.utils import faultpoints

        clock, server, (a, b) = self._frontends()
        fault = faultpoints.arm("lease.cas", "commit-lost", rate=1.0, count=1)
        try:
            # The write COMMITTED server-side but the caller saw a loss.
            assert a.acquire_lease("leader", "a", 15.0) == 0
            assert fault.fires == 1
            stored = server.get_object("leases", "kube-system", "leader")
            assert stored["spec"]["holderIdentity"] == "a"
            assert stored["spec"]["leaseTransitions"] == 1
            # Split-brain seed absorbed: the re-campaign observes itself as
            # holder — same generation, no phantom handoff.
            assert a.acquire_lease("leader", "a", 15.0) == 1
            assert a.get_lease("leader")[2] == 1
            # The committed term really does exclude the rival.
            assert b.acquire_lease("leader", "b", 15.0) == 0
            clock.advance(16.0)
            assert b.acquire_lease("leader", "b", 15.0) == 2
        finally:
            faultpoints.disarm_all()

    def test_conflict_loses_the_cas_without_touching_the_server(self):
        from karpenter_tpu.utils import faultpoints

        clock, server, (a, b) = self._frontends()
        fault = faultpoints.arm("lease.cas", "conflict", rate=1.0, count=1)
        try:
            assert a.acquire_lease("leader", "a", 15.0) == 0
            assert fault.fires == 1
            # Conflict fires at entry: nothing reached the server, so the
            # very next attempt (fault exhausted) wins cleanly.
            assert server.get_object("leases", "kube-system", "leader") is None
            assert a.acquire_lease("leader", "a", 15.0) == 1
        finally:
            faultpoints.disarm_all()

    def test_commit_lost_on_renewal_keeps_the_holder_in_office(self):
        from karpenter_tpu.utils import faultpoints

        clock, server, (a, b) = self._frontends()
        assert a.acquire_lease("leader", "a", 15.0) == 1
        clock.advance(5.0)
        fault = faultpoints.arm("lease.cas", "commit-lost", rate=1.0, count=1)
        try:
            # Renewal reported lost, but the server term WAS extended.
            assert a.acquire_lease("leader", "a", 15.0) == 0
            assert fault.fires == 1
            clock.advance(11.0)  # past the ORIGINAL expiry, inside the renewed
            assert b.acquire_lease("leader", "b", 15.0) == 0
            assert a.acquire_lease("leader", "a", 15.0) == 1  # still gen 1
        finally:
            faultpoints.disarm_all()
