"""Test environment: force an 8-device virtual CPU mesh so multi-chip sharding
paths are exercised without TPU hardware (the driver separately dry-runs the
real multichip path via __graft_entry__.dryrun_multichip).

Under the axon TPU harness, a sitecustomize registers the 'axon' PJRT backend
at interpreter start (before this conftest can set JAX_PLATFORMS), and
selecting cpu via env alone then hangs in backend init. So: update the already
-imported jax config and drop the axon factory before any backend initializes.
"""

import os

import pytest

from karpenter_tpu.utils.backend_health import force_cpu_backend

force_cpu_backend(host_devices=8)


@pytest.fixture(autouse=True)
def _crashpoints_disarmed():
    """No crashpoint survives a test (tests/test_crash_consistency.py and
    the parity suite's apiserver re-run arm them): an armed site leaking
    across tests would kill an unrelated provision pass, and a non-empty
    passage counter keeps the fast path on the lock."""
    from karpenter_tpu.utils import crashpoints

    crashpoints.disarm_all()
    yield
    crashpoints.disarm_all()


@pytest.fixture(autouse=True)
def _market_book_reset():
    """The market PriceBook is process-global-active (market/pricebook.py
    set_active_book — Manager sets it at boot): a book leaking across tests
    would silently reprice every solver-layer fleet build. Tests that want
    one set it themselves."""
    from karpenter_tpu.market.pricebook import set_active_book

    set_active_book(None)
    yield
    set_active_book(None)


@pytest.fixture(autouse=True)
def _faultpoints_disarmed():
    """Same isolation for chaos faults (tests/test_chaos.py and the parity
    re-runs arm them): every apiserver-backed Harness routes through
    ChaosTransport, so a leaked fault would inject into unrelated tests."""
    from karpenter_tpu.utils import faultpoints

    faultpoints.disarm_all()
    yield
    faultpoints.disarm_all()


def pytest_collection_modifyitems(config, items):
    """KARPENTER_RANDOM_ORDER=<seed|auto> shuffles test order — the
    reference battletest's randomized-spec analogue (ref Makefile:33-38,
    ginkgo --randomizeAllSpecs). Seed is printed for reproduction; `make
    battletest` turns this on."""
    import random

    spec = os.environ.get("KARPENTER_RANDOM_ORDER")
    if not spec:
        return
    seed = int(spec) if spec.isdigit() else random.randrange(1 << 32)
    print(f"\nKARPENTER_RANDOM_ORDER seed={seed}")
    random.Random(seed).shuffle(items)
