"""Test environment: force an 8-device virtual CPU mesh so multi-chip sharding
paths are exercised without TPU hardware (the driver separately dry-runs the
real multichip path via __graft_entry__.dryrun_multichip)."""

import os

# Must happen before jax is imported anywhere.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
