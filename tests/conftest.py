"""Test environment: force an 8-device virtual CPU mesh so multi-chip sharding
paths are exercised without TPU hardware (the driver separately dry-runs the
real multichip path via __graft_entry__.dryrun_multichip).

Under the axon TPU harness, a sitecustomize registers the 'axon' PJRT backend
at interpreter start (before this conftest can set JAX_PLATFORMS), and
selecting cpu via env alone then hangs in backend init. So: update the already
-imported jax config and drop the axon factory before any backend initializes.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:  # pragma: no cover — jax internals moved; env var still set
    pass


def pytest_collection_modifyitems(config, items):
    """KARPENTER_RANDOM_ORDER=<seed|auto> shuffles test order — the
    reference battletest's randomized-spec analogue (ref Makefile:33-38,
    ginkgo --randomizeAllSpecs). Seed is printed for reproduction; `make
    battletest` turns this on."""
    import random

    spec = os.environ.get("KARPENTER_RANDOM_ORDER")
    if not spec:
        return
    seed = int(spec) if spec.isdigit() else random.randrange(1 << 32)
    print(f"\nKARPENTER_RANDOM_ORDER seed={seed}")
    random.Random(seed).shuffle(items)
