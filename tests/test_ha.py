"""HA control plane: write fencing by lease generation, lease transitions
on both store backends, the warm standby, cooperative sweep abort, and the
live-reload path (SIGHUP / POST /debug/loglevel).

Ref: cmd/controller/main.go:80-81 (controller-runtime leader election) and
the coordination.k8s.io Lease's ``leaseTransitions`` field, which this repo
uses as the fencing token.
"""

import json
import types
import urllib.request

import pytest

from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.cloudprovider import NodeSpec
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.controllers.provisioning import ProvisionerWorker
from karpenter_tpu.kubeapi import ApiServerCluster, KubeClient
from karpenter_tpu.runtime import LeaderElector, Manager, serve_http
from karpenter_tpu.utils import crashpoints
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils import options as options_pkg
from karpenter_tpu.utils.backoff import jittered_s
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.fence import (
    LEADER_FENCE_REJECTED_TOTAL,
    FencedWriteError,
    WriteFence,
    bind_thread,
)
from karpenter_tpu.utils.options import Options

from tests.fake_apiserver import DirectTransport, FakeApiServer


class TestWriteFence:
    def test_unarmed_passes_and_reports_no_generation(self):
        fence = WriteFence()
        fence.check("bind_pod")  # pass-through: no leadership machinery wired
        assert fence.generation is None
        assert not fence.revoked()

    def test_active_passes_and_exposes_generation(self):
        fence = WriteFence()
        fence.arm("a", 3)
        fence.check("bind_pod")
        assert fence.generation == 3

    def test_revoked_raises_counts_and_is_a_plain_exception(self):
        fence = WriteFence()
        fence.arm("a", 2)
        fence.revoke("a")
        before = LEADER_FENCE_REJECTED_TOTAL.get("bind_pod")
        with pytest.raises(FencedWriteError) as info:
            fence.check("bind_pod")
        assert info.value.verb == "bind_pod"
        assert info.value.generation == 2
        # Must travel ordinary recovery paths (reconcile error handling),
        # so it cannot be a BaseException-only escape hatch.
        assert isinstance(info.value, Exception)
        assert LEADER_FENCE_REJECTED_TOTAL.get("bind_pod") == before + 1
        # Revoked fence reports no usable generation: a launch identity
        # minted after revocation must not carry the stale token.
        assert fence.generation is None

    def test_revoke_is_keyed_by_holder(self):
        fence = WriteFence()
        fence.arm("a", 1)
        fence.revoke("b")  # a rival cannot revoke a fence it never armed
        fence.check("bind_pod")
        assert fence.generation == 1

    def test_rearm_after_revocation_restores_writes(self):
        fence = WriteFence()
        fence.arm("a", 1)
        fence.revoke("a")
        fence.arm("b", 2)  # the successor arms at the bumped generation
        fence.check("bind_pod")
        assert fence.generation == 2

    def test_disarm_returns_to_passthrough(self):
        fence = WriteFence()
        fence.arm("a", 1)
        fence.disarm("a")
        fence.check("bind_pod")
        assert fence.generation is None


class TestLeaseTransitionsInMemory:
    def test_holder_change_bumps_renewal_does_not(self):
        clock = FakeClock()
        cluster = Cluster(clock=clock)
        assert cluster.acquire_lease("leader", "a", 15.0) == 1
        clock.advance(5.0)
        assert cluster.acquire_lease("leader", "a", 15.0) == 1  # renewal
        clock.advance(16.0)
        assert cluster.acquire_lease("leader", "b", 15.0) == 2  # handoff
        assert cluster.get_lease("leader")[2] == 2

    def test_same_holder_reacquire_after_expiry_keeps_generation(self):
        clock = FakeClock()
        cluster = Cluster(clock=clock)
        assert cluster.acquire_lease("leader", "a", 15.0) == 1
        clock.advance(30.0)  # expired with no rival: not a handoff
        assert cluster.acquire_lease("leader", "a", 15.0) == 1

    def test_release_preserves_the_counter(self):
        """The tombstoned release keeps transitions so the next holder's
        generation cannot alias the previous one's."""
        clock = FakeClock()
        cluster = Cluster(clock=clock)
        assert cluster.acquire_lease("leader", "a", 15.0) == 1
        assert cluster.release_lease("leader", "a")
        assert cluster.get_lease("leader") is None
        assert cluster.acquire_lease("leader", "b", 15.0) == 2

    def test_refused_cas_returns_zero(self):
        clock = FakeClock()
        cluster = Cluster(clock=clock)
        assert cluster.acquire_lease("leader", "a", 15.0) == 1
        assert cluster.acquire_lease("leader", "b", 15.0) == 0


class TestLeaseTransitionsOnApiServer:
    def _clusters(self, count=2):
        clock = FakeClock()
        server = FakeApiServer(clock=clock)
        clusters = [
            ApiServerCluster(
                KubeClient(DirectTransport(server), qps=1e6, burst=10**6),
                clock=clock,
            )
            for _ in range(count)
        ]
        return clock, server, clusters

    def test_lease_transitions_survive_handoff_and_release(self):
        clock, server, (a, b) = self._clusters()
        assert a.acquire_lease("leader", "a", 15.0) == 1
        stored = server.get_object("leases", "kube-system", "leader")
        assert stored["spec"]["leaseTransitions"] == 1
        clock.advance(16.0)
        assert b.acquire_lease("leader", "b", 15.0) == 2
        stored = server.get_object("leases", "kube-system", "leader")
        assert stored["spec"]["leaseTransitions"] == 2
        # Release tombstones (holder cleared, counter kept) instead of
        # deleting, so the NEXT acquire still bumps past 2.
        assert b.release_lease("leader", "b")
        stored = server.get_object("leases", "kube-system", "leader")
        assert stored["spec"]["holderIdentity"] == ""
        assert stored["spec"]["leaseTransitions"] == 2
        assert a.acquire_lease("leader", "a", 15.0) == 3

    def test_renewal_keeps_generation(self):
        clock, server, (a,) = self._clusters(count=1)
        assert a.acquire_lease("leader", "a", 15.0) == 1
        clock.advance(5.0)
        assert a.acquire_lease("leader", "a", 15.0) == 1
        assert a.get_lease("leader")[2] == 1


class TestElectorFencing:
    def _cluster(self):
        clock = FakeClock()
        return Cluster(clock=clock), clock

    def test_acquire_arms_fence_with_lease_generation(self):
        cluster, clock = self._cluster()
        elector = LeaderElector(cluster, "a")
        assert elector.try_acquire()
        assert elector.generation == 1
        assert cluster.fence.generation == 1
        cluster.apply_pod(PodSpec(name="p1", uid="u1"))  # writes pass

    def test_missed_renew_deadline_revokes_and_rejects_writes(self):
        cluster, clock = self._cluster()
        lost = []
        elector = LeaderElector(cluster, "a", on_lost=lambda: lost.append("a"))
        assert elector.try_acquire()
        clock.advance(LeaderElector.LEASE_SECONDS + 1)
        assert elector._renew_once() is False
        assert lost == ["a"]
        assert cluster.fence.revoked()
        with pytest.raises(FencedWriteError):
            cluster.apply_pod(PodSpec(name="p1", uid="u1"))
        with pytest.raises(FencedWriteError):
            cluster.fence.check("cloud.create")

    def test_takeover_bumps_generation_and_rearms_successor(self):
        cluster, clock = self._cluster()
        a = LeaderElector(cluster, "a")
        b = LeaderElector(cluster, "b")
        assert a.try_acquire()
        assert not b.try_acquire()  # stamps b's campaign
        clock.advance(LeaderElector.LEASE_SECONDS + 1)
        assert a._renew_once() is False  # a notices the missed deadline
        assert b.try_acquire()
        assert b.generation == 2
        assert cluster.fence.generation == 2
        cluster.apply_pod(PodSpec(name="p1", uid="u1"))  # successor writes pass

    def test_stale_leader_writes_refused_while_successor_proceeds(self):
        """Two replicas, each with its OWN store frontend (and fence) over
        one shared apiserver — the production topology. The paused leader's
        writes die at its fence; the successor's land on the server."""
        clock = FakeClock()
        server = FakeApiServer(clock=clock)

        def frontend():
            return ApiServerCluster(
                KubeClient(DirectTransport(server), qps=1e6, burst=10**6),
                clock=clock,
            )

        cluster_a, cluster_b = frontend(), frontend()
        a = LeaderElector(cluster_a, "a")
        b = LeaderElector(cluster_b, "b")
        assert a.try_acquire()
        assert not b.try_acquire()
        clock.advance(LeaderElector.LEASE_SECONDS + 1)  # a pauses past TTL
        assert b.try_acquire()
        assert b.generation == 2
        # The resumed stale leader observes the missed deadline: fence drops.
        assert a._renew_once() is False
        with pytest.raises(FencedWriteError):
            cluster_a.apply_pod(PodSpec(name="stale", uid="u-stale"))
        assert server.get_object("pods", "default", "stale") is None
        cluster_b.apply_pod(PodSpec(name="fresh", uid="u-fresh"))
        assert server.get_object("pods", "default", "fresh") is not None

    def test_release_disarms_fence(self):
        cluster, clock = self._cluster()
        elector = LeaderElector(cluster, "a")
        assert elector.try_acquire()
        elector.release()
        assert cluster.fence.generation is None
        cluster.apply_pod(PodSpec(name="p1", uid="u1"))  # pass-through again


class TestLaunchIdentityGeneration:
    def _packing(self):
        return types.SimpleNamespace(
            pods=[PodSpec(name="p", uid="u1")],
            node_quantity=1,
            instance_type_options=[],
            pool_options=[],
        )

    def test_generation_folds_into_the_identity(self):
        ident = ProvisionerWorker._launch_identity
        packing = self._packing()
        bare = ident("default", packing)
        gen1 = ident("default", packing, lease_generation=1)
        gen2 = ident("default", packing, lease_generation=2)
        # Same batch, same generation: stable (crash-replay still adopts).
        assert gen1 == ident("default", packing, lease_generation=1)
        # A successor's re-solve of the SAME pods mints a fresh token.
        assert len({bare, gen1, gen2}) == 3


class TestCooperativeAbort:
    def test_revoked_thread_fence_aborts_at_crashpoints(self):
        fence = WriteFence()
        fence.arm("a", 1)
        bind_thread(fence)
        try:
            crashpoints.crashpoint("provision.before-launch")  # armed: passes
            fence.revoke("a")
            with pytest.raises(FencedWriteError) as info:
                crashpoints.crashpoint("provision.before-launch")
            assert info.value.verb == "sweep:provision.before-launch"
        finally:
            bind_thread(None)

    def test_unbound_thread_is_unaffected(self):
        bind_thread(None)
        crashpoints.crashpoint("provision.before-launch")


class TestWarmStandby:
    def _manager(self):
        return Manager(
            Cluster(),
            FakeCloudProvider(),
            Options(cluster_name="ha", solver="greedy", leader_election=False),
        )

    def test_standby_is_warm_but_not_ready_until_activated(self):
        mgr = self._manager()
        try:
            mgr.start_standby()
            assert mgr.standby.is_set()
            assert mgr.warm.wait(timeout=10.0)
            assert not mgr.ready.is_set()  # warm, but not routable
            mgr.start()  # takeover: activate
            assert not mgr.standby.is_set()
            assert mgr.ready.is_set()
        finally:
            mgr.stop()

    def test_readyz_answers_standby_then_ok(self):
        mgr = self._manager()
        server = serve_http(mgr, 0, address="127.0.0.1")
        port = server.server_address[1]

        def fetch(path):
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5.0
                ) as resp:
                    return resp.status, resp.read()
            except urllib.error.HTTPError as err:
                return err.code, err.read()

        try:
            mgr.start_standby()
            assert mgr.warm.wait(timeout=10.0)
            assert fetch("/healthz")[0] == 200  # liveness must NOT kill us
            status, body = fetch("/readyz")
            assert (status, body) == (503, b"standby")
            mgr.start()
            assert fetch("/readyz")[0] == 200
        finally:
            mgr.stop()
            server.shutdown()


class TestLiveReload:
    def test_apply_reload_touches_only_the_reloadable_subset(self):
        live = options_pkg.parse(["--cluster-name", "c", "--log-level", "info"])
        fresh = options_pkg.parse(
            ["--cluster-name", "other", "--log-level", "debug"]
        )
        changed = options_pkg.apply_reload(live, fresh)
        assert changed == {"log_level": "debug"}
        assert live.log_level == "debug"
        assert live.cluster_name == "c"  # not reloadable: untouched

    def test_manager_reload_applies_log_level(self):
        mgr = Manager(
            Cluster(),
            FakeCloudProvider(),
            Options(cluster_name="ha", solver="greedy", leader_election=False),
        )
        previous = klog.get_level()
        try:
            mgr.reload_options({"log_level": "debug"})
            assert klog.get_level() == "debug"
        finally:
            klog.set_level(previous)

    def test_debug_loglevel_endpoint_round_trips(self):
        mgr = Manager(
            Cluster(),
            FakeCloudProvider(),
            Options(cluster_name="ha", solver="greedy", leader_election=False),
        )
        server = serve_http(mgr, 0, address="127.0.0.1")
        port = server.server_address[1]
        base = f"http://127.0.0.1:{port}/debug/loglevel"
        previous = klog.get_level()

        def request(method, body=None):
            req = urllib.request.Request(base, data=body, method=method)
            try:
                with urllib.request.urlopen(req, timeout=5.0) as resp:
                    return resp.status, resp.read()
            except urllib.error.HTTPError as err:
                return err.code, err.read()

        try:
            status, body = request("POST", b'{"level": "debug"}')
            assert status == 200
            assert klog.get_level() == "debug"
            assert mgr.options.log_level == "debug"
            status, body = request("GET")
            assert status == 200
            assert json.loads(body) == {"level": "debug"}
            status, _ = request("POST", b"warning")  # raw level, no JSON
            assert status == 200
            assert klog.get_level() == "warning"
            status, _ = request("POST", b"shouting")
            assert status == 400
            assert klog.get_level() == "warning"  # bad input changes nothing
        finally:
            klog.set_level(previous)
            server.shutdown()


class TestJitter:
    def test_jittered_s_stays_within_the_fraction_band(self):
        import random

        rng = random.Random(7)
        for _ in range(200):
            value = jittered_s(5.0, rng=rng)
            assert 4.0 <= value <= 6.0
        assert jittered_s(0.0, rng=rng) == 0.0


class TestFencedCloudVerbsInMemory:
    def test_store_verbs_fence_on_the_in_memory_backend(self):
        cluster = Cluster()
        cluster.fence.arm("a", 1)
        cluster.fence.revoke("a")
        with pytest.raises(FencedWriteError):
            cluster.create_node(NodeSpec(name="n1"))
        with pytest.raises(FencedWriteError):
            cluster.apply_pod(PodSpec(name="p1", uid="u1"))
