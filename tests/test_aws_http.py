"""The real AWS wire binding (cloudprovider/ec2/aws_http.py) under test:
SigV4 known-answer vector, Query-API request encoding, pagination, error
mapping, SSM JSON — against stub/recorded responses — plus the ENTIRE EC2
provider suite (tests/test_ec2.py) re-run with the wire binding swapped in,
so launch templates, fleets, ICE blackouts, discovery and terminate all
round-trip through real request/response bytes.

Ref: the calls mirrored here are the reference's SDK usage —
CreateFleet (aws/instance.go:116-133), DescribeInstanceTypes/Offerings
(aws/instancetypes.go:61-104), subnet/SG discovery (aws/subnets.go:52-69),
SSM GetParameter (aws/ami.go:49-110)."""

import datetime
import json

import pytest

from karpenter_tpu.cloudprovider.ec2.api import (
    ApiError,
    FleetOverride,
    FleetRequest,
    LaunchTemplate,
    QueueMessage,
    is_not_found,
)
from karpenter_tpu.cloudprovider.ec2.aws_http import (
    AwsHttpEc2Api,
    Credentials,
    HttpResponse,
    HttpTransport,
    RetryPolicy,
    UrllibTransport,
    sign_request,
)
from tests.wire_fake import FlakyTransport, WireFakeTransport, wire_api


class TestSigV4:
    def test_known_answer_vector(self):
        """AWS's documented GET iam.amazonaws.com ListUsers example."""
        headers = sign_request(
            "GET",
            "https://iam.amazonaws.com/?Action=ListUsers&Version=2010-05-08",
            {"Content-Type": "application/x-www-form-urlencoded; charset=utf-8"},
            b"",
            "us-east-1",
            "iam",
            Credentials("AKIDEXAMPLE", "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY"),
            now=datetime.datetime(
                2015, 8, 30, 12, 36, 0, tzinfo=datetime.timezone.utc
            ),
        )
        assert headers["Authorization"] == (
            "AWS4-HMAC-SHA256 "
            "Credential=AKIDEXAMPLE/20150830/us-east-1/iam/aws4_request, "
            "SignedHeaders=content-type;host;x-amz-date, "
            "Signature=5d672d79c15b13162d9279b0855cfba6789a8edb4c82c400e06"
            "b5924a6f2b5d7"
        )

    def test_session_token_is_signed(self):
        headers = sign_request(
            "POST", "https://ec2.us-east-1.amazonaws.com/", {}, b"x",
            "us-east-1", "ec2", Credentials("AKID", "secret", "the-token"),
            now=datetime.datetime(2026, 1, 1, tzinfo=datetime.timezone.utc),
        )
        assert headers["X-Amz-Security-Token"] == "the-token"
        assert "x-amz-security-token" in headers["Authorization"]


class RecordedTransport(HttpTransport):
    """Replays canned responses; records every outgoing request."""

    def __init__(self, responses):
        self.responses = list(responses)
        self.sent = []

    def send(self, method, url, headers, body):
        self.sent.append((method, url, dict(headers), body))
        return self.responses.pop(0)


def recorded_api(*responses, retry_policy=None) -> AwsHttpEc2Api:
    # Default: retries OFF, so encoding/parsing tests see exactly one
    # attempt per canned response. Retry behavior is covered by TestRetry.
    return AwsHttpEc2Api(
        region="us-test-1",
        credentials=Credentials("AKID", "secret"),
        transport=RecordedTransport(responses),
        price_catalog={"m5.large": 0.096},
        retry_policy=retry_policy
        or RetryPolicy(max_retries=0, sleep=lambda _s: None),
    )


def _params(transport_body: bytes) -> dict:
    import urllib.parse

    return dict(urllib.parse.parse_qsl(transport_body.decode()))


class TestRequestEncoding:
    def test_create_fleet_request_params(self):
        api = recorded_api(
            HttpResponse(
                200,
                b'<CreateFleetResponse xmlns="http://ec2.amazonaws.com/doc/'
                b'2016-11-15/"><fleetInstanceSet><item><instanceIds>'
                b"<item>i-1</item><item>i-2</item></instanceIds></item>"
                b"</fleetInstanceSet><errorSet/></CreateFleetResponse>",
            )
        )
        result = api.create_fleet(
            FleetRequest(
                launch_template_name="lt-name",
                overrides=[
                    FleetOverride("m5.large", "subnet-1", "us-test-1a", priority=0.0),
                    FleetOverride("c5.large", "subnet-2", "us-test-1b", priority=1.0),
                ],
                capacity_type="spot",
                quantity=2,
                tags={"Name": "karpenter"},
            )
        )
        assert result.instance_ids == ["i-1", "i-2"]
        params = _params(api.transport.sent[0][3])
        assert params["Action"] == "CreateFleet"
        assert params["Type"] == "instant"
        assert params["SpotOptions.AllocationStrategy"] == (
            "capacity-optimized-prioritized"
        )
        assert params["TargetCapacitySpecification.TotalTargetCapacity"] == "2"
        assert params[
            "LaunchTemplateConfigs.1.Overrides.2.InstanceType"
        ] == "c5.large"
        assert params["LaunchTemplateConfigs.1.Overrides.2.Priority"] == "1.0"
        assert params["TagSpecification.1.Tag.1.Key"] == "Name"

    def test_on_demand_fleet_uses_lowest_price(self):
        api = recorded_api(
            HttpResponse(
                200,
                b"<CreateFleetResponse><fleetInstanceSet/><errorSet/>"
                b"</CreateFleetResponse>",
            )
        )
        api.create_fleet(
            FleetRequest(
                launch_template_name="lt",
                overrides=[FleetOverride("m5.large", "subnet-1", "z")],
                capacity_type="on-demand",
                quantity=1,
            )
        )
        params = _params(api.transport.sent[0][3])
        assert params["OnDemandOptions.AllocationStrategy"] == "lowest-price"

    def test_tag_filters_encode_tag_key_and_exact_value(self):
        api = recorded_api(
            HttpResponse(200, b"<DescribeSubnetsResponse><subnetSet/>"
                              b"</DescribeSubnetsResponse>")
        )
        api.describe_subnets({"kubernetes.io/cluster/c": "*", "Name": "private"})
        params = _params(api.transport.sent[0][3])
        assert params["Filter.1.Name"] == "tag:Name"
        assert params["Filter.1.Value.1"] == "private"
        assert params["Filter.2.Name"] == "tag-key"
        assert params["Filter.2.Value.1"] == "kubernetes.io/cluster/c"

    def test_requests_are_signed_for_the_ec2_service(self):
        api = recorded_api(
            HttpResponse(200, b"<TerminateInstancesResponse/>")
        )
        api.terminate_instances(["i-1"])
        headers = api.transport.sent[0][2]
        assert "/us-test-1/ec2/aws4_request" in headers["Authorization"]


class TestPagination:
    def test_describe_instances_follows_next_token(self):
        page1 = (
            b"<DescribeInstancesResponse><reservationSet><item><instancesSet>"
            b"<item><instanceId>i-1</instanceId><instanceType>m5.large"
            b"</instanceType><placement><availabilityZone>z-a"
            b"</availabilityZone></placement></item></instancesSet></item>"
            b"</reservationSet><nextToken>tok-1</nextToken>"
            b"</DescribeInstancesResponse>"
        )
        page2 = (
            b"<DescribeInstancesResponse><reservationSet><item><instancesSet>"
            b"<item><instanceId>i-2</instanceId><instanceType>c5.large"
            b"</instanceType><placement><availabilityZone>z-b"
            b"</availabilityZone></placement><instanceLifecycle>spot"
            b"</instanceLifecycle></item></instancesSet></item>"
            b"</reservationSet></DescribeInstancesResponse>"
        )
        api = recorded_api(HttpResponse(200, page1), HttpResponse(200, page2))
        instances = api.describe_instances(["i-1", "i-2"])
        assert [i.instance_id for i in instances] == ["i-1", "i-2"]
        assert instances[1].spot
        assert _params(api.transport.sent[1][3])["NextToken"] == "tok-1"


class TestErrorMapping:
    def test_ec2_error_xml_maps_to_api_error(self):
        api = recorded_api(
            HttpResponse(
                400,
                b"<Response><Errors><Error>"
                b"<Code>InvalidInstanceID.NotFound</Code>"
                b"<Message>i-missing does not exist</Message>"
                b"</Error></Errors></Response>",
            )
        )
        with pytest.raises(ApiError) as err:
            api.describe_instances(["i-missing"])
        assert err.value.code == "InvalidInstanceID.NotFound"
        assert is_not_found(err.value)

    def test_ssm_error_json_maps_to_api_error(self):
        api = recorded_api(
            HttpResponse(
                400,
                json.dumps(
                    {"__type": "com.amazon.ssm#ParameterNotFound", "message": "x"}
                ).encode(),
            )
        )
        with pytest.raises(ApiError) as err:
            api.get_ami_parameter("/aws/service/missing")
        assert err.value.code == "ParameterNotFound"
        assert is_not_found(err.value)

    def test_garbage_2xx_body_maps_to_coded_error(self):
        """A misbehaving proxy can 200 with an HTML body; the binding must
        raise a coded ApiError, never a bare XML ParseError."""
        api = recorded_api(HttpResponse(200, b"<html>gateway says hi</html "))
        with pytest.raises(ApiError) as err:
            api.describe_instances(["i-1"])
        assert err.value.code == "MalformedResponse"

    def test_5xx_html_body_maps_to_coded_error(self):
        api = recorded_api(HttpResponse(503, b"<html>Service Unavailable"))
        with pytest.raises(ApiError) as err:
            api.describe_instances(["i-1"])
        assert err.value.code == "HTTP503"

    def test_well_formed_non_ec2_xml_is_malformed_not_empty(self):
        """An XHTML error page parses as XML; it must not read as an empty
        EC2 result set (callers would conclude live instances vanished)."""
        api = recorded_api(
            HttpResponse(200, b"<html><body>Bad Gateway</body></html>")
        )
        with pytest.raises(ApiError) as err:
            api.describe_instances(["i-1"])
        assert err.value.code == "MalformedResponse"

    def test_ssm_garbage_2xx_is_malformed_not_parameter_not_found(self):
        api = recorded_api(HttpResponse(200, b"<html>gateway</html>"))
        with pytest.raises(ApiError) as err:
            api.get_ami_parameter("/aws/service/x")
        assert err.value.code == "MalformedResponse"
        assert not is_not_found(err.value)

    def test_transport_error_is_coded(self):
        """Socket-level failures surface as ApiError('TransportError'), not a
        raw URLError, so classification is uniform vs the fakes."""
        from karpenter_tpu.cloudprovider.ec2.aws_http import UrllibTransport

        transport = UrllibTransport(timeout=0.01)
        api = AwsHttpEc2Api(
            region="us-test-1",
            credentials=Credentials("AKID", "secret"),
            transport=transport,
            ec2_endpoint="http://127.0.0.1:9/",  # discard port: refuses fast
            retry_policy=RetryPolicy(max_retries=0, sleep=lambda _s: None),
        )
        with pytest.raises(ApiError) as err:
            api.describe_instances(["i-1"])
        assert err.value.code == "TransportError"

    def test_ssm_parameter_value_parsed(self):
        api = recorded_api(
            HttpResponse(
                200,
                json.dumps({"Parameter": {"Value": "ami-12345"}}).encode(),
            )
        )
        assert api.get_ami_parameter("/aws/service/x") == "ami-12345"


_THROTTLE_XML = HttpResponse(
    503,
    b"<Response><Errors><Error><Code>RequestLimitExceeded</Code>"
    b"<Message>Request limit exceeded.</Message></Error></Errors></Response>",
)
_OK_DESCRIBE = HttpResponse(
    200,
    b'<DescribeInstancesResponse xmlns="http://ec2.amazonaws.com/doc/'
    b'2016-11-15/"><reservationSet><item><instancesSet><item>'
    b"<instanceId>i-1</instanceId><instanceType>m5.large</instanceType>"
    b"</item></instancesSet></item></reservationSet>"
    b"</DescribeInstancesResponse>",
)


class TestSqsInterruptionQueue:
    """The interruption-queue poll action: signed SQS JSON-RPC with the
    shared retry budget and aws_retry_total accounting."""

    QUEUE = "https://sqs.us-test-1.amazonaws.com/000000000000/interruptions"

    def test_receive_and_delete_encode_and_sign_for_sqs(self):
        api = recorded_api(
            HttpResponse(
                200,
                json.dumps(
                    {
                        "Messages": [
                            {
                                "MessageId": "m1",
                                "ReceiptHandle": "rh1",
                                "Body": "{}",
                            }
                        ]
                    }
                ).encode(),
            ),
            HttpResponse(200, b"{}"),
        )
        api.interruption_queue_url = self.QUEUE
        assert api.receive_queue_messages() == [QueueMessage("m1", "rh1", "{}")]
        api.delete_queue_message("rh1")
        receive, delete = api.transport.sent
        assert receive[2]["X-Amz-Target"] == "AmazonSQS.ReceiveMessage"
        assert "/sqs/aws4_request" in receive[2]["Authorization"]
        assert json.loads(receive[3])["QueueUrl"] == self.QUEUE
        assert delete[2]["X-Amz-Target"] == "AmazonSQS.DeleteMessage"
        assert json.loads(delete[3])["ReceiptHandle"] == "rh1"

    def test_no_queue_configured_makes_no_wire_calls(self):
        api = recorded_api()
        assert api.receive_queue_messages() == []
        api.delete_queue_message("rh")
        assert api.transport.sent == []

    def test_throttled_receive_retries_and_counts(self):
        from karpenter_tpu.cloudprovider.ec2.aws_http import AWS_RETRY_TOTAL

        before = AWS_RETRY_TOTAL.get("ReceiveMessage", "ThrottlingException")
        api = recorded_api(
            HttpResponse(
                400, json.dumps({"__type": "ThrottlingException"}).encode()
            ),
            HttpResponse(200, json.dumps({"Messages": []}).encode()),
            retry_policy=RetryPolicy(max_retries=2, sleep=lambda _s: None),
        )
        api.interruption_queue_url = self.QUEUE
        assert api.receive_queue_messages() == []
        assert (
            AWS_RETRY_TOTAL.get("ReceiveMessage", "ThrottlingException")
            - before
            == 1
        )

    def test_expired_receipt_handle_is_ack_success(self):
        api = recorded_api(
            HttpResponse(
                400, json.dumps({"__type": "ReceiptHandleIsInvalid"}).encode()
            )
        )
        api.interruption_queue_url = self.QUEUE
        api.delete_queue_message("stale")  # must not raise


class TestRetry:
    """The binding's DefaultRetryer analogue (ref: aws/cloudprovider.go:67-69
    installs client.DefaultRetryer on every EC2/SSM call): throttles, 5xx and
    transport failures back off with jittered-exponential delays inside a
    bounded attempt budget."""

    def _sleep_recorder(self):
        slept = []
        return slept, slept.append

    def test_throttle_sequence_recovers(self):
        slept, sleep = self._sleep_recorder()
        api = recorded_api(
            _THROTTLE_XML,
            HttpResponse(500, b"<html>internal"),
            _OK_DESCRIBE,
            retry_policy=RetryPolicy(sleep=sleep, rng=lambda: 0.5),
        )
        instances = api.describe_instances(["i-1"])
        assert [i.instance_id for i in instances] == ["i-1"]
        assert len(slept) == 2  # two failures, two backoffs
        assert len(api.transport.sent) == 3

    def test_budget_exhaustion_raises_with_bounded_attempts(self):
        slept, sleep = self._sleep_recorder()
        api = recorded_api(
            *([_THROTTLE_XML] * 4),
            retry_policy=RetryPolicy(
                max_retries=3, sleep=sleep, rng=lambda: 0.0
            ),
        )
        with pytest.raises(ApiError) as err:
            api.describe_instances(["i-1"])
        assert err.value.code == "RequestLimitExceeded"
        assert len(api.transport.sent) == 4  # 1 + 3 retries, no more
        assert len(slept) == 3

    def test_throttle_backs_off_harder_than_transient(self):
        policy = RetryPolicy(rng=lambda: 0.0, sleep=lambda _s: None)
        assert policy.delay(0, "RequestLimitExceeded") > policy.delay(
            0, "HTTP503"
        )
        # Exponential growth, capped.
        assert policy.delay(2, "Throttling") > policy.delay(0, "Throttling")
        assert policy.delay(30, "Throttling") <= policy.max_delay

    def test_bare_429_and_408_are_retryable_throttles(self):
        """A proxy/LB throttle or timeout with no parseable envelope
        synthesizes HTTP429/HTTP408 — the SDK retries these statuses even
        without an error code, and 429 backs off on the throttle schedule."""
        policy = RetryPolicy(rng=lambda: 0.0, sleep=lambda _s: None)
        assert policy.is_retryable("HTTP429")
        assert policy.is_retryable("HTTP408")
        assert not policy.is_retryable("HTTP404")
        assert policy.delay(0, "HTTP429") == policy.delay(
            0, "RequestLimitExceeded"
        )

    def test_non_retryable_error_fails_fast(self):
        slept, sleep = self._sleep_recorder()
        api = recorded_api(
            HttpResponse(
                400,
                b"<Response><Errors><Error><Code>InvalidInstanceID.NotFound"
                b"</Code><Message>nope</Message></Error></Errors></Response>",
            ),
            retry_policy=RetryPolicy(sleep=sleep),
        )
        with pytest.raises(ApiError):
            api.describe_instances(["i-missing"])
        assert slept == [] and len(api.transport.sent) == 1

    def test_transport_failure_retries(self):
        class FlakySocket(HttpTransport):
            def __init__(self):
                self.sent = []

            def send(self, method, url, headers, body):
                self.sent.append(body)
                if len(self.sent) == 1:
                    raise ApiError("TransportError", "connection reset")
                return _OK_DESCRIBE

        api = AwsHttpEc2Api(
            region="us-test-1",
            credentials=Credentials("AKID", "secret"),
            transport=FlakySocket(),
            retry_policy=RetryPolicy(sleep=lambda _s: None),
        )
        assert api.describe_instances(["i-1"])[0].instance_id == "i-1"
        assert len(api.transport.sent) == 2

    def test_ssm_throttle_recovers(self):
        slept, sleep = self._sleep_recorder()
        api = recorded_api(
            HttpResponse(
                400,
                json.dumps({"__type": "ThrottlingException"}).encode(),
            ),
            HttpResponse(
                200, json.dumps({"Parameter": {"Value": "ami-9"}}).encode()
            ),
            retry_policy=RetryPolicy(sleep=sleep),
        )
        assert api.get_ami_parameter("/aws/service/x") == "ami-9"
        assert len(slept) == 1

    def test_create_fleet_retry_reuses_one_client_token(self):
        """A retried CreateFleet must carry the SAME idempotency token so a
        5xx whose first attempt executed server-side cannot double-launch."""
        ok_fleet = HttpResponse(
            200,
            b'<CreateFleetResponse xmlns="http://ec2.amazonaws.com/doc/'
            b'2016-11-15/"><fleetInstanceSet/><errorSet/>'
            b"</CreateFleetResponse>",
        )
        api = recorded_api(
            HttpResponse(500, b""),
            ok_fleet,
            retry_policy=RetryPolicy(sleep=lambda _s: None),
        )
        api.create_fleet(
            FleetRequest(
                launch_template_name="lt",
                capacity_type="on-demand",
                quantity=1,
                overrides=[],
            )
        )
        tokens = [
            _params(body).get("ClientToken")
            for _m, _u, _h, body in api.transport.sent
        ]
        assert len(tokens) == 2 and tokens[0] == tokens[1] and tokens[0]


class TestWireFakeRoundTrip:
    """Direct binding<->wire-fake round trips for calls with structure the
    provider suite doesn't inspect at the wire level."""

    def test_instance_types_round_trip_gpu_arch_and_usage(self):
        api = wire_api()
        infos = {i.name: i for i in api.describe_instance_types()}
        assert infos["p3.8xlarge"].nvidia_gpus == 4
        assert infos["m6g.large"].architectures == ("arm64",)
        assert infos["m5.metal"].bare_metal
        assert infos["f1.2xlarge"].fpga
        assert infos["inf1.6xlarge"].neurons == 4
        assert infos["m5.large"].memory_mib == 8 * 1024

    def test_offerings_expand_usage_classes_with_catalog_prices(self):
        api = wire_api()
        offerings = api.describe_instance_type_offerings()
        m5 = [o for o in offerings if o.instance_type == "m5.large"]
        assert {o.capacity_type for o in m5} == {"on-demand", "spot"}
        od = next(o for o in m5 if o.capacity_type == "on-demand")
        spot = next(o for o in m5 if o.capacity_type == "spot")
        assert od.price == pytest.approx(0.096)
        assert spot.price == pytest.approx(0.096 * 0.6)

    def test_launch_template_round_trip(self):
        api = wire_api()
        created = api.create_launch_template(
            LaunchTemplate(
                name="karpenter-abc",
                image_id="ami-1",
                instance_profile="prof",
                security_group_ids=("sg-test1", "sg-test2"),
                user_data="#!/bin/bash",
                tags={"k": "v"},
            )
        )
        assert created.template_id.startswith("lt-")
        fetched = api.describe_launch_template("karpenter-abc")
        assert fetched.image_id == "ami-1"
        assert fetched.instance_profile == "prof"
        assert tuple(fetched.security_group_ids) == ("sg-test1", "sg-test2")
        assert fetched.user_data == "#!/bin/bash"

    def test_missing_launch_template_is_not_found(self):
        api = wire_api()
        with pytest.raises(ApiError) as err:
            api.describe_launch_template("nope")
        assert is_not_found(err.value)

    def test_pagination_exercised_by_small_pages(self):
        api = wire_api(page_size=2)
        infos = api.describe_instance_types()
        assert len(infos) == len(api.fake.instance_type_infos)
        transport = api.transport
        pages = [r for r in transport.requests if r[0] == "DescribeInstanceTypes"]
        assert len(pages) > 1  # NextToken loop actually ran
        assert any("NextToken" in p for _, p in pages)


# --- Re-run the whole provider suite over the wire binding ------------------
#
# tests/test_ec2.py builds its Ec2Api through make_api(); swapping that for
# the wire binding re-runs every scenario (vendor hooks, adaptation,
# discovery, launch templates, fleets, ICE blackout, terminate, end-to-end
# provisioning) through SigV4-signed Query-API bytes with paginated
# responses.

from tests import test_ec2 as _suite  # noqa: E402


@pytest.fixture(autouse=True)
def _wire_backend(monkeypatch):
    monkeypatch.setattr(_suite, "make_api", lambda: wire_api(page_size=4))


class TestVendorExtensionOverWire(_suite.TestVendorExtension):
    pass


class TestInstanceTypeAdaptationOverWire(_suite.TestInstanceTypeAdaptation):
    pass


class TestDiscoveryOverWire(_suite.TestDiscovery):
    pass


class TestLaunchTemplatesOverWire(_suite.TestLaunchTemplates):
    pass


class TestFleetLaunchOverWire(_suite.TestFleetLaunch):
    pass


class TestInsufficientCapacityOverWire(_suite.TestInsufficientCapacity):
    pass


class TestTerminateOverWire(_suite.TestTerminate):
    pass


class TestEndToEndOverWire(_suite.TestEndToEnd):
    pass


class TestPoolPinnedLaunchOverWire(_suite.TestPoolPinnedLaunch):
    pass


class TestMarketPollOverWire(_suite.TestMarketPoll):
    """The market feed's EC2 leg over real bytes: injected spot-price rows
    serialize through the wire fake's DescribeSpotPriceHistory XML (ISO
    timestamps and all) and come back as the identical tick stream."""


class TestUrllibTransportOverRealSockets:
    """The PRODUCTION transport (urllib) against a real HTTP server fronting
    the wire fake: signing, pagination, error mapping, and throttle retry all
    ride actual sockets — the exact bytes-on-wire path a live deployment
    uses, minus AWS itself."""

    @pytest.fixture()
    def http_api(self):
        import http.server
        import threading

        inner = FlakyTransport(WireFakeTransport(page_size=3), period=3)

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                try:
                    response = inner.send(
                        "POST", self.path, dict(self.headers), body
                    )
                    status, payload = response.status, response.body
                except ApiError:
                    # FlakyTransport's socket-fault slot: actually sever the
                    # connection so urllib sees a real transport error.
                    self.connection.close()
                    return
                self.send_response(status)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):
                pass

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        endpoint = f"http://127.0.0.1:{httpd.server_port}/"
        api = AwsHttpEc2Api(
            region="us-test-1",
            credentials=Credentials("AKIDEXAMPLE", "secret", "token"),
            transport=UrllibTransport(timeout=5.0),
            ec2_endpoint=endpoint,
            ssm_endpoint=endpoint,
            price_catalog={
                info.name: info.price_on_demand
                for info in inner.inner.fake.instance_type_infos
            },
            retry_policy=RetryPolicy(sleep=lambda _s: None),
        )
        yield api, inner
        httpd.shutdown()
        httpd.server_close()

    def test_paginated_discovery_with_faults_over_sockets(self, http_api):
        api, flaky = http_api
        infos = api.describe_instance_types()
        assert len(infos) == len(flaky.inner.fake.instance_type_infos)
        assert flaky.faults_injected > 0  # retryer absorbed real failures
        offerings = api.describe_instance_type_offerings()
        assert offerings

    def test_fleet_launch_over_sockets(self, http_api):
        api, _ = http_api
        api.create_launch_template(
            LaunchTemplate(name="socket-lt", image_id="ami-1", user_data="x")
        )
        result = api.create_fleet(
            FleetRequest(
                launch_template_name="socket-lt",
                capacity_type="on-demand",
                quantity=2,
                overrides=[
                    FleetOverride(
                        instance_type="m5.large",
                        subnet_id="subnet-test1",
                        zone="test-zone-1",
                    )
                ],
            )
        )
        assert len(result.instance_ids) == 2
        instances = api.describe_instances(result.instance_ids)
        assert {i.instance_id for i in instances} == set(result.instance_ids)

    def test_coded_error_maps_over_sockets(self, http_api):
        api, _ = http_api
        with pytest.raises(ApiError) as err:
            api.describe_launch_template("missing-template")
        assert is_not_found(err.value)


class TestRestartIdempotency:
    """Crash-consistent launches (ISSUE 2): ClientTokens derive from the
    logical call's content, so a RESTARTED controller re-issuing the same
    call is a server-side no-op — strictly stronger than the per-call retry
    reuse TestRetry covers."""

    _OK_LAUNCH_TEMPLATE = HttpResponse(
        200,
        b'<CreateLaunchTemplateResponse xmlns="http://ec2.amazonaws.com/doc/'
        b'2016-11-15/"><launchTemplate>'
        b"<launchTemplateName>karpenter-lt</launchTemplateName>"
        b"<launchTemplateId>lt-0abc</launchTemplateId>"
        b"</launchTemplate></CreateLaunchTemplateResponse>",
    )

    def _template(self):
        from karpenter_tpu.cloudprovider.ec2.api import LaunchTemplate

        return LaunchTemplate(name="karpenter-lt", image_id="ami-1")

    def test_create_launch_template_retry_reuses_one_client_token(self):
        """Regression for the satellite: a retried CreateLaunchTemplate must
        re-send the IDENTICAL token (one body per logical call), matching
        the CreateFleet contract."""
        api = recorded_api(
            HttpResponse(500, b""),
            self._OK_LAUNCH_TEMPLATE,
            retry_policy=RetryPolicy(sleep=lambda _s: None),
        )
        api.create_launch_template(self._template())
        tokens = [
            _params(body).get("ClientToken")
            for _m, _u, _h, body in api.transport.sent
        ]
        assert len(tokens) == 2 and tokens[0] == tokens[1] and tokens[0]

    def test_create_launch_template_token_survives_process_restart(self):
        """Two independent api instances (a controller before and after a
        crash) ensuring the same template derive the SAME token, so the
        second create is a server-side no-op instead of AlreadyExists."""
        tokens = []
        for _ in range(2):
            api = recorded_api(self._OK_LAUNCH_TEMPLATE)
            api.create_launch_template(self._template())
            tokens.append(_params(api.transport.sent[0][3])["ClientToken"])
        assert tokens[0] == tokens[1]
        # ...and a DIFFERENT template content derives a different token.
        from karpenter_tpu.cloudprovider.ec2.api import LaunchTemplate

        api = recorded_api(self._OK_LAUNCH_TEMPLATE)
        other = LaunchTemplate(name="karpenter-lt", image_id="ami-2")
        api.create_launch_template(other)
        assert _params(api.transport.sent[0][3])["ClientToken"] != tokens[0]

    def test_create_fleet_forwards_caller_token_verbatim(self):
        ok_fleet = HttpResponse(
            200,
            b'<CreateFleetResponse xmlns="http://ec2.amazonaws.com/doc/'
            b'2016-11-15/"><fleetInstanceSet/><errorSet/>'
            b"</CreateFleetResponse>",
        )
        api = recorded_api(ok_fleet)
        api.create_fleet(
            FleetRequest(
                launch_template_name="lt",
                capacity_type="on-demand",
                quantity=1,
                overrides=[],
                client_token="ktpu-deadbeef",
            )
        )
        assert (
            _params(api.transport.sent[0][3])["ClientToken"] == "ktpu-deadbeef"
        )

    def test_derive_client_token_is_stable_and_bounded(self):
        from karpenter_tpu.cloudprovider.ec2.aws_http import derive_client_token

        token = derive_client_token("CreateFleet", "cluster", "batch", "0")
        assert token == derive_client_token("CreateFleet", "cluster", "batch", "0")
        assert token != derive_client_token("CreateFleet", "cluster", "batch", "1")
        assert len(token) <= 64  # the EC2 ClientToken budget

    def test_describe_instances_by_tag_encodes_filters_and_parses_instance(self):
        api = recorded_api(
            HttpResponse(
                200,
                b'<DescribeInstancesResponse xmlns="http://ec2.amazonaws.com/'
                b'doc/2016-11-15/"><reservationSet><item><instancesSet><item>'
                b"<instanceId>i-leak</instanceId>"
                b"<instanceType>m5.large</instanceType>"
                b"<placement><availabilityZone>us-test-1a</availabilityZone>"
                b"</placement>"
                b"<launchTime>2026-08-02T10:00:00Z</launchTime>"
                b"<tagSet><item><key>karpenter.tpu/cluster/c</key>"
                b"<value>owned</value></item></tagSet>"
                b"</item></instancesSet></item></reservationSet>"
                b"</DescribeInstancesResponse>",
            )
        )
        instances = api.describe_instances_by_tag(
            {"karpenter.tpu/cluster/c": "owned"}
        )
        params = _params(api.transport.sent[0][3])
        assert params["Filter.1.Name"] == "tag:karpenter.tpu/cluster/c"
        assert params["Filter.1.Value.1"] == "owned"
        (instance,) = instances
        assert instance.instance_id == "i-leak"
        assert instance.tags == {"karpenter.tpu/cluster/c": "owned"}
        assert instance.launched_at > 0

    def test_retries_are_counted_by_action_and_code(self):
        from karpenter_tpu.cloudprovider.ec2.aws_http import AWS_RETRY_TOTAL

        before = AWS_RETRY_TOTAL.get("DescribeInstances", "HTTP500")
        api = recorded_api(
            HttpResponse(500, b"<html>internal"),
            _OK_DESCRIBE,
            retry_policy=RetryPolicy(sleep=lambda _s: None),
        )
        api.describe_instances(["i-1"])
        assert AWS_RETRY_TOTAL.get("DescribeInstances", "HTTP500") - before == 1


class TestInterruptionFeedOverWire(_suite.TestInterruptionFeed):
    """The interruption feed through real SQS JSON-RPC bytes: signed
    ReceiveMessage/DeleteMessage requests against the wire fake's queue."""


class TestCrashConsistentLaunchOverWire(_suite.TestCrashConsistentLaunch):
    """The restart-idempotency + GC-listing scenarios through SigV4-signed
    Query-API bytes: deterministic ClientTokens survive the wire, the fleet
    replay honors them server-side, and the by-tag DescribeInstances sweep
    round-trips tags for the ownership join."""
