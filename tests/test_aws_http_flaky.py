"""The ENTIRE EC2 provider suite re-run over a FLAKY wire: every other HTTP
request is answered with a rotating throttle / 5xx / empty-body / socket
fault before reaching the wire fake. With the binding's retryer
(aws_http.RetryPolicy) this must stay green — the operational guarantee the
reference inherits from the SDK's DefaultRetryer
(ref: pkg/cloudprovider/aws/cloudprovider.go:67-69).
"""

import pytest

from tests import test_ec2 as _suite
from tests.wire_fake import wire_api


@pytest.fixture(autouse=True)
def _flaky_wire_backend(monkeypatch):
    # period=2: literally half of all wire requests fail first try.
    monkeypatch.setattr(
        _suite, "make_api", lambda: wire_api(page_size=4, flaky_period=2)
    )


class TestVendorExtensionFlaky(_suite.TestVendorExtension):
    pass


class TestInstanceTypeAdaptationFlaky(_suite.TestInstanceTypeAdaptation):
    pass


class TestDiscoveryFlaky(_suite.TestDiscovery):
    pass


class TestLaunchTemplatesFlaky(_suite.TestLaunchTemplates):
    pass


class TestFleetLaunchFlaky(_suite.TestFleetLaunch):
    pass


class TestInsufficientCapacityFlaky(_suite.TestInsufficientCapacity):
    pass


class TestTerminateFlaky(_suite.TestTerminate):
    pass


class TestEndToEndFlaky(_suite.TestEndToEnd):
    pass


class TestPoolPinnedLaunchFlaky(_suite.TestPoolPinnedLaunch):
    pass
