"""Battletest: the full threaded Manager under randomized churn.

Ref: the reference's `make battletest` runs its suites under the Go race
detector with randomized parallel specs (/root/reference/Makefile:33-38).
Python has no -race; what this runtime CAN be held to is the same class of
invariant under the same class of load: every thread of the real Manager
(watch pumps, selection/provisioning/termination/node loops, batch thread,
eviction pump, parallel bind fan-out) running against the apiserver-backed
store while a seeded adversary churns pods/nodes/provisioners, severs watch
connections, and compacts watch history (forcing the 410 re-list path under
load). Afterwards: conservation invariants (tests/test_replay.py), informer
cache vs apiserver-store coherence, zero non-conflict reconcile exceptions,
and a clean bounded shutdown.

The churn scenario runs once per solver: "greedy" (reference-parity
packer) and "cost" (the full cost engine — column-LP mix, adaptive host
dispatch, candidate scoring — in the single-chip production config).

Run via `make battletest` (KARPENTER_BATTLETEST=1); skipped in the normal
suite to keep it fast. KARPENTER_BATTLETEST_SECONDS / _SEED tune the run.
"""

import logging
import os
import random
import threading
import time

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import Provisioner, ProvisionerSpec
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.kubeapi import ApiError, ApiServerCluster, KubeClient
from karpenter_tpu.runtime import Manager
from karpenter_tpu.utils.options import Options

from tests.fake_apiserver import DirectTransport, FakeApiServer

pytestmark = pytest.mark.skipif(
    os.environ.get("KARPENTER_BATTLETEST") != "1",
    reason="battletest: run via `make battletest` (KARPENTER_BATTLETEST=1)",
)

# 15s default: the 6s run never surfaced the stale-replay resurrection,
# bind-404, or orphaned-pod classes that a 30s soak caught — churn volume
# matters. KARPENTER_BATTLETEST_SECONDS raises it further for soaks.
DURATION_S = float(os.environ.get("KARPENTER_BATTLETEST_SECONDS", "15"))
SEED = int(os.environ.get("KARPENTER_BATTLETEST_SEED", str(int(time.time()))))


class _ExceptionCollector(logging.Handler):
    """Captures reconcile-loop exceptions (ReconcileLoop logs them with
    exc_info). Write conflicts (409) are legitimate under churn — optimistic
    concurrency retried by requeue — anything else is a bug."""

    def __init__(self):
        super().__init__(level=logging.ERROR)
        self.failures = []

    def emit(self, record):
        error = record.exc_info[1] if record.exc_info else None
        if isinstance(error, ApiError) and error.status == 409:
            return
        self.failures.append(
            f"{record.name}: {record.getMessage()} ({error!r})"
        )


class TestLeaderFailoverMidStorm:
    def test_rival_takes_over_and_finishes_the_storm(self):
        """Two controller replicas share one apiserver: the leader dies
        (stops renewing WITHOUT releasing — a crash, not a clean handoff)
        mid-storm; the rival must CAS-acquire the expired Lease and drain
        the remainder. Covers lease-expiry semantics over the apiserver
        backend under real load (ref: cmd/controller/main.go:80-81
        exit-on-lost-lease + controller-runtime leader election)."""
        from karpenter_tpu.runtime import LeaderElector

        apiserver = FakeApiServer(history_limit=65536)

        def make_replica(identity):
            cluster = ApiServerCluster(
                KubeClient(DirectTransport(apiserver), qps=1e9, burst=10**9)
            ).start()
            manager = Manager(
                cluster,
                FakeCloudProvider(),
                Options(cluster_name="failover", solver="greedy",
                        leader_election=False),
            )
            elector = LeaderElector(cluster, identity)
            return cluster, manager, elector

        cluster_a = manager_a = elector_a = None
        cluster_b = manager_b = elector_b = None
        try:
            cluster_a, manager_a, elector_a = make_replica("replica-a")
            cluster_b, manager_b, elector_b = make_replica("replica-b")
            assert elector_a.acquire(blocking=False)
            assert not elector_b.try_acquire()  # lease held by a
            cluster_a.apply_provisioner(Provisioner(name="failover"))
            manager_a.start()
            num_pods = 6000  # three 2000-pod batches: can't finish pre-crash
            for i in range(num_pods):
                cluster_a.apply_pod(
                    PodSpec(name=f"fo-{i}", unschedulable=True,
                            requests={"cpu": "100m", "memory": "128Mi"})
                )

            def bound(cluster):
                return sum(
                    1 for p in cluster.list_pods() if p.node_name is not None
                )

            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline and bound(cluster_a) < 500:
                time.sleep(0.05)
            at_crash = bound(cluster_a)
            assert at_crash >= 500, "leader never started draining"

            # CRASH the leader: stop reconciling and renewing, no release.
            manager_a.stop()
            elector_a._stop.set()
            # The crash must land MID-storm or the failover-drain assertion
            # below is vacuous (a regression where the rival can't resume
            # provisioning would still pass).
            assert at_crash < num_pods, (
                f"storm finished ({at_crash} bound) before the crash — "
                "raise num_pods to keep the failover meaningful"
            )

            # The rival campaigns; it must win only after the TTL expires.
            campaign_deadline = time.monotonic() + LeaderElector.LEASE_SECONDS + 10
            won = False
            while time.monotonic() < campaign_deadline:
                if elector_b.try_acquire():
                    won = True
                    break
                time.sleep(0.5)
            assert won, "rival never acquired the expired lease"
            # Production shape: hold the lease WITH the renew loop running
            # while draining (cmd/controller wiring uses acquire()).
            assert elector_b.acquire(blocking=False)
            manager_b.start()

            drain_deadline = time.monotonic() + 90.0
            while time.monotonic() < drain_deadline:
                if bound(cluster_b) >= num_pods:
                    break
                time.sleep(0.2)
            assert bound(cluster_b) >= num_pods, (
                f"storm did not finish after failover: {bound(cluster_b)}"
                f"/{num_pods} bound"
            )
            assert elector_b.is_leader.is_set(), (
                "replica-b lost the lease while draining (renewal broken?)"
            )
            print(
                f"failover OK: replica-b drained the remaining "
                f"{num_pods - at_crash} pods holding a renewed lease"
            )
        finally:
            for manager in (manager_a, manager_b):
                if manager is not None:
                    manager.stop()
            for elector in (elector_a, elector_b):
                if elector is not None:
                    elector.release()
            for cluster in (cluster_a, cluster_b):
                if cluster is not None:
                    cluster.close()


class TestBattletest:
    @pytest.mark.parametrize("solver_name", ["greedy", "cost"])
    def test_manager_survives_randomized_churn(self, solver_name, monkeypatch):
        # The cost variant drives the FULL cost engine (column-LP mix,
        # adaptive host dispatch, candidate scoring) under the same churn;
        # KARPENTER_SHARDED_SOLVE=0 pins the single-chip production config
        # so no jit compile races the churn window on the CPU test mesh.
        if solver_name == "cost":
            monkeypatch.setenv("KARPENTER_SHARDED_SOLVE", "0")
            monkeypatch.delenv("KARPENTER_HOST_SOLVE", raising=False)
        print(f"\nbattletest seed={SEED} duration={DURATION_S}s solver={solver_name}")
        rng = random.Random(SEED)
        apiserver = FakeApiServer(history_limit=2048)
        cluster = ApiServerCluster(
            KubeClient(DirectTransport(apiserver), qps=1e9, burst=10**9)
        ).start()
        manager = Manager(
            cluster,
            FakeCloudProvider(),
            Options(cluster_name="battle", solver=solver_name,
                    leader_election=False),
        )
        collector = _ExceptionCollector()
        logging.getLogger().addHandler(collector)
        counter = [0]

        def next_name(prefix):
            counter[0] += 1
            return f"{prefix}-{counter[0]}"

        def churn_once():
            roll = rng.random()
            if roll < 0.55:  # pod storm pressure
                annotations = {}
                if rng.random() < 0.05:
                    # Drain blockers: the terminator must pause whole-node
                    # drains behind these without wedging anything else.
                    annotations[wellknown.DO_NOT_EVICT_ANNOTATION] = "true"
                cluster.apply_pod(
                    PodSpec(
                        name=next_name("battle-pod"),
                        unschedulable=True,
                        labels={"battle/app": f"app-{rng.randrange(4)}"},
                        annotations=annotations,
                        requests={
                            "cpu": f"{rng.choice([100, 250, 500, 1000])}m",
                            "memory": f"{rng.choice([128, 256, 512])}Mi",
                        },
                    )
                )
            elif roll < 0.70:  # random pod deletion (incl. bound pods)
                pods = cluster.list_pods()
                if pods:
                    victim = rng.choice(pods)
                    try:
                        cluster.delete_pod(victim.namespace, victim.name)
                    except ApiError:
                        pass  # raced with another deletion
            elif roll < 0.80:  # kubelet heartbeats: mark nodes ready
                for node in cluster.list_nodes():
                    node.ready = True
                    node.status_reported_at = cluster.clock.now()
                    try:
                        cluster.update_node(node)
                    except ApiError:
                        pass
            elif roll < 0.88:  # node deletion -> finalizer-driven teardown
                nodes = [
                    n for n in cluster.list_nodes()
                    if n.labels.get(wellknown.PROVISIONER_NAME_LABEL)
                ]
                if nodes:
                    try:
                        cluster.delete_node(rng.choice(nodes).name)
                    except ApiError:
                        pass
            elif roll < 0.92:  # provisioner spec churn
                spec = ProvisionerSpec()
                spec.labels = {"battle/epoch": next_name("epoch")}
                cluster.apply_provisioner(Provisioner(name="battle", spec=spec))
            elif roll < 0.94:  # PDBs gate evictions; daemonsets change
                # per-node overhead mid-flight — both must hold up under
                # concurrent solves and drains.
                if rng.random() < 0.5:
                    cluster.apply_pdb(
                        f"battle-pdb-{rng.randrange(2)}",
                        {"battle/app": f"app-{rng.randrange(4)}"},
                        min_available=rng.randrange(3),
                    )
                else:
                    cluster.apply_daemonset(
                        f"battle-ds-{rng.randrange(2)}",
                        PodSpec(
                            name="battle-ds",
                            requests={
                                "cpu": f"{rng.choice([50, 100])}m",
                                "memory": "64Mi",
                            },
                        ),
                    )
            elif roll < 0.985:  # sever every watch stream mid-flight
                apiserver.drop_watch_connections()
            else:  # compact history too: reconnects must take the 410 re-list
                apiserver.drop_watch_connections()
                apiserver.expire_history()

        try:
            cluster.apply_provisioner(Provisioner(name="battle"))
            manager.start()
            deadline = time.monotonic() + DURATION_S
            while time.monotonic() < deadline:
                churn_once()
                time.sleep(rng.uniform(0.0, 0.004))

            # --- quiesce: every surviving unschedulable pod gets a node,
            # and every orphan (bound to a node deleted mid-bind) is reaped
            # by the podgc sweep (two sightings, 10s apart) ------------------
            def unbound():
                return [
                    p for p in cluster.list_pods()
                    if p.unschedulable and p.node_name is None
                    and p.deletion_timestamp is None
                ]

            def orphaned():
                node_names = {n.name for n in cluster.list_nodes()}
                return [
                    p for p in cluster.list_pods()
                    if p.node_name is not None
                    and p.deletion_timestamp is None
                    and p.node_name not in node_names
                ]

            quiesce_deadline = time.monotonic() + 60.0
            while time.monotonic() < quiesce_deadline:
                for node in cluster.list_nodes():  # keep heartbeats flowing
                    if not node.ready:
                        node.ready = True
                        node.status_reported_at = cluster.clock.now()
                        try:
                            cluster.update_node(node)
                        except ApiError:
                            pass
                if not unbound() and not orphaned():
                    break
                time.sleep(0.05)
            remaining = unbound()
            assert not remaining, (
                f"seed {SEED}: {len(remaining)} pods never scheduled, e.g. "
                f"{[p.name for p in remaining[:5]]}"
            )
            still_orphaned = orphaned()
            assert not still_orphaned, (
                f"seed {SEED}: {len(still_orphaned)} orphaned pods survived "
                f"podgc, e.g. {[p.name for p in still_orphaned[:5]]}"
            )

            # --- conservation invariants (tests/test_replay.py) ------------
            nodes = {n.name: n for n in cluster.list_nodes()}
            for pod in cluster.list_pods():
                if pod.node_name is not None and pod.deletion_timestamp is None:
                    assert pod.node_name in nodes, (
                        f"seed {SEED}: {pod.name} bound to missing node "
                        f"{pod.node_name}"
                    )
            for node in nodes.values():
                if node.labels.get(wellknown.PROVISIONER_NAME_LABEL):
                    assert wellknown.TERMINATION_FINALIZER in node.finalizers, (
                        f"seed {SEED}: node {node.name} lost its finalizer"
                    )

            # --- informer cache coheres with the apiserver store -----------
            # (the watch plane took drops and 410 compactions mid-churn; a
            # wedged or stale cache shows up as a set difference here)
            # Both sides sampled with the SAME membership rule (terminating
            # objects included — evicted pods stay terminating forever in
            # the fake, which has no kubelet to reap them, and the cache
            # must mirror that state too).
            def stable_names(kind, lister):
                while True:
                    live = {o["metadata"]["name"]
                            for o in apiserver._collection(kind).values()}
                    time.sleep(0.3)
                    cached = {obj.name for obj in lister()}
                    again = {o["metadata"]["name"]
                             for o in apiserver._collection(kind).values()}
                    if live == again:  # store quiet between samples
                        return live, cached

            live_pods, cached_pods = stable_names("pods", cluster.list_pods)
            assert cached_pods == live_pods, (
                f"seed {SEED}: informer pod cache diverged: "
                f"missing={sorted(live_pods - cached_pods)[:5]} "
                f"stale={sorted(cached_pods - live_pods)[:5]}"
            )

            assert not collector.failures, (
                f"seed {SEED}: non-conflict reconcile exceptions:\n  "
                + "\n  ".join(collector.failures[:10])
            )
        finally:
            logging.getLogger().removeHandler(collector)
            stop_started = time.monotonic()
            manager.stop()
            cluster.close()
            for loop in manager.loops.values():
                for thread in loop._threads:
                    thread.join(timeout=5.0)
                    assert not thread.is_alive(), (
                        f"seed {SEED}: {thread.name} did not stop"
                    )
            shutdown_s = time.monotonic() - stop_started
            assert shutdown_s < 10.0, f"shutdown took {shutdown_s:.1f}s"
            # NOTE: shutdown checks run in finally, so reaching here does not
            # mean the churn assertions passed — only pytest's verdict does.
            print(
                f"battletest shutdown clean: seed={SEED} pods={counter[0]} "
                f"shutdown={shutdown_s:.2f}s"
            )
