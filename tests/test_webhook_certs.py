"""Webhook cert self-provisioning + rotation + caBundle injection
(utils/certs.py). Ref: cmd/webhook/main.go:44-62 — knative's certificate
controller generates/rotates the serving cert and injects the CA bundle;
these tests hold the rebuilt behavior to that contract."""

import datetime
import json
import ssl
import urllib.request

import pytest

# Cert generation (utils/certs.py) and these assertions both need the
# cryptography package, which the minimal image may not carry — an
# environmental gap, not a regression, so skip with a reason instead of
# failing the suite.
pytest.importorskip(
    "cryptography", reason="cryptography not installed (environmental)"
)

from karpenter_tpu.utils.certs import (
    MUTATING_WEBHOOK_NAME,
    VALIDATING_WEBHOOK_NAME,
    CertManager,
    generate_self_signed,
    inject_ca_bundle,
)


class TestGenerateSelfSigned:
    def test_cert_carries_sans_and_validity(self):
        cert_pem, key_pem = generate_self_signed(
            "svc.ns.svc", ["svc.ns.svc", "svc.ns.svc.cluster.local", "127.0.0.1"],
            lifetime=datetime.timedelta(days=30),
        )
        from cryptography import x509

        cert = x509.load_pem_x509_certificate(cert_pem)
        sans = cert.extensions.get_extension_for_class(x509.SubjectAlternativeName)
        names = sans.value.get_values_for_type(x509.DNSName)
        assert "svc.ns.svc" in names and "svc.ns.svc.cluster.local" in names
        ips = sans.value.get_values_for_type(x509.IPAddress)
        assert [str(ip) for ip in ips] == ["127.0.0.1"]
        lifetime = cert.not_valid_after_utc - cert.not_valid_before_utc
        assert datetime.timedelta(days=29) < lifetime < datetime.timedelta(days=31)
        assert b"PRIVATE KEY" in key_pem

    def test_key_loads_with_cert(self, tmp_path):
        cert_pem, key_pem = generate_self_signed("x")
        (tmp_path / "tls.crt").write_bytes(cert_pem)
        (tmp_path / "tls.key").write_bytes(key_pem)
        context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        context.load_cert_chain(
            str(tmp_path / "tls.crt"), str(tmp_path / "tls.key")
        )  # raises on mismatch


class _ManualClock:
    def __init__(self):
        self.now = datetime.datetime(2026, 1, 1, tzinfo=datetime.timezone.utc)

    def __call__(self):
        return self.now


class TestCertManager:
    def test_ensure_provisions_once(self, tmp_path):
        clock = _ManualClock()
        manager = CertManager("cn", cert_dir=str(tmp_path), clock=clock)
        cert_path, key_path = manager.ensure()
        first = open(cert_path, "rb").read()
        manager.ensure()  # fresh cert: no regeneration
        assert open(cert_path, "rb").read() == first
        assert manager.ca_bundle_b64()

    def test_rotates_when_lifetime_mostly_spent(self, tmp_path):
        clock = _ManualClock()
        manager = CertManager(
            "cn", cert_dir=str(tmp_path),
            lifetime=datetime.timedelta(days=10), clock=clock,
        )
        manager.ensure()
        first = manager.ca_bundle_b64()
        assert not manager.due_for_rotation()
        clock.now += datetime.timedelta(days=7)
        assert not manager.due_for_rotation()  # 30% remaining: not yet
        clock.now += datetime.timedelta(days=2)  # 10% remaining
        assert manager.due_for_rotation()
        rotated_bundles = []
        manager.on_rotate = rotated_bundles.append
        assert manager.rotate_if_due()
        assert manager.ca_bundle_b64() != first
        assert rotated_bundles == [manager.ca_bundle_b64()]
        assert not manager.due_for_rotation()

    def test_rotation_hot_reloads_registered_context(self, tmp_path):
        clock = _ManualClock()
        manager = CertManager(
            "127.0.0.1", cert_dir=str(tmp_path),
            lifetime=datetime.timedelta(days=10), clock=clock,
        )
        cert_path, key_path = manager.ensure()
        context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        context.load_cert_chain(cert_path, key_path)
        manager.register_context(context)
        clock.now += datetime.timedelta(days=9, hours=12)
        assert manager.rotate_if_due()  # load_cert_chain on the live context


class _StubKube:
    """Records get/update; serves canned webhook-configuration objects."""

    def __init__(self, objects):
        self.objects = objects
        self.updates = []

    def try_get(self, path):
        return self.objects.get(path)

    def update(self, path, obj):
        self.objects[path] = obj
        self.updates.append(path)
        return obj


def _webhook_config(name, ca=""):
    return {
        "metadata": {"name": name},
        "webhooks": [
            {"name": name, "clientConfig": {"caBundle": ca, "service": {"name": "s"}}}
        ],
    }


MUTATING_PATH = (
    "/apis/admissionregistration.k8s.io/v1/mutatingwebhookconfigurations/"
    + MUTATING_WEBHOOK_NAME
)
VALIDATING_PATH = (
    "/apis/admissionregistration.k8s.io/v1/validatingwebhookconfigurations/"
    + VALIDATING_WEBHOOK_NAME
)


class TestInjectCaBundle:
    def test_writes_bundle_into_both_configurations(self):
        kube = _StubKube(
            {
                MUTATING_PATH: _webhook_config(MUTATING_WEBHOOK_NAME),
                VALIDATING_PATH: _webhook_config(VALIDATING_WEBHOOK_NAME),
            }
        )
        assert inject_ca_bundle(kube, "Q0E=") == 2
        for path in (MUTATING_PATH, VALIDATING_PATH):
            webhook = kube.objects[path]["webhooks"][0]
            assert webhook["clientConfig"]["caBundle"] == "Q0E="
            # Sibling fields survive (read-modify-write, not merge-patch).
            assert webhook["clientConfig"]["service"] == {"name": "s"}

    def test_idempotent_and_missing_config_skipped(self):
        kube = _StubKube(
            {MUTATING_PATH: _webhook_config(MUTATING_WEBHOOK_NAME, ca="Q0E=")}
        )
        assert inject_ca_bundle(kube, "Q0E=") == 0  # same bundle: no write
        assert kube.updates == []


class TestFlagParsing:
    def test_bare_boolean_flag_does_not_eat_next_flag(self):
        from karpenter_tpu.cmd.webhook import _extract_flag

        argv = ["--tls-self-signed", "--cluster-store", "incluster"]
        assert _extract_flag(argv, "tls-self-signed") == ""  # bare = true
        assert argv == ["--cluster-store", "incluster"]

    def test_flag_value_forms(self):
        from karpenter_tpu.cmd.webhook import _extract_flag

        argv = ["--port=18450", "--tls-dns-names", "a,b"]
        assert _extract_flag(argv, "port") == "18450"
        assert _extract_flag(argv, "tls-dns-names") == "a,b"
        assert _extract_flag(argv, "missing") is None


class TestSelfSignedServing:
    def test_webhook_self_provisions_and_serves_https(self):
        """The chart's no-secret default: --tls-self-signed provisions the
        cert and the apiserver-shaped AdmissionReview call succeeds over
        HTTPS against the generated CA."""
        from karpenter_tpu.api.provisioner import Provisioner, ProvisionerSpec
        from karpenter_tpu.api.serialization import provisioner_to_dict
        from karpenter_tpu.cmd.webhook import main as webhook_main

        server = webhook_main(
            [
                "--cluster-name", "test",
                "--tls-self-signed", "true",
                "--tls-dns-names", "127.0.0.1,localhost",
            ],
            port=18447,
            block=False,
        )
        try:
            manager = server.cert_manager
            context = ssl.create_default_context(cafile=manager.cert_path)
            # SAN is 127.0.0.1: hostname verification included.
            obj = provisioner_to_dict(
                Provisioner(name="default", spec=ProvisionerSpec())
            )
            review = {
                "apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview",
                "request": {"uid": "u1", "object": obj},
            }
            request = urllib.request.Request(
                "https://127.0.0.1:18447/validate",
                data=json.dumps(review).encode(),
                method="POST",
            )
            with urllib.request.urlopen(request, context=context) as resp:
                payload = json.loads(resp.read())
            assert payload["response"]["allowed"] is True
        finally:
            manager.stop()
            server.shutdown()
