"""A simulated kubelet fleet — the kwok-style node-lifecycle harness.

Large clusters with realistic node lifecycles have to fit in CI: every
controller test so far flipped ``node.ready`` / ``status_reported_at`` by
hand, which exercises none of the heartbeat plumbing and cannot express a
node that *misbehaves*. This fleet plays the kubelet side of the protocol
against either store backend through the ordinary Cluster verbs, clock-
driven and threadless (tests and smokes call ``step()`` like chaos_smoke's
``nudge``):

- **join**: the first heartbeat stamps ``status_reported_at`` and flips the
  node Ready (``Cluster.heartbeat_node`` — a status-only write on the
  apiserver backend, exactly the patch a real kubelet's status loop issues);
- **heartbeats**: every beat refreshes the stamp while the kubelet is alive;
- **pod-ready transitions**: pods bound to the node are acknowledged as
  running on the following beat;
- **eviction handling**: a pod the controllers marked terminating
  (deletionTimestamp set) is completed — deleted — by its node's kubelet,
  the role the real kubelet plays in an eviction.

Per-node misbehavior is drawn from the ``kubelet.*`` faultpoints
(utils/faultpoints.py), so a storm armed after ``faultpoints.seed(n)``
replays bit-identically:

- ``kubelet.register``: ``drop`` = never-join, ``delay`` = slow-join,
  ``zombie`` = after its node is DELETED the kubelet re-registers under the
  old name with the dead incarnation's provider id — the adoption-defense
  prey (controllers/health.py must reject it);
- ``kubelet.heartbeat``: ``drop`` = the kubelet goes permanently dark
  mid-life (latched), ``flap`` = one beat reports NotReady then recovers;
- ``kubelet.pod-ready``: ``delay`` holds a pod's running acknowledgment;
- ``kubelet.eviction``: ``black-hole`` = the pod sticks terminating forever
  (latched per pod) — the stuck-drain breaker's prey.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.cloudprovider import NodeSpec
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.utils import faultpoints


class FakeKubelet:
    """One node's kubelet: behavior is drawn ONCE at adoption (first-winner
    semantics over the stacked ``kubelet.register`` faults gives each node
    at most one registration behavior), heartbeat/eviction faults roll per
    beat and latch where the physical failure would."""

    def __init__(
        self,
        cluster: Cluster,
        node: NodeSpec,
        slow_join_s: float = 2.0,
        heartbeat_interval_s: float = 0.0,
    ):
        self.cluster = cluster
        self.name = node.name
        # Status-loop period (fake seconds): 0 = report every step; storm
        # harnesses raise it so a 500-kubelet fleet doesn't issue 500 status
        # patches per beat (a real kubelet reports every ~10s, not per tick).
        self.heartbeat_interval_s = heartbeat_interval_s
        self._last_heartbeat: float = float("-inf")
        self.provider_id = node.provider_id
        self.labels = dict(node.labels)
        self.instance_type = node.instance_type
        self.zone = node.zone
        self.capacity = dict(node.capacity)
        self.capacity_type = node.capacity_type
        self.never_join = False
        self.zombie = False
        self.rejoined = False
        self.dark = False  # heartbeat-loss latched: permanently silent
        self.join_at = cluster.clock.now()
        fault = faultpoints.draw("kubelet.register")
        if fault is not None:
            if fault.kind == "drop":
                self.never_join = True
            elif fault.kind == "delay":
                self.join_at += fault.delay_s or slow_join_s
            elif fault.kind == "zombie":
                self.zombie = True
        self.joined = False
        # Pods acknowledged running; a pod-ready delay holds the ack a beat.
        self.running: Set[Tuple[str, str]] = set()
        self._ready_held: Set[Tuple[str, str]] = set()
        # Pods whose eviction this kubelet will never complete.
        self.black_holed: Set[Tuple[str, str]] = set()

    def step(self, now: float, pods: Optional[List] = None) -> None:
        """One kubelet tick. `pods` is an optional pre-indexed list of this
        node's pods (the fleet builds one index per step instead of letting
        500 kubelets each filter the full pod list)."""
        if self.never_join or self.dark:
            return
        if now < self.join_at:
            return  # slow-join: registration lands late
        node = self.cluster.try_get_node(self.name)
        if node is None:
            if self.zombie and self.joined and not self.rejoined:
                self._rejoin()
            elif not self.zombie:
                return
            node = self.cluster.try_get_node(self.name)
            if node is None:
                return  # rejoin rejected (or never attempted): stay dead
        if pods is None:
            pods = self.cluster.list_pods(node_name=self.name)
        if node.deletion_timestamp is not None:
            # A deleting node's kubelet keeps serving evictions (the drain
            # depends on it) but its heartbeats no longer matter.
            self._handle_evictions(pods)
            return
        if now - self._last_heartbeat >= self.heartbeat_interval_s:
            ready = True
            fault = faultpoints.draw("kubelet.heartbeat")
            if fault is not None:
                if fault.kind == "drop":
                    self.dark = True  # mid-life heartbeat loss: latched
                    return
                if fault.kind == "flap":
                    ready = False  # one NotReady beat; next beat recovers
            self.cluster.heartbeat_node(self.name, ready=ready)
            self.joined = True
            self._last_heartbeat = now
        if not self.joined:
            return  # first status report hasn't happened yet
        self._acknowledge_pods(pods)
        self._handle_evictions(pods)

    def _rejoin(self) -> None:
        """The zombie: its Node was deleted (instance terminated at the
        cloud) but the kubelet never got the memo and re-registers under the
        SAME name with the DEAD incarnation's provider id. The health
        controller must reject this instead of adopting it."""
        self.rejoined = True
        ghost = NodeSpec(
            name=self.name,
            provider_id=self.provider_id,
            labels=dict(self.labels),
            instance_type=self.instance_type,
            zone=self.zone,
            capacity=dict(self.capacity),
            capacity_type=self.capacity_type,
            ready=True,
        )
        try:
            self.cluster.create_node(ghost)
        except Exception:  # noqa: BLE001 — a 409 means the name was retaken
            return

    def _acknowledge_pods(self, pods: List) -> None:
        for pod in pods:
            key = (pod.namespace, pod.name)
            if key in self.running or pod.deletion_timestamp is not None:
                continue
            if key not in self._ready_held:
                fault = faultpoints.draw("kubelet.pod-ready")
                if fault is not None and fault.kind == "delay":
                    self._ready_held.add(key)  # ack on a later beat
                    continue
            self._ready_held.discard(key)
            self.running.add(key)

    def _handle_evictions(self, pods: List) -> None:
        """Complete evictions: the kubelet kills the container and the pod
        object goes away — unless this kubelet black-holes it."""
        for pod in pods:
            if pod.deletion_timestamp is None:
                continue
            key = (pod.namespace, pod.name)
            if key in self.black_holed:
                continue
            fault = faultpoints.draw("kubelet.eviction")
            if fault is not None and fault.kind == "black-hole":
                self.black_holed.add(key)  # stuck terminating forever
                continue
            self.running.discard(key)
            self.cluster.delete_pod(pod.namespace, pod.name)


class FakeKubeletFleet:
    """Adopts a kubelet for every managed node as it appears and beats the
    whole fleet once per ``step()``. Deleted nodes keep their kubelet object
    (a zombie needs it to rejoin); re-adoption is suppressed so a zombie's
    re-registration doesn't mint a fresh, well-behaved kubelet."""

    def __init__(
        self,
        cluster: Cluster,
        slow_join_s: float = 2.0,
        heartbeat_interval_s: float = 0.0,
    ):
        self.cluster = cluster
        self.slow_join_s = slow_join_s
        self.heartbeat_interval_s = heartbeat_interval_s
        self.kubelets: Dict[str, FakeKubelet] = {}

    def sync(self) -> None:
        for node in self.cluster.list_nodes():
            if node.name in self.kubelets:
                continue
            if wellknown.PROVISIONER_NAME_LABEL not in node.labels:
                continue  # foreign nodes bring their own kubelet
            self.kubelets[node.name] = FakeKubelet(
                self.cluster,
                node,
                slow_join_s=self.slow_join_s,
                heartbeat_interval_s=self.heartbeat_interval_s,
            )

    def step(self) -> None:
        self.sync()
        now = self.cluster.clock.now()
        by_node: Dict[str, List] = {}
        for pod in self.cluster.list_pods():
            if pod.node_name is not None:
                by_node.setdefault(pod.node_name, []).append(pod)
        for kubelet in list(self.kubelets.values()):
            kubelet.step(now, pods=by_node.get(kubelet.name, []))

    def kubelet(self, name: str) -> Optional[FakeKubelet]:
        return self.kubelets.get(name)

    # --- storm accounting ----------------------------------------------------

    def counts(self) -> Dict[str, int]:
        """Behavior census for storm logs/assertions."""
        return {
            "total": len(self.kubelets),
            "never_join": sum(1 for k in self.kubelets.values() if k.never_join),
            "dark": sum(1 for k in self.kubelets.values() if k.dark),
            "zombies": sum(1 for k in self.kubelets.values() if k.zombie),
            "rejoined": sum(1 for k in self.kubelets.values() if k.rejoined),
            "black_holed_pods": sum(
                len(k.black_holed) for k in self.kubelets.values()
            ),
        }
