"""Shim: the two backend-ownership invariants migrated into tools/vet as
proper checkers (jax-platforms-ownership, import-time-device-touch) when the
unified vet suite landed — see tools/vet/checkers/backend.py for the rules
and docs/design/vet.md for the catalog. This file keeps the historical test
names alive (external invocations, bisects) as thin calls into the
framework; tests/test_vet.py exercises the checkers' positive/negative
fixtures.
"""

from tools.vet import checker_findings


def _render(findings):
    return [finding.render() for finding in findings]


def test_only_backend_health_spells_jax_platforms():
    assert _render(checker_findings("jax-platforms-ownership")) == []


def test_no_import_time_device_touch():
    assert _render(checker_findings("import-time-device-touch")) == []
