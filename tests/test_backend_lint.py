"""Grep-lint: backend_health owns every backend decision.

Two invariants, enforced over the whole production tree (karpenter_tpu/
plus the driver entry files) so the copy-drifted probe/pin sites this PR
replaced can never grow back:

1. No module outside utils/backend_health.py uses the JAX_PLATFORMS env
   key (the env-trust bug behind r05's rc:124 lived in exactly such a
   site). Matched as the AST string literal, so docstrings/comments that
   merely mention the variable don't trip it — env reads/writes must spell
   the key as a literal to work at all.
2. No module calls jax.devices()/jax.device_count()/jax.local_devices()
   at import time: an import must never be the first device touch (a
   wedged tunnel would hang module import, before any probe can run).
"""

import ast
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SCOPE = sorted(
    list((REPO / "karpenter_tpu").rglob("*.py"))
    + [REPO / "__graft_entry__.py", REPO / "bench.py"]
)
OWNER = REPO / "karpenter_tpu" / "utils" / "backend_health.py"

DEVICE_TOUCHES = {"devices", "device_count", "local_devices"}


def test_only_backend_health_spells_jax_platforms():
    offenders = []
    for path in SCOPE:
        if path == OWNER:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and node.value == "JAX_PLATFORMS":
                offenders.append(f"{path.relative_to(REPO)}:{node.lineno}")
    assert not offenders, (
        "JAX_PLATFORMS is owned by utils/backend_health (ensure_backend/"
        f"pin_cpu); route these through it: {offenders}"
    )


def _import_time_nodes(tree):
    """Every AST node reachable while the module body executes — module and
    class bodies included, function/lambda bodies excluded."""
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def test_no_import_time_device_touch():
    offenders = []
    for path in SCOPE:
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in _import_time_nodes(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in DEVICE_TOUCHES
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "jax"
            ):
                offenders.append(f"{path.relative_to(REPO)}:{node.lineno}")
    assert not offenders, (
        "import-time device touch (hangs module import on a wedged tunnel); "
        f"move inside a function behind the BackendHealth verdict: {offenders}"
    )
