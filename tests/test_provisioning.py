"""Provisioning suite (ref: provisioning/suite_test.go:65-250): batch
provisioning, accelerators, limits, daemonset overhead, labels, taints."""

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import (
    Constraints,
    Limits,
    Provisioner,
    ProvisionerSpec,
)
from karpenter_tpu.api.requirements import Requirement, Requirements
from karpenter_tpu.api.taints import Taint, Toleration
from karpenter_tpu.controllers.provisioning import global_requirements, spec_hash

from tests import fixtures
from tests.harness import Harness


def default_provisioner(**kwargs) -> Provisioner:
    return Provisioner(name="default", spec=ProvisionerSpec(**kwargs))


class TestProvisioning:
    def test_batch_provisions_and_binds(self):
        h = Harness()
        h.apply_provisioner(default_provisioner())
        pods = fixtures.pods(10)
        h.provision(*pods)
        nodes = {h.expect_scheduled(p).name for p in pods}
        assert len(nodes) == 1  # all fit one default node
        node = h.cluster.get_node(next(iter(nodes)))
        assert node.labels[wellknown.PROVISIONER_NAME_LABEL] == "default"
        assert wellknown.TERMINATION_FINALIZER in node.finalizers
        assert any(t.key == wellknown.NOT_READY_TAINT_KEY for t in node.taints)

    def test_no_provisioner_no_schedule(self):
        h = Harness()
        pods = fixtures.pods(2)
        h.provision(*pods)
        for pod in pods:
            h.expect_not_scheduled(pod)

    def test_gpu_pod_gets_gpu_node(self):
        h = Harness()
        h.apply_provisioner(default_provisioner())
        pod = fixtures.pod(extra_requests={wellknown.RESOURCE_NVIDIA_GPU: 1.0})
        h.provision(pod)
        node = h.expect_scheduled(pod)
        assert node.instance_type == "nvidia-gpu-instance-type"

    def test_tpu_pod_gets_tpu_node(self):
        h = Harness()
        h.apply_provisioner(default_provisioner())
        pod = fixtures.pod(extra_requests={wellknown.RESOURCE_GOOGLE_TPU: 4.0})
        h.provision(pod)
        node = h.expect_scheduled(pod)
        assert node.instance_type == "tpu-instance-type"

    def test_limits_stop_launches(self):
        h = Harness()
        provisioner = default_provisioner(limits=Limits(resources={"cpu": "1"}))
        h.apply_provisioner(provisioner)
        first = fixtures.pods(1)
        h.provision(*first)
        h.expect_scheduled(first[0])
        # Counter has now recorded >= 1 cpu of capacity; the next launch must
        # be blocked (ref: provisioner.go:187-195).
        second = fixtures.pods(1)
        h.provision(*second)
        h.expect_not_scheduled(second[0])

    def test_daemonset_overhead_reserved(self):
        h = Harness(
            instance_types=[fixtures.cpu_instance("only", cpu=4, mem_gib=16)]
        )
        h.apply_provisioner(default_provisioner())
        h.cluster.apply_daemonset(
            "logging-agent", PodSpec(name="logger", requests={"cpu": "1"})
        )
        pods = fixtures.pods(6, cpu="1")  # 3 fit per node (4 - 1 daemon)
        h.provision(*pods)
        nodes = {h.expect_scheduled(p).name for p in pods}
        assert len(nodes) == 2

    def test_provisioner_labels_applied(self):
        h = Harness()
        h.apply_provisioner(
            default_provisioner(constraints=Constraints(labels={"team": "infra"}))
        )
        pod = fixtures.pod()
        h.provision(pod)
        node = h.expect_scheduled(pod)
        assert node.labels["team"] == "infra"

    def test_taints_require_toleration(self):
        h = Harness()
        h.apply_provisioner(
            default_provisioner(
                constraints=Constraints(taints=[Taint(key="dedicated", value="ml")])
            )
        )
        plain = fixtures.pod()
        tolerant = fixtures.pod(
            tolerations=[Toleration(key="dedicated", value="ml", effect="NoSchedule")]
        )
        h.provision(plain, tolerant)
        h.expect_not_scheduled(plain)
        h.expect_scheduled(tolerant)

    def test_zone_selector_honored(self):
        h = Harness()
        h.apply_provisioner(default_provisioner())
        pod = fixtures.pod(node_selector={wellknown.ZONE_LABEL: "test-zone-2"})
        h.provision(pod)
        node = h.expect_scheduled(pod)
        assert node.zone == "test-zone-2"

    def test_unschedulable_giant_left_pending(self):
        h = Harness()
        h.apply_provisioner(default_provisioner())
        giant = fixtures.pod(cpu="1000")
        h.provision(giant)
        h.expect_not_scheduled(giant)

    def test_bound_pods_filtered_from_batch(self):
        h = Harness()
        h.apply_provisioner(default_provisioner())
        pod = fixtures.pod()
        h.cluster.apply_pod(pod)
        h.selection.reconcile(pod.namespace, pod.name)
        # Pod gets bound out-of-band before the batch drains.
        pod.node_name = "elsewhere"
        for worker in h.provisioning.workers.values():
            stats = worker.provision()
            assert stats.scheduled_pods == 0


class TestProvisionerLifecycle:
    def test_requirements_refreshed_from_fleet(self):
        h = Harness()
        provisioner = h.apply_provisioner(default_provisioner())
        # The worker's effective copy carries the fleet-derived requirements;
        # the stored spec stays pristine so fleet drift can widen it again.
        worker = h.provisioning.worker("default")
        zones = worker.provisioner.spec.constraints.requirements.zones()
        assert zones == {"test-zone-1", "test-zone-2", "test-zone-3"}
        assert provisioner.spec.constraints.requirements.zones() is None

    def test_fleet_recovery_widens_envelope(self):
        # An offering that disappears (ICE blackout) and comes back must be
        # usable again — the requirements refresh can't ratchet.
        h = Harness()
        h.apply_provisioner(default_provisioner())
        h.cloud.cache_unavailable("small-instance-type", "test-zone-1", "spot")
        h.cloud.cache_unavailable("small-instance-type", "test-zone-1", "on-demand")
        h.provisioning.reconcile("default")
        h.clock.advance(60)  # blackout expires
        h.provisioning.reconcile("default")
        worker = h.provisioning.worker("default")
        allowed = worker.provisioner.spec.constraints.requirements.zones()
        assert "test-zone-1" in allowed

    def test_spec_hash_change_restarts_worker(self):
        h = Harness()
        provisioner = h.apply_provisioner(default_provisioner())
        worker1 = h.provisioning.worker("default")
        h.provisioning.reconcile("default")
        assert h.provisioning.worker("default") is worker1  # unchanged spec
        provisioner.spec.constraints.labels["team"] = "infra"
        h.cluster.apply_provisioner(provisioner)
        h.provisioning.reconcile("default")
        assert h.provisioning.worker("default") is not worker1

    def test_delete_stops_worker(self):
        h = Harness()
        h.apply_provisioner(default_provisioner())
        assert h.provisioning.worker("default") is not None
        h.cluster.delete_provisioner("default")
        h.provisioning.reconcile("default")
        assert h.provisioning.worker("default") is None

    def test_global_requirements_union(self):
        reqs = global_requirements(fixtures.default_catalog())
        assert "arm64" in reqs.architectures()
        assert "amd64" in reqs.architectures()
        assert reqs.capacity_types() == {"on-demand", "spot"}

    def test_batching_window(self):
        h = Harness()
        h.apply_provisioner(default_provisioner())
        worker = h.provisioning.worker("default")
        pod = fixtures.pod()
        h.cluster.apply_pod(pod)
        worker.add(pod)
        assert not worker.batch_ready()  # window still open
        h.clock.advance(1.1)  # idle > 1s
        assert worker.batch_ready()

    def test_batching_max_window(self):
        h = Harness()
        h.apply_provisioner(default_provisioner())
        worker = h.provisioning.worker("default")
        for i in range(20):
            pod = fixtures.pod()
            h.cluster.apply_pod(pod)
            worker.add(pod)
            h.clock.advance(0.6)  # keeps idle window open
        assert worker.batch_ready()  # 10s max window exceeded


class TestCapacityFeedback:
    def test_later_schedule_resolved_after_capacity_failure(self):
        """Schedules solve as one batch against a pre-launch snapshot; when an
        earlier schedule's launch hits insufficient capacity (blacking out its
        pools), later schedules must be re-solved against fresh instance
        types or they retry the exhausted pools (ref: the sequential loop's
        implicit feedback via aws/instancetypes.go:174-183)."""
        from karpenter_tpu.models.solver import CostSolver

        # A is cheap and the obvious pick; B costs >1.3x so the cost plan's
        # pool rows never include it as a fallback row for an A-packed node.
        type_a = fixtures.cpu_instance("type-a", cpu=8, mem_gib=16, price=0.10)
        type_b = fixtures.cpu_instance("type-b", cpu=8, mem_gib=16, price=0.24)
        h = Harness(instance_types=[type_a, type_b], solver=CostSolver())
        h.apply_provisioner(default_provisioner())
        # Exhaust every type-a pool in zone 1 before the pass.
        for capacity_type in ("on-demand", "spot"):
            h.cloud.insufficient_capacity_pools.add(
                ("type-a", "test-zone-1", capacity_type)
            )

        # Schedule 1: pinned to type-a in zone-1 — its launch must fail and
        # black out the pools. Schedule 2: zone-1, free choice of type.
        probe = fixtures.pod(
            node_selector={
                wellknown.INSTANCE_TYPE_LABEL: "type-a",
                wellknown.ZONE_LABEL: "test-zone-1",
            }
        )
        followers = fixtures.pods(
            6, cpu="1", memory="1Gi",
            node_selector={wellknown.ZONE_LABEL: "test-zone-1"},
        )
        h.provision(probe, *followers)

        h.expect_not_scheduled(probe)  # its only pool is exhausted
        for pod in followers:
            node = h.expect_scheduled(pod)
            assert node.labels[wellknown.INSTANCE_TYPE_LABEL] == "type-b"


class TestParallelBind:
    """Ref: provisioner.go:239-247 — pod binds fan out concurrently."""

    def test_many_pods_bound_to_one_node(self):
        h = Harness()
        h.apply_provisioner(default_provisioner())
        pods = fixtures.pods(200)
        h.provision(*pods)
        for pod in pods:
            assert h.cluster.get_pod(pod.namespace, pod.name).node_name is not None

    def test_failed_bind_is_not_fatal(self):
        from karpenter_tpu.cloudprovider import NodeSpec

        h = Harness()
        h.apply_provisioner(default_provisioner())
        worker = h.provisioning.workers["default"]
        applied = fixtures.pods(3)
        for pod in applied:
            h.cluster.apply_pod(pod)
        ghost = PodSpec(name="never-applied")  # bind raises NotFoundError
        node = NodeSpec(name="bind-test-node")
        worker._register_and_bind(node, [*applied, ghost])
        for pod in applied:
            assert h.cluster.get_pod(pod.namespace, pod.name).node_name == node.name


class TestBatchOverflow:
    """Pods beyond MAX_PODS_PER_BATCH park in the worker's overflow backlog
    (not the selection queue) and refill the next window at drain — the
    mechanism that keeps a 50k-pod storm off the GIL-bound re-verify path."""

    def _worker(self, h):
        h.apply_provisioner(default_provisioner())
        return h.provisioning.worker("default")

    def test_overflow_accepted_and_refills_next_batch(self):
        from karpenter_tpu.controllers.provisioning import MAX_PODS_PER_BATCH

        h = Harness()
        worker = self._worker(h)
        total = MAX_PODS_PER_BATCH + 700
        pods = fixtures.pods(total, cpu="100m", memory="64Mi")
        for pod in pods:
            h.cluster.apply_pod(pod)
            worker.add(pod)
        assert len(worker._pending) == MAX_PODS_PER_BATCH
        assert len(worker._overflow) == 700
        assert worker.batch_ready()  # full window closes immediately

        first = worker._drain()
        assert len(first) == MAX_PODS_PER_BATCH
        # Overflow refilled the window and restarted its clock.
        assert len(worker._pending) == 700
        assert not worker._overflow
        assert worker._first_add is not None
        h.clock.advance(1.5)  # idle window elapses
        assert worker.batch_ready()
        second = worker._drain()
        assert len(second) == 700
        # Nothing lost, nothing duplicated across the two batches.
        uids = [p.uid for p in first + second]
        assert len(uids) == len(set(uids)) == total

    def test_duplicate_adds_collapse_across_batch_and_overflow(self):
        from karpenter_tpu.controllers.provisioning import MAX_PODS_PER_BATCH

        h = Harness()
        worker = self._worker(h)
        pods = fixtures.pods(MAX_PODS_PER_BATCH + 5)
        for pod in pods:
            worker.add(pod)
        for pod in pods:  # re-verify storm: every pod re-added
            worker.add(pod)
        assert len(worker._pending) == MAX_PODS_PER_BATCH
        assert len(worker._overflow) == 5

    def test_hot_swap_hands_backlog_to_replacement(self):
        """A spec-hash flip mid-storm must not dump the parked backlog back
        onto the slow selection re-verify path."""
        from karpenter_tpu.controllers.provisioning import MAX_PODS_PER_BATCH

        h = Harness()
        provisioner = default_provisioner()
        h.apply_provisioner(provisioner)
        worker = h.provisioning.worker("default")
        pods = fixtures.pods(MAX_PODS_PER_BATCH + 300)
        for pod in pods:
            worker.add(pod)
        # Force a spec change -> new hash -> hot swap.
        provisioner.spec.constraints.labels = {"swap/epoch": "two"}
        h.apply_provisioner(provisioner)
        replacement = h.provisioning.worker("default")
        assert replacement is not worker
        assert not worker._pending and not worker._overflow  # fully drained
        carried = len(replacement._pending) + len(replacement._overflow)
        assert carried == len(pods)

    def test_hot_swap_drops_pods_incompatible_with_new_constraints(self):
        """The hash flipped because constraints changed: carried pods are
        re-validated at hand-off, and now-incompatible ones are left to the
        selection re-verify (which can relax and re-route them)."""
        from karpenter_tpu.api import wellknown

        h = Harness()
        provisioner = default_provisioner()
        h.apply_provisioner(provisioner)
        worker = h.provisioning.worker("default")
        plain = fixtures.pod(name="plain")
        pinned = fixtures.pod(name="pinned")
        pinned.node_selector = {wellknown.ZONE_LABEL: "test-zone-1"}
        worker.add(plain)
        worker.add(pinned)
        # Narrow the provisioner to a different zone: `pinned` no longer fits.
        provisioner.spec.constraints.requirements = Requirements(
            [Requirement.in_(wellknown.ZONE_LABEL, ["test-zone-2"])]
        )
        h.apply_provisioner(provisioner)
        replacement = h.provisioning.worker("default")
        assert replacement is not worker
        carried = {p.name for p in replacement._pending}
        assert carried == {"plain"}
