"""Device-residency tests: on-device plan compaction decodes bit-identically
to the dense spill, the solve->bind pipeline returns exactly the barrier
path's results, fetch staging degrades cleanly on backends without
copy_to_host_async, and _HostOverlap's error contract holds (pool-matrix
failure re-raises; mix failure degrades to no-mix)."""

import threading

import numpy as np
import pytest

from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.models import solver as S
from karpenter_tpu.models.warmup import make_synthetic_problem
from karpenter_tpu.ops import pack_kernel as PK

from tests import fixtures


def _dense_words(rounds_list, feasible_any):
    """Re-implement the dense spill layout (rounds_ints order) on host."""
    parts = []
    for r in rounds_list:
        parts += [
            np.asarray(r.round_type).ravel(),
            np.asarray(r.round_fill).ravel(),
            np.asarray(r.round_repl).ravel(),
            np.asarray([int(r.num_rounds)]),
            np.asarray(r.unschedulable).ravel(),
            np.asarray([int(bool(r.overflow))]),
        ]
    parts.append(np.asarray(feasible_any).astype(np.int64).ravel())
    return np.concatenate([p.astype(np.int64) for p in parts])


class TestCompaction:
    @pytest.mark.parametrize("num_groups,num_types", [(3, 7), (8, 16), (16, 400)])
    def test_compact_decodes_bit_identical_to_dense(self, num_groups, num_types):
        vectors, counts, capacity = make_synthetic_problem(
            num_groups, num_types, pods_per_group=23
        )
        prices = 0.1 * np.arange(1, num_types + 1, dtype=np.float32)
        handle = S.cost_solve_dispatch(
            vectors, counts, capacity, capacity.copy(), prices, 8, count=False
        )
        plan = S.fetch_plan(handle)
        dense = np.asarray(S._to_host(handle.dense))
        ffd_d, cost_d, feasible_d = S.unpack_dense(dense, handle.num_groups)
        for compacted, spilled in (
            (plan.rounds_ffd, ffd_d),
            (plan.rounds_cost, cost_d),
        ):
            assert np.array_equal(compacted.round_type, spilled.round_type)
            assert np.array_equal(compacted.round_fill, spilled.round_fill)
            assert np.array_equal(compacted.round_repl, spilled.round_repl)
            assert int(compacted.num_rounds) == int(spilled.num_rounds)
            assert np.array_equal(compacted.unschedulable, spilled.unschedulable)
            assert bool(compacted.overflow) == bool(spilled.overflow)
        assert np.array_equal(plan.feasible_any, feasible_d)

    def test_eager_payload_matches_shape_math_and_budget(self):
        vectors, counts, capacity = make_synthetic_problem(16, 400)
        prices = 0.1 * np.arange(1, 401, dtype=np.float32)
        handle = S.cost_solve_dispatch(
            vectors, counts, capacity, capacity.copy(), prices, 8, count=False
        )
        # On the suite's 8-device mesh the dispatch routes sharded, so the
        # eager payload follows the per-shard segment layout; shards=1 is
        # the single-device layout — the shape math covers both.
        assert S.fetch_bytes(handle.eager) == 4 * PK.compact_words_sharded(
            handle.num_groups, handle.shards
        ) + 4
        # The acceptance bar: 50k pods / 400 types = a 16-group bucket.
        assert PK.compact_bytes(16) <= 4096

    def test_entry_budget_overflow_falls_back_to_dense(self):
        """A compact payload whose nnz exceeds the COO budget must decode
        via the dense spill, not corrupt the plan."""
        num_groups = 8
        mr = PK.max_rounds(num_groups)
        budget = PK.entry_budget(num_groups)
        rounds = PK.PackRounds(
            round_type=np.arange(mr, dtype=np.int64),
            round_fill=np.ones((mr, num_groups), np.int64) * 3,
            round_repl=np.ones(mr, np.int64),
            num_rounds=np.int64(mr),
            unschedulable=np.zeros(num_groups, np.int64),
            overflow=False,
        )
        feasible = np.ones(num_groups, bool)
        # Hand-build a compact payload claiming nnz > budget for candidate 0.
        def segments(r, nnz):
            return [
                np.asarray(r.round_type),
                np.asarray(r.round_repl),
                np.asarray([int(r.num_rounds)]),
                np.asarray(r.unschedulable),
                np.asarray([0]),
                np.asarray([nnz]),
                np.zeros(budget, np.int64),
                np.zeros(budget, np.int64),
            ]

        compact = np.concatenate(
            [s.astype(np.int64) for s in segments(rounds, budget + 1)]
            + [s.astype(np.int64) for s in segments(rounds, budget + 1)]
            + [feasible.astype(np.int64)]
        )
        handle = S.FusedHandle(
            compact=compact,
            objective=np.asarray([1.5], np.float32),
            dense=_dense_words([rounds, rounds], feasible),
            lp=np.zeros(num_groups * 4, np.float32),
            num_groups=num_groups,
            num_types=4,
        )
        (plan,) = S.fetch_plans([handle])
        assert np.array_equal(plan.rounds_ffd.round_fill, rounds.round_fill)
        assert np.array_equal(plan.rounds_cost.round_type, rounds.round_type)
        assert plan.lp_objective == pytest.approx(1.5)

    def test_lp_assignment_is_deferred_and_correct(self):
        num_groups, num_types = 4, 8
        vectors, counts, capacity = make_synthetic_problem(num_groups, num_types)
        prices = 0.1 * np.arange(1, num_types + 1, dtype=np.float32)
        handle = S.cost_solve_dispatch(
            vectors, counts, capacity, capacity.copy(), prices, 8, count=False
        )
        plan = S.fetch_plan(handle)
        assert plan._lp is None  # nothing fetched yet
        lp = plan.lp_assignment()
        assert lp.shape == (handle.num_groups, handle.num_types)
        assert plan.lp_assignment() is lp  # cached


class TestStartFetch:
    def test_backend_without_copy_to_host_async(self):
        """Leaves lacking copy_to_host_async (older/foreign backends, plain
        numpy) must be skipped silently — staging is an optimization."""

        class Plain:
            pass

        S._start_fetch((Plain(), np.zeros(3)))  # must not raise

    def test_copy_async_failure_degrades_silently(self):
        calls = []

        class Raising:
            def copy_to_host_async(self):
                calls.append("raise")
                raise RuntimeError("backend refused")

        class Counting:
            def copy_to_host_async(self):
                calls.append("ok")

        # The first failure aborts staging for the rest of the tree (the
        # backend clearly doesn't support it) without raising.
        S._start_fetch((Raising(), Counting()))
        assert calls == ["raise"]
        S._start_fetch((Counting(), Counting()))
        assert calls == ["raise", "ok", "ok"]


class TestHostOverlap:
    def test_pool_matrix_failure_reraises_on_join(self):
        def boom():
            raise ValueError("matrix build failed")

        overlap = S._HostOverlap([(None, None, None, boom)]).start()
        with pytest.raises(ValueError, match="matrix build failed"):
            overlap.join()

    def test_pool_matrix_failure_poisons_only_later_items(self):
        vectors = np.array([[1000.0, 512.0]], np.float32)
        counts = np.array([1], np.int32)
        capacity = np.array([[4000.0, 8192.0]], np.float32)
        pool = np.array([[0.1]])

        def boom():
            raise ValueError("second item")

        overlap = S._HostOverlap(
            [
                (vectors, counts, capacity, pool),
                (vectors, counts, capacity, boom),
            ]
        ).start()
        overlap.wait(0)  # first item unaffected
        assert overlap.pool_prices[0] is pool
        with pytest.raises(ValueError, match="second item"):
            overlap.wait(1)
        with pytest.raises(ValueError, match="second item"):
            overlap.join()

    def test_mix_failure_degrades_to_no_mix(self, monkeypatch):
        vectors = np.array([[1000.0, 512.0]], np.float32)
        counts = np.array([4], np.int32)
        capacity = np.array([[4000.0, 8192.0]], np.float32)
        pool = np.array([[0.1]])

        def broken_mix(*args, **kwargs):
            raise RuntimeError("mix exploded")

        monkeypatch.setattr(S, "compute_mix_candidate", broken_mix)
        overlap = S._HostOverlap([(vectors, counts, capacity, pool)]).start()
        pool_prices, mix_plans = overlap.join()  # must NOT raise
        assert pool_prices == [pool]
        assert mix_plans == [None]

    def test_wait_blocks_until_item_ready(self):
        release = threading.Event()

        def slow_pool():
            release.wait(timeout=5.0)
            return np.array([[0.2]])

        overlap = S._HostOverlap([(None, None, None, slow_pool)]).start()
        assert not overlap._done[0].is_set()
        release.set()
        overlap.wait(0)
        assert overlap.pool_prices[0] is not None


class TestPipelinedSolve:
    def _problems(self):
        problems = []
        for i in range(4):
            pods = fixtures.pods(
                40 + 17 * i, cpu=f"{1 + i % 3}", memory=f"{512 * (1 + i % 2)}Mi"
            )
            catalog = fixtures.size_ladder(6 + i)
            problems.append((pods, catalog, Constraints(), ()))
        return problems

    def _signature(self, result):
        return (
            sorted(
                (packing.instance_type_options[0].name, packing.node_quantity)
                for packing in result.packings
            ),
            len(result.unschedulable),
            round(result.projected_cost(), 6),
        )

    def test_pipelined_results_match_barrier_results(self, monkeypatch):
        # Force the device path so the pipeline's dispatch/fetch machinery
        # (not the host gate) is what's under test.
        monkeypatch.setenv("KARPENTER_HOST_SOLVE", "0")
        solver = S.CostSolver(lp_steps=8)
        problems = self._problems()
        barrier = solver.solve_many(problems)
        pipelined = list(solver.solve_many_pipelined(problems))
        assert len(barrier) == len(pipelined)
        for b, p in zip(barrier, pipelined):
            assert self._signature(b) == self._signature(p)

    def test_base_solver_pipelined_matches_many(self):
        solver = S.GreedySolver()
        problems = self._problems()
        barrier = solver.solve_many(problems)
        pipelined = list(solver.solve_many_pipelined(problems))
        for b, p in zip(barrier, pipelined):
            assert self._signature(b) == self._signature(p)

    def test_pipelined_handles_empty_schedules(self):
        solver = S.CostSolver(lp_steps=8)
        pods = fixtures.pods(10, cpu="1", memory="512Mi")
        problems = [
            (pods, [], Constraints(), ()),  # empty fleet
            (pods, fixtures.size_ladder(4), Constraints(), ()),
        ]
        results = list(solver.solve_many_pipelined(problems))
        assert len(results[0].unschedulable) == 10
        assert results[1].packings


class TestConsolidationLazyRows:
    def _problem(self):
        from karpenter_tpu.ops.consolidate import ConsolidationProblem

        rng = np.random.default_rng(3)
        return ConsolidationProblem(
            pod_vectors=rng.integers(1, 5, (5, 3, 8)).astype(np.float32) * 250.0,
            pod_counts=rng.integers(0, 4, (5, 3)).astype(np.int32),
            headroom=rng.integers(4, 33, (9, 8)).astype(np.float32) * 1000.0,
            bin_mask=np.ones((5, 9), bool),
            node_prices=np.linspace(0.4, 1.6, 5),
            type_capacity=rng.integers(4, 65, (11, 8)).astype(np.float32) * 1000.0,
            type_prices=np.linspace(0.1, 1.1, 11).astype(np.float32),
            type_valid=np.ones((5, 11), bool),
        )

    def test_take_row_matches_full_tensor(self):
        from karpenter_tpu.ops import consolidate

        verdicts = consolidate.solve_candidates(self._problem())
        full = verdicts.delete_take
        for candidate in range(5):
            assert np.array_equal(verdicts.take_row(candidate), full[candidate])

    def test_winner_row_prefetched(self):
        from karpenter_tpu.ops import consolidate

        verdicts = consolidate.solve_candidates(self._problem())
        best = verdicts.best()
        if best >= 0:
            # The argmax winner's row came with the eager fetch — already
            # cached before any lazy accessor runs.
            assert best in verdicts._rows

    def test_eager_fetch_is_small(self):
        from karpenter_tpu.ops import consolidate

        verdicts = consolidate.solve_candidates(self._problem())
        full_bytes = verdicts.delete_take.nbytes
        # Eager payload: [C] columns + one [G, N] row — far below the
        # padded [C, G, N] tensor the dense path used to pull every sweep.
        assert consolidate.LAST_FETCH_BYTES < 8 * full_bytes  # sanity
        assert consolidate.LAST_FETCH_BYTES <= 4096


class TestDeviceResident:
    def test_content_keyed_reuse(self):
        PK.reset_device_resident()
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        first = PK.device_resident(a)
        second = PK.device_resident(a.copy())  # same content, new object
        assert first is second
        third = PK.device_resident(a + 1.0)
        assert third is not first
        PK.reset_device_resident()

    def test_passthrough_for_non_numpy(self):
        sentinel = object()
        assert PK.device_resident(sentinel) is sentinel
