"""The live market subsystem (karpenter_tpu/market): feed determinism, the
PriceBook fold + generation protocol, the market sweep's chaos legs and
debounce, cache invalidation on reprice, and the forecast penalty's
kernel/numpy bit-parity.

The crash/restart class (TestMarketCrashRestart) re-runs on the apiserver
backend via tests/test_backend_parity.py — a restarted controller re-folds
the provider's replayable tick history from seq 0 and must reconstruct the
IDENTICAL book state and generation, whichever store it rides.
"""

import numpy as np
import pytest

from karpenter_tpu.api.provisioner import Provisioner, ProvisionerSpec
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.controllers.market import MarketController
from karpenter_tpu.market import forecast
from karpenter_tpu.market.feed import (
    TICK_ICE_CLOSE,
    TICK_ICE_OPEN,
    TICK_PRICE,
    MarketFeed,
    MarketTick,
    catalog_pools,
)
from karpenter_tpu.market.pricebook import (
    REASON_ICE,
    REASON_PRICE,
    PriceBook,
    active_book,
    set_active_book,
    stamp_epoch,
)
from karpenter_tpu.utils import crashpoints, faultpoints
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.crashpoints import SimulatedCrash
from tests import fixtures
from tests.harness import Harness

POOLS = [("a.large", "test-zone-1"), ("b.large", "test-zone-2")]


def price_tick(seq, pool=POOLS[0], discount=0.5, depth=1.0, at=0.0):
    return MarketTick(
        seq=seq,
        kind=TICK_PRICE,
        instance_type=pool[0],
        zone=pool[1],
        discount=discount,
        depth=depth,
        at=at,
    )


class TestMarketFeed:
    def test_same_seed_same_steps_byte_identical(self):
        """The determinism contract: the tick sequence is a pure function of
        (pools, seed, steps) — compared on the canonical wire encoding."""
        a = MarketFeed(POOLS, seed=7, ice_close_rate=0.1)
        b = MarketFeed(POOLS, seed=7, ice_close_rate=0.1)
        a.advance(25.0)
        b.advance(25.0)
        assert a.encode_history() == b.encode_history()
        assert a.last_seq == b.last_seq > len(POOLS)  # snapshot + steps

    def test_different_seed_diverges(self):
        a = MarketFeed(POOLS, seed=1)
        b = MarketFeed(POOLS, seed=2)
        a.advance(25.0)
        b.advance(25.0)
        assert a.encode_history() != b.encode_history()

    def test_advance_is_incremental_and_idempotent(self):
        """advance(now) emits exactly the elapsed steps; re-advancing to the
        same now emits nothing; a fold from 0 equals the concatenation."""
        whole = MarketFeed(POOLS, seed=3)
        whole.advance(10.0)
        pieces = MarketFeed(POOLS, seed=3)
        pieces.advance(4.0)
        cut = pieces.last_seq
        assert pieces.advance(4.0) == 0
        pieces.advance(10.0)
        assert pieces.encode_history() == whole.encode_history()
        assert [t.seq for t in pieces.ticks_after(cut)] == list(
            range(cut + 1, pieces.last_seq + 1)
        )

    def test_forced_spike_is_an_ordinary_tick(self):
        """A scripted spike lands as a recorded price tick at the next step
        (replay determinism untouched) and ratchets discount up, depth down."""
        feed = MarketFeed(POOLS, seed=5)
        before = feed.ticks_after(0)[0]  # snapshot tick for POOLS[0]
        feed.force_spike([POOLS[0]], factor=1.8)
        feed.advance(1.0)
        spiked = [
            t
            for t in feed.ticks_after(len(POOLS))
            if t.pool == POOLS[0] and t.kind == TICK_PRICE
        ]
        assert spiked and spiked[0].discount > before.discount
        assert spiked[0].depth < before.depth

    def test_forced_ice_close_and_reopen(self):
        feed = MarketFeed(POOLS, seed=5, ice_reopen_rate=0.0)
        feed.force_ice([POOLS[1]], close=True)
        feed.advance(1.0)
        kinds = [t.kind for t in feed.ticks_after(0) if t.pool == POOLS[1]]
        assert TICK_ICE_CLOSE in kinds
        feed.force_ice([POOLS[1]], close=False)
        feed.advance(2.0)
        kinds = [t.kind for t in feed.ticks_after(0) if t.pool == POOLS[1]]
        assert TICK_ICE_OPEN in kinds

    def test_catalog_pools(self):
        pools = catalog_pools(fixtures.default_catalog())
        assert ("small-instance-type", "test-zone-1") in pools
        assert len(pools) == len(set(pools))


class TestPriceBook:
    def test_first_sighting_anchors_silently(self):
        """The initial market snapshot is not a reprice — boot must not
        storm one generation bump per pool."""
        book = PriceBook(clock=FakeClock())
        assert book.apply(price_tick(1, discount=0.5)) is None
        assert book.generation == 0
        assert book.spot_discount(POOLS[0]) == 0.5

    def test_threshold_crossing_reprices(self):
        book = PriceBook(clock=FakeClock(), reprice_threshold=0.1)
        book.apply(price_tick(1, discount=0.5))
        # 6% drift: below the 10% relative threshold.
        assert book.apply(price_tick(2, discount=0.53)) is None
        assert book.generation == 0
        reprice = book.apply(price_tick(3, discount=0.56))
        assert reprice is not None and reprice.reason == REASON_PRICE
        assert reprice.old_discount == 0.5 and reprice.new_discount == 0.56
        assert book.generation == reprice.generation == 1

    def test_cumulative_subthreshold_drift_reprices(self):
        """Many tiny ticks that cumulatively cross the threshold DO reprice:
        the anchor is the discount at the last bump, not the last tick."""
        book = PriceBook(clock=FakeClock(), reprice_threshold=0.1)
        book.apply(price_tick(1, discount=0.5))
        discount, seq = 0.5, 1
        while book.generation == 0 and seq < 50:
            seq += 1
            discount *= 1.02  # 2% per tick, far under 10%
            book.apply(price_tick(seq, discount=discount))
        assert book.generation == 1
        assert seq < 50

    def test_ice_always_reprices(self):
        book = PriceBook(clock=FakeClock())
        tick = MarketTick(
            seq=1, kind=TICK_ICE_CLOSE,
            instance_type=POOLS[0][0], zone=POOLS[0][1],
        )
        reprice = book.apply(tick)
        assert reprice is not None and reprice.reason == REASON_ICE
        assert book.is_closed(POOLS[0])
        reopened = book.apply(
            MarketTick(
                seq=2, kind=TICK_ICE_OPEN,
                instance_type=POOLS[0][0], zone=POOLS[0][1],
            )
        )
        assert reopened is not None and not book.is_closed(POOLS[0])
        assert book.generation == 2

    def test_replay_is_idempotent(self):
        """At-least-once delivery: a tick at or below the high-water mark is
        a no-op — the restart re-fold and redelivering providers lean on it."""
        book = PriceBook(clock=FakeClock(), reprice_threshold=0.1)
        ticks = [
            price_tick(1, discount=0.5),
            price_tick(2, discount=0.7),
            price_tick(3, discount=0.9),
        ]
        for t in ticks:
            book.apply(t)
        state = (book.generation, book.spot_discount(POOLS[0]), book.last_seq)
        for t in ticks:  # full redelivery
            assert book.apply(t) is None
        assert (
            book.generation, book.spot_discount(POOLS[0]), book.last_seq
        ) == state

    def test_staleness_tracks_newest_applied_tick(self):
        clock = FakeClock()
        book = PriceBook(clock=clock)
        assert book.staleness_s() == 0.0
        book.apply(price_tick(1, at=clock.now()))
        clock.advance(7.0)
        assert book.staleness_s() == pytest.approx(7.0)

    def test_interruption_raises_quantized_risk(self):
        clock = FakeClock()
        book = PriceBook(clock=clock)
        assert book.pool_risk(POOLS[0]) == 0.0 and not book.has_risk()
        before = book.risk_generation
        book.note_interruption(POOLS[0])
        risk = book.pool_risk(POOLS[0])
        assert 0.0 < risk < 1.0
        assert risk % (1.0 / 32.0) == pytest.approx(0.0)  # quantized
        assert book.has_risk() and book.risk_generation > before
        # Decay: half-life 300s halves the pressure, lowering the risk.
        clock.advance(900.0)
        assert book.pool_risk(POOLS[0]) < risk

    def test_depth_decline_trend_raises_risk(self):
        book = PriceBook(clock=FakeClock())
        book.apply(price_tick(1, depth=2.0))
        for seq in range(2, 8):
            book.apply(price_tick(seq, depth=2.0 * 0.6 ** (seq - 1)))
        assert book.pool_risk(POOLS[0]) > 0.0
        # A stable pool stays at zero.
        book.apply(price_tick(8, pool=POOLS[1], depth=1.0))
        book.apply(price_tick(9, pool=POOLS[1], depth=1.0))
        assert book.pool_risk(POOLS[1]) == 0.0


def build_market(clock=None, threshold=0.1, debounce=5.0, seed=11, harness=None):
    """A Harness + fed FakeCloudProvider + MarketController triple."""
    harness = harness or Harness(clock=clock)
    feed = MarketFeed(
        catalog_pools(fixtures.default_catalog()),
        seed=seed,
        start_at=harness.clock.now(),
    )
    harness.cloud.attach_market_feed(feed)
    book = PriceBook(clock=harness.clock, reprice_threshold=threshold)
    harness.cloud.attach_market(book)
    controller = MarketController(
        harness.cluster, harness.cloud, book, debounce_seconds=debounce
    )
    return harness, feed, controller


class TestMarketController:
    def test_sweep_folds_feed_into_book(self):
        harness, feed, controller = build_market()
        harness.clock.advance(5.0)
        controller.reconcile()
        assert controller.book.last_seq == feed.last_seq > 0
        for pool in catalog_pools(fixtures.default_catalog()):
            assert controller.book.spot_discount(pool) is not None

    def test_advertised_prices_track_the_folded_market(self):
        """attach_market: the catalog's spot offering prices follow the
        book (on-demand anchor x live discount); ICE-closed pools drop
        their spot offering."""
        harness, feed, controller = build_market()
        pool = ("small-instance-type", "test-zone-1")
        feed.force_spike([pool], factor=1.3)
        harness.clock.advance(2.0)
        controller.reconcile()
        discount = controller.book.spot_discount(pool)
        it = {t.name: t for t in harness.cloud.get_instance_types()}[pool[0]]
        spot = [
            o for o in it.offerings
            if o.zone == pool[1] and o.capacity_type == "spot"
        ]
        od = [
            o for o in it.offerings
            if o.zone == pool[1] and o.capacity_type == "on-demand"
        ]
        assert spot[0].price == pytest.approx(od[0].price * discount)
        # ICE-close: the pool's spot offering vanishes from the catalog.
        feed.force_ice([pool], close=True)
        harness.clock.advance(1.0)
        controller.reconcile()
        it = {t.name: t for t in harness.cloud.get_instance_types()}[pool[0]]
        assert not [
            o for o in it.offerings
            if o.zone == pool[1] and o.capacity_type == "spot"
        ]

    def test_reprice_requeues_and_flight_records(self):
        from karpenter_tpu.utils.obs import RECORDER

        harness, feed, controller = build_market(threshold=0.05)
        requeues = []
        controller.requeue = lambda: requeues.append(True)
        baseline = RECORDER.count("reprice")
        feed.force_spike(
            [("small-instance-type", "test-zone-1")], factor=1.5
        )
        harness.clock.advance(2.0)
        controller.reconcile()
        assert requeues, "an above-threshold spike never requeued"
        assert RECORDER.count("reprice") > baseline

    def test_subthreshold_storm_never_requeues(self):
        """The debounce test's stronger sibling: a storm of ticks that never
        crosses the threshold leaves the sweep cadence untouched — zero
        requeues, zero generation bumps."""
        harness, feed, controller = build_market(threshold=0.9)
        requeues = []
        controller.requeue = lambda: requeues.append(True)
        for _ in range(20):
            harness.clock.advance(1.0)
            controller.reconcile()
        assert controller.book.last_seq > 20  # the storm was real
        assert controller.book.generation == 0
        assert requeues == []

    def test_debounce_coalesces_reprices_per_pool(self):
        """A repricing pool requeues at most once per debounce window; bumps
        inside the window coalesce into the pending set and requeue when the
        window reopens (driven with scripted Reprices so the seeded walk's
        own drift on OTHER pools can't confound the count)."""
        from karpenter_tpu.market.pricebook import Reprice

        harness, feed, controller = build_market(debounce=30.0)
        requeues = []
        controller.requeue = lambda: requeues.append(harness.clock.now())
        pool = ("small-instance-type", "test-zone-1")

        def bump(generation):
            return Reprice(
                pool=pool, reason=REASON_PRICE,
                old_discount=0.5, new_discount=0.6, generation=generation,
            )

        controller._requeue_due([bump(1)])
        assert len(requeues) == 1
        # More bumps inside the window: coalesced into pending, NOT requeued.
        for generation in (2, 3, 4):
            harness.clock.advance(1.0)
            controller._requeue_due([bump(generation)])
        assert len(requeues) == 1
        assert pool in controller._pending
        # Window reopens: the coalesced pending reprice requeues once.
        harness.clock.advance(31.0)
        controller._requeue_due([])
        assert len(requeues) == 2
        assert pool not in controller._pending

    def test_blackout_fault_skips_poll_and_staleness_climbs(self):
        from karpenter_tpu.controllers.market import MARKET_FEED_STALENESS

        harness, feed, controller = build_market()
        harness.clock.advance(2.0)
        controller.reconcile()
        folded = controller.book.last_seq
        faultpoints.seed(4)
        faultpoints.arm("market.feed", "blackout", rate=1.0)
        harness.clock.advance(10.0)
        controller.reconcile()
        assert controller.book.last_seq == folded  # nothing delivered
        assert MARKET_FEED_STALENESS.get() >= 10.0
        faultpoints.disarm_all()
        controller.reconcile()  # blackout lifts: history catches us up
        assert controller.book.last_seq == feed.last_seq > folded

    def test_stale_fault_redelivers_next_sweep(self):
        harness, feed, controller = build_market()
        faultpoints.seed(4)
        faultpoints.arm("market.feed", "stale", rate=1.0)
        harness.clock.advance(3.0)
        controller.reconcile()
        held_back = feed.last_seq - controller.book.last_seq
        assert held_back > 0  # the newest half was held
        faultpoints.disarm_all()
        controller.reconcile()
        assert controller.book.last_seq == feed.last_seq

    def test_reorder_fault_absorbed_by_sorted_fold(self):
        """Two controllers over byte-identical feeds — one through a
        reordering fault — fold to the same book state and generation."""
        ha, feed_a, ca = build_market(seed=21, threshold=0.02)
        hb, feed_b, cb = build_market(seed=21, threshold=0.02)
        faultpoints.seed(4)
        faultpoints.arm("market.feed", "reorder", rate=1.0)
        ha.clock.advance(20.0)
        ca.reconcile()
        faultpoints.disarm_all()
        hb.clock.advance(20.0)
        cb.reconcile()
        assert feed_a.encode_history() == feed_b.encode_history()
        assert ca.book.generation == cb.book.generation
        assert ca.book.fingerprint() == cb.book.fingerprint()
        for pool in ca.book.pools():
            assert ca.book.spot_discount(pool) == cb.book.spot_discount(pool)


class TestMarketCrashRestart:
    """market.mid-tick: a controller killed between folded ticks restarts,
    re-polls the replayable feed from seq 0, and reconstructs the IDENTICAL
    book state and generation. Re-run on the apiserver backend via
    tests/test_backend_parity.py."""

    def test_mid_tick_crash_refolds_identically(self):
        harness, feed, controller = build_market(seed=31, threshold=0.02)
        harness.clock.advance(15.0)
        crashpoints.arm("market.mid-tick", at=4)
        with pytest.raises(SimulatedCrash):
            controller.reconcile()
        crashpoints.disarm_all()
        torn = controller.book.last_seq
        assert 0 < torn < feed.last_seq  # died mid-fold, partially folded

        # "Restart": a fresh book + controller over the SURVIVING provider
        # (the feed is the durable history), re-folding from seq 0.
        restarted = MarketController(
            harness.cluster,
            harness.cloud,
            PriceBook(clock=harness.clock, reprice_threshold=0.02),
        )
        restarted.reconcile()

        # Control: the same walk folded straight through, no crash.
        control_h, control_feed, control = build_market(
            seed=31, threshold=0.02
        )
        control_h.clock.advance(15.0)
        control.reconcile()
        assert feed.encode_history() == control_feed.encode_history()
        assert restarted.book.generation == control.book.generation
        assert restarted.book.last_seq == control.book.last_seq
        for pool in control.book.pools():
            assert restarted.book.spot_discount(
                pool
            ) == control.book.spot_discount(pool)


class TestCacheInvalidation:
    def test_stamp_epoch_changes_on_generation_bump(self):
        """The compiled-envelope cache keys on stamp_epoch(tag): a reprice
        must change it, a quiet market must not, and None tags (no caching)
        stay None."""
        book = PriceBook(clock=FakeClock(), reprice_threshold=0.1)
        set_active_book(book)
        tag = (3, 17)
        book.apply(price_tick(1, discount=0.5))
        before = stamp_epoch(tag)
        assert stamp_epoch(tag) == before  # quiet market: stable key
        assert stamp_epoch(None) is None
        book.apply(price_tick(2, discount=0.9))  # reprice
        assert book.generation == 1
        assert stamp_epoch(tag) != before

    def test_stamp_epoch_passthrough_without_book(self):
        assert active_book() is None
        assert stamp_epoch((1, 2)) == (1, 2)

    def test_fleet_cache_invalidates_on_reprice_and_risk(self):
        """DeviceClusterState.encode_fleet keys on the book's fingerprint:
        a generation bump (reprice) and a risk_generation bump (observed
        interruption) each force a rebuild; a quiet market serves the
        cached fleet."""
        from karpenter_tpu.controllers.cluster import Cluster
        from karpenter_tpu.models.cluster_state import DeviceClusterState

        clock = FakeClock()
        book = PriceBook(clock=clock, reprice_threshold=0.1)
        set_active_book(book)
        state = DeviceClusterState(Cluster(clock=clock))
        catalog = fixtures.default_catalog()
        constraints = ProvisionerSpec().constraints

        first = state.encode_fleet(catalog, constraints, (), None)
        assert state.encode_fleet(catalog, constraints, (), None) is first
        book.apply(price_tick(1, discount=0.5))
        book.apply(price_tick(2, discount=0.9))  # generation bump
        second = state.encode_fleet(catalog, constraints, (), None)
        assert second is not first
        assert state.encode_fleet(catalog, constraints, (), None) is second
        book.note_interruption(POOLS[0])  # risk_generation bump
        assert state.encode_fleet(catalog, constraints, (), None) is not second


class TestForecastPenalty:
    def test_numpy_jax_mirror_bit_identical(self):
        """The acceptance gate's parity clause: penalize_prices (numpy) and
        penalize_prices_jnp (jax) agree to the last bit across magnitudes."""
        rng = np.random.default_rng(9)
        prices = (rng.uniform(0.01, 64.0, size=257)).astype(np.float32)
        risks = (
            np.floor(rng.uniform(0.0, 1.0, size=257) * 32.0) / 32.0
        ).astype(np.float32)
        host = forecast.penalize_prices(prices, risks)
        device = np.asarray(forecast.penalize_prices_jnp(prices, risks))
        assert host.dtype == device.dtype == np.float32
        assert np.array_equal(host, device)  # bit-identical, not approx

    def test_penalty_column_shape_and_zero_risk_identity(self):
        prices = np.array([1.0, 2.0, 4.0], np.float32)
        zero = np.zeros(3, np.float32)
        assert np.array_equal(forecast.penalize_prices(prices, zero), prices)
        column = forecast.penalty_column(prices, np.full(3, 0.5, np.float32))
        assert np.array_equal(column, prices * 0.5)

    def test_build_fleet_penalizes_spot_prices(self):
        """A risky pool's type prices out of cheapest: build_fleet's [T]
        column carries the penalty exactly as forecast.penalize_prices
        computes it, and with no risk (or no book) is bit-identical to the
        pre-market behavior."""
        from karpenter_tpu.ops.encode import build_fleet

        catalog = fixtures.default_catalog()
        constraints = ProvisionerSpec().constraints
        baseline = build_fleet(catalog, constraints, pods=[])
        assert baseline.capacity_type == "spot"

        clock = FakeClock()
        book = PriceBook(clock=clock)
        set_active_book(book)
        calm = build_fleet(catalog, constraints, pods=[])
        assert np.array_equal(calm.prices, baseline.prices)  # no risk = no-op

        risky = "small-instance-type"
        for zone in fixtures.ZONES:
            book.note_interruption((risky, zone))
        penalized = build_fleet(catalog, constraints, pods=[])
        index = [it.name for it in penalized.instance_types].index(risky)
        risk = book.pool_risk((risky, fixtures.ZONES[0]))
        expected = np.array(baseline.prices)
        expected[index] = np.float32(
            baseline.prices[index]
            + baseline.prices[index]
            * np.float32(risk)
            * np.float32(forecast.RISK_PRICE_WEIGHT)
        )
        assert np.array_equal(penalized.prices, expected)

    def test_pool_price_matrix_penalizes_risky_pools_only(self):
        from karpenter_tpu.models.solver import _pool_price_matrix
        from karpenter_tpu.ops.encode import build_fleet

        catalog = fixtures.default_catalog()
        constraints = ProvisionerSpec().constraints
        fleet = build_fleet(catalog, constraints, pods=[])
        zones, baseline = _pool_price_matrix(fleet)

        book = PriceBook(clock=FakeClock())
        set_active_book(book)
        risky = ("small-instance-type", zones[0])
        book.note_interruption(risky)
        _, penalized = _pool_price_matrix(fleet)
        ti = [it.name for it in fleet.instance_types].index(risky[0])
        assert penalized[ti, 0] > baseline[ti, 0]
        untouched = np.ones_like(baseline, dtype=bool)
        untouched[ti, 0] = False
        assert np.array_equal(penalized[untouched], baseline[untouched])
        assert np.isinf(penalized).sum() == np.isinf(baseline).sum()

    def test_packing_avoids_risky_pool_before_it_interrupts(self):
        """End to end through the fused cost dispatch: with the forecast
        armed, a provision pass routes away from the hazardous (cheapest)
        type BEFORE any blackout exists. (The greedy FFD baseline is size-
        windowed and price-blind by reference fidelity — the steering lives
        in the cost solver's penalized [T] price column.)"""
        from karpenter_tpu.models.solver import CostSolver

        catalog = [
            fixtures.cpu_instance("risky.large", cpu=4, mem_gib=16, price=0.2),
            fixtures.cpu_instance("calm.large", cpu=4, mem_gib=16, price=0.21),
        ]
        book = PriceBook(clock=FakeClock())
        set_active_book(book)
        for zone in fixtures.ZONES:
            book.note_interruption(("risky.large", zone))
            book.note_interruption(("risky.large", zone))
        harness = Harness(instance_types=catalog, solver=CostSolver())
        harness.apply_provisioner(
            Provisioner(name="default", spec=ProvisionerSpec())
        )
        harness.provision(fixtures.pod(cpu="2"))
        nodes = harness.cluster.list_nodes()
        assert nodes and all(n.instance_type == "calm.large" for n in nodes)


class TestSimulatePlanCostExcluded:
    def test_infeasible_fallback_respects_excluded(self):
        """Satellite regression: a packing whose EVERY pool is excluded must
        price at inf, not at its best advertised offering (which silently
        under-reported storm-time cost)."""
        from karpenter_tpu.api.provisioner import Constraints
        from karpenter_tpu.cloudprovider.market import simulate_plan_cost
        from karpenter_tpu.models.solver import GreedySolver

        catalog = [fixtures.cpu_instance("only.large", cpu=8, mem_gib=32)]
        result = GreedySolver().solve(
            [fixtures.pod(cpu="2")], catalog, Constraints(), []
        )
        assert result.packings
        every_pool = [
            ("only.large", zone) for zone in fixtures.ZONES
        ]
        healthy = simulate_plan_cost(
            result, Constraints(), None, fixtures.ZONES
        )
        assert np.isfinite(healthy) and healthy > 0
        blacked_out = simulate_plan_cost(
            result, Constraints(), None, fixtures.ZONES, excluded=every_pool
        )
        assert blacked_out == float("inf")

    def test_partial_exclusion_prices_at_best_survivor(self):
        from karpenter_tpu.api.provisioner import Constraints
        from karpenter_tpu.cloudprovider.market import simulate_plan_cost
        from karpenter_tpu.models.solver import GreedySolver

        catalog = [fixtures.cpu_instance("only.large", cpu=8, mem_gib=32)]
        result = GreedySolver().solve(
            [fixtures.pod(cpu="2")], catalog, Constraints(), []
        )
        # Exclude every pool in the plan's zone filter; the fallback must
        # price at the cheapest offering of the SURVIVING zone.
        excluded = [("only.large", z) for z in fixtures.ZONES[:2]]
        cost = simulate_plan_cost(
            result,
            Constraints(),
            None,
            fixtures.ZONES[:2],
            excluded=excluded,
        )
        it = catalog[0]
        survivor_prices = [
            o.price
            for o in it.offerings
            if ("only.large", o.zone) not in excluded
        ]
        nodes = sum(p.node_quantity for p in result.packings)
        assert cost == pytest.approx(min(survivor_prices) * nodes)


class TestDisplacementPdbGateServerTruth:
    def test_stale_informer_cache_cannot_overspend_the_budget(self):
        """The market-storm regression: under watch chaos a duplicated
        pre-displacement event can resurrect a victim's bound state in the
        informer cache; the displacement gate must count the budget from
        the SERVER, not the cache, or one drain sweep displaces every
        replica behind the PDB."""
        from karpenter_tpu.controllers.errors import PDBViolationError

        harness = Harness(backend="apiserver")
        harness.apply_provisioner(
            Provisioner(name="default", spec=ProvisionerSpec())
        )
        pods = [fixtures.pod(name=f"guarded-{i}") for i in range(2)]
        for pod in pods:
            pod.labels["app"] = "guarded"
        harness.cluster.apply_pdb("guarded", {"app": "guarded"}, 1)
        harness.provision(*pods)
        assert all(
            p.node_name for p in harness.cluster.list_pods()
        )
        # First displacement: allowed (2 healthy - 1 >= minAvailable 1).
        harness.cluster.reschedule_pod("default", "guarded-0")
        # Simulate the chaos race: a duplicated stale watch event re-binds
        # the displaced pod IN THE CACHE ONLY (the server still says
        # unbound).
        cached = harness.cluster.try_get_pod("default", "guarded-0")
        cached.node_name = "phantom-node"
        # Second displacement must refuse on server truth (1 healthy - 1 <
        # minAvailable 1) even though the cache claims 2 healthy.
        with pytest.raises(PDBViolationError):
            harness.cluster.reschedule_pod("default", "guarded-1")
        harness.cluster.close()

    def test_restarted_cluster_relists_pdbs(self):
        """The other market-storm regression: a RESTARTED controller's
        cluster must re-seed its PDB table from the server — with an empty
        table every post-restart drain displaces unbudgeted (one
        interruption sweep took all four replicas behind a PDB down)."""
        from karpenter_tpu.controllers.errors import PDBViolationError
        from karpenter_tpu.kubeapi import ApiServerCluster, KubeClient
        from karpenter_tpu.kubeapi.chaos import ChaosTransport
        from tests.fake_apiserver import DirectTransport

        harness = Harness(backend="apiserver")
        harness.apply_provisioner(
            Provisioner(name="default", spec=ProvisionerSpec())
        )
        pods = [fixtures.pod(name=f"guarded-{i}") for i in range(2)]
        for pod in pods:
            pod.labels["app"] = "guarded"
        harness.cluster.apply_pdb("guarded", {"app": "guarded"}, 2)
        harness.provision(*pods)
        # The "restart": a fresh cluster over the surviving apiserver.
        restarted = ApiServerCluster(
            KubeClient(
                ChaosTransport(
                    DirectTransport(harness.apiserver), clock=harness.clock
                ),
                qps=1e6,
                burst=10**6,
                clock=harness.clock,
            ),
            clock=harness.clock,
        ).start()
        try:
            with pytest.raises(PDBViolationError):
                restarted.reschedule_pod("default", "guarded-0")
        finally:
            restarted.close()
            harness.cluster.close()


class TestLaunchGenerationStamp:
    def test_launch_flight_record_names_market_generation(self):
        from karpenter_tpu.utils.obs import RECORDER

        book = PriceBook(clock=FakeClock(), reprice_threshold=0.1)
        set_active_book(book)
        book.apply(price_tick(1, discount=0.5))
        book.apply(price_tick(2, discount=0.9))
        assert book.generation == 1
        harness = Harness()
        harness.apply_provisioner(
            Provisioner(name="default", spec=ProvisionerSpec())
        )
        harness.provision(fixtures.pod())
        launches = [
            e
            for e in RECORDER.snapshot()["events"]
            if e["kind"] == "launch"
        ]
        assert launches
        assert launches[-1]["market_generation"] == 1


class TestRiskDecayRequantization:
    def test_decay_requantizes_and_bumps_risk_generation(self):
        """Hazard decay must reach the fleet-cache fingerprint, not just
        ad-hoc pool_risk() reads: the sweep's requantized_risks() bumps
        risk_generation on any quantum crossing — including DOWNWARD, for
        pools that never tick again — so the packer stops paying a stale
        penalty and the published gauge matches what it pays."""
        from karpenter_tpu.market.pricebook import INTERRUPTION_HALF_LIFE_S

        clock = FakeClock()
        book = PriceBook(clock=clock)
        book.apply(price_tick(1))
        book.note_interruption(POOLS[0])
        spiked = book.requantized_risks()[POOLS[0]]
        assert spiked > 0.0
        rg = book.risk_generation
        fp = book.fingerprint()
        # A sweep with no decay movement is quiet: no generation churn.
        assert book.requantized_risks()[POOLS[0]] == spiked
        assert book.risk_generation == rg
        # Ten half-lives later the hazard is gone; the sweep's read must
        # requantize to 0 AND invalidate (fingerprint change).
        clock.advance(10 * INTERRUPTION_HALF_LIFE_S)
        assert book.requantized_risks()[POOLS[0]] == 0.0
        assert book.risk_generation > rg
        assert book.fingerprint() != fp
        assert not book.has_risk()

    def test_sweep_publishes_decayed_risk(self):
        """The market sweep's gauge rides the requantizing read: after the
        hazard decays, a sweep with NO ticks at all (quiet feed) publishes
        the decayed 0 and invalidates the fingerprint. A feed-free cloud
        isolates the interruption leg from walk-generated trend noise."""
        from karpenter_tpu.controllers.market import FORECAST_RISK_SCORE
        from karpenter_tpu.market.pricebook import INTERRUPTION_HALF_LIFE_S

        harness = Harness()
        book = PriceBook(clock=harness.clock)
        controller = MarketController(harness.cluster, harness.cloud, book)
        pool = catalog_pools(fixtures.default_catalog())[0]
        book.apply(price_tick(1, pool=pool))
        book.note_interruption(pool)
        controller.reconcile()
        label = f"{pool[0]}/{pool[1]}"
        assert FORECAST_RISK_SCORE.get(label) > 0.0
        fp = book.fingerprint()
        harness.clock.advance(10 * INTERRUPTION_HALF_LIFE_S)
        controller.reconcile()
        assert FORECAST_RISK_SCORE.get(label) == 0.0
        assert book.fingerprint() != fp


class TestFeedRebase:
    def test_attach_rebases_epoch_anchored_feed(self):
        """A feed built with the default start_at=0.0 attached to a provider
        whose clock sits at 1e6 must NOT owe a million steps at the first
        poll — attach re-anchors it to the provider clock."""
        harness = Harness()
        feed = MarketFeed(catalog_pools(fixtures.default_catalog()), seed=3)
        harness.cloud.attach_market_feed(feed)
        # Only the initial per-pool snapshot exists; its stamps moved to
        # the provider clock (staleness starts near zero, not at 1e6).
        snapshot = harness.cloud.poll_market_events()
        assert {t.at for t in snapshot} == {harness.clock.now()}
        before = feed.last_seq
        harness.clock.advance(3.0)
        ticks = harness.cloud.poll_market_events(after_seq=before)
        pools = len(catalog_pools(fixtures.default_catalog()))
        assert 0 < len(ticks) <= 3 * pools + before

    def test_rebase_is_a_noop_once_stepped(self):
        feed = MarketFeed(POOLS, seed=4, start_at=10.0)
        feed.advance(12.0)
        history = feed.encode_history()
        feed.rebase(500.0)
        assert feed.encode_history() == history


class TestFakeMarketPricingParity:
    def test_spot_only_zone_keeps_catalog_price(self):
        """A zone with no on-demand offering has no anchor: the fake must
        serve the catalog spot price untouched (the EC2 backend's od<=0
        behavior) — applying the discount to an already-discounted spot
        price would systematically over-prefer the pool."""
        from karpenter_tpu.cloudprovider import InstanceType, Offering

        clock = FakeClock()
        catalog = [
            InstanceType(
                name="spotonly.large",
                capacity={"cpu": 16, "memory": "64Gi", "pods": 110},
                architecture="amd64",
                offerings=[
                    Offering(zone="solo-z", capacity_type="spot", price=0.6)
                ],
            )
        ]
        cloud = FakeCloudProvider(catalog, clock=clock)
        book = PriceBook(clock=clock)
        book.apply(
            price_tick(1, pool=("spotonly.large", "solo-z"), discount=0.55)
        )
        cloud.attach_market(book)
        it = cloud.get_instance_types()[0]
        assert [o.price for o in it.offerings] == [0.6]


class TestInterruptionHazardDedup:
    def test_redelivered_event_notes_hazard_once(self):
        """The interruption feed is at-least-once (a failed ack redelivers);
        note_interruption is a counted increment, so the ingest dedups it
        per event id — one physical interruption must not double its
        hazard contribution."""
        from karpenter_tpu.api.pods import PodSpec
        from karpenter_tpu.api.provisioner import Provisioner

        harness = Harness()
        book = PriceBook(clock=harness.clock)
        harness.interruption.price_book = book
        harness.apply_provisioner(Provisioner(name="default"))
        [pod] = harness.provision(
            PodSpec(name="hz-pod", unschedulable=True, requests={"cpu": "100m"})
        )
        node = harness.expect_scheduled(pod)
        event = harness.cloud.inject_interruption(node, deadline_in=120.0)
        harness.interruption._ingest(event)
        once = book.pool_risk((node.instance_type, node.zone))
        assert once > 0.0
        # Redelivery of the SAME event (ack lost): hazard unchanged.
        rg = book.risk_generation
        harness.interruption._ingest(event)
        assert book.pool_risk((node.instance_type, node.zone)) == once
        assert book.risk_generation == rg


class TestClosedPoolPriceGauge:
    def test_ice_close_drops_the_price_series(self):
        """An ICE-closed pool advertises NO spot offering: its
        market_price_dollars series must drop (not freeze at the last
        price), and the reopen tick republishes it."""
        from karpenter_tpu.controllers.market import MARKET_PRICE_DOLLARS

        harness, feed, controller = build_market()
        harness.clock.advance(2.0)
        controller.reconcile()
        pool = catalog_pools(fixtures.default_catalog())[0]
        kind = f"{pool[0]}/{pool[1]}"
        assert MARKET_PRICE_DOLLARS.get(kind) > 0.0
        feed.force_ice([pool], close=True)
        harness.clock.advance(1.0)
        controller.reconcile()
        assert MARKET_PRICE_DOLLARS.get(kind) == 0.0  # series dropped
        feed.force_ice([pool], close=False)
        harness.clock.advance(1.0)
        controller.reconcile()
        assert MARKET_PRICE_DOLLARS.get(kind) > 0.0
