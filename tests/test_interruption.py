"""Interruption battletest: a reclaim notice on a loaded node must produce
cordon → deadline-driven drain (escalation only past the configured fraction,
override metric emitted) → replacement launched with the interrupted pool
excluded → every displaced pod rebound exactly once → node deleted through
the finalizer path → zero leaked instances after GC — and the same properties
must survive a controller killed at any interruption crashpoint.

`make interruption-smoke` wraps the preemption-storm chaos harness
(tools/interruption_smoke.py) around the same subsystem; this module is the
deterministic matrix. test_backend_parity re-runs the classes against the
fake apiserver.
"""

from __future__ import annotations

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.provisioner import Provisioner, ProvisionerSpec
from karpenter_tpu.cloudprovider import (
    INTERRUPTION_REBALANCE,
    INTERRUPTION_SPOT,
)
from karpenter_tpu.controllers.instancegc import (
    LAUNCH_GRACE_SECONDS,
    InstanceGcController,
)
from karpenter_tpu.controllers.interruption import (
    INTERRUPTION_DISPLACED_TOTAL,
    INTERRUPTION_EVENTS_TOTAL,
    INTERRUPTION_OVERRIDE_TOTAL,
    INTERRUPTION_UNMATCHED_TOTAL,
    InterruptionController,
)
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.selection import SelectionController
from karpenter_tpu.controllers.termination import TerminationController
from karpenter_tpu.utils import crashpoints
from karpenter_tpu.utils.crashpoints import SimulatedCrash

from tests import fixtures
from tests.harness import Harness


class BindRecorder:
    """Watch-driven record of every node a pod was ever bound to (consecutive
    duplicates collapsed) — the 'rebinds exactly once' oracle."""

    def __init__(self, cluster):
        self.bound = {}
        cluster.watch(self._on)

    def _on(self, kind, obj) -> None:
        if kind != "pod" or getattr(obj, "node_name", None) is None:
            return
        seq = self.bound.setdefault(obj.uid, [])
        if not seq or seq[-1] != obj.node_name:
            seq.append(obj.node_name)


def loaded_harness(n_pods=3, pods=None):
    """Harness + provisioner + n pods packed onto one node; returns
    (harness, recorder, pods, node)."""
    h = Harness()
    recorder = BindRecorder(h.cluster)
    h.apply_provisioner(Provisioner(name="default", spec=ProvisionerSpec()))
    pods = pods if pods is not None else fixtures.pods(n_pods)
    h.provision(*pods)
    node = h.expect_scheduled(pods[0])
    for pod in pods[1:]:
        assert h.expect_scheduled(pod).name == node.name
    return h, recorder, pods, node


def restart(h: Harness) -> None:
    """A controller-process restart over the surviving cluster + cloud state,
    including the interruption controller, plus the boot re-list routing
    still-pending pods back through selection."""
    h.provisioning = ProvisioningController(h.cluster, h.cloud, None)
    h.selection = SelectionController(h.cluster, h.provisioning)
    h.termination = TerminationController(h.cluster, h.cloud)
    h.instancegc = InstanceGcController(h.cluster, h.cloud)
    h.interruption = InterruptionController(
        h.cluster, h.cloud, h.provisioning, h.termination
    )
    for provisioner in h.cluster.list_provisioners():
        h.provisioning.reconcile(provisioner.name)
    for pod in h.cluster.list_pods():
        if pod.is_provisionable():
            h.selection.reconcile(pod.namespace, pod.name)


def converge(h: Harness, rounds: int = 5) -> None:
    """Drive interruption sweeps + provisioning + terminations to a fixpoint."""
    for _ in range(rounds):
        h.interruption.reconcile()
        for worker in list(h.provisioning.workers.values()):
            worker.provision()
        h.reconcile_terminations(rounds=3)


def assert_rebound_exactly_once(h, recorder, pods, old_node) -> None:
    for pod in pods:
        live = h.cluster.get_pod(pod.namespace, pod.name)
        assert live.node_name is not None, f"{pod.name} never rebound"
        assert live.node_name != old_node.name
        assert h.cluster.try_get_node(live.node_name) is not None
        assert recorder.bound[pod.uid] == [old_node.name, live.node_name], (
            f"{pod.name} bind history {recorder.bound[pod.uid]}"
        )


def assert_no_leaks(h: Harness) -> None:
    h.clock.advance(LAUNCH_GRACE_SECONDS + 1)
    h.instancegc.reconcile()
    h.instancegc.reconcile()
    node_ids = {n.provider_id for n in h.cluster.list_nodes()}
    leaked = set(h.cloud.instances) - node_ids
    assert not leaked, f"instances with no Node after GC grace: {sorted(leaked)}"


class TestInterruption:
    def test_spot_interruption_drain_replace_rebind(self):
        """The acceptance scenario: injected spot-interruption on a loaded
        node → cordon, drain, replacement excluding the interrupted pool,
        every pod rebound exactly once, node gone, zero leaks, event acked —
        all inside the reclaim deadline."""
        h, recorder, pods, node = loaded_harness()
        pool = (node.instance_type, node.zone, node.capacity_type)
        event = h.cloud.inject_interruption(node, deadline_in=120.0)

        h.interruption.reconcile()
        live = h.cluster.get_node(node.name)
        assert live.unschedulable, "victim was not cordoned"
        assert (
            live.annotations[wellknown.INTERRUPTION_KIND_ANNOTATION]
            == INTERRUPTION_SPOT
        )
        # Replaceable pods were displaced in the first sweep and the node
        # handed to the finalizer path.
        assert live.deletion_timestamp is not None
        assert h.cloud.poll_interruptions() == []  # acked after recording

        # The interrupted pool is blacked out of the catalog the re-solve sees.
        for it in h.cloud.get_instance_types():
            if it.name != node.instance_type:
                continue
            assert not any(
                o.zone == node.zone and o.capacity_type == node.capacity_type
                for o in it.offerings
            ), "interrupted pool still offered"

        converge(h)
        assert_rebound_exactly_once(h, recorder, pods, node)
        for pod in pods:
            replacement = h.cluster.get_node(
                h.cluster.get_pod(pod.namespace, pod.name).node_name
            )
            assert (
                replacement.instance_type,
                replacement.zone,
                replacement.capacity_type,
            ) != pool, "replacement landed on the reclaimed pool"
        assert h.cluster.try_get_node(node.name) is None
        assert node.name in h.cloud.deleted_nodes
        # Bounded interruption-to-rebind window: everything above happened
        # before the reclaim deadline expired.
        assert h.clock.now() < event.deadline
        assert_no_leaks(h)

    def test_polite_phase_respects_pdb_and_do_not_evict(self):
        protected = fixtures.pod(
            annotations={wellknown.DO_NOT_EVICT_ANNOTATION: "true"}
        )
        guarded = [fixtures.pod(labels={"app": "db"}) for _ in range(2)]
        h, recorder, pods, node = loaded_harness(pods=[protected] + guarded)
        h.cluster.apply_pdb("db-pdb", {"app": "db"}, min_available=2)
        before = INTERRUPTION_OVERRIDE_TOTAL.get("pdb")
        h.cloud.inject_interruption(node, deadline_in=120.0)

        h.interruption.reconcile()  # t=0: polite phase — nothing moves
        for pod in pods:
            assert h.cluster.get_pod(pod.namespace, pod.name).node_name == node.name
        live = h.cluster.get_node(node.name)
        assert live.unschedulable and live.deletion_timestamp is None
        assert INTERRUPTION_OVERRIDE_TOTAL.get("pdb") == before

    def test_escalation_overrides_pdb_and_do_not_evict_loudly(self):
        protected = fixtures.pod(
            annotations={wellknown.DO_NOT_EVICT_ANNOTATION: "true"}
        )
        guarded = [fixtures.pod(labels={"app": "db"}) for _ in range(2)]
        h, recorder, pods, node = loaded_harness(pods=[protected] + guarded)
        h.cluster.apply_pdb("db-pdb", {"app": "db"}, min_available=2)
        pdb_before = INTERRUPTION_OVERRIDE_TOTAL.get("pdb")
        dne_before = INTERRUPTION_OVERRIDE_TOTAL.get("do-not-evict")
        h.cloud.inject_interruption(node, deadline_in=120.0)

        h.interruption.reconcile()  # anchors the escalation window at t=0
        h.clock.advance(61.0)  # past escalate_fraction (0.5) of the window
        h.interruption.reconcile()
        assert h.cluster.get_node(node.name).deletion_timestamp is not None
        assert INTERRUPTION_OVERRIDE_TOTAL.get("pdb") - pdb_before == 2
        assert INTERRUPTION_OVERRIDE_TOTAL.get("do-not-evict") - dne_before == 1

        converge(h)
        assert_rebound_exactly_once(h, recorder, pods, node)
        assert_no_leaks(h)

    def test_polite_drain_spends_at_most_the_pdb_budget_per_sweep(self):
        """A displaced pod is down until it rebinds, so it must stop counting
        as healthy: with minAvailable=1 over two replicas, one polite sweep
        may displace exactly ONE — the drain rolls, one budget-worth per
        rebind, instead of taking the whole deployment down at once."""
        guarded = [fixtures.pod(labels={"app": "web"}) for _ in range(2)]
        h, recorder, pods, node = loaded_harness(pods=guarded)
        h.cluster.apply_pdb("web-pdb", {"app": "web"}, min_available=1)
        h.cloud.inject_interruption(node, deadline_in=120.0)
        h.interruption.reconcile()
        pending = [
            p
            for p in pods
            if h.cluster.get_pod(p.namespace, p.name).node_name is None
        ]
        assert len(pending) == 1, "polite sweep overspent the PDB budget"
        assert h.cluster.get_node(node.name).deletion_timestamp is None
        # The displaced replica rebinds; the next sweep takes the other.
        for worker in h.provisioning.workers.values():
            worker.provision()
        h.interruption.reconcile()
        converge(h)
        assert_rebound_exactly_once(h, recorder, pods, node)
        assert_no_leaks(h)

    def test_soft_event_with_a_deadline_still_never_escalates(self):
        """Escalation requires a HARD kind, not merely a deadline: a
        rebalance notice that happens to carry one must not buy the right
        to override protections."""
        protected = fixtures.pod(
            annotations={wellknown.DO_NOT_EVICT_ANNOTATION: "true"}
        )
        h, recorder, pods, node = loaded_harness(pods=[protected])
        before = INTERRUPTION_OVERRIDE_TOTAL.get("do-not-evict")
        h.cloud.inject_interruption(
            node, kind=INTERRUPTION_REBALANCE, deadline_in=120.0
        )
        h.interruption.reconcile()
        h.clock.advance(3600.0)
        h.interruption.reconcile()
        assert (
            h.cluster.get_pod(protected.namespace, protected.name).node_name
            == node.name
        )
        assert INTERRUPTION_OVERRIDE_TOTAL.get("do-not-evict") == before

    def test_rebalance_recommendation_drains_politely_without_escalation(self):
        """A soft event still cordons and replaces, but a protected pod is
        never overridden — there is no deadline to escalate against."""
        protected = fixtures.pod(
            annotations={wellknown.DO_NOT_EVICT_ANNOTATION: "true"}
        )
        plain = fixtures.pod()
        h, recorder, pods, node = loaded_harness(pods=[plain, protected])
        h.cloud.inject_interruption(
            node, kind=INTERRUPTION_REBALANCE, deadline_in=None
        )
        h.interruption.reconcile()
        h.clock.advance(3600.0)
        h.interruption.reconcile()
        live = h.cluster.get_node(node.name)
        assert live.unschedulable
        assert live.deletion_timestamp is None  # protected pod blocks forever
        assert (
            h.cluster.get_pod(protected.namespace, protected.name).node_name
            == node.name
        )
        # The unprotected pod was still displaced for replacement.
        assert h.cluster.get_pod(plain.namespace, plain.name).node_name is None

    def test_hard_event_upgrades_a_soft_stamp(self):
        h, recorder, pods, node = loaded_harness(n_pods=1)
        h.cloud.inject_interruption(
            node, kind=INTERRUPTION_REBALANCE, deadline_in=None
        )
        h.interruption.reconcile()
        assert (
            h.cluster.get_node(node.name).annotations[
                wellknown.INTERRUPTION_KIND_ANNOTATION
            ]
            == INTERRUPTION_REBALANCE
        )
        h.cloud.inject_interruption(node, kind=INTERRUPTION_SPOT, deadline_in=90.0)
        h.interruption.reconcile()
        live = h.cluster.get_node(node.name)
        assert (
            live.annotations[wellknown.INTERRUPTION_KIND_ANNOTATION]
            == INTERRUPTION_SPOT
        )
        assert wellknown.INTERRUPTION_DEADLINE_ANNOTATION in live.annotations

    def test_unmatched_event_is_counted_and_acked(self):
        h = Harness()
        h.apply_provisioner(Provisioner(name="default", spec=ProvisionerSpec()))
        from karpenter_tpu.cloudprovider import NodeSpec

        ghost = NodeSpec(name="ghost", provider_id="fake:///z/fi-ghost")
        before = INTERRUPTION_UNMATCHED_TOTAL.get()
        h.cloud.inject_interruption(ghost)
        h.interruption.reconcile()
        assert INTERRUPTION_UNMATCHED_TOTAL.get() - before == 1
        assert h.cloud.poll_interruptions() == []

    def test_event_metrics_by_kind(self):
        h, recorder, pods, node = loaded_harness(n_pods=1)
        before = INTERRUPTION_EVENTS_TOTAL.get(INTERRUPTION_SPOT)
        displaced_before = INTERRUPTION_DISPLACED_TOTAL.get()
        h.cloud.inject_interruption(node)
        h.interruption.reconcile()
        assert INTERRUPTION_EVENTS_TOTAL.get(INTERRUPTION_SPOT) - before == 1
        assert INTERRUPTION_DISPLACED_TOTAL.get() - displaced_before == 1


# Every interruption site, plus mid-drain at its second passage (first pod
# displaced and fed, controller dies before the rest).
INTERRUPTION_MATRIX = [
    (site, 1) for site in crashpoints.INTERRUPTION_SITES
] + [("interruption.mid-drain", 2)]


class TestInterruptionCrashMatrix:
    """The crash half of the acceptance criteria: the controller killed at
    every interruption commit point, restarted over the surviving state,
    and the reclaim still converges — pods rebound exactly once, victim
    gone, zero leaked instances."""

    @pytest.mark.parametrize(
        "site,at", INTERRUPTION_MATRIX,
        ids=[f"{s}@{a}" for s, a in INTERRUPTION_MATRIX],
    )
    def test_kill_restart_converges(self, site, at):
        h, recorder, pods, node = loaded_harness()
        h.cloud.inject_interruption(node, deadline_in=120.0)
        crashpoints.arm(site, at=at)
        with pytest.raises(SimulatedCrash) as crash:
            h.interruption.reconcile()
        assert crash.value.site == site
        restart(h)
        converge(h)
        assert_rebound_exactly_once(h, recorder, pods, node)
        assert h.cluster.try_get_node(node.name) is None
        assert_no_leaks(h)

    def test_crash_before_ack_redelivers_the_event(self):
        """Record-then-ack: a controller that dies after annotating but
        before acking sees the event again; the re-ingest is idempotent and
        the second attempt acks it."""
        h, recorder, pods, node = loaded_harness(n_pods=1)
        h.cloud.inject_interruption(node)
        crashpoints.arm("interruption.after-annotate")
        with pytest.raises(SimulatedCrash):
            h.interruption.reconcile()
        assert len(h.cloud.poll_interruptions()) == 1  # still queued
        assert (
            wellknown.INTERRUPTION_KIND_ANNOTATION
            in h.cluster.get_node(node.name).annotations
        )
        restart(h)
        h.interruption.reconcile()
        assert h.cloud.poll_interruptions() == []
