"""EC2 provider-stack suite (ref: aws/suite_test.go:104-465 against fake
EC2): vendor defaulting/validation, subnet/SG discovery, launch-template
reuse-by-hash, specialized-hardware AMI routing, spot/OD capacity choice,
override cross-products, ICE blackout fallback, terminate semantics."""

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.provisioner import Constraints, Provisioner, ProvisionerSpec
from karpenter_tpu.api.requirements import Requirement, Requirements
from karpenter_tpu.api.taints import Taint
from karpenter_tpu.cloudprovider import CloudProviderError
from karpenter_tpu.cloudprovider.ec2 import Ec2CloudProvider
from karpenter_tpu.cloudprovider.ec2.api import ApiError, is_not_found
from karpenter_tpu.cloudprovider.ec2.fake import FakeEc2
from karpenter_tpu.cloudprovider.ec2.instancetypes import (
    ICE_BLACKOUT_TTL,
    VM_AVAILABLE_MEMORY_FACTOR,
    adapt_instance_type,
    kube_reserved_cpu_millis,
    pods_per_node,
)
from karpenter_tpu.cloudprovider.ec2.vendor import (
    Ec2Provider,
    VendorValidationError,
    default_provider_blob,
    merge_tags,
)
from karpenter_tpu.utils.clock import FakeClock


def make_api():
    """The Ec2Api backend under test. tests/test_aws_http.py re-runs this
    whole suite with the wire binding (AwsHttpEc2Api over a wire-level fake)
    swapped in here, so every scenario exercises both backends."""
    return FakeEc2()


def make_provider(clock=None):
    clock = clock or FakeClock()
    api = make_api()
    return Ec2CloudProvider(api=api, clock=clock), api, clock


def constraints_with_blob(**requirement_kwargs) -> Constraints:
    provisioner = Provisioner(name="default", spec=ProvisionerSpec())
    if requirement_kwargs:
        provisioner.spec.constraints.requirements = Requirements(
            [Requirement.in_(k, v) for k, v in requirement_kwargs.items()]
        )
    provisioner.spec.constraints.provider = {"instanceProfile": "test-profile"}
    default_provider_blob(provisioner, "test-cluster")
    return provisioner.spec.constraints


class TestVendorExtension:
    def test_defaulting_installs_selectors_arch_and_capacity_type(self):
        provisioner = Provisioner(name="default", spec=ProvisionerSpec())
        provisioner.spec.constraints.provider = {"instanceProfile": "p"}
        default_provider_blob(provisioner, "my-cluster")
        blob = provisioner.spec.constraints.provider
        assert blob["subnetSelector"] == {"kubernetes.io/cluster/my-cluster": "*"}
        assert blob["securityGroupSelector"] == {
            "kubernetes.io/cluster/my-cluster": "*"
        }
        requirements = provisioner.spec.constraints.requirements
        assert requirements.allowed(wellknown.ARCH_LABEL).finite_values() == {"amd64"}
        assert requirements.allowed(
            wellknown.CAPACITY_TYPE_LABEL
        ).finite_values() == {"on-demand"}

    def test_defaulting_respects_existing_requirements(self):
        provisioner = Provisioner(name="default", spec=ProvisionerSpec())
        provisioner.spec.constraints.requirements = Requirements(
            [Requirement.in_(wellknown.CAPACITY_TYPE_LABEL, ["spot"])]
        )
        default_provider_blob(provisioner, "c")
        allowed = provisioner.spec.constraints.requirements.allowed(
            wellknown.CAPACITY_TYPE_LABEL
        )
        assert allowed.finite_values() == {"spot"}

    def test_validation_requires_instance_profile(self):
        with pytest.raises(VendorValidationError, match="instanceProfile"):
            Ec2Provider(
                subnet_selector={"a": "b"}, security_group_selector={"a": "b"}
            ).validate()

    def test_validation_rejects_empty_selector_values(self):
        with pytest.raises(VendorValidationError, match="subnetSelector"):
            Ec2Provider(
                instance_profile="p",
                subnet_selector={"a": ""},
                security_group_selector={"a": "b"},
            ).validate()

    def test_deserialize_rejects_unknown_fields(self):
        constraints = Constraints(provider={"instanceProfile": "p", "bogus": 1})
        with pytest.raises(VendorValidationError, match="bogus"):
            Ec2Provider.deserialize(constraints)

    def test_deserialize_requires_blob(self):
        with pytest.raises(VendorValidationError, match="defaulting hook"):
            Ec2Provider.deserialize(Constraints())

    def test_merge_tags_user_tags_win(self):
        tags = merge_tags("c", "p", {"Name": "custom"})
        assert tags["Name"] == "custom"
        assert tags["kubernetes.io/cluster/c"] == "owned"
        assert tags["karpenter.tpu/cluster/c"] == "owned"


class TestInstanceTypeAdaptation:
    def test_eni_pod_formula_and_memory_factor(self):
        provider, api, _ = make_provider()
        types = {t.name: t for t in provider.get_instance_types()}
        m5_xlarge = types["m5.xlarge"]
        # ENI formula: 4 * (15 - 1) + 2 = 58 (ref: instancetype.go:72-77).
        assert m5_xlarge.get("pods") == 58
        # 16GiB * 0.925, in bytes.
        expected_mib = int(16 * 1024 * VM_AVAILABLE_MEMORY_FACTOR)
        assert m5_xlarge.get("memory") == expected_mib * 1024 * 1024

    def test_overhead_model(self):
        # 2 vCPU: 100m system + 60m (6% of core 1) + 10m (1% of core 2) = 170m.
        assert kube_reserved_cpu_millis(2) == 170
        # 32 vCPU: 100 + 60 + 10 + 10 + 70 = 250m.
        assert kube_reserved_cpu_millis(32) == 250

    def test_opinionated_filter_drops_metal_fpga_and_unknown_families(self):
        provider, _, _ = make_provider()
        names = {t.name for t in provider.get_instance_types()}
        assert "m5.metal" not in names  # bare metal
        assert "f1.2xlarge" not in names  # FPGA
        assert "d3.xlarge" not in names  # unsupported family prefix
        assert {"m5.large", "c5.large", "t3.medium", "p3.8xlarge"} <= names

    def test_gpu_and_arm_catalog_rows(self):
        provider, _, _ = make_provider()
        types = {t.name: t for t in provider.get_instance_types()}
        assert types["p3.8xlarge"].get(wellknown.RESOURCE_NVIDIA_GPU) == 4
        assert types["inf1.6xlarge"].get(wellknown.RESOURCE_AWS_NEURON) == 4
        assert types["m6g.large"].architecture == "arm64"
        assert types["m5.4xlarge"].get(wellknown.RESOURCE_AWS_POD_ENI) == 54

    def test_offerings_carry_prices_and_both_capacity_types(self):
        provider, _, _ = make_provider()
        types = {t.name: t for t in provider.get_instance_types()}
        offerings = types["m5.large"].offerings
        spot = [o for o in offerings if o.capacity_type == "spot"]
        on_demand = [o for o in offerings if o.capacity_type == "on-demand"]
        assert spot and on_demand
        assert all(o.price < od.price for o in spot for od in on_demand)


class TestDiscovery:
    def test_subnet_selector_wildcard_matches_tag_key(self):
        provider, api, _ = make_provider()
        subnets = provider.subnets.get(
            Ec2Provider(
                instance_profile="p",
                subnet_selector={"kubernetes.io/cluster/test-cluster": "*"},
            )
        )
        assert len(subnets) == len(api.zones)

    def test_subnet_selector_exact_value(self):
        provider, api, _ = make_provider()
        subnets = provider.subnets.get(
            Ec2Provider(
                instance_profile="p",
                subnet_selector={"Name": "private-test-zone-1a"},
            )
        )
        assert [s.zone for s in subnets] == ["test-zone-1a"]

    def test_at_most_one_cluster_tagged_security_group(self):
        # sg-test1 and sg-test2 both carry the cluster tag; only the first
        # survives (ref: securitygroups.go:44-66).
        provider, _, _ = make_provider()
        groups = provider.security_groups.get(
            Ec2Provider(
                instance_profile="p",
                security_group_selector={
                    "kubernetes.io/cluster/test-cluster": "*"
                },
            )
        )
        assert groups == ["sg-test1"]

    def test_instance_types_cached_for_five_minutes(self):
        provider, api, clock = make_provider()
        provider.get_instance_types()
        api.instance_type_infos.clear()
        assert provider.get_instance_types()  # cache still serves
        clock.advance(6 * 60)
        assert provider.get_instance_types() == []


class TestLaunchTemplates:
    def test_reused_by_hash_for_identical_constraints(self):
        provider, api, _ = make_provider()
        constraints = constraints_with_blob()
        types = provider.get_instance_types(constraints)
        small = [t for t in types if t.name == "m5.large"]
        for _ in range(2):
            provider.create(constraints, small, 1, lambda node: None)
        assert len(api.calls["create_launch_template"]) == 1

    def test_different_taints_produce_different_templates(self):
        provider, api, _ = make_provider()
        c1 = constraints_with_blob()
        types = [t for t in provider.get_instance_types(c1) if t.name == "m5.large"]
        provider.create(c1, types, 1, lambda node: None)
        c2 = constraints_with_blob()
        c2.taints.append(Taint(key="dedicated", value="gpu", effect="NoSchedule"))
        provider.create(c2, types, 1, lambda node: None)
        assert len(api.calls["create_launch_template"]) == 2

    def test_gpu_types_get_accelerator_image(self):
        provider, api, _ = make_provider()
        constraints = constraints_with_blob()
        types = {t.name: t for t in provider.get_instance_types(constraints)}
        by_ami = provider.amis.get([types["p3.8xlarge"], types["m5.large"]])
        assert len(by_ami) == 2  # gpu image and plain image differ

    def test_user_specified_template_bypasses_generation(self):
        provider, api, _ = make_provider()
        constraints = constraints_with_blob()
        constraints.provider["launchTemplate"] = "my-custom-template"
        api.launch_templates["my-custom-template"] = (
            api.create_launch_template(
                __import__(
                    "karpenter_tpu.cloudprovider.ec2.api", fromlist=["LaunchTemplate"]
                ).LaunchTemplate(name="my-custom-template")
            )
        )
        types = [
            t for t in provider.get_instance_types(constraints) if t.name == "m5.large"
        ]
        provider.create(constraints, types, 1, lambda node: None)
        assert len(api.calls["create_launch_template"]) == 1  # only our manual one
        assert (
            api.calls["create_fleet"][-1].launch_template_name
            == "my-custom-template"
        )


class TestFleetLaunch:
    def test_on_demand_picks_single_cheapest_pool(self):
        provider, api, _ = make_provider()
        constraints = constraints_with_blob()
        types = sorted(
            provider.get_instance_types(constraints), key=lambda t: t.get("cpu")
        )
        nodes = []
        provider.create(constraints, types[:3], 2, nodes.append)
        assert len(nodes) == 2
        assert all(n.capacity_type == "on-demand" for n in nodes)
        request = api.calls["create_fleet"][-1]
        assert request.capacity_type == "on-demand"
        assert all(o.priority is None for o in request.overrides)

    def test_spot_chosen_when_allowed_with_priorities(self):
        provider, api, _ = make_provider()
        constraints = constraints_with_blob(
            **{wellknown.CAPACITY_TYPE_LABEL: ["spot", "on-demand"]}
        )
        types = sorted(
            provider.get_instance_types(constraints), key=lambda t: t.get("cpu")
        )
        nodes = []
        provider.create(constraints, types[:3], 1, nodes.append)
        assert nodes[0].capacity_type == "spot"
        request = api.calls["create_fleet"][-1]
        # Spot priorities follow the smallest-first ordering of the input.
        assert [o.priority for o in request.overrides] == sorted(
            o.priority for o in request.overrides
        )

    def test_zone_constraint_restricts_overrides(self):
        provider, api, _ = make_provider()
        constraints = constraints_with_blob(
            **{wellknown.ZONE_LABEL: ["test-zone-1b"]}
        )
        types = [
            t for t in provider.get_instance_types(constraints) if t.name == "m5.large"
        ]
        nodes = []
        provider.create(constraints, types, 1, nodes.append)
        assert nodes[0].zone == "test-zone-1b"
        assert all(
            o.zone == "test-zone-1b" for o in api.calls["create_fleet"][-1].overrides
        )

    def test_node_carries_labels_capacity_and_provider_id(self):
        provider, _, _ = make_provider()
        constraints = constraints_with_blob()
        types = [
            t for t in provider.get_instance_types(constraints) if t.name == "m5.xlarge"
        ]
        nodes = []
        provider.create(constraints, types, 1, nodes.append)
        node = nodes[0]
        assert node.labels[wellknown.INSTANCE_TYPE_LABEL] == "m5.xlarge"
        assert node.labels[wellknown.ZONE_LABEL] == node.zone
        assert node.provider_id.startswith("aws:///")
        assert node.capacity["cpu"] == 4


class TestInsufficientCapacity:
    def test_ice_pool_blacked_out_and_second_attempt_uses_other_pool(self):
        """The reference's headline ICE test (aws/suite_test.go): first fleet
        call hits InsufficientInstanceCapacity, the offering is blacked out,
        and the retry lands on a different type/zone."""
        provider, api, clock = make_provider()
        constraints = constraints_with_blob()
        types = sorted(
            provider.get_instance_types(constraints), key=lambda t: t.get("cpu")
        )
        target = types[0]
        # Every on-demand pool of the cheapest type is capacity-starved.
        for offering in target.offerings:
            if offering.capacity_type == "on-demand":
                api.insufficient_capacity_pools.add(
                    (target.name, offering.zone, "on-demand")
                )
        nodes = []
        provider.create(constraints, types[:2], 1, nodes.append)
        # Fleet fell through to the second type in the same call.
        assert nodes and nodes[0].instance_type == types[1].name
        # And the pools are now blacked out of the catalog.
        refreshed = {
            t.name: t for t in provider.get_instance_types(constraints)
        }
        assert all(
            o.capacity_type != "on-demand"
            for o in refreshed[target.name].offerings
        ) or target.name not in refreshed

    def test_blackout_expires_after_ttl(self):
        provider, api, clock = make_provider()
        provider.instance_types.cache_unavailable("m5.large", "test-zone-1a", "on-demand")
        assert provider.instance_types.is_unavailable(
            "m5.large", "test-zone-1a", "on-demand"
        )
        clock.advance(ICE_BLACKOUT_TTL + 1)
        assert not provider.instance_types.is_unavailable(
            "m5.large", "test-zone-1a", "on-demand"
        )

    def test_all_pools_starved_reports_errors(self):
        provider, api, _ = make_provider()
        constraints = constraints_with_blob()
        types = [
            t for t in provider.get_instance_types(constraints) if t.name == "m5.large"
        ]
        for offering in types[0].offerings:
            api.insufficient_capacity_pools.add(
                ("m5.large", offering.zone, offering.capacity_type)
            )
        errors = provider.create(constraints, types, 1, lambda node: None)
        assert errors and "InsufficientInstanceCapacity" in str(errors[0])


class TestTerminate:
    def test_terminate_by_provider_id(self):
        provider, api, _ = make_provider()
        constraints = constraints_with_blob()
        types = [
            t for t in provider.get_instance_types(constraints) if t.name == "m5.large"
        ]
        nodes = []
        provider.create(constraints, types, 1, nodes.append)
        provider.delete(nodes[0])
        assert api.calls["terminate_instances"]
        assert not api.instances

    def test_terminate_missing_instance_is_success(self):
        provider, _, _ = make_provider()
        node_like = type(
            "N", (), {"provider_id": "aws:///test-zone-1a/i-doesnotexist", "name": "n"}
        )()
        provider.delete(node_like)  # must not raise

    def test_not_found_classifier(self):
        assert is_not_found(ApiError("InvalidInstanceID.NotFound"))
        assert not is_not_found(ApiError("Throttled"))
        assert not is_not_found(ValueError("x"))


class TestRegistryIntegration:
    def test_ec2_provider_registered_and_installs_hooks(self):
        from karpenter_tpu.api import validation
        from karpenter_tpu.cloudprovider import registry

        provider = registry.new_cloud_provider("ec2")
        try:
            provisioner = Provisioner(name="default", spec=ProvisionerSpec())
            provisioner.spec.constraints.provider = {"instanceProfile": "p"}
            validation.default_provisioner(provisioner)
            assert "subnetSelector" in provisioner.spec.constraints.provider
            validation.validate_provisioner(provisioner)
        finally:
            registry.new_cloud_provider("fake")


class TestEndToEnd:
    def test_pods_provisioned_onto_ec2_backed_nodes(self):
        """Full control-plane slice over the EC2 stack: unschedulable pods →
        selection → batch → solver → fleet launch → bind."""
        from tests import fixtures
        from tests.harness import Harness
        from karpenter_tpu.api import validation
        from karpenter_tpu.cloudprovider import registry
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        provider = Ec2CloudProvider(api=make_api(), clock=clock)
        validation.DEFAULT_HOOK = provider.default
        validation.VALIDATE_HOOK = provider.validate
        try:
            h = Harness(clock=clock, cloud=provider)
            provisioner = Provisioner(name="default", spec=ProvisionerSpec())
            provisioner.spec.constraints.provider = {"instanceProfile": "test"}
            h.apply_provisioner(provisioner)
            pods = [fixtures.pod(name=f"p-{i}") for i in range(5)]
            live = h.provision(*pods)
            for pod in live:
                node = h.expect_scheduled(pod)
                assert node.provider_id.startswith("aws:///")
                assert node.labels[wellknown.INSTANCE_TYPE_LABEL]
        finally:
            validation.DEFAULT_HOOK = None
            validation.VALIDATE_HOOK = None


class TestPoolPinnedLaunch:
    """Cost-aware plans pin per-pool override rows (PoolOption) that flow
    through create() into the fleet request with per-pool priorities."""

    def _pools(self, provider, constraints, names_zones):
        from karpenter_tpu.ops.ffd import PoolOption

        by_name = {t.name: t for t in provider.get_instance_types(constraints)}
        return [
            PoolOption(
                instance_type=by_name[name],
                zone=zone,
                price=0.1 * (i + 1),
                priority=i,
            )
            for i, (name, zone) in enumerate(names_zones)
        ]

    def test_pinned_pools_become_override_rows_with_pool_priorities(self):
        provider, api, _ = make_provider()
        constraints = constraints_with_blob(
            **{wellknown.CAPACITY_TYPE_LABEL: ["spot", "on-demand"]}
        )
        pools = self._pools(
            provider,
            constraints,
            [
                ("m5.large", "test-zone-1b"),
                ("c5.large", "test-zone-1a"),
                ("m5.xlarge", "test-zone-1b"),
            ],
        )
        types = [p.instance_type for p in pools]
        nodes = []
        provider.create(constraints, types, 1, nodes.append, pool_options=pools)
        request = api.calls["create_fleet"][-1]
        rows = [(o.instance_type, o.zone, o.priority) for o in request.overrides]
        assert rows == [
            ("m5.large", "test-zone-1b", 0.0),
            ("c5.large", "test-zone-1a", 1.0),
            ("m5.xlarge", "test-zone-1b", 2.0),
        ]
        assert len(nodes) == 1

    def test_pinned_pools_respect_zone_constraints(self):
        provider, api, _ = make_provider()
        constraints = constraints_with_blob(
            **{wellknown.ZONE_LABEL: ["test-zone-1a"]}
        )
        pools = self._pools(
            provider,
            constraints,
            [("m5.large", "test-zone-1b"), ("c5.large", "test-zone-1a")],
        )
        types = [p.instance_type for p in pools]
        provider.create(constraints, types, 1, lambda n: None, pool_options=pools)
        request = api.calls["create_fleet"][-1]
        assert [(o.instance_type, o.zone) for o in request.overrides] == [
            ("c5.large", "test-zone-1a")
        ]


class TestCrashConsistentLaunch:
    """Restart-safe launches (ISSUE 2): a `launch_id` flows down to
    deterministic CreateFleet ClientTokens, a repeated token is a server-side
    replay (adoption, not a second purchase), and the by-tag instance listing
    gives the leaked-capacity GC its ground truth."""

    def _small_types(self, provider, constraints):
        return [
            t
            for t in provider.get_instance_types(constraints)
            if t.name == "m5.large"
        ]

    def test_launch_id_produces_deterministic_client_token(self):
        """The same logical launch re-issued (crashed controller restarting)
        derives the SAME token — across provider instances, i.e. across
        process restarts."""
        provider_a, api_a, _ = make_provider()
        provider_b, api_b, _ = make_provider()
        constraints = constraints_with_blob()
        for provider, api in ((provider_a, api_a), (provider_b, api_b)):
            types = self._small_types(provider, constraints)
            provider.create(
                constraints, types, 1, lambda node: None, launch_id="batch-1"
            )
        token_a = api_a.calls["create_fleet"][-1].client_token
        token_b = api_b.calls["create_fleet"][-1].client_token
        assert token_a and token_a == token_b

    def test_reissued_launch_adopts_instead_of_rebuying(self):
        provider, api, _ = make_provider()
        constraints = constraints_with_blob()
        types = self._small_types(provider, constraints)
        first, second = [], []
        provider.create(constraints, types, 1, first.append, launch_id="b")
        provider.create(constraints, types, 1, second.append, launch_id="b")
        assert len(api.instances) == 1  # one purchase, not two
        assert [n.provider_id for n in first] == [
            n.provider_id for n in second
        ]
        tokens = {r.client_token for r in api.calls["create_fleet"]}
        assert len(tokens) == 1

    def test_no_launch_id_stays_fresh_purchase(self):
        provider, api, _ = make_provider()
        constraints = constraints_with_blob()
        types = self._small_types(provider, constraints)
        provider.create(constraints, types, 1, lambda node: None)
        provider.create(constraints, types, 1, lambda node: None)
        assert len(api.instances) == 2
        # No launch_id -> no replayable identity: either no token at all
        # (the in-memory fake) or a random one per call (the wire binding),
        # never the SAME token twice.
        tokens = [r.client_token for r in api.calls["create_fleet"]]
        assert not tokens[0] or tokens[0] != tokens[1]

    def test_terminated_replay_falls_through_to_fresh_launch(self):
        """A stale token whose instances are gone (GC reaped them while the
        controller was down) must not wedge the retry loop. EC2 keeps the
        corpses describable and REPLAYS their ids under the original token,
        so the recovery is client-side: filter dead states, then walk to
        the next deterministic token generation and buy fresh."""
        provider, api, _ = make_provider()
        constraints = constraints_with_blob()
        types = self._small_types(provider, constraints)
        nodes = []
        provider.create(constraints, types, 1, nodes.append, launch_id="b")
        first_token = api.calls["create_fleet"][-1].client_token
        api.terminate_instances(list(api.instances))
        fresh = []
        provider.create(constraints, types, 1, fresh.append, launch_id="b")
        assert len(fresh) == 1
        assert fresh[0].provider_id != nodes[0].provider_id
        # The re-issue first replayed the original token (getting only the
        # corpse back), then walked to generation 1 for the fresh purchase.
        replay, fresh_buy = api.calls["create_fleet"][-2:]
        assert replay.client_token == first_token
        assert fresh_buy.client_token and fresh_buy.client_token != first_token
        # Crashing and re-issuing AGAIN reproduces the same walk: the
        # generation sequence is part of the deterministic identity.
        again = []
        provider.create(constraints, types, 1, again.append, launch_id="b")
        assert [n.provider_id for n in again] == [n.provider_id for n in fresh]

    def test_replay_adopts_only_live_instances(self):
        """A mixed replay (some capacity since terminated) adopts the live
        subset — partial fulfillment, never a Node backed by a corpse."""
        provider, api, _ = make_provider()
        constraints = constraints_with_blob()
        types = self._small_types(provider, constraints)
        nodes = []
        provider.create(constraints, types, 2, nodes.append, launch_id="b")
        assert len(nodes) == 2
        from karpenter_tpu.cloudprovider.ec2.instances import parse_instance_id

        dead_id = parse_instance_id(nodes[0].provider_id)
        api.terminate_instances([dead_id])
        adopted = []
        provider.create(constraints, types, 2, adopted.append, launch_id="b")
        assert [n.provider_id for n in adopted] == [nodes[1].provider_id]

    def test_parameter_drift_mints_fresh_token_instead_of_mismatch(self):
        """The token is bound to the full request content: a restart that
        rebuilds different parameters for the same logical launch (blackout
        cache emptied, catalogs drifted) must buy fresh under a NEW token —
        reusing the old one would be rejected by EC2 as
        IdempotentParameterMismatch and wedge the launch loop."""
        provider, api, _ = make_provider()
        constraints = constraints_with_blob()
        types = self._small_types(provider, constraints)
        provider.create(constraints, types, 1, lambda n: None, launch_id="b")
        token_one = api.calls["create_fleet"][-1].client_token
        # Same logical launch, drifted content (quantity here; override rows
        # drift the same way): no ApiError, a distinct token, a fresh buy.
        provider.create(constraints, types, 2, lambda n: None, launch_id="b")
        token_two = api.calls["create_fleet"][-1].client_token
        assert token_two and token_two != token_one
        assert len(api.instances) == 3

    def test_fake_rejects_reused_token_with_drifted_parameters(self):
        """FakeEc2 faithfulness: EC2 rejects a reused ClientToken whose
        request parameters changed — the guard that makes any future
        token-derivation regression loud in tests."""
        from karpenter_tpu.cloudprovider.ec2.api import (
            FleetOverride,
            FleetRequest,
            LaunchTemplate,
        )

        api = make_api()
        api.create_launch_template(LaunchTemplate(name="lt"))
        override = FleetOverride(
            instance_type="m5.large", subnet_id="subnet-test1",
            zone="test-zone-1a",
        )
        request = FleetRequest(
            launch_template_name="lt", overrides=[override],
            capacity_type="on-demand", quantity=1, client_token="tok",
        )
        api.create_fleet(request)
        drifted = FleetRequest(
            launch_template_name="lt", overrides=[override],
            capacity_type="on-demand", quantity=2, client_token="tok",
        )
        with pytest.raises(ApiError) as error:
            api.create_fleet(drifted)
        assert error.value.code == "IdempotentParameterMismatch"

    def test_list_instances_reports_owned_capacity(self):
        provider, api, _ = make_provider()
        constraints = constraints_with_blob()
        types = self._small_types(provider, constraints)
        nodes = []
        provider.create(constraints, types, 1, nodes.append)
        listed = provider.list_instances()
        assert [i.provider_id for i in listed] == [nodes[0].provider_id]
        assert listed[0].instance_type == "m5.large"
        assert listed[0].capacity_type == "on-demand"

    def test_list_instances_excludes_other_clusters(self):
        """The by-tag sweep must only see instances THIS cluster owns —
        terminating another cluster's capacity is the one failure mode worse
        than leaking ours."""
        from karpenter_tpu.cloudprovider.ec2.api import Instance

        provider, api, _ = make_provider()
        api.instances["i-foreign"] = Instance(
            instance_id="i-foreign",
            instance_type="m5.large",
            zone="test-zone-1a",
            tags={"karpenter.tpu/cluster/other-cluster": "owned"},
        )
        assert provider.list_instances() == []

    def test_terminate_instance_tolerates_not_found(self):
        from karpenter_tpu.cloudprovider import CloudInstance

        provider, _, _ = make_provider()
        provider.terminate_instance(
            CloudInstance(instance_id="i-gone", provider_id="aws:///z/i-gone")
        )  # raced normal termination: must not raise

    def test_terminate_instance_removes_owned_capacity(self):
        provider, api, _ = make_provider()
        constraints = constraints_with_blob()
        types = self._small_types(provider, constraints)
        provider.create(constraints, types, 1, lambda node: None)
        (listed,) = provider.list_instances()
        provider.terminate_instance(listed)
        assert provider.list_instances() == []


class TestInterruptionFeed:
    """EventBridge envelope -> typed InterruptionEvent, at-least-once ack,
    noise filtering, and interruption-driven pool blackout — the EC2 half of
    the interruption subsystem (controllers/interruption.py drives it)."""

    def test_spot_warning_maps_to_hard_event_with_deadline(self):
        import datetime

        from karpenter_tpu.cloudprovider import INTERRUPTION_SPOT

        cloud, api, clock = make_provider()
        api.inject_interruption_message(
            "EC2 Spot Instance Interruption Warning",
            "i-0123",
            time_iso="2026-08-02T12:00:00Z",
        )
        events = cloud.poll_interruptions()
        assert len(events) == 1
        event = events[0]
        assert event.kind == INTERRUPTION_SPOT and event.is_hard()
        assert event.instance_id == "i-0123"
        warned_at = datetime.datetime(
            2026, 8, 2, 12, 0, tzinfo=datetime.timezone.utc
        ).timestamp()
        assert event.deadline == pytest.approx(warned_at + 120.0)

    def test_unacked_event_redelivers_then_ack_removes(self):
        cloud, api, clock = make_provider()
        api.inject_interruption_message(
            "EC2 Spot Instance Interruption Warning", "i-0123"
        )
        (event,) = cloud.poll_interruptions()
        assert len(cloud.poll_interruptions()) == 1  # visibility model
        cloud.ack_interruption(event)
        assert cloud.poll_interruptions() == []
        assert api.calls["delete_queue_message"]

    def test_rebalance_recommendation_is_soft(self):
        from karpenter_tpu.cloudprovider import INTERRUPTION_REBALANCE

        cloud, api, clock = make_provider()
        api.inject_interruption_message(
            "EC2 Instance Rebalance Recommendation", "i-0456"
        )
        (event,) = cloud.poll_interruptions()
        assert event.kind == INTERRUPTION_REBALANCE
        assert not event.is_hard() and event.deadline is None

    def test_stopping_state_change_is_hard(self):
        from karpenter_tpu.cloudprovider import INTERRUPTION_STOPPING

        cloud, api, clock = make_provider()
        api.inject_interruption_message(
            "EC2 Instance State-change Notification",
            "i-0789",
            detail={"state": "stopping"},
        )
        (event,) = cloud.poll_interruptions()
        assert event.kind == INTERRUPTION_STOPPING and event.is_hard()
        assert event.deadline is not None

    def test_noise_is_deleted_not_delivered(self):
        """Running-state changes and unparseable bodies must not clog the
        queue: poll filters AND deletes them."""
        cloud, api, clock = make_provider()
        api.inject_interruption_message(
            "EC2 Instance State-change Notification",
            "i-0aaa",
            detail={"state": "running"},
        )
        assert cloud.poll_interruptions() == []
        assert cloud.poll_interruptions() == []  # deleted, not redelivered
        assert len(api.calls["delete_queue_message"]) == 1

    def test_poison_messages_cannot_wedge_the_feed(self):
        """Valid-JSON-but-wrong-shape bodies (anything can land on an SQS
        queue) must be deleted as noise, not raise out of the poll — a
        poison message re-delivering forever would starve every real
        reclaim warning behind it."""
        from karpenter_tpu.cloudprovider.ec2.api import QueueMessage

        cloud, api, clock = make_provider()
        for poison in ("123", "[1, 2]", '"text"', '{"detail": 7, "detail-type": 5}',
                       '{"detail-type": "EC2 Spot Instance Interruption Warning", '
                       '"detail": {"instance-id": 9}, "time": 4}'):
            handle = f"rh-poison-{len(api.interruption_messages)}"
            api.interruption_messages[handle] = QueueMessage(
                message_id=handle, receipt_handle=handle, body=poison
            )
        api.inject_interruption_message(
            "EC2 Spot Instance Interruption Warning", "i-real"
        )
        events = cloud.poll_interruptions()
        assert [e.instance_id for e in events] == ["i-real"]
        # The poison is gone; only the real (unacked) event remains queued.
        assert len(api.interruption_messages) == 1

    def test_interruption_blackout_excludes_pool_from_catalog(self):
        cloud, api, clock = make_provider()
        zone = api.zones[0]
        cloud.blackout_offering("m5.large", zone, "spot")
        for it in cloud.get_instance_types():
            if it.name != "m5.large":
                continue
            assert not any(
                o.zone == zone and o.capacity_type == "spot"
                for o in it.offerings
            ), "blacked-out pool still offered"


def _raw_fake(api):
    """The underlying FakeEc2 regardless of backend — the wire binding
    (tests/test_aws_http.py) exposes it as .fake, so market-history
    injection works when this class re-runs over real bytes."""
    return getattr(api, "fake", api)


class TestMarketPoll:
    """DescribeSpotPriceHistory -> poll_market_events: rows become a
    strictly-ordered tick stream with seqs that stay stable when the API's
    sliding history window drops old rows (design/market.md)."""

    def test_rows_become_ordered_ticks_with_catalog_discounts(self):
        cloud, api, clock = make_provider()
        fake = _raw_fake(api)
        zone = fake.zones[0]
        # Injected newest-first: the poll's total order sorts them back.
        fake.inject_spot_price("m5.large", zone, 0.060, timestamp=20.0)
        fake.inject_spot_price("m5.large", zone, 0.048, timestamp=10.0)
        ticks = cloud.poll_market_events()
        assert [t.seq for t in ticks] == [1, 2]
        assert [t.at for t in ticks] == [10.0, 20.0]
        # Discounts anchor on the catalog's on-demand price (0.096).
        assert ticks[0].discount == pytest.approx(0.048 / 0.096)
        assert ticks[1].discount == pytest.approx(0.060 / 0.096)
        assert all(t.kind == "price" for t in ticks)
        # Cursor semantics: nothing new past the high-water mark, and a
        # re-fold from 0 replays the identical sequence.
        assert cloud.poll_market_events(after_seq=2) == []
        assert [t.encode() for t in cloud.poll_market_events(0)] == [
            t.encode() for t in ticks
        ]

    def test_window_slide_keeps_seqs_stable(self):
        """The regression the rank-derived numbering had: rows aging out of
        the sliding window must not renumber (and so re-deliver or hide)
        later rows."""
        cloud, api, clock = make_provider()
        fake = _raw_fake(api)
        zone = fake.zones[0]
        fake.inject_spot_price("m5.large", zone, 0.048, timestamp=10.0)
        fake.inject_spot_price("m5.large", zone, 0.050, timestamp=20.0)
        assert [t.seq for t in cloud.poll_market_events()] == [1, 2]
        # The window slides: the oldest row ages out while a new one lands.
        fake.spot_price_history.pop(0)
        fake.inject_spot_price("m5.large", zone, 0.052, timestamp=30.0)
        fresh = cloud.poll_market_events(after_seq=2)
        assert [t.seq for t in fresh] == [3]
        assert fresh[0].discount == pytest.approx(0.052 / 0.096)

    def test_stale_and_unanchored_rows_are_dropped(self):
        cloud, api, clock = make_provider()
        fake = _raw_fake(api)
        zone = fake.zones[0]
        fake.inject_spot_price("m5.large", zone, 0.048, timestamp=10.0)
        assert len(cloud.poll_market_events()) == 1
        # A late row sorting BELOW the cursor is stale information (the
        # book only folds forward) — dropped, never renumbered.
        fake.inject_spot_price("m5.large", zone, 0.040, timestamp=5.0)
        # A row with no on-demand anchor advances the cursor but emits no
        # tick; seqs stay dense.
        fake.inject_spot_price("unknown.type", zone, 0.020, timestamp=40.0)
        assert cloud.poll_market_events(after_seq=1) == []
        fake.inject_spot_price("m5.large", zone, 0.060, timestamp=50.0)
        assert [t.seq for t in cloud.poll_market_events(after_seq=1)] == [2]

    def test_late_row_for_quiet_pool_is_not_shadowed(self):
        """Cursors are PER POOL: DescribeSpotPriceHistory is eventually
        consistent, so a late-published row for pool B must fold even when
        pool A's cursor has already advanced past its timestamp."""
        cloud, api, clock = make_provider()
        fake = _raw_fake(api)
        za, zb = fake.zones[0], fake.zones[1]
        fake.inject_spot_price("m5.large", za, 0.048, timestamp=180.0)
        assert [t.seq for t in cloud.poll_market_events()] == [1]
        # The late row for a DIFFERENT pool, older than A's cursor.
        fake.inject_spot_price("m5.large", zb, 0.050, timestamp=150.0)
        late = cloud.poll_market_events(after_seq=1)
        assert [(t.seq, t.zone, t.at) for t in late] == [(2, zb, 150.0)]
        # But a late row for the SAME pool below its own cursor stays stale.
        fake.inject_spot_price("m5.large", za, 0.040, timestamp=100.0)
        assert cloud.poll_market_events(after_seq=2) == []

    def test_history_compaction_keeps_snapshot_and_seqs(self):
        """Past the retained-tick budget the oldest half collapses to its
        newest tick per pool; seqs survive compaction (ordered, not dense)
        and a re-fold from 0 still anchors every pool."""
        cloud, api, clock = make_provider()
        fake = _raw_fake(api)
        za, zb = fake.zones[0], fake.zones[1]
        cloud.MARKET_HISTORY_MAX = 4
        # Pool B ticks once early, then only pool A keeps ticking.
        fake.inject_spot_price("m5.large", zb, 0.050, timestamp=1.0)
        for i in range(6):
            fake.inject_spot_price("m5.large", za, 0.048 + 0.001 * i,
                                   timestamp=10.0 + i)
        replay = cloud.poll_market_events(0)
        seqs = [t.seq for t in replay]
        assert seqs == sorted(seqs) and len(seqs) < 7
        # B's newest (only) tick survived compaction as its snapshot...
        assert [t.zone for t in replay if t.zone == zb] == [zb]
        # ...and A's latest price is the stream's last word on A.
        a_ticks = [t for t in replay if t.zone == za]
        assert a_ticks[-1].discount == pytest.approx(0.053 / 0.096)
        # The cursor still rejects anything at or below the folded window.
        assert cloud.poll_market_events(after_seq=seqs[-1]) == []

    def test_rising_price_raises_forecast_hazard_via_depth_proxy(self):
        """EC2 never reveals pool depth; ticks proxy it as 1/discount so a
        sustained price climb (the pool being bought out) fires the
        forecast's trend leg BEFORE any interruption lands — folding the
        polled ticks into a PriceBook must yield nonzero risk."""
        from karpenter_tpu.market.pricebook import PriceBook
        from karpenter_tpu.utils.clock import FakeClock

        cloud, api, clock = make_provider()
        fake = _raw_fake(api)
        zone = fake.zones[0]
        for i, price in enumerate((0.048, 0.060, 0.075, 0.090)):
            fake.inject_spot_price("m5.large", zone, price, timestamp=float(i))
        book = PriceBook(clock=FakeClock())
        for tick in cloud.poll_market_events():
            assert tick.depth == pytest.approx(1.0 / tick.discount)
            book.apply(tick)
        assert book.pool_risk(("m5.large", zone)) > 0.0
