"""API-layer tests: quantities, requirement algebra, taints, constraints,
validation — mirrors the reference's v1alpha5 suite (ref:
pkg/apis/provisioning/v1alpha5/suite_test.go:42-154) plus the Consolidate and
compatibility corner cases called out in requirements.go:81-133."""

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec, PreferredTerm
from karpenter_tpu.api.provisioner import (
    Constraints,
    Limits,
    PodIncompatibleError,
    Provisioner,
    ProvisionerSpec,
)
from karpenter_tpu.api.requirements import Requirement, Requirements
from karpenter_tpu.api.resources import (
    add_resources,
    fits_within,
    parse_quantity,
    subtract_resources,
)
from karpenter_tpu.api.taints import (
    Taint,
    Toleration,
    OP_EXISTS,
    taints_for_pod,
    taints_tolerate_pod,
)
from karpenter_tpu.api.validation import ValidationError, validate_provisioner


class TestQuantities:
    def test_plain_numbers(self):
        assert parse_quantity("2") == 2.0
        assert parse_quantity(1.5) == 1.5
        assert parse_quantity("0.5") == 0.5

    def test_millicores(self):
        assert parse_quantity("100m") == pytest.approx(0.1)
        assert parse_quantity("1500m") == pytest.approx(1.5)

    def test_binary_suffixes(self):
        assert parse_quantity("512Mi") == 512 * 1024**2
        assert parse_quantity("2Gi") == 2 * 1024**3
        assert parse_quantity("1Ki") == 1024

    def test_decimal_suffixes(self):
        assert parse_quantity("1k") == 1000.0
        assert parse_quantity("2G") == 2e9

    def test_invalid(self):
        with pytest.raises(ValueError):
            parse_quantity("abc")

    def test_arithmetic(self):
        a = {"cpu": 1.0, "memory": 100.0}
        b = {"cpu": 2.0, "pods": 1.0}
        assert add_resources(a, b) == {"cpu": 3.0, "memory": 100.0, "pods": 1.0}
        assert subtract_resources(add_resources(a, b), b) == {
            "cpu": 1.0,
            "memory": 100.0,
            "pods": 0.0,
        }

    def test_fits_within(self):
        assert fits_within({"cpu": 1.0}, {"cpu": 1.0, "memory": 5.0})
        assert not fits_within({"cpu": 2.0}, {"cpu": 1.0})
        # Zero requests fit anywhere, even against absent capacity.
        assert fits_within({"gpu": 0.0}, {})


class TestRequirements:
    def test_in_intersection(self):
        reqs = Requirements(
            [
                Requirement.in_("zone", ["a", "b", "c"]),
                Requirement.in_("zone", ["b", "c", "d"]),
            ]
        )
        assert reqs.allowed("zone").finite_values() == {"b", "c"}

    def test_not_in_subtraction(self):
        reqs = Requirements(
            [
                Requirement.in_("zone", ["a", "b"]),
                Requirement.not_in("zone", ["b"]),
            ]
        )
        assert reqs.allowed("zone").finite_values() == {"a"}

    def test_unconstrained_key_is_complement(self):
        reqs = Requirements([Requirement.not_in("zone", ["a"])])
        keyset = reqs.allowed("zone")
        assert keyset.complement
        assert keyset.contains("b")
        assert not keyset.contains("a")

    def test_conflict_is_empty_not_dropped(self):
        # Ref: requirements.go Consolidate preserves conflicting (empty) sets.
        reqs = Requirements(
            [Requirement.in_("zone", ["a"]), Requirement.in_("zone", ["b"])]
        )
        assert reqs.allowed("zone").is_empty()
        consolidated = reqs.consolidate()
        assert consolidated.allowed("zone").is_empty()
        assert len(consolidated) == 1  # the conflict survives consolidation

    def test_consolidate_merges_per_key(self):
        reqs = Requirements(
            [
                Requirement.in_("zone", ["a", "b"]),
                Requirement.in_("arch", ["amd64"]),
                Requirement.not_in("zone", ["a"]),
            ]
        )
        consolidated = reqs.consolidate()
        assert len(consolidated) == 2
        assert consolidated.allowed("zone").finite_values() == {"b"}
        assert consolidated.allowed("arch").finite_values() == {"amd64"}

    def test_compatibility(self):
        a = Requirements([Requirement.in_("zone", ["a", "b"])])
        b = Requirements([Requirement.in_("zone", ["b", "c"])])
        c = Requirements([Requirement.in_("zone", ["c"])])
        assert a.compatible_with(b)
        assert not a.compatible_with(c)
        # Unconstrained is compatible with anything.
        assert Requirements().compatible_with(c)

    def test_labels_to_requirements(self):
        reqs = Requirements.from_labels({"team": "infra"})
        assert reqs.allowed("team").finite_values() == {"infra"}

    def test_satisfied_by_labels(self):
        reqs = Requirements([Requirement.in_("zone", ["a"])])
        assert reqs.satisfied_by_labels({"zone": "a"})
        assert not reqs.satisfied_by_labels({"zone": "b"})
        assert not reqs.satisfied_by_labels({})  # finite set requires presence
        not_in = Requirements([Requirement.not_in("zone", ["a"])])
        assert not_in.satisfied_by_labels({})  # complement tolerates absence

    def test_well_known_accessors(self):
        reqs = Requirements(
            [
                Requirement.in_(wellknown.ZONE_LABEL, ["us-east-1a"]),
                Requirement.in_(wellknown.CAPACITY_TYPE_LABEL, ["spot"]),
                Requirement.in_("custom", ["x"]),
            ]
        )
        assert reqs.zones() == {"us-east-1a"}
        assert reqs.capacity_types() == {"spot"}
        assert reqs.instance_types() is None  # unconstrained
        assert len(reqs.well_known()) == 2

    def test_canonical_key_grouping(self):
        a = Requirements(
            [Requirement.in_("zone", ["a", "b"]), Requirement.in_("arch", ["amd64"])]
        )
        b = Requirements(
            [Requirement.in_("arch", ["amd64"]), Requirement.in_("zone", ["b", "a"])]
        )
        assert a.canonical_key() == b.canonical_key()


class TestTaints:
    def test_tolerates(self):
        taints = [Taint(key="team", value="infra")]
        assert not taints_tolerate_pod(taints, [])
        assert taints_tolerate_pod(
            taints, [Toleration(key="team", value="infra", effect="NoSchedule")]
        )
        assert taints_tolerate_pod(taints, [Toleration(key="team", operator=OP_EXISTS)])
        assert taints_tolerate_pod(taints, [Toleration(operator=OP_EXISTS)])
        assert not taints_tolerate_pod(taints, [Toleration(key="team", value="other")])

    def test_prefer_no_schedule_never_blocks(self):
        taints = [Taint(key="soft", effect="PreferNoSchedule")]
        assert taints_tolerate_pod(taints, [])

    def test_taints_for_pod_imprints_equal_tolerations(self):
        tolerations = [
            Toleration(key="dedicated", value="ml", effect="NoSchedule"),
            Toleration(key="any", operator=OP_EXISTS),  # Exists: no imprint
            Toleration(key="noeffect", value="x"),  # no effect: no imprint
        ]
        taints = taints_for_pod([], tolerations)
        assert taints == [Taint(key="dedicated", value="ml", effect="NoSchedule")]

    def test_taints_for_pod_no_duplicates(self):
        existing = [Taint(key="dedicated", value="other", effect="NoSchedule")]
        tolerations = [Toleration(key="dedicated", value="ml", effect="NoSchedule")]
        assert taints_for_pod(existing, tolerations) == existing


class TestPodSpec:
    def test_pod_slot_implied(self):
        pod = PodSpec(name="p", requests={"cpu": "1"})
        assert pod.requests[wellknown.RESOURCE_PODS] == 1.0

    def test_scheduling_requirements_fold(self):
        pod = PodSpec(
            name="p",
            node_selector={"zone": "a"},
            preferred_terms=[
                PreferredTerm(weight=1, requirements=[Requirement.in_("arch", ["arm64"])]),
                PreferredTerm(weight=10, requirements=[Requirement.in_("arch", ["amd64"])]),
            ],
            required_terms=[
                [Requirement.in_("os", ["linux"])],
                [Requirement.in_("os", ["windows"])],  # dropped: only first term
            ],
        )
        reqs = pod.scheduling_requirements()
        assert reqs.allowed("zone").finite_values() == {"a"}
        assert reqs.allowed("arch").finite_values() == {"amd64"}  # heaviest wins
        assert reqs.allowed("os").finite_values() == {"linux"}

    def test_provisionable(self):
        pod = PodSpec(name="p", unschedulable=True)
        assert pod.is_provisionable()
        assert not PodSpec(name="p2").is_provisionable()
        assert not PodSpec(
            name="p3", unschedulable=True, owner_kind="DaemonSet"
        ).is_provisionable()
        assert not PodSpec(
            name="p4", unschedulable=True, node_name="n1"
        ).is_provisionable()


class TestConstraints:
    def test_validate_pod_taints(self):
        constraints = Constraints(taints=[Taint(key="team", value="infra")])
        with pytest.raises(PodIncompatibleError):
            constraints.validate_pod(PodSpec(name="p"))
        constraints.validate_pod(
            PodSpec(name="p", tolerations=[Toleration(key="team", value="infra")])
        )

    def test_validate_pod_requirements(self):
        constraints = Constraints(
            requirements=Requirements([Requirement.in_(wellknown.ZONE_LABEL, ["a"])])
        )
        constraints.validate_pod(PodSpec(name="ok"))
        with pytest.raises(PodIncompatibleError):
            constraints.validate_pod(
                PodSpec(name="bad", node_selector={wellknown.ZONE_LABEL: "b"})
            )

    def test_labels_act_as_requirements(self):
        constraints = Constraints(labels={"team": "infra"})
        with pytest.raises(PodIncompatibleError):
            constraints.validate_pod(PodSpec(name="p", node_selector={"team": "web"}))

    def test_tighten_is_well_known_only(self):
        constraints = Constraints(
            requirements=Requirements(
                [Requirement.in_(wellknown.ZONE_LABEL, ["a", "b"])]
            )
        )
        pod = PodSpec(name="p", node_selector={wellknown.ZONE_LABEL: "a", "custom": "x"})
        tightened = constraints.tighten(pod)
        assert tightened.requirements.allowed(wellknown.ZONE_LABEL).finite_values() == {"a"}
        assert tightened.requirements.allowed("custom").is_any()  # filtered out


class TestLimits:
    def test_exceeded_by(self):
        limits = Limits(resources={"cpu": "100"})
        assert limits.exceeded_by({"cpu": 50.0}) is None
        assert limits.exceeded_by({"cpu": 100.0}) is not None
        assert limits.exceeded_by({}) is None


class TestValidation:
    def _provisioner(self, **kwargs) -> Provisioner:
        return Provisioner(name="default", spec=ProvisionerSpec(**kwargs))

    def test_valid_provisioner(self):
        validate_provisioner(self._provisioner())

    def test_negative_ttl(self):
        with pytest.raises(ValidationError):
            validate_provisioner(self._provisioner(ttl_seconds_after_empty=-1))

    def test_restricted_label_domain(self):
        with pytest.raises(ValidationError):
            validate_provisioner(
                self._provisioner(
                    constraints=Constraints(labels={"karpenter.sh/custom": "x"})
                )
            )

    def test_well_known_requirement_keys_only(self):
        with pytest.raises(ValidationError):
            validate_provisioner(
                self._provisioner(
                    constraints=Constraints(
                        requirements=Requirements([Requirement.in_("custom", ["x"])])
                    )
                )
            )

    def test_bad_operator(self):
        with pytest.raises(ValidationError):
            validate_provisioner(
                self._provisioner(
                    constraints=Constraints(
                        requirements=Requirements(
                            [Requirement(key=wellknown.ZONE_LABEL, operator="Exists", values=())]
                        )
                    )
                )
            )

    def test_bad_taint_effect(self):
        with pytest.raises(ValidationError):
            validate_provisioner(
                self._provisioner(
                    constraints=Constraints(taints=[Taint(key="k", effect="Nope")])
                )
            )


class TestRequestImmutability:
    def test_requests_frozen_after_parse(self):
        """The per-pod dense-vector cache depends on requests never changing
        after construction; the invariant is enforced, not assumed."""
        import pytest

        from karpenter_tpu.api.pods import PodSpec

        pod = PodSpec(name="frozen", requests={"cpu": "1"})
        with pytest.raises(TypeError):
            pod.requests["cpu"] = 2.0
        # Reading and copying still work.
        assert pod.requests["cpu"] == 1.0
        assert dict(pod.total_requests())["cpu"] == 1.0
