"""Tensor codecs and method constants for the solver wire protocol.

gRPC service stubs are hand-wired (grpc_tools isn't vendored; protoc only
generates the messages), so the method paths and (de)serializers live here
and both ends import them — the contract is in exactly one place.
"""

from __future__ import annotations

import numpy as np

from karpenter_tpu.solver_service import solver_pb2 as pb

SERVICE = "karpenter.solver.v1.Solver"
SOLVE_METHOD = f"/{SERVICE}/Solve"
SOLVE_STREAM_METHOD = f"/{SERVICE}/SolveStream"
HEALTH_METHOD = f"/{SERVICE}/Health"

_DTYPES = {
    "f32": np.float32,
    "f64": np.float64,
    "i32": np.int32,
    "i64": np.int64,
    "bool": np.bool_,
}
_DTYPE_NAMES = {np.dtype(v): k for k, v in _DTYPES.items()}


def encode_tensor(array: np.ndarray) -> pb.Tensor:
    array = np.ascontiguousarray(array)
    name = _DTYPE_NAMES.get(array.dtype)
    if name is None:
        raise ValueError(f"unsupported wire dtype {array.dtype}")
    return pb.Tensor(shape=list(array.shape), dtype=name, data=array.tobytes())


def decode_tensor(message: pb.Tensor) -> np.ndarray:
    dtype = _DTYPES.get(message.dtype)
    if dtype is None:
        raise ValueError(f"unsupported wire dtype {message.dtype!r}")
    array = np.frombuffer(message.data, dtype=dtype)
    return array.reshape(tuple(message.shape)).copy()
