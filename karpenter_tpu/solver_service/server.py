"""The solver sidecar: a stateless gRPC service owning the accelerator.

Ref: SURVEY.md §2.7 / §7 step 5 — the north star's `pkg/cloudprovider/solver`
plugin analogue. The control plane (any process, any language with protobuf)
sends one SolveRequest per schedule; the sidecar runs the fused TPU cost
solve (models/solver.cost_solve_dense) and streams back launch rounds +
price-ranked pool options as indices. No request state survives a call
(ref: SURVEY.md §5 checkpoint/resume — the reference keeps all state in the
cluster API; the sidecar keeps none at all), so a crashed sidecar is replaced
by simply restarting it; the client meanwhile degrades to host greedy.

Run: python -m karpenter_tpu.solver_service.server --port 9090
"""

from __future__ import annotations

import argparse
import threading
import time
from concurrent import futures
from typing import Optional

import grpc
import numpy as np

from karpenter_tpu.models import solver as solver_models
from karpenter_tpu.ops import ffd, native
from karpenter_tpu.solver_service import solver_pb2 as pb
from karpenter_tpu.solver_service import wire
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.tracing import TRACE_METADATA_KEY, TRACER

log = klog.named("solver-server")


def _trace_from_context(context) -> Optional[str]:
    """The batch trace id the client rode in on the RPC metadata, or None.
    Request-scoped stream contexts (no invocation_metadata) read as None —
    the enclosing stream handler already entered the trace."""
    metadata_fn = getattr(context, "invocation_metadata", None)
    if metadata_fn is None:
        return None
    for key, value in metadata_fn():
        if key == TRACE_METADATA_KEY:
            return value
    return None


class _RequestAbort(Exception):
    def __init__(self, code, details):
        super().__init__(details)
        self.code = code
        self.details = details


class _RequestScopedContext:
    """abort() raises instead of killing the stream: one malformed request in
    a SolveStream batch must not tear down every other in-flight response and
    trip the client's 30s blackout + whole-batch fallback."""

    def abort(self, code, details):
        raise _RequestAbort(code, details)


def _error_response(detail: str) -> pb.SolveResponse:
    """Per-request failure marker inside a stream: the client host-solves
    this item and keeps the rest of the batch."""
    log.warning("stream request failed, marking for client fallback: %s", detail)
    response = pb.SolveResponse()
    response.solver = "error"
    response.fallback = True
    return response


def _host_rounds(vectors, counts, capacity, total, quirk):
    """Compiled-host FFD with pure-Python fallback — the no-accelerator path."""
    result = native.ffd_pack_rounds(
        vectors, counts.astype(np.int64), capacity, total, quirk=quirk
    )
    if result is not None:
        return result
    return ffd.pack_rounds_dense(vectors, counts, capacity, total, quirk=quirk)


def _encode_rounds(response, rounds, options_by_fill=None):
    """Fill Round/OptionSet messages; option sets dedup by fill bytes."""
    set_index: dict = {}
    for t, fill, repl in rounds:
        option_set = -1
        if options_by_fill is not None:
            # Key from the solver's own fill array (kernel fills are i32, LP
            # fills i64) BEFORE widening for the wire.
            key = fill.tobytes()
            option_set = set_index.get(key)
            if option_set is None:
                type_indices, pool_rows = options_by_fill[key]
                message = pb.OptionSet(
                    type_indices=list(type_indices),
                    has_pools=pool_rows is not None,
                )
                if pool_rows is not None:
                    for ti, zi, price in pool_rows:
                        message.pools.add(type_index=ti, zone_index=zi, price=price)
                option_set = len(response.option_sets)
                response.option_sets.append(message)
                set_index[key] = option_set
        response.rounds.add(
            type_index=int(t),
            fill=wire.encode_tensor(fill.astype(np.int64)),
            replication=int(repl),
            option_set=option_set,
        )


class _Handler:
    """RPC implementations. gRPC handlers are hand-wired generic method
    handlers (no generated stubs — grpc_tools isn't vendored)."""

    def __init__(self):
        self.solves = 0
        self._lock = threading.Lock()
        # Flips after boot warmup precompiles the bucket ladder; readiness
        # probes (client.healthy / k8s) gate traffic on it so the first
        # production batch never pays a multi-second jit compile.
        self.warmed = threading.Event()

    def solve(self, request: pb.SolveRequest, context) -> pb.SolveResponse:
        with TRACER.trace(_trace_from_context(context)), TRACER.span(
            "solver.serve", mode=request.mode or "cost"
        ):
            return self._solve(request, context)

    def _solve(self, request: pb.SolveRequest, context) -> pb.SolveResponse:
        start = time.perf_counter()
        vectors = wire.decode_tensor(request.group_vectors)
        counts = wire.decode_tensor(request.group_counts)
        capacity = wire.decode_tensor(request.capacity)
        total = wire.decode_tensor(request.total)
        prices = wire.decode_tensor(request.prices)

        response = pb.SolveResponse()
        num_groups = int(vectors.shape[0])
        if num_groups == 0 or capacity.shape[0] == 0:
            # Nothing to pack / nothing to pack onto: every pod is
            # unschedulable, mirroring pack_groups' empty-fleet path.
            response.solver = "empty"
            response.unschedulable.CopyFrom(
                wire.encode_tensor(counts.astype(np.int64))
            )
            response.solve_ms = (time.perf_counter() - start) * 1e3
            return response

        mode = request.mode or "cost"
        if mode == "cost":
            pool_prices = wire.decode_tensor(request.pool_prices)
            dense = solver_models.cost_solve_dense(
                vectors,
                counts,
                capacity,
                total,
                prices,
                pool_prices,
                lp_steps=int(request.lp_steps) or 300,
            )
            unschedulable = self._encode_cost(
                response, dense, vectors, counts, capacity, total
            )
        elif mode == "ffd":
            rounds, unschedulable, used = self._ffd_rounds(
                vectors, counts, capacity, total, prices, request.quirk
            )
            response.solver = used
            response.fallback = used != "tpu-ffd"
            _encode_rounds(response, rounds)
        else:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, f"unknown mode {mode!r}"
            )

        response.unschedulable.CopyFrom(
            wire.encode_tensor(
                np.asarray(unschedulable, dtype=np.int64)  # vet: host-array(post-fetch counts)
            )
        )
        response.solve_ms = (time.perf_counter() - start) * 1e3
        with self._lock:
            self.solves += 1
        return response

    @staticmethod
    def _encode_cost(response, dense, vectors, counts, capacity, total):
        """Encode a cost-solve outcome (host-greedy fallback when dense is
        None); returns the unschedulable counts for the caller to attach."""
        if dense is None:
            rounds, unschedulable = _host_rounds(
                vectors, counts, capacity, total, quirk=True
            )
            response.solver = "host-greedy"
            response.fallback = True
            _encode_rounds(response, rounds)
            return unschedulable
        response.solver = "tpu-cost"
        _encode_rounds(response, dense.rounds, dense.options)
        return dense.unschedulable

    def solve_stream(self, request_iterator, context):
        """SolveStream entry: enter the client's batch trace (RPC metadata)
        for the whole stream — ingest, serve span, and every per-item
        solve record under the one id the host minted."""
        with TRACER.trace(_trace_from_context(context)):
            yield from self._solve_stream(request_iterator, context)

    def _ingest_stream(self, request_iterator):
        """Dispatch phase of the pipelined stream: every cost-mode request's
        kernel launched (and its compacted device->host copy staged) before
        any result is fetched. Returns (ready, pending, order) — inline
        answers by slot, dispatched work in arrival order, total count."""
        ready = {}  # order -> finished SolveResponse
        pending = []  # (order, start, fused, arrays..., pool_prices)
        order = 0
        for request in request_iterator:
            mode = request.mode or "cost"
            # Route on the shape fields alone — full tensor decode only on
            # the path that consumes the data.
            num_groups = (list(request.group_vectors.shape) or [0])[0]
            num_types = (list(request.capacity.shape) or [0])[0]
            try:
                if mode != "cost" or num_groups == 0 or num_types == 0:
                    # Request-scoped context: an unknown mode aborts THIS
                    # request only, not the whole stream.
                    ready[order] = self.solve(request, _RequestScopedContext())
                elif solver_models.host_solve_enabled(
                    int(np.sum(wire.decode_tensor(request.group_counts))),
                    batched=True,
                ):
                    # Small schedule: the unary path's adaptive host solve
                    # answers inline in milliseconds — no reason to ride
                    # the batched device fetch.
                    ready[order] = self.solve(request, _RequestScopedContext())
                else:
                    start = time.perf_counter()
                    vectors = wire.decode_tensor(request.group_vectors)
                    counts = wire.decode_tensor(request.group_counts)
                    capacity = wire.decode_tensor(request.capacity)
                    total = wire.decode_tensor(request.total)
                    prices = wire.decode_tensor(request.prices)
                    pool_prices = wire.decode_tensor(request.pool_prices)
                    fused = solver_models.cost_solve_dispatch(
                        vectors,
                        counts,
                        capacity,
                        total,
                        prices,
                        int(request.lp_steps) or 300,
                    )
                    solver_models.plan_start_fetch(fused)
                    pending.append(
                        (order, start, fused, vectors, counts, capacity, total,
                         prices, pool_prices)
                    )
            except _RequestAbort as err:
                ready[order] = _error_response(err.details)
            except Exception as err:  # noqa: BLE001 — isolate malformed input
                ready[order] = _error_response(repr(err))
            order += 1
        return ready, pending, order

    def _solve_stream(self, request_iterator, context):
        """Batched, pipelined solve: dispatch every cost-mode request's
        kernel before fetching any result (_ingest_stream), then yield
        responses IN REQUEST ORDER as each finishes — the client starts
        decoding/binding schedule N while schedules N+1.. are still
        computing and copying on the device. Each per-item fetch finds its
        payload already staged (plan_start_fetch at dispatch), so the
        stream still pays ~one round trip of latency, not one per item.
        Non-cost / empty requests take the unary path inline."""
        ready, pending, order = self._ingest_stream(request_iterator)
        # Column-LP mix candidates: host work running in a worker thread
        # CONCURRENTLY with the (staged) fetches — the same _HostOverlap the
        # in-process paths use, consumed per item so request N's response
        # doesn't wait on request N+1's mix candidate. Best-effort per slot;
        # pool matrices arrive off the wire, so wait() cannot raise here.
        # The finish phase stays isolated per request: a poisoned fetch or
        # finish failure marks only ITS slot for client fallback — completed
        # responses always reach the client, and the responses already
        # yielded were on the wire before the failure happened.
        overlap = None
        if pending:
            overlap = solver_models._HostOverlap(
                [
                    (entry[3], entry[4], entry[5], entry[8])
                    for entry in pending
                ]
            ).start()
        next_pending = 0
        with TRACER.span("solver.serve.stream", solves=len(pending)):
            for slot in range(order):
                if slot in ready:
                    yield ready[slot]
                    continue
                k = next_pending
                next_pending += 1
                (_, start, fused, vectors, counts, capacity, total, prices,
                 pool_prices) = pending[k]
                try:
                    overlap.wait(k)
                    plan = solver_models.fetch_plan(fused)
                    response = pb.SolveResponse()
                    dense = solver_models.cost_solve_finish(
                        plan, vectors, counts, capacity, total, prices,
                        pool_prices, mix_plan=overlap.mix_plans[k],
                    )
                    unschedulable = self._encode_cost(
                        response, dense, vectors, counts, capacity, total
                    )
                    response.unschedulable.CopyFrom(
                        wire.encode_tensor(
                            np.asarray(unschedulable, dtype=np.int64)  # vet: host-array(post-fetch counts)
                        )
                    )
                    response.solve_ms = (time.perf_counter() - start) * 1e3
                    with self._lock:
                        self.solves += 1
                except Exception as err:  # noqa: BLE001
                    response = _error_response(repr(err))
                yield response

    @staticmethod
    def _ffd_rounds(vectors, counts, capacity, total, prices, quirk):
        """Reference-parity FFD on the accelerator, host fallback on overflow."""
        num_groups = int(vectors.shape[0])
        rounds = solver_models._to_host(
            solver_models.run_kernel_dense(
                vectors, counts, capacity, total, prices, mode="ffd", quirk=quirk
            )
        )
        if bool(rounds.overflow):
            round_list, unschedulable = _host_rounds(
                vectors, counts, capacity, total, quirk=quirk
            )
            return round_list, unschedulable, "host-greedy"
        return (
            solver_models._kernel_rounds_to_list(rounds, num_groups),
            rounds.unschedulable[:num_groups],
            "tpu-ffd",
        )

    def health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        import jax

        return pb.HealthResponse(
            status="ok" if self.warmed.is_set() else "warming",
            platform=jax.default_backend(),
            device_count=jax.device_count(),
            solves=self.solves,
        )

    def health_v1_check(self, request: bytes, context) -> bytes:
        """Standard grpc.health.v1.Health/Check, hand-encoded (no
        grpc_health dependency): HealthCheckResponse{status} where
        SERVING=1 / NOT_SERVING=2 wire-encodes as field-1 varint. This is
        what a Kubernetes gRPC readinessProbe calls, so the probe gates pod
        traffic on the boot warmup — the consumer of the 'warming' state."""
        return b"\x08\x01" if self.warmed.is_set() else b"\x08\x02"


class SolverServer:
    """In-process harness around the gRPC server — tests start it on port 0
    and read back the bound port; __main__ serves forever."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1", workers: int = 4):
        self.handler = _Handler()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=workers))
        method_handlers = {
            "Solve": grpc.unary_unary_rpc_method_handler(
                self.handler.solve,
                request_deserializer=pb.SolveRequest.FromString,
                response_serializer=pb.SolveResponse.SerializeToString,
            ),
            "SolveStream": grpc.stream_stream_rpc_method_handler(
                self.handler.solve_stream,
                request_deserializer=pb.SolveRequest.FromString,
                response_serializer=pb.SolveResponse.SerializeToString,
            ),
            "Health": grpc.unary_unary_rpc_method_handler(
                self.handler.health,
                request_deserializer=pb.HealthRequest.FromString,
                response_serializer=pb.HealthResponse.SerializeToString,
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(wire.SERVICE, method_handlers),)
        )
        identity = lambda raw: raw  # noqa: E731 — hand-encoded wire bytes
        self._server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "grpc.health.v1.Health",
                    {
                        "Check": grpc.unary_unary_rpc_method_handler(
                            self.handler.health_v1_check,
                            request_deserializer=identity,
                            response_serializer=identity,
                        )
                    },
                ),
            )
        )
        self.port = self._server.add_insecure_port(f"{host}:{port}")

    def start(self, warmup: bool = True) -> "SolverServer":
        self._server.start()
        log.info("solver sidecar listening on :%d", self.port)
        if warmup:
            threading.Thread(
                target=self._warmup, name="solver-warmup", daemon=True
            ).start()
        else:
            self.handler.warmed.set()
        return self

    def _warmup(self) -> None:
        """Precompile the bucket ladder (and, via cost_solve_dispatch's mesh
        auto-selection, the sharded kernel on multi-chip runtimes) BEFORE
        health reports ok, so warmup_compile_s is paid at boot, never by a
        live batch (models/warmup.py — shared with the in-process Manager).

        Ref: the reference has no compile step at all — its first batch is
        never seconds late; with this, neither is ours (VERDICT r3 §missing
        3). Serving starts immediately; readiness (health != ok) keeps
        traffic away until the ladder is warm."""
        from karpenter_tpu.models.warmup import warmup_ladder

        warmup_ladder()
        self.handler.warmed.set()

    def stop(self, grace: Optional[float] = None) -> None:
        self._server.stop(grace).wait()

    def wait(self) -> None:
        self._server.wait_for_termination()


def main(argv=None) -> None:
    from karpenter_tpu.utils.gctune import tune_gc

    tune_gc()  # long-running service: GOGC-style collector headroom
    from karpenter_tpu.ops.pack_kernel import suppress_donation_advisory

    suppress_donation_advisory()  # CPU-fallback rigs warn per compile
    parser = argparse.ArgumentParser(description="karpenter-tpu solver sidecar")
    parser.add_argument("--port", type=int, default=9090)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--workers", type=int, default=4)
    args = parser.parse_args(argv)
    # One device-liveness verdict before ANY in-process device touch (the
    # distributed init and the warmup ladder below both touch the backend):
    # a wedged accelerator pins the CPU backend and the dispatch gate routes
    # solves to the native host hybrid (models/solver.host_solve_enabled) —
    # the sidecar serves degraded instead of hanging in backend init.
    from karpenter_tpu.utils import backend_health

    boot_verdict = backend_health.ensure_backend()
    if boot_verdict.state == backend_health.DEGRADED:
        log.warning(
            "accelerator backend degraded at boot (%s): serving on the CPU "
            "backend with host-hybrid routing", boot_verdict.reason
        )
    # Multi-host slice (KARPENTER_COORDINATOR/_NUM_PROCESSES/_PROCESS_ID or
    # KARPENTER_MULTIHOST=auto): join the jax.distributed runtime BEFORE the
    # first device touch, so jax.devices() is the global set and
    # cost_solve_dispatch auto-selects the mesh-sharded kernel spanning
    # every host's chips. Rank 0 serves RPCs and replicates each solve to
    # the slice; other ranks mirror dispatches in the SPMD follower loop
    # (parallel/spmd.py) — multi-process JAX requires every process to
    # launch the same computation.
    from karpenter_tpu.parallel.multihost import init_distributed

    distributed = init_distributed()
    if distributed:
        import jax

        if jax.process_index() > 0:
            from karpenter_tpu.parallel import spmd

            spmd.follower_loop()
            return
    server = SolverServer(port=args.port, host=args.host, workers=args.workers)
    server.start()
    # Terminate on SIGTERM (Kubernetes pod shutdown) as well as SIGINT, so
    # the finally block actually runs under a rollout and the followers get
    # their OP_STOP instead of timing out in a dead collective.
    import signal
    import threading

    stop = threading.Event()
    for signum in (signal.SIGTERM, signal.SIGINT):
        signal.signal(signum, lambda *_: stop.set())
    try:
        stop.wait()
        server.stop(grace=5.0)
    finally:
        if distributed:
            from karpenter_tpu.parallel import spmd

            spmd.lead_stop()


if __name__ == "__main__":
    main()
