"""The gRPC solver-plugin boundary — this framework's communication backend
(SURVEY.md §2.7 mandate): the control plane streams densified problem tensors
to a stateless sidecar that owns the accelerator; launch decisions come back
as indices into the fleet the control plane already holds.

Layout:
  solver.proto / solver_pb2  — wire schema (regenerate with `make proto`)
  wire                       — tensor <-> Tensor message codecs
  server                     — the sidecar (python -m karpenter_tpu.solver_service.server)
  client                     — RemoteSolver: Solver impl with greedy fallback
                               + failure blackout (the ICE-cache pattern,
                               ref: aws/instancetypes.go:174-183)
"""


def __getattr__(name):
    # Lazy: submodules import solver_pb2 through this package, so eager
    # client/server imports here would be circular.
    if name == "RemoteSolver":
        from karpenter_tpu.solver_service.client import RemoteSolver

        return RemoteSolver
    if name == "SolverServer":
        from karpenter_tpu.solver_service.server import SolverServer

        return SolverServer
    raise AttributeError(name)
