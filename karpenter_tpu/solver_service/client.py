"""RemoteSolver: the control plane's side of the solver-plugin boundary.

A `Solver` implementation that ships each schedule's densified problem to the
sidecar and rehydrates the returned rounds/options against the fleet objects
it holds. When the sidecar is unreachable or errors, it degrades to the
in-process compiled-host greedy packer and blacks out the endpoint for
BLACKOUT_SECONDS before trying again — the same failure-detection pattern the
reference applies to exhausted capacity pools (ICE blackout cache,
ref: aws/instancetypes.go:37,174-183).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional

import grpc
import numpy as np

from karpenter_tpu.models.solver import (
    NativeSolver,
    Solver,
    _decode_rounds,
    _pool_price_matrix,
    pool_rows_to_options,
)
from karpenter_tpu.ops import ffd
from karpenter_tpu.ops.encode import InstanceFleet, PodGroups
from karpenter_tpu.solver_service import solver_pb2 as pb
from karpenter_tpu.solver_service import wire
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.clock import SYSTEM_CLOCK
from karpenter_tpu.utils.metrics import REGISTRY
from karpenter_tpu.utils.tracing import TRACE_METADATA_KEY, TRACER, Span

log = klog.named("remote-solver")


def _trace_metadata():
    """gRPC call metadata carrying the current batch trace id, or None —
    the sidecar enters the same trace for its serve spans, so one merged
    Chrome trace stitches the host, RPC, and solve lanes."""
    trace_id = TRACER.current_trace()
    if not trace_id:
        return None
    return ((TRACE_METADATA_KEY, trace_id),)

# Endpoint blackout after a failed RPC (the ICE-cache pattern).
BLACKOUT_SECONDS = 30.0
# Generous per-solve deadline: the 50k x 400 north-star config solves in
# ~110ms; anything past this is a wedged sidecar, not a slow solve.
DEFAULT_TIMEOUT_SECONDS = 10.0
# Stream deadline: base covers compile-on-first-shape, then a small per-item
# increment, hard-capped — a wedged sidecar must degrade to host fallback in
# seconds regardless of batch size (timeout_s * len(items) let a large pass
# block provisioning for minutes).
STREAM_PER_ITEM_SECONDS = 0.25
STREAM_TIMEOUT_CAP_SECONDS = 30.0

_RPC_HISTOGRAM = REGISTRY.histogram(
    "solver_rpc_duration_seconds",
    "Wall time of sidecar Solve RPCs",
    labels=("outcome",),
)

# Every blackout arming is a sidecar outage window the fleet should see
# climbing BEFORE operators notice solves running host-side (the ICE-cache
# observability gap, closed): labeled by which failure shape armed it.
BLACKOUT_TOTAL = REGISTRY.counter(
    "remote_solver_blackout_total",
    "Sidecar endpoint blackouts armed, by failure shape",
    ["reason"],
)


def _await_half_close(received, stream_done, failure) -> None:
    """After every pipelined item yielded, give the stream's half-close
    event a moment to land so the RPC histogram records true wire time (the
    drain stamps stream_done before its terminal put)."""
    if failure is None and stream_done[0] is None:
        try:
            received.get(timeout=1.0)
        except queue.Empty:  # pragma: no cover — wedged half-close
            pass


class RemoteSolver(Solver):
    def __init__(
        self,
        endpoint: str,
        mode: str = "cost",
        lp_steps: int = 300,
        quirk: bool = False,
        fallback: Optional[Solver] = None,
        timeout_s: float = DEFAULT_TIMEOUT_SECONDS,
        blackout_s: float = BLACKOUT_SECONDS,
        clock: Callable[[], float] = SYSTEM_CLOCK.monotonic,
    ):
        self.endpoint = endpoint
        self.mode = mode
        self.lp_steps = lp_steps
        self.quirk = quirk
        self.fallback = fallback or NativeSolver()
        self.timeout_s = timeout_s
        self.blackout_s = blackout_s
        self.clock = clock
        self._blackout_until = -float("inf")
        # Until the sidecar's boot warmup finishes (health status
        # "warming"), solves go straight to host fallback WITHOUT arming
        # the failure blackout: a warming sidecar is healthy-but-not-ready,
        # and the first live batch must not pay its jit compile. Checked
        # once; an "ok" sticks for the client's lifetime (readiness probes
        # own steady-state gating).
        self._warm_verified = False
        self._channel = grpc.insecure_channel(endpoint)
        self._solve_rpc = self._channel.unary_unary(
            wire.SOLVE_METHOD,
            request_serializer=pb.SolveRequest.SerializeToString,
            response_deserializer=pb.SolveResponse.FromString,
        )
        self._stream_rpc = self._channel.stream_stream(
            wire.SOLVE_STREAM_METHOD,
            request_serializer=pb.SolveRequest.SerializeToString,
            response_deserializer=pb.SolveResponse.FromString,
        )
        self._health_rpc = self._channel.unary_unary(
            wire.HEALTH_METHOD,
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString,
        )

    def healthy(self, timeout_s: float = 2.0) -> Optional[pb.HealthResponse]:
        try:
            return self._health_rpc(pb.HealthRequest(), timeout=timeout_s)
        except grpc.RpcError:
            return None

    def _check_warm(self) -> bool:
        """True once the sidecar has reported status "ok" (warmup done).
        While it reports "warming", callers host-solve WITHOUT arming the
        blackout — the sidecar is healthy, just precompiling; the next
        batch re-checks. An UNREACHABLE sidecar returns True on purpose:
        the solve proceeds to its RPC, whose failure path owns arming the
        blackout (this method must never swallow an outage silently)."""
        if self._warm_verified:
            return True
        health = self.healthy(timeout_s=1.0)
        if health is None or health.status == "ok":
            # Unreachable: proceed to the RPC (its error path arms the
            # blackout properly). "ok": verified warm.
            self._warm_verified = health is not None
            return True
        log.info(
            "sidecar %s warming; host-solving this batch", self.endpoint
        )
        return False

    def _build_request(self, groups: PodGroups, fleet: InstanceFleet):
        zones, pool_prices = _pool_price_matrix(fleet)
        request = pb.SolveRequest(
            group_vectors=wire.encode_tensor(groups.vectors),
            group_counts=wire.encode_tensor(groups.counts.astype(np.int32)),
            capacity=wire.encode_tensor(fleet.capacity),
            total=wire.encode_tensor(fleet.total),
            prices=wire.encode_tensor(fleet.prices),
            pool_prices=wire.encode_tensor(pool_prices),
            zones=zones,
            capacity_type=fleet.capacity_type,
            mode=self.mode,
            lp_steps=self.lp_steps,
            quirk=self.quirk,
        )
        return request, zones

    def solve_encoded_many(self, items) -> list:
        """Batch of schedules over the streaming RPC: the sidecar dispatches
        every kernel before fetching, so the batch shares one device round
        trip. Falls back (whole batch) to the host solver on RPC failure."""
        items = list(items)
        if not items:
            return []
        if self.clock() < self._blackout_until:
            return self.fallback.solve_encoded_many(items)
        if not self._check_warm():
            return self.fallback.solve_encoded_many(items)
        built = [self._build_request(groups, fleet) for groups, fleet in items]
        start = self.clock()
        responses = None
        rpc_error = None
        with TRACER.span(
            "solver.rpc.stream", endpoint=self.endpoint, solves=len(items)
        ) as span:
            try:
                deadline = min(
                    STREAM_TIMEOUT_CAP_SECONDS,
                    self.timeout_s + STREAM_PER_ITEM_SECONDS * len(items),
                )
                responses = list(
                    self._stream_rpc(
                        iter(request for request, _ in built),
                        timeout=deadline,
                        metadata=_trace_metadata(),
                    )
                )
                span.set(outcome="ok")
            except grpc.RpcError as error:
                span.set(outcome="error")
                rpc_error = error
        if responses is None or len(responses) != len(items):
            _RPC_HISTOGRAM.observe(self.clock() - start, "error")
            self._blackout_until = self.clock() + self.blackout_s
            BLACKOUT_TOTAL.inc("stream")
            log.warning(
                "sidecar %s stream failed (%s); host fallback for %.0fs",
                self.endpoint,
                getattr(rpc_error, "code", lambda: "short stream")(),
                self.blackout_s,
            )
            return self.fallback.solve_encoded_many(items)
        _RPC_HISTOGRAM.observe(self.clock() - start, "ok")
        # A per-request "error" marker means the sidecar isolated a failure
        # to that item (server solve_stream); host-solve it alone instead of
        # failing or blacking out the whole batch. But a batch where EVERY
        # item errored (e.g. the server's batched fetch is poisoned) is a
        # sidecar failure in a well-formed envelope — arm the blackout like
        # an RPC failure so the next passes don't repeat the doomed trip.
        if responses and all(r.solver == "error" for r in responses):
            self._blackout_until = self.clock() + self.blackout_s
            BLACKOUT_TOTAL.inc("stream_poisoned")
            log.warning(
                "sidecar %s errored every stream item; host fallback for %.0fs",
                self.endpoint,
                self.blackout_s,
            )
        return [
            self.fallback.solve_encoded(groups, fleet)
            if response.solver == "error"
            else self._decode(response, groups, fleet, zones)
            for response, (groups, fleet), (_, zones) in zip(
                responses, items, built
            )
        ]

    def solve_encoded_pipelined(self, items):
        """The remote half of the solve->bind pipeline: responses decode and
        yield AS THEY ARRIVE off the stream (the sidecar yields each
        schedule's response the moment it finishes —
        solver_service/server.solve_stream), so the provisioner binds
        schedule N while the sidecar still solves N+1.. across the wire.

        Failure semantics degrade per item instead of per batch: results
        already yielded are live (they may already be binding), so a
        mid-stream RPC failure arms the blackout and host-solves only the
        REMAINING schedules; per-request "error" markers host-solve that
        item inline, and a stream where EVERY item errored arms the
        poisoned-batch blackout exactly like solve_encoded_many.

        A receiver thread drains the stream EAGERLY into a queue: the gRPC
        deadline (sized for solve time) must never span the caller's
        bind/launch work between pulls — lazy next() calls over seconds of
        binds would hit DEADLINE_EXCEEDED on a perfectly healthy sidecar.
        The same thread stamps stream completion, so the RPC histogram
        records wire time only, not bind time."""
        items = list(items)
        if not items:
            return
        if self.clock() < self._blackout_until or not self._check_warm():
            yield from self.fallback.solve_encoded_pipelined(items)
            return
        built = [self._build_request(groups, fleet) for groups, fleet in items]
        deadline = min(
            STREAM_TIMEOUT_CAP_SECONDS,
            self.timeout_s + STREAM_PER_ITEM_SECONDS * len(items),
        )
        start = self.clock()
        span_trace = TRACER.current_trace()
        span_parent = TRACER.current_parent()
        span_start = time.perf_counter()
        responses = self._stream_rpc(
            iter(request for request, _ in built),
            timeout=deadline,
            metadata=_trace_metadata(),
        )
        received, stream_done = self._start_stream_drain(responses)
        produced = 0
        errored = 0
        failure = None
        while produced < len(items):
            kind, payload = received.get()
            if kind == "error":
                failure = getattr(payload, "code", lambda: payload)()
                break
            if kind == "end":
                failure = "short stream"
                break
            groups, fleet = items[produced]
            _, zones = built[produced]
            if payload.solver == "error":
                errored += 1
                yield self.fallback.solve_encoded(groups, fleet)
            else:
                yield self._decode(payload, groups, fleet, zones)
            produced += 1
        _await_half_close(received, stream_done, failure)
        rpc_elapsed = (stream_done[0] or self.clock()) - start
        self._record_stream_span(
            span_trace, span_parent, span_start, rpc_elapsed,
            len(items), failure,
        )
        if self._note_stream_outcome(
            failure, produced, len(items), errored, rpc_elapsed
        ):
            for groups, fleet in items[produced:]:
                yield self.fallback.solve_encoded(groups, fleet)

    def _record_stream_span(
        self, trace, parent, start_s: float, duration_s: float,
        solves: int, failure,
    ) -> None:
        """The pipelined stream's RPC span, recorded manually: a `with`
        span around the generator would charge the caller's bind work
        between pulls to the wire, so this takes the drain thread's
        wire-time stamps instead (the same reason the RPC histogram does).
        Trace/parent were captured before the first yield, while the
        caller's batch trace context and span stack were still current."""
        if not TRACER.enabled:
            return
        TRACER.record(
            Span(
                name="solver.rpc.stream",
                start_s=start_s,
                duration_s=duration_s,
                attributes={
                    "endpoint": self.endpoint,
                    "solves": solves,
                    "pipelined": True,
                    "outcome": "ok" if failure is None else "error",
                },
                parent=parent,
                thread_id=threading.get_ident(),
                thread_name=threading.current_thread().name,
                trace=trace or "",
            )
        )

    def _start_stream_drain(self, responses):
        """Eagerly drain a SolveStream response iterator into a queue from a
        background thread (see solve_encoded_pipelined). Returns the queue
        and a 1-box stamped with the stream's end time (wire time, bind-free
        — the terminal put always follows the stamp)."""
        received: "queue.Queue" = queue.Queue()
        stream_done = [None]

        def _drain():
            try:
                for response in responses:
                    received.put(("item", response))
            except grpc.RpcError as error:
                stream_done[0] = self.clock()
                received.put(("error", error))
            else:
                stream_done[0] = self.clock()
                received.put(("end", None))

        threading.Thread(
            target=_drain, name="remote-solve-drain", daemon=True
        ).start()
        return received, stream_done

    def _note_stream_outcome(
        self, failure, produced: int, total: int, errored: int,
        rpc_elapsed: float,
    ) -> bool:
        """Histogram + blackout bookkeeping after a pipelined stream ends;
        True means the caller must host-solve the unyielded remainder."""
        if failure is not None:
            _RPC_HISTOGRAM.observe(rpc_elapsed, "error")
            self._blackout_until = self.clock() + self.blackout_s
            BLACKOUT_TOTAL.inc("stream")
            log.warning(
                "sidecar %s pipelined stream failed after %d/%d (%s); host "
                "fallback for %.0fs",
                self.endpoint, produced, total, failure, self.blackout_s,
            )
            return True
        _RPC_HISTOGRAM.observe(rpc_elapsed, "ok")
        if errored == total:
            self._blackout_until = self.clock() + self.blackout_s
            BLACKOUT_TOTAL.inc("stream_poisoned")
            log.warning(
                "sidecar %s errored every stream item; host fallback for %.0fs",
                self.endpoint, self.blackout_s,
            )
        return False

    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        if self.clock() < self._blackout_until:
            return self.fallback.solve_encoded(groups, fleet)
        if not self._check_warm():
            return self.fallback.solve_encoded(groups, fleet)

        request, zones = self._build_request(groups, fleet)
        start = self.clock()
        response = None
        # The span covers ONLY the RPC hop — the fallback solve runs outside
        # it so an outage doesn't misattribute host solve time to the wire.
        with TRACER.span(
            "solver.rpc",
            endpoint=self.endpoint,
            mode=self.mode,
            groups=groups.num_groups,
            types=fleet.num_types,
        ) as span:
            try:
                response = self._solve_rpc(
                    request, timeout=self.timeout_s, metadata=_trace_metadata()
                )
            except grpc.RpcError as error:
                span.set(outcome="error")
                rpc_error = error
            else:
                span.set(
                    outcome="ok", server_ms=response.solve_ms, solver=response.solver
                )
        if response is None:
            _RPC_HISTOGRAM.observe(self.clock() - start, "error")
            self._blackout_until = self.clock() + self.blackout_s
            BLACKOUT_TOTAL.inc("unary")
            log.warning(
                "sidecar %s unavailable (%s); host greedy for %.0fs",
                self.endpoint,
                getattr(rpc_error, "code", lambda: rpc_error)(),
                self.blackout_s,
            )
            return self.fallback.solve_encoded(groups, fleet)
        _RPC_HISTOGRAM.observe(self.clock() - start, "ok")
        return self._decode(response, groups, fleet, zones)

    @staticmethod
    def _decode(
        response: pb.SolveResponse,
        groups: PodGroups,
        fleet: InstanceFleet,
        zones,
    ) -> ffd.PackResult:
        rounds = [
            (
                round.type_index,
                wire.decode_tensor(round.fill),
                round.replication,
            )
            for round in response.rounds
        ]
        unschedulable = wire.decode_tensor(response.unschedulable)

        # fill bytes -> OptionSet (the server dedups option sets by fill, so
        # the mapping is well-defined); -1 rounds use the reference window.
        option_for_fill = {}
        for round in response.rounds:
            if round.option_set >= 0:
                option_for_fill[round.fill.data] = response.option_sets[
                    round.option_set
                ]

        def options_fn(t: int, fill: np.ndarray):
            option_set = option_for_fill.get(fill.astype(np.int64).tobytes())
            if option_set is None:
                upper = min(t + ffd.MAX_INSTANCE_TYPES, fleet.num_types)
                return list(range(t, upper)), None
            rows = (
                [(p.type_index, p.zone_index, p.price) for p in option_set.pools]
                if option_set.has_pools
                else None
            )
            return list(option_set.type_indices), pool_rows_to_options(
                rows, fleet, zones
            )

        return _decode_rounds(rounds, unschedulable, groups, fleet, options_fn)

    def close(self) -> None:
        self._channel.close()
