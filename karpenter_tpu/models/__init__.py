"""Solver models: the greedy host fallback and the TPU batched solver."""

from karpenter_tpu.models.solver import GreedySolver, TPUSolver, Solver

__all__ = ["GreedySolver", "TPUSolver", "Solver"]
