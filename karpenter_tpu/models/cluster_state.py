"""DeviceClusterState — device-resident cluster tensors updated O(churn).

Every sweep used to re-encode the full cluster snapshot (``group_pods`` over
50k pods + ``build_fleet`` — encode_warm ~20 ms in BENCH_r04, scaling with
cluster size, not churn). This module closes ROADMAP item 2: ``Cluster``
watch events stream into slot arrays that live ON DEVICE, and per-sweep
encode work becomes proportional to the watch-event churn.

Architecture:

- **Slot allocator with free-list reuse.** Pod groups (distinct request
  vectors) and nodes each own a row in mirror arrays (numpy, host) with a
  device copy. Deleting a group/node frees its slot into a free-list
  (row left behind as a tombstone, masked by the live flags); the next
  allocation reuses it. Slot indices are NEVER stored in per-pod records —
  records hold the vector key / node name and resolve slots through the
  slot maps, so compaction remaps O(G+N) map entries, not O(pods).

- **Sync-by-key, not op-replay.** ``Cluster.watch_deltas`` delivery order
  across threads is unordered, so each event is only a hint: the handler
  re-reads the store (always at least as new as the event) and reconciles
  the pod's recorded contribution (pending group / node used) to what it
  sees. Out-of-order delivery converges because the LAST event per key
  syncs against the final store state.

- **O(delta) flush.** Host syncs mark dirty slots; ``flush()`` drains them
  under the lock and applies one jitted masked scatter per array OUTSIDE
  the lock (ops/incremental.py). Device work per sweep is O(churn).

- **Epoch-tagged generations + snapshot rebuild.** Rebuilds, compactions,
  and capacity growth bump ``epoch``; every flush bumps ``generation``. A
  consumer holding an older handle detects staleness via ``is_current`` and
  simply re-encodes; the state itself falls back to the SNAPSHOT path
  (``group_pods`` over a fresh ``cluster.list_pods()`` — which stays
  authoritative and bit-identical, asserted by the parity suite) whenever
  an apply was torn mid-way (``encode.mid-apply`` crashpoint, a callback
  error, or a failed flush).

- **Masked compaction.** When tombstone density (freed-but-unreused slots
  over the high-water mark) crosses ``compaction_threshold``, the live rows
  are packed to the front, slot maps remapped, and the (possibly shrunken)
  mirrors re-uploaded — an epoch bump, amortized-rare and O(live).

Donation: the device slot arrays are NEVER donated (ops/incremental.py has
no donating kernel). The per-sweep sorted gather outputs handed to the
solver are fresh temporaries, and the solver still routes them through the
NON-donating fused kernel variant (models/solver.cost_solve_dispatch) so a
handle stays readable after its solve — see docs/design/incremental-encode.md
for the interplay with PR 6's donation and fetch-discipline rules.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.controllers.cluster import Cluster, PodKey
from karpenter_tpu.ops import incremental
from karpenter_tpu.ops.encode import (
    InstanceFleet,
    PodGroups,
    build_fleet,
    group_pods,
    group_sort_key,
    resource_vector,
)
from karpenter_tpu.ops.pack_kernel import bucket_size
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.crashpoints import crashpoint
from karpenter_tpu.utils.metrics import REGISTRY

log = klog.named("cluster-state")

# Per-flush device update latency — the number the <2ms-per-sweep budget
# watches (bench.py encode_incremental publishes the same quantity as
# encode_delta_ms). Buckets sized for sub-ms..tens-of-ms.
ENCODE_DELTA_SECONDS = REGISTRY.histogram(
    "encode_delta_seconds",
    "Incremental encode flush duration (delta scatter path only)",
    buckets=(0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25),
)
# Every full rebuild from the snapshot path, by why it was needed. A rising
# non-"initial" rate means the delta path keeps invalidating itself —
# investigate before trusting the O(churn) story.
ENCODE_REBUILDS_TOTAL = REGISTRY.counter(
    "encode_rebuilds_total",
    "Full snapshot rebuilds of the incremental encode state, by reason",
    ["reason"],
)

DEFAULT_COMPACTION_THRESHOLD = 0.5
# Below this high-water mark compaction is pointless — the arrays are
# already a single bucket.
_COMPACTION_MIN_ROWS = 16

_NUM_DIMS = wellknown.NUM_RESOURCE_DIMS


class StaleEncodingError(RuntimeError):
    """A consumer asserted freshness on a handle whose epoch or generation
    the state has moved past — re-encode via pending_groups()/the snapshot
    path."""


@dataclass
class DevicePodGroups(PodGroups):
    """A PodGroups snapshot whose tensors ALSO exist on device: vectors and
    counts are the sorted, bucket-padded gather of the state's slot arrays
    (host mirrors sliced identically — bit-identical to group_pods over the
    same pending set). epoch/generation tag which array generation produced
    it; ``state.is_current(handle)`` tells a lagging consumer to re-encode."""

    epoch: int = 0
    generation: int = 0
    device_vectors: object = None  # [Gbucket, R] f32 on device — never donated
    device_counts: object = None  # [Gbucket] i32 on device — never donated
    state: Optional["DeviceClusterState"] = None


@dataclass(slots=True)
class _PodRecord:
    """One pod's recorded contribution. Slot indices are resolved through
    the slot maps at use time (never stored) so compaction stays O(G+N).
    slots=True: one record exists per pod in the cluster — at 10^5-10^6
    pods the dict-less layout is a real rebuild-time and memory win."""

    vector: np.ndarray
    vec_key: bytes
    pending: bool
    node_name: Optional[str]
    counted: bool  # contributes to node_used (bound and not terminal)


class DeviceClusterState:
    """Owns the device-resident pod/node arrays and keeps them synced to a
    ``Cluster`` via its verb-level watch feed. Construct once per process
    (the Manager does) and hand to the provisioning / consolidation /
    interruption controllers."""

    def __init__(
        self,
        cluster: Cluster,
        compaction_threshold: float = DEFAULT_COMPACTION_THRESHOLD,
        subscribe: bool = True,
    ):
        self.cluster = cluster
        self.compaction_threshold = compaction_threshold
        self._lock = threading.RLock()
        self._flush_cv = threading.Condition(self._lock)
        # --- pod-group side ---------------------------------------------------
        self._pod_rec: Dict[PodKey, _PodRecord] = {}  # vet: guarded-by(self._lock)
        self._group_slot: Dict[bytes, int] = {}  # vet: guarded-by(self._lock)
        self._group_vectors = np.zeros((8, _NUM_DIMS), np.float32)  # vet: guarded-by(self._lock)
        self._group_counts = np.zeros(8, np.int32)  # vet: guarded-by(self._lock)
        self._group_live = np.zeros(8, bool)  # vet: guarded-by(self._lock)
        self._group_members: List[Dict[PodKey, PodSpec]] = [dict() for _ in range(8)]  # vet: guarded-by(self._lock)
        self._group_free: List[int] = []  # vet: guarded-by(self._lock)
        self._group_high = 0  # vet: guarded-by(self._lock)
        self._group_dirty: set = set()  # vet: guarded-by(self._lock)
        self._pending_total = 0  # vet: guarded-by(self._lock)
        # --- node side --------------------------------------------------------
        self._node_slot: Dict[str, int] = {}  # vet: guarded-by(self._lock)
        self._node_capacity = np.zeros((8, _NUM_DIMS), np.float32)  # vet: guarded-by(self._lock)
        # float64 HOST mirror: used is maintained by += / -= churn for the
        # process lifetime, and while kernel-unit vectors are integral
        # (exact in f32 to 2^24), f64 keeps the ledger exact to 2^53 so no
        # pathological magnitude or fractional request can ever accrete
        # rounding residue vs a fresh pod-walk sum. The DEVICE copy is cast
        # to f32 at flush (what the kernels consume).
        self._node_used = np.zeros((8, _NUM_DIMS), np.float64)  # vet: guarded-by(self._lock)
        self._node_live = np.zeros(8, bool)  # vet: guarded-by(self._lock)
        self._node_free: List[int] = []  # vet: guarded-by(self._lock)
        self._node_high = 0  # vet: guarded-by(self._lock)
        self._node_dirty: set = set()  # vet: guarded-by(self._lock)
        self._node_pods: Dict[str, Dict[PodKey, PodSpec]] = {}  # vet: guarded-by(self._lock)
        # --- generations ------------------------------------------------------
        self._dev: Optional[Dict[str, object]] = None  # vet: guarded-by(self._lock)
        self._epoch = 0  # vet: guarded-by(self._lock)
        self._generation = 0  # vet: guarded-by(self._lock)
        self._torn: Optional[str] = "initial"  # vet: guarded-by(self._lock)
        self._full_upload = True  # vet: guarded-by(self._lock)
        self._flushing = False  # vet: guarded-by(self._lock)
        self._event_seq = 0  # vet: guarded-by(self._lock)
        self._fleet_cache: Dict[Tuple, InstanceFleet] = {}  # vet: guarded-by(self._lock)
        self.compaction_count = 0  # vet: unguarded(monotonic int for bench/tests; writes hold the lock)
        self.rebuild_count = 0  # vet: unguarded(monotonic int for bench/tests; writes hold the lock)
        if subscribe:
            cluster.watch_deltas(self._on_event)

    # --- event intake --------------------------------------------------------

    def _on_event(self, verb: str, kind: str, obj) -> None:
        try:
            if kind == "pod":
                self._sync_pod((obj.namespace, obj.name))
            elif kind == "node":
                self._sync_node(obj.name)
            elif kind == "daemonset":
                with self._lock:
                    # Daemon overhead feeds build_fleet — drop cached fleets.
                    self._fleet_cache.clear()
        except Exception:  # noqa: BLE001 — a sync bug must not break store verbs
            # SimulatedCrash is a BaseException and punches through (the
            # encode.mid-apply battletest depends on it); anything else
            # marks the state torn so the next flush rebuilds from the
            # snapshot path instead of serving silently-wrong tensors.
            log.exception("incremental sync failed; state marked torn")
            with self._lock:
                self._torn = self._torn or "error"

    def _sync_pod(self, key: PodKey) -> None:
        with self._lock:
            # The point read happens UNDER our lock (it is lock-free on the
            # store side, so there is no lock-order hazard): read-then-apply
            # is atomic against other syncs of the same key, so the handler
            # serialized LAST for a key always reconciles against the
            # newest store state — read outside the lock, two concurrent
            # events could apply in reverse order of their reads and leave
            # the bookkeeping permanently stale.
            pod = self.cluster.try_get_pod(*key)
            self._event_seq += 1
            torn_before = self._torn
            # Torn marker held across the two-phase update: a crash between
            # remove and add leaves it set, and the next flush rebuilds.
            self._torn = self._torn or "torn"
            self._remove_pod_locked(key)
            crashpoint("encode.mid-apply")
            if pod is not None:
                self._add_pod_locked(key, pod)
            self._torn = torn_before

    def _sync_node(self, name: str) -> None:
        with self._lock:
            # Under the lock for the same read-then-apply atomicity as
            # _sync_pod (the store read itself is lock-free).
            node = self.cluster.try_get_node(name)
            self._event_seq += 1
            if node is None:
                slot = self._node_slot.pop(name, None)
                if slot is not None:
                    self._node_live[slot] = False
                    self._node_capacity[slot] = 0.0
                    self._node_used[slot] = 0.0
                    self._node_free.append(slot)
                    self._node_dirty.add(slot)
                return
            slot = self._ensure_node_locked(name)
            capacity = resource_vector(node.capacity)
            if not np.array_equal(self._node_capacity[slot], capacity):
                self._node_capacity[slot] = capacity
                self._node_dirty.add(slot)

    # --- contribution bookkeeping (lock held) --------------------------------

    def _remove_pod_locked(self, key: PodKey) -> None:
        record = self._pod_rec.pop(key, None)
        if record is None:
            return
        if record.pending:
            slot = self._group_slot.get(record.vec_key)
            if slot is not None:
                self._group_counts[slot] -= 1
                self._group_members[slot].pop(key, None)
                self._group_dirty.add(slot)
                self._pending_total -= 1
                if self._group_counts[slot] <= 0:
                    # Free-list reuse: the vector row stays behind as a
                    # tombstone (masked by live=False) until reuse/compaction.
                    self._group_slot.pop(record.vec_key, None)
                    self._group_live[slot] = False
                    self._group_counts[slot] = 0
                    self._group_members[slot] = {}
                    self._group_free.append(slot)
        if record.node_name is not None:
            pods = self._node_pods.get(record.node_name)
            if pods is not None:
                pods.pop(key, None)
                if not pods:
                    self._node_pods.pop(record.node_name, None)
            if record.counted:
                slot = self._node_slot.get(record.node_name)
                if slot is not None:
                    self._node_used[slot] -= record.vector
                    self._node_dirty.add(slot)

    def _add_pod_locked(self, key: PodKey, pod: PodSpec) -> None:
        cached = pod.dense_vector
        if cached is None:  # pragma: no cover — defensive, mirrors group_pods
            from karpenter_tpu.api.pods import _dense_request_cache

            pod.dense_vector = cached = _dense_request_cache(pod.requests)
        vector, vec_key = cached[0], cached[1]
        pending = pod.is_provisionable()
        node_name = pod.node_name
        counted = bool(node_name) and not pod.is_terminal()
        if pending:
            slot = self._group_slot.get(vec_key)
            if slot is None:
                slot = self._alloc_group_locked(vec_key, vector)
            self._group_counts[slot] += 1
            self._group_members[slot][key] = pod
            self._group_dirty.add(slot)
            self._pending_total += 1
        if node_name:
            self._node_pods.setdefault(node_name, {})[key] = pod
            if counted:
                slot = self._ensure_node_locked(node_name)
                self._node_used[slot] += vector
                self._node_dirty.add(slot)
        self._pod_rec[key] = _PodRecord(
            vector=vector,
            vec_key=vec_key,
            pending=pending,
            node_name=node_name if node_name else None,
            counted=counted,
        )

    def _alloc_group_locked(self, vec_key: bytes, vector: np.ndarray) -> int:
        if self._group_free:
            slot = self._group_free.pop()
        else:
            slot = self._group_high
            self._group_high += 1
            if self._group_high > self._group_vectors.shape[0]:
                self._grow_groups_locked()
        self._group_slot[vec_key] = slot
        self._group_vectors[slot] = vector
        self._group_counts[slot] = 0
        self._group_live[slot] = True
        self._group_members[slot] = {}
        self._group_dirty.add(slot)
        return slot

    def _ensure_node_locked(self, name: str) -> int:
        slot = self._node_slot.get(name)
        if slot is not None:
            return slot
        if self._node_free:
            slot = self._node_free.pop()
        else:
            slot = self._node_high
            self._node_high += 1
            if self._node_high > self._node_capacity.shape[0]:
                self._grow_nodes_locked()
        self._node_slot[name] = slot
        self._node_capacity[slot] = 0.0
        self._node_used[slot] = 0.0
        self._node_live[slot] = True
        self._node_dirty.add(slot)
        return slot

    def _grow_groups_locked(self) -> None:
        cap = bucket_size(self._group_high)
        grow = cap - self._group_vectors.shape[0]
        self._group_vectors = np.concatenate(
            [self._group_vectors, np.zeros((grow, _NUM_DIMS), np.float32)]
        )
        self._group_counts = np.concatenate(
            [self._group_counts, np.zeros(grow, np.int32)]
        )
        self._group_live = np.concatenate([self._group_live, np.zeros(grow, bool)])
        self._group_members.extend(dict() for _ in range(grow))
        self._full_upload = True

    def _grow_nodes_locked(self) -> None:
        cap = bucket_size(self._node_high)
        grow = cap - self._node_capacity.shape[0]
        self._node_capacity = np.concatenate(
            [self._node_capacity, np.zeros((grow, _NUM_DIMS), np.float32)]
        )
        self._node_used = np.concatenate(
            [self._node_used, np.zeros((grow, _NUM_DIMS), np.float64)]
        )
        self._node_live = np.concatenate([self._node_live, np.zeros(grow, bool)])
        self._full_upload = True

    # --- compaction ----------------------------------------------------------

    def tombstone_density(self) -> Tuple[float, float]:
        """(group, node) tombstone density: freed-but-unreused slots over the
        high-water mark."""
        with self._lock:
            return (
                self._density_locked(self._group_high, self._group_live),
                self._density_locked(self._node_high, self._node_live),
            )

    @staticmethod
    def _density_locked(high: int, live: np.ndarray) -> float:
        if high <= 0:
            return 0.0
        return 1.0 - float(live[:high].sum()) / float(high)

    def _maybe_compact_locked(self) -> None:
        if (
            self._group_high >= _COMPACTION_MIN_ROWS
            and self._density_locked(self._group_high, self._group_live)
            >= self.compaction_threshold
        ):
            self._compact_groups_locked()
        if (
            self._node_high >= _COMPACTION_MIN_ROWS
            and self._density_locked(self._node_high, self._node_live)
            >= self.compaction_threshold
        ):
            self._compact_nodes_locked()

    def _compact_groups_locked(self) -> None:
        order = [s for s in range(self._group_high) if self._group_live[s]]
        cap = bucket_size(max(len(order), 8))
        vectors = np.zeros((cap, _NUM_DIMS), np.float32)
        counts = np.zeros(cap, np.int32)
        live = np.zeros(cap, bool)
        members: List[Dict[PodKey, PodSpec]] = [dict() for _ in range(cap)]
        remap: Dict[int, int] = {}
        for new, old in enumerate(order):
            vectors[new] = self._group_vectors[old]
            counts[new] = self._group_counts[old]
            live[new] = True
            members[new] = self._group_members[old]
            remap[old] = new
        self._group_slot = {
            key: remap[slot] for key, slot in self._group_slot.items()
        }
        self._group_vectors, self._group_counts = vectors, counts
        self._group_live, self._group_members = live, members
        self._group_free = []
        self._group_high = len(order)
        self._group_dirty = set()
        self._full_upload = True
        self.compaction_count += 1

    def _compact_nodes_locked(self) -> None:
        order = [s for s in range(self._node_high) if self._node_live[s]]
        cap = bucket_size(max(len(order), 8))
        capacity = np.zeros((cap, _NUM_DIMS), np.float32)
        used = np.zeros((cap, _NUM_DIMS), np.float64)
        live = np.zeros(cap, bool)
        remap: Dict[int, int] = {}
        for new, old in enumerate(order):
            capacity[new] = self._node_capacity[old]
            used[new] = self._node_used[old]
            live[new] = True
            remap[old] = new
        self._node_slot = {
            name: remap[slot] for name, slot in self._node_slot.items()
        }
        self._node_capacity, self._node_used, self._node_live = capacity, used, live
        self._node_free = []
        self._node_high = len(order)
        self._node_dirty = set()
        self._full_upload = True
        self.compaction_count += 1

    # --- snapshot rebuild ----------------------------------------------------

    def _rebuild_locked(self, reason: str) -> None:
        """Reconstruct ALL host bookkeeping from the authoritative snapshot
        path: group_pods over the live pending set (bit-identical tensors by
        construction) + a single pod/node walk for the bound side. Runs
        under the lock so no sync can interleave; pure host work (the device
        upload happens in the flush that called us)."""
        ENCODE_REBUILDS_TOTAL.inc(reason)
        self.rebuild_count += 1
        pods = self.cluster.list_pods()
        nodes = self.cluster.list_nodes()
        pending = [p for p in pods if p.is_provisionable()]
        groups = group_pods(pending)
        gcap = bucket_size(max(groups.num_groups, 8))
        self._group_vectors = np.zeros((gcap, _NUM_DIMS), np.float32)
        self._group_counts = np.zeros(gcap, np.int32)
        self._group_live = np.zeros(gcap, bool)
        self._group_members = [dict() for _ in range(gcap)]
        self._group_slot = {}
        self._group_free = []
        self._group_high = groups.num_groups
        self._group_dirty = set()
        self._pending_total = groups.num_pods
        for slot in range(groups.num_groups):
            vec = groups.vectors[slot]
            self._group_vectors[slot] = vec
            self._group_counts[slot] = groups.counts[slot]
            self._group_live[slot] = True
            self._group_members[slot] = {
                (p.namespace, p.name): p for p in groups.members[slot]
            }
            self._group_slot[vec.tobytes()] = slot
        ncap = bucket_size(max(len(nodes), 8))
        self._node_capacity = np.zeros((ncap, _NUM_DIMS), np.float32)
        self._node_used = np.zeros((ncap, _NUM_DIMS), np.float64)
        self._node_live = np.zeros(ncap, bool)
        self._node_slot = {}
        self._node_free = []
        self._node_high = len(nodes)
        self._node_dirty = set()
        self._node_pods = {}
        for slot, node in enumerate(nodes):
            self._node_slot[node.name] = slot
            self._node_capacity[slot] = resource_vector(node.capacity)
            self._node_live[slot] = True
        self._pod_rec = {}
        for pod in pods:
            key = (pod.namespace, pod.name)
            cached = pod.dense_vector
            if cached is None:  # pragma: no cover — defensive
                from karpenter_tpu.api.pods import _dense_request_cache

                pod.dense_vector = cached = _dense_request_cache(pod.requests)
            vector, vec_key = cached[0], cached[1]
            pending_pod = pod.is_provisionable()
            node_name = pod.node_name
            counted = bool(node_name) and not pod.is_terminal()
            if node_name:
                self._node_pods.setdefault(node_name, {})[key] = pod
                if counted:
                    slot = self._ensure_node_locked(node_name)
                    self._node_used[slot] += vector
            self._pod_rec[key] = _PodRecord(
                vector=vector,
                vec_key=vec_key,
                pending=pending_pod,
                node_name=node_name if node_name else None,
                counted=counted,
            )
        self._torn = None
        self._full_upload = True

    # --- flush ---------------------------------------------------------------

    def flush(self) -> None:
        """Bring the device arrays up to date with the host mirrors: the
        O(delta) scatter in steady state, a full snapshot rebuild + upload
        when the state is torn/new, a full upload after growth/compaction.
        Device work runs OUTSIDE the lock (blocking-under-lock discipline);
        concurrent flushes serialize on a condition flag."""
        with self._lock:
            while self._flushing:
                self._flush_cv.wait()
            if (
                self._dev is not None
                and not self._full_upload
                and self._torn is None
                and not self._group_dirty
                and not self._node_dirty
            ):
                return  # already current
            self._flushing = True
            plan = self._drain_plan_locked()
        completed = False
        try:
            start = time.perf_counter()
            arrays = self._dispatch_plan(plan)
            if not plan["full"]:
                ENCODE_DELTA_SECONDS.observe(time.perf_counter() - start)
            completed = True
        finally:
            with self._lock:
                self._flushing = False
                self._flush_cv.notify_all()
                if completed:
                    self._dev = arrays
                    self._generation += 1
                    if plan["full"]:
                        self._epoch += 1
                        self._full_upload = False
                else:
                    # The drained deltas never reached the device: rebuild
                    # next time rather than serve a silently-partial state.
                    self._torn = "flush-failed"

    def _drain_plan_locked(self) -> dict:
        if self._torn is not None:
            self._rebuild_locked(self._torn)
        self._maybe_compact_locked()
        if self._full_upload or self._dev is None:
            self._group_dirty = set()
            self._node_dirty = set()
            return {
                "full": True,
                "mirrors": {
                    "group_vectors": self._group_vectors.copy(),
                    "group_counts": self._group_counts.copy(),
                    "node_capacity": self._node_capacity.copy(),
                    "node_used": self._node_used.astype(np.float32),
                    "node_live": self._node_live.copy(),
                },
            }
        group_idx = np.fromiter(sorted(self._group_dirty), np.int32, len(self._group_dirty))
        node_idx = np.fromiter(sorted(self._node_dirty), np.int32, len(self._node_dirty))
        plan = {
            "full": False,
            "dev": self._dev,
            "group": None,
            "node": None,
        }
        if len(group_idx):
            padded = incremental.pad_indices(group_idx, self._group_vectors.shape[0])
            plan["group"] = (
                padded,
                self._group_vectors[group_idx].copy(),
                self._group_counts[group_idx].copy(),
            )
        if len(node_idx):
            padded = incremental.pad_indices(node_idx, self._node_capacity.shape[0])
            plan["node"] = (
                padded,
                self._node_capacity[node_idx].copy(),
                self._node_used[node_idx].astype(np.float32),
                self._node_live[node_idx].copy(),
            )
        self._group_dirty = set()
        self._node_dirty = set()
        return plan

    @staticmethod
    def _dispatch_plan(plan: dict) -> Dict[str, object]:
        if plan["full"]:
            mirrors = plan["mirrors"]
            return {
                name: incremental.device_slots(array)
                for name, array in mirrors.items()
            }
        arrays = dict(plan["dev"])
        if plan["group"] is not None:
            idx, rows, counts = plan["group"]
            arrays["group_vectors"] = incremental.scatter_rows(
                arrays["group_vectors"], idx, rows
            )
            arrays["group_counts"] = incremental.scatter_vals(
                arrays["group_counts"], idx, counts
            )
        if plan["node"] is not None:
            idx, capacity, used, live = plan["node"]
            arrays["node_capacity"] = incremental.scatter_rows(
                arrays["node_capacity"], idx, capacity
            )
            arrays["node_used"] = incremental.scatter_rows(
                arrays["node_used"], idx, used
            )
            arrays["node_live"] = incremental.scatter_vals(
                arrays["node_live"], idx, live
            )
        return arrays

    # --- epoch / freshness protocol ------------------------------------------

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def compile_tag(self) -> Optional[Tuple[int, int]]:
        """(epoch, generation) for keying compiled-constraint envelopes
        (constraints/compiler.CompilerCache). Generation bumps on EVERY
        delta flush and epoch on full uploads, so the pair changes whenever
        the encoded cluster changes — epoch alone would serve a stale
        envelope across ordinary watch deltas. None while deltas are still
        pending (or the state is torn/unflushed): the store has moved past
        the last flush, so callers skip caching rather than key live
        cluster reads (spread seed counts, anti-affinity exclusions) to a
        tag that predates them."""
        with self._lock:
            if (
                self._dev is None
                or self._full_upload
                or self._torn is not None
                or self._group_dirty
                or self._node_dirty
            ):
                return None
            return (self._epoch, self._generation)

    def is_current(self, handle: DevicePodGroups) -> bool:
        with self._lock:
            return (
                handle.epoch == self._epoch
                and handle.generation == self._generation
            )

    def assert_current(self, handle: DevicePodGroups) -> None:
        if not self.is_current(handle):
            raise StaleEncodingError(
                "encoded handle is from a superseded array generation — "
                "re-encode via pending_groups() (the snapshot path stays "
                "authoritative)"
            )

    # --- consumer views ------------------------------------------------------

    def pending_groups(self) -> DevicePodGroups:
        """The pending (provisionable) pods as sorted group tensors, host +
        device — bit-identical to ``group_pods`` over the same pods. Flushes
        first; O(churn + G log G) per call."""
        self.flush()
        with self._lock:
            clean = (
                self._torn is None
                and not self._group_dirty
                and not self._node_dirty
                and not self._full_upload
            )
            live = [s for s in range(self._group_high) if self._group_live[s]]
            live.sort(key=lambda s: group_sort_key(self._group_vectors[s]))
            perm = np.array(live, np.int32)
            vectors = (
                self._group_vectors[perm]
                if len(perm)
                else np.zeros((0, _NUM_DIMS), np.float32)
            )
            counts = (
                self._group_counts[perm] if len(perm) else np.zeros(0, np.int32)
            )
            # Member lists are FROZEN copies taken in the same critical
            # section as the tensors: a handle's members may never diverge
            # from its counts snapshot (the bind path slices members by the
            # solved counts — a live view would drop or invent pods under
            # churn). list(dict.values()) is one C-level call per group.
            members = [list(self._group_members[s].values()) for s in live]
            dev = self._dev if clean else None
            epoch, generation = self._epoch, self._generation
        device_vectors = device_counts = None
        if dev is not None:
            # Sorted + bucket-padded gather OUT of the slot arrays — data
            # never leaves the device. Padding lanes read back zeros (an
            # empty group), inert in every kernel.
            padded = incremental.pad_indices(
                perm, int(dev["group_vectors"].shape[0])
            )
            device_vectors = incremental.gather_rows(dev["group_vectors"], padded)
            device_counts = incremental.gather_rows(dev["group_counts"], padded)
        else:
            # A sync raced in between flush and capture (or the state is
            # torn): fall back to uploading the host slices — exact, just
            # not zero-copy. Rare by construction.
            padded_len = bucket_size(max(len(perm), 8))
            device_vectors = incremental.device_slots(
                incremental.pad_to(vectors, padded_len)
            )
            device_counts = incremental.device_slots(
                incremental.pad_to(counts, padded_len)
            )
        return DevicePodGroups(
            vectors=vectors,
            counts=counts,
            members=members,
            epoch=epoch,
            generation=generation,
            device_vectors=device_vectors,
            device_counts=device_counts,
            state=self,
        )

    def _ensure_host_fresh(self) -> None:
        with self._lock:
            torn = self._torn is not None
        if torn:
            self.flush()

    def pods_on_node(self, name: str) -> List[PodSpec]:
        """All pods bound to `name` (terminal included — parity with
        ``cluster.list_pods(node_name=name)``), O(pods on that node) instead
        of O(cluster)."""
        self._ensure_host_fresh()
        with self._lock:
            pods = self._node_pods.get(name)
            return list(pods.values()) if pods else []

    def node_used(self, name: str) -> Optional[np.ndarray]:
        """Summed request vector of the node's non-terminal pods (float64
        copy — the consolidation controller's accounting dtype). None for an
        unknown node."""
        self._ensure_host_fresh()
        with self._lock:
            slot = self._node_slot.get(name)
            if slot is None:
                return None
            return self._node_used[slot].copy()

    def pending_count(self) -> int:
        self._ensure_host_fresh()
        with self._lock:
            return self._pending_total

    def covers(self, pods: Sequence[PodSpec]) -> bool:
        """True iff `pods` is EXACTLY the tracked pending set (the
        provisioner's hot path: one schedule draining the whole backlog) —
        then pending_groups() encodes this batch O(churn)."""
        self._ensure_host_fresh()
        with self._lock:
            if len(pods) != self._pending_total:
                return False
            for pod in pods:
                record = self._pod_rec.get((pod.namespace, pod.name))
                if record is None or not record.pending:
                    return False
            return True

    def device_view(self) -> Tuple[int, Optional[Dict[str, object]]]:
        """(epoch, current device arrays) — test/bench surface."""
        with self._lock:
            return self._epoch, self._dev

    # --- fleet (offering-array) cache ----------------------------------------

    def encode_fleet(
        self,
        instance_types,
        constraints,
        daemons: Sequence[PodSpec],
        pods_need: Optional[np.ndarray],
    ) -> InstanceFleet:
        """build_fleet behind a content-fingerprint cache: repeat sweeps over
        an unchanged catalog/constraint envelope skip the filter + densify
        walk entirely, and the fleet arrays then ride PR 6's device_resident
        cache at dispatch — the offering arrays never leave the device
        between sweeps. Any content drift (price/ICE churn, new types,
        daemonset change) misses and rebuilds."""
        need_key = pods_need.tobytes() if pods_need is not None else b""
        # The market fingerprint keys the live price surface into the cache:
        # a reprice (generation) or a forecast-risk move (risk_generation)
        # rebuilds the fleet — whose changed price bytes then also miss the
        # content-keyed device_resident cache at dispatch, so the offering
        # arrays re-upload exactly when the market moved. None (no active
        # book) keys a static market, the pre-market behavior.
        from karpenter_tpu.market.pricebook import active_fingerprint

        key = (
            _constraints_fingerprint(constraints),
            _catalog_fingerprint(instance_types),
            tuple(sorted(p.uid for p in daemons)),
            need_key,
            active_fingerprint(),
        )
        with self._lock:
            fleet = self._fleet_cache.get(key)
        if fleet is not None:
            return fleet
        fleet = build_fleet(
            instance_types, constraints, pods=[], daemons=daemons,
            pods_need=pods_need
            if pods_need is not None
            else np.zeros(_NUM_DIMS, np.float32),
        )
        with self._lock:
            if len(self._fleet_cache) >= 8:
                self._fleet_cache.clear()
            self._fleet_cache[key] = fleet
        return fleet

    def encode_schedule(
        self, pods: Sequence[PodSpec], instance_types, constraints, daemons
    ) -> Optional[Tuple[DevicePodGroups, InstanceFleet]]:
        """The provisioning fast path: when `pods` is exactly the tracked
        pending set, return (groups, fleet) without walking the batch —
        group tensors from the slot arrays, fleet from the fingerprint
        cache. None → caller takes the snapshot path.

        The coverage check runs AGAINST THE ENCODED SNAPSHOT, not just the
        live bookkeeping: covers() alone races a pod applied between the
        check and the capture, and a foreign pod encoded into the tensors
        would be bound without ever passing the scheduler — so the frozen
        member lists are re-verified to be exactly the batch."""
        if not self.covers(pods):
            return None
        groups = self.pending_groups()
        keys = {(p.namespace, p.name) for p in pods}
        if groups.num_pods != len(keys):
            return None
        for g in range(groups.num_groups):
            for member in groups.members[g]:
                if (member.namespace, member.name) not in keys:
                    return None
        pods_need = (
            groups.vectors.max(axis=0) if groups.num_groups else None
        )
        fleet = self.encode_fleet(instance_types, constraints, daemons, pods_need)
        return groups, fleet


def _constraints_fingerprint(constraints) -> Tuple:
    return (
        tuple(sorted(constraints.labels.items())),
        tuple(constraints.taints),
        constraints.requirements.canonical_key(),
    )


def _catalog_fingerprint(instance_types) -> Tuple:
    return tuple(
        (
            it.name,
            it.architecture,
            tuple(sorted(it.capacity.items())),
            tuple(sorted(it.overhead.items())),
            tuple(
                (
                    o.zone,
                    o.capacity_type,
                    o.price,
                    getattr(o, "available", True),
                    getattr(o, "consolidatable", True),
                )
                for o in it.offerings
            ),
        )
        for it in instance_types
    )
