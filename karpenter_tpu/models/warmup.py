"""Boot-time bucket-ladder precompile, shared by every deployment shape.

The device solve path jit-compiles one kernel per (groups, types) bucket;
the first solve at a cold bucket pays seconds of XLA compile. The reference
has no compile step at all (cmd/controller/main.go:61-99 goes straight from
registration to serving), so a deployment must pay that debt at boot —
never on a live batch. The solver sidecar runs this behind its
grpc.health.v1 gate (solver_service/server.py), and the in-process Manager
runs it behind /readyz (runtime.py) — same contract, both callers.

Shapes come from KARPENTER_WARMUP_SHAPES ("GxT,GxT,..."; the default covers
the small/medium/headline buckets). On multi-chip runtimes
cost_solve_dispatch's mesh auto-selection means this also compiles the
sharded kernel.
"""

from __future__ import annotations

import os
import time

import numpy as np

from karpenter_tpu.utils import logging as klog

DEFAULT_SHAPES = "8x8,8x16,16x64,16x512"

log = klog.named("warmup")


def make_synthetic_problem(num_groups: int, num_types: int, pods_per_group: int = 1):
    """One synthetic dense solve problem — (vectors, counts, capacity) —
    shared by the warmup compile pass and the break-even probes so the
    shapes they compile and the shapes they time can never drift apart."""
    rng = np.random.default_rng(0)
    vectors = np.zeros((num_groups, 8), np.float32)
    vectors[:, 0] = rng.integers(1, 9, num_groups) * 250
    vectors[:, 1] = rng.integers(1, 17, num_groups) * 256
    vectors[:, 2] = 1.0
    counts = np.full(num_groups, pods_per_group, np.int32)
    sizes = np.arange(1, num_types + 1, dtype=np.float32)
    capacity = np.zeros((num_types, 8), np.float32)
    capacity[:, 0] = 4000.0 * sizes
    capacity[:, 1] = 16384.0 * sizes
    capacity[:, 2] = 110.0
    return vectors, counts, capacity


def warmup_ladder(shapes: str | None = None) -> float:
    """Precompile the bucket ladder; returns elapsed seconds. Each shape
    failure is logged and skipped — warmup must never kill a boot."""
    from karpenter_tpu.models import solver as solver_models

    if shapes is None:
        shapes = os.environ.get("KARPENTER_WARMUP_SHAPES", DEFAULT_SHAPES)
    start = time.perf_counter()
    # Solves racing this warmup prefer the host path (steady-state latency)
    # over cold device buckets; cleared in the finally below.
    solver_models.set_warming_host_preference(True)
    try:
        _compile_shapes(shapes)
    finally:
        solver_models.set_warming_host_preference(False)
    # With the ladder warm the device path is live — measure the actual
    # fetch floor, host rate, AND warm device compute on THIS rig and
    # derive the host/device break-even from them (instead of the baked-in
    # bench-rig constants). Device compute = a warm re-solve of the
    # mid-ladder shape minus the fetch floor, measured on whatever backend
    # this process actually runs (a jax-CPU fallback rig times ITS kernel,
    # not the TPU's).
    try:
        floor_ms = solver_models._probe_fetch_floor_ms()
        warm_solve_ms = _timed_device_solve_ms(16, 64)
        device_compute_ms = max(warm_solve_ms - floor_ms, 1.0)
        cal = solver_models.calibrate_break_even(
            fetch_floor_ms=floor_ms, device_compute_ms=device_compute_ms
        )
        log.info(
            "dispatch break-even: fetch floor %.2fms, host %.4fms/pod "
            "-> host <= %d pods (batched <= %d)",
            cal.fetch_floor_ms, cal.host_ms_per_pod,
            cal.max_pods, cal.max_pods_batched,
        )
    except Exception:  # noqa: BLE001 — calibration must never kill boot
        log.warning("break-even calibration failed", exc_info=True)
    elapsed = time.perf_counter() - start
    log.info("bucket ladder warm in %.1fs (%s)", elapsed, shapes)
    return elapsed


def _compile_shapes(shapes: str) -> None:
    from karpenter_tpu.models import solver as solver_models

    for token in shapes.split(","):
        token = token.strip()
        if not token:
            continue
        try:
            num_groups, num_types = (int(x) for x in token.split("x"))
            _timed_device_solve_ms(num_groups, num_types)
            # The encoded-state fast path (models/cluster_state) dispatches
            # device-resident pod tensors through the NON-donating fused
            # kernel twin, which carries its own jit cache — compile it per
            # rung too, or the first incremental solve at each bucket pays
            # the XLA debt on a live batch.
            _timed_device_solve_ms(num_groups, num_types, device_pods=True)
        except Exception:  # noqa: BLE001 — warmup must never kill boot
            log.warning("warmup shape %s failed", token, exc_info=True)


def _timed_device_solve_ms(
    num_groups: int, num_types: int, device_pods: bool = False
) -> float:
    """Run one device solve at the given shape (compiling it if cold) and
    return its wall time — the warmup compile pass and the device-compute
    probe are the same call. Fetches through the COMPACTED helper so the
    timed number is the real pipeline's cost (eager payload only), not the
    dense spill + LP assignment the hot path never transfers.
    device_pods=True feeds the pod tensors as bucket-padded device arrays,
    routing through (and compiling) the non-donating kernel twin the
    incremental-encode fast path uses."""
    import jax

    from karpenter_tpu.models import solver as solver_models
    from karpenter_tpu.ops.pack_kernel import bucket_size, pad_to

    vectors, counts, capacity = make_synthetic_problem(num_groups, num_types)
    prices = (0.1 * np.arange(1, num_types + 1, dtype=np.float32))
    if device_pods:
        bucket = bucket_size(num_groups)
        vectors = jax.device_put(pad_to(vectors, bucket))
        counts = jax.device_put(pad_to(counts, bucket))
    start = time.perf_counter()
    solver_models.fetch_plan(
        solver_models.cost_solve_dispatch(
            vectors, counts, capacity, capacity.copy(), prices, 300,
            count=False,  # warmup, not a routed solve
        )
    )
    return (time.perf_counter() - start) * 1e3
