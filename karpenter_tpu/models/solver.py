"""Solver models — the pluggable "solver boundary" of the framework.

Ref: the north star's `pkg/cloudprovider/solver` plugin analogue (SURVEY.md
§2.7): the provisioning controller calls a Solver; TPUSolver runs the batched
JAX FFD kernel, CostSolver layers the price-aware strategies on top and keeps
the cheapest feasible packing, GreedySolver is the in-process fallback used
when no accelerator is available (and the correctness/cost oracle in tests
and benchmarks).
"""

from __future__ import annotations

import abc
from typing import List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider import InstanceType
from karpenter_tpu.ops import ffd
from karpenter_tpu.ops.encode import InstanceFleet, PodGroups, build_fleet, group_pods
from karpenter_tpu.ops.pack_kernel import bucket_size, pack_kernel, pad_to
from karpenter_tpu.ops.score_kernel import lp_relax_solve, round_assignment


class Solver(abc.ABC):
    """The solver boundary. Pods must already share one schedule's
    constraints (the scheduler groups them; ref: scheduling/scheduler.go:67).
    `solve` densifies specs then delegates to `solve_encoded`, the
    tensor-level entry point the benchmark and sidecar call directly."""

    def solve(
        self,
        pods: Sequence[PodSpec],
        instance_types: Sequence[InstanceType],
        constraints: Constraints,
        daemons: Sequence[PodSpec] = (),
    ) -> ffd.PackResult:
        groups = group_pods(list(pods))
        fleet = build_fleet(instance_types, constraints, pods, daemons)
        return self.solve_encoded(groups, fleet)

    @abc.abstractmethod
    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        ...


class GreedySolver(Solver):
    """Host-side grouped FFD in pure Python — reference-faithful oracle."""

    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        return ffd.pack_groups(fleet, groups)


class NativeSolver(Solver):
    """Compiled host FFD (native/ffd.cc via ctypes): same rounds as
    GreedySolver, at compiled-code speed — the fallback when no accelerator
    is attached, mirroring the role of the reference's compiled Go packer.
    Degrades to the pure-Python path when the library can't be built."""

    def __init__(self, quirk: bool = True):
        self.quirk = quirk

    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        from karpenter_tpu.ops import native

        if fleet.num_types == 0 or groups.num_groups == 0:
            return ffd.pack_groups(fleet, groups)
        result = native.ffd_pack_rounds(
            groups.vectors,
            groups.counts.astype(np.int64),
            fleet.capacity,
            fleet.total,
            quirk=self.quirk,
        )
        if result is None:
            return ffd.pack_groups(fleet, groups)
        round_list, unschedulable_counts = result
        return _decode_rounds(round_list, unschedulable_counts, groups, fleet)


def _run_kernel(groups: PodGroups, fleet: InstanceFleet, mode: str, quirk: bool):
    g_pad = bucket_size(groups.num_groups)
    t_pad = bucket_size(fleet.num_types)
    return pack_kernel(
        pad_to(groups.vectors, g_pad),
        pad_to(groups.counts.astype(np.int32), g_pad),
        pad_to(fleet.capacity, t_pad),
        pad_to(fleet.total, t_pad),
        pad_to(np.ones(fleet.num_types, bool), t_pad),
        pad_to(fleet.prices, t_pad),
        quirk=quirk,
        mode=mode,
    )


def _decode_rounds(
    round_list: List[Tuple[int, np.ndarray, int]],
    unschedulable_counts: np.ndarray,
    groups: PodGroups,
    fleet: InstanceFleet,
) -> ffd.PackResult:
    """Turn (type, fill, replication) rounds into Packing objects, merging by
    instance-option tuple (ref: packer.go:126-135 hashes options only)."""
    cursors = [0] * groups.num_groups
    by_options = {}
    packings: List[ffd.Packing] = []
    for t, fill, repl in round_list:
        options = fleet.instance_types[t : t + ffd.MAX_INSTANCE_TYPES]
        nodes = []
        for _ in range(repl):
            node_pods = []
            for g in np.nonzero(fill > 0)[0]:
                n = int(fill[g])
                node_pods.extend(groups.members[g][cursors[g] : cursors[g] + n])
                cursors[g] += n
            nodes.append(node_pods)
        key = tuple(it.name for it in options)
        existing = by_options.get(key)
        if existing is not None:
            existing.node_quantity += repl
            existing.pods_per_node.extend(nodes)
        else:
            packing = ffd.Packing(
                pods_per_node=nodes,
                instance_type_options=list(options),
                node_quantity=repl,
            )
            by_options[key] = packing
            packings.append(packing)

    unschedulable: List[PodSpec] = []
    for g in np.nonzero(unschedulable_counts > 0)[0]:
        n = int(unschedulable_counts[g])
        unschedulable.extend(groups.members[g][cursors[g] : cursors[g] + n])
        cursors[g] += n
    return ffd.PackResult(packings=packings, unschedulable=unschedulable)


def _kernel_rounds_to_list(rounds, num_groups: int):
    num_rounds = int(rounds.num_rounds)
    return [
        (
            int(np.asarray(rounds.round_type)[r]),
            np.asarray(rounds.round_fill)[r, :num_groups],
            int(np.asarray(rounds.round_repl)[r]),
        )
        for r in range(num_rounds)
    ]


class TPUSolver(Solver):
    """Batched solve on accelerator via ops.pack_kernel.

    mode="ffd" reproduces the reference packing (quirk=True bit-for-bit);
    mode="cost" picks price-efficient types each round. Shapes are bucketed to
    powers of two so repeat solves hit the jit cache.
    """

    def __init__(self, mode: str = "ffd", quirk: bool = False):
        self.mode = mode
        self.quirk = quirk

    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        if fleet.num_types == 0 or groups.num_groups == 0:
            return ffd.pack_groups(fleet, groups)
        rounds = _run_kernel(groups, fleet, self.mode, self.quirk)
        if bool(rounds.overflow):
            # Defensive: static round budget exhausted — fall back to host FFD
            # rather than return a partial packing.
            return ffd.pack_groups(fleet, groups)
        return _decode_rounds(
            _kernel_rounds_to_list(rounds, groups.num_groups),
            np.asarray(rounds.unschedulable)[: groups.num_groups],
            groups,
            fleet,
        )


class CostSolver(Solver):
    """The flagship: runs pure-greedy FFD, cost-greedy, and the LP-relaxation
    plan on TPU, returns the cheapest feasible packing. Because greedy is
    always among the candidates, projected $/hr can only match or beat the
    baseline."""

    def __init__(self, lp_steps: int = 300):
        self.lp_steps = lp_steps

    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        if fleet.num_types == 0 or groups.num_groups == 0:
            return ffd.pack_groups(fleet, groups)

        candidates: List[ffd.PackResult] = []
        for mode in ("ffd", "cost"):
            rounds = _run_kernel(groups, fleet, mode, False)
            if not bool(rounds.overflow):
                candidates.append(
                    _decode_rounds(
                        _kernel_rounds_to_list(rounds, groups.num_groups),
                        np.asarray(rounds.unschedulable)[: groups.num_groups],
                        groups,
                        fleet,
                    )
                )
        lp_result = self._solve_lp(groups, fleet)
        if lp_result is not None:
            candidates.append(lp_result)
        if not candidates:
            return ffd.pack_groups(fleet, groups)

        # A candidate that leaves more pods unschedulable never wins on price.
        best = min(
            candidates,
            key=lambda r: (len(r.unschedulable), r.projected_cost(), r.node_count),
        )
        return best

    def _solve_lp(
        self, groups: PodGroups, fleet: InstanceFleet
    ) -> Optional[ffd.PackResult]:
        g_pad = bucket_size(groups.num_groups)
        t_pad = bucket_size(fleet.num_types)
        vectors = pad_to(groups.vectors, g_pad)
        counts = pad_to(groups.counts.astype(np.int32), g_pad)
        capacity = pad_to(fleet.capacity, t_pad)
        valid = pad_to(np.ones(fleet.num_types, bool), t_pad)
        prices = pad_to(fleet.prices, t_pad)

        feasible = np.asarray(
            vectors[:, None, :] <= capacity[None, :, :] + 1e-6
        ).all(axis=-1) & valid[None, :]
        feasible_any = feasible.any(axis=1)
        unschedulable_counts = np.where(feasible_any, 0, counts)[: groups.num_groups]
        solvable_counts = np.where(feasible_any, counts, 0)

        if solvable_counts.sum() == 0:
            return None

        lp = lp_relax_solve(
            vectors,
            solvable_counts,
            capacity,
            valid,
            prices,
            steps=self.lp_steps,
        )
        assignment = round_assignment(np.asarray(lp.assignment), solvable_counts)

        # Realize the plan: per type, greedily fill nodes (pure greedy, no
        # quirk) with that type's assigned pods.
        round_list: List[Tuple[int, np.ndarray, int]] = []
        num_groups = groups.num_groups
        for t in range(fleet.num_types):
            counts_t = assignment[:num_groups, t].astype(np.int64).copy()
            guard = 0
            while counts_t.sum() > 0:
                fill = ffd.fill_node(
                    fleet.capacity[t],
                    fleet.total[t],
                    groups.vectors,
                    counts_t,
                    quirk=False,
                )
                if fill.sum() == 0:
                    # Should not happen (feasibility pre-checked); bail out.
                    return None
                repl_per_group = np.where(fill > 0, counts_t // np.maximum(fill, 1), np.iinfo(np.int64).max)
                repl = max(1, int(repl_per_group.min()))
                round_list.append((t, fill.copy(), repl))
                counts_t -= repl * fill
                guard += 1
                if guard > 4 * num_groups + 16:
                    return None
        return _decode_rounds(round_list, unschedulable_counts, groups, fleet)
