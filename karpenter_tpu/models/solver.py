"""Solver models — the pluggable "solver boundary" of the framework.

Ref: the north star's `pkg/cloudprovider/solver` plugin analogue (SURVEY.md
§2.7): the provisioning controller calls a Solver; TPUSolver runs the batched
JAX FFD kernel, CostSolver layers the price-aware strategies on top and keeps
the cheapest feasible packing, GreedySolver is the in-process fallback used
when no accelerator is available (and the correctness/cost oracle in tests
and benchmarks).
"""

from __future__ import annotations

import abc
import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider import InstanceType
from karpenter_tpu.ops import ffd
from karpenter_tpu.ops.encode import InstanceFleet, PodGroups, build_fleet, group_pods
from karpenter_tpu.ops.pack_kernel import bucket_size, pack_kernel, pad_to
from karpenter_tpu.ops.score_kernel import (
    feasibility_mask,
    lp_relax_solve,
    round_assignment,
)


class Solver(abc.ABC):
    """The solver boundary. Pods must already share one schedule's
    constraints (the scheduler groups them; ref: scheduling/scheduler.go:67).
    `solve` densifies specs then delegates to `solve_encoded`, the
    tensor-level entry point the benchmark and sidecar call directly."""

    def solve(
        self,
        pods: Sequence[PodSpec],
        instance_types: Sequence[InstanceType],
        constraints: Constraints,
        daemons: Sequence[PodSpec] = (),
    ) -> ffd.PackResult:
        groups = group_pods(list(pods))
        fleet = build_fleet(instance_types, constraints, pods, daemons)
        return self.solve_encoded(groups, fleet)

    @abc.abstractmethod
    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        ...


class GreedySolver(Solver):
    """Host-side grouped FFD in pure Python — reference-faithful oracle."""

    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        return ffd.pack_groups(fleet, groups)


class NativeSolver(Solver):
    """Compiled host FFD (native/ffd.cc via ctypes): same rounds as
    GreedySolver, at compiled-code speed — the fallback when no accelerator
    is attached, mirroring the role of the reference's compiled Go packer.
    Degrades to the pure-Python path when the library can't be built."""

    def __init__(self, quirk: bool = True):
        self.quirk = quirk

    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        from karpenter_tpu.ops import native

        if fleet.num_types == 0 or groups.num_groups == 0:
            return ffd.pack_groups(fleet, groups)
        result = native.ffd_pack_rounds(
            groups.vectors,
            groups.counts.astype(np.int64),
            fleet.capacity,
            fleet.total,
            quirk=self.quirk,
        )
        if result is None:
            return ffd.pack_groups(fleet, groups)
        round_list, unschedulable_counts = result
        return _decode_rounds(round_list, unschedulable_counts, groups, fleet)


@functools.partial(jax.jit, static_argnames=("lp_steps",))
def _cost_fused_kernel(
    vectors, counts, capacity, total, valid, prices, *, lp_steps: int
):
    """All three CostSolver candidates as ONE XLA computation: greedy-FFD
    rounds, cost-greedy rounds, and the LP relaxation. Fusing them means a
    single dispatch and a single device->host round trip per solve — on a
    tunneled accelerator the round trips cost more than the math."""
    rounds_ffd = pack_kernel(
        vectors, counts, capacity, total, valid, prices, quirk=False, mode="ffd"
    )
    rounds_cost = pack_kernel(
        vectors, counts, capacity, total, valid, prices, quirk=False, mode="cost"
    )
    feasible_any = feasibility_mask(vectors, capacity, valid).any(axis=1)
    solvable = jnp.where(feasible_any, counts, 0)
    lp = lp_relax_solve(vectors, solvable, capacity, valid, prices, steps=lp_steps)
    return rounds_ffd, rounds_cost, lp.assignment, feasible_any


def _run_kernel(groups: PodGroups, fleet: InstanceFleet, mode: str, quirk: bool):
    g_pad = bucket_size(groups.num_groups)
    t_pad = bucket_size(fleet.num_types)
    return pack_kernel(
        pad_to(groups.vectors, g_pad),
        pad_to(groups.counts.astype(np.int32), g_pad),
        pad_to(fleet.capacity, t_pad),
        pad_to(fleet.total, t_pad),
        pad_to(np.ones(fleet.num_types, bool), t_pad),
        pad_to(fleet.prices, t_pad),
        quirk=quirk,
        mode=mode,
    )


def _cheapest_feasible_options(
    fill: np.ndarray, t: int, groups: PodGroups, fleet: InstanceFleet
) -> List[int]:
    """Indices of the up-to-MAX_INSTANCE_TYPES cheapest types whose usable
    capacity holds this node's total demand.

    The reference offers the ascending-size window [t, t+20) as launch
    options (packer.go:178-180); any of those types can host the packing, and
    the fleet buys the cheapest. But so can ANY type with enough capacity —
    offering the cheapest feasible set instead of the next-larger set lowers
    the purchase price without touching the packing. The chosen type t is
    always included as the feasibility anchor."""
    demand = (fill.astype(np.float64)[:, None] * groups.vectors).sum(axis=0)
    feasible = np.nonzero((fleet.capacity >= demand - 1e-6).all(axis=1))[0]
    ranked = feasible[np.argsort(fleet.prices[feasible], kind="stable")]
    chosen = list(ranked[: ffd.MAX_INSTANCE_TYPES])
    if t not in chosen:
        chosen[-1 if len(chosen) == ffd.MAX_INSTANCE_TYPES else len(chosen):] = [t]
    return chosen


def _decode_rounds(
    round_list: List[Tuple[int, np.ndarray, int]],
    unschedulable_counts: np.ndarray,
    groups: PodGroups,
    fleet: InstanceFleet,
    options_fn=None,
) -> ffd.PackResult:
    """Turn (type, fill, replication) rounds into Packing objects, merging by
    instance-option tuple (ref: packer.go:126-135 hashes options only).

    options_fn(t, fill) -> [type index] overrides the reference's
    ascending-size option window (the CostSolver passes its memoized
    cheapest-feasible selector)."""
    cursors = [0] * groups.num_groups
    by_options = {}
    packings: List[ffd.Packing] = []
    for t, fill, repl in round_list:
        if options_fn is not None:
            options = [fleet.instance_types[i] for i in options_fn(t, fill)]
        else:
            options = fleet.instance_types[t : t + ffd.MAX_INSTANCE_TYPES]
        filled_groups = [(int(g), int(fill[g])) for g in np.nonzero(fill > 0)[0]]
        nodes = []
        for _ in range(repl):
            node_pods = []
            for g, n in filled_groups:
                node_pods.extend(groups.members[g][cursors[g] : cursors[g] + n])
                cursors[g] += n
            nodes.append(node_pods)
        key = tuple(it.name for it in options)
        existing = by_options.get(key)
        if existing is not None:
            existing.node_quantity += repl
            existing.pods_per_node.extend(nodes)
        else:
            packing = ffd.Packing(
                pods_per_node=nodes,
                instance_type_options=list(options),
                node_quantity=repl,
            )
            by_options[key] = packing
            packings.append(packing)

    unschedulable: List[PodSpec] = []
    for g in np.nonzero(unschedulable_counts > 0)[0]:
        n = int(unschedulable_counts[g])
        unschedulable.extend(groups.members[g][cursors[g] : cursors[g] + n])
        cursors[g] += n
    return ffd.PackResult(packings=packings, unschedulable=unschedulable)


def _to_host(tree):
    """Device->host via jax.device_get, ONE call per kernel invocation.

    Every device_get is a full round trip to the accelerator (tens of ms over
    a tunneled device), and np.asarray on a jax Array is worse still (a slow
    element-protocol path). So kernel outputs are fetched as a single pytree
    transfer and everything downstream is plain numpy."""
    return jax.device_get(tree)


def _kernel_rounds_to_list(host_rounds: "PackRounds", num_groups: int):
    num_rounds = int(host_rounds.num_rounds)
    return [
        (
            int(host_rounds.round_type[r]),
            host_rounds.round_fill[r, :num_groups],
            int(host_rounds.round_repl[r]),
        )
        for r in range(num_rounds)
    ]


class TPUSolver(Solver):
    """Batched solve on accelerator via ops.pack_kernel.

    mode="ffd" reproduces the reference packing (quirk=True bit-for-bit);
    mode="cost" picks price-efficient types each round. Shapes are bucketed to
    powers of two so repeat solves hit the jit cache.
    """

    def __init__(self, mode: str = "ffd", quirk: bool = False):
        self.mode = mode
        self.quirk = quirk

    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        if fleet.num_types == 0 or groups.num_groups == 0:
            return ffd.pack_groups(fleet, groups)
        rounds = _to_host(_run_kernel(groups, fleet, self.mode, self.quirk))
        if bool(rounds.overflow):
            # Defensive: static round budget exhausted — fall back to host FFD
            # rather than return a partial packing.
            return ffd.pack_groups(fleet, groups)
        return _decode_rounds(
            _kernel_rounds_to_list(rounds, groups.num_groups),
            rounds.unschedulable[: groups.num_groups],
            groups,
            fleet,
        )


class CostSolver(Solver):
    """The flagship: runs pure-greedy FFD, cost-greedy, and the LP-relaxation
    plan on TPU, returns the cheapest feasible packing. Because greedy is
    always among the candidates, projected $/hr can only match or beat the
    baseline."""

    def __init__(self, lp_steps: int = 300):
        self.lp_steps = lp_steps

    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        if fleet.num_types == 0 or groups.num_groups == 0:
            return ffd.pack_groups(fleet, groups)

        # One fused accelerator computation (greedy rounds + cost rounds + LP
        # relaxation) and ONE device->host fetch: round-trip latency to the
        # device, not compute, dominates this problem size.
        #
        # Price model: a node packed for type t launches as the CHEAPEST of
        # its MAX_INSTANCE_TYPES option window (the fleet call's lowest-price
        # strategy; ref: instance.go:116-133), so the cost objective sees the
        # windowed minimum price, not the raw per-type price.
        effective_prices = np.array(
            [
                fleet.prices[t : t + ffd.MAX_INSTANCE_TYPES].min()
                for t in range(fleet.num_types)
            ],
            dtype=np.float32,
        )
        g_pad = bucket_size(groups.num_groups)
        t_pad = bucket_size(fleet.num_types)
        fused = _cost_fused_kernel(
            pad_to(groups.vectors, g_pad),
            pad_to(groups.counts.astype(np.int32), g_pad),
            pad_to(fleet.capacity, t_pad),
            pad_to(fleet.total, t_pad),
            pad_to(np.ones(fleet.num_types, bool), t_pad),
            pad_to(effective_prices, t_pad),
            lp_steps=self.lp_steps,
        )
        rounds_ffd, rounds_cost, lp_assignment, feasible_any = _to_host(fused)

        # Candidates stay in round form; only the winner pays the decode into
        # concrete per-node pod lists.
        candidates: List[Tuple[List[Tuple[int, np.ndarray, int]], np.ndarray]] = []
        for rounds in (rounds_ffd, rounds_cost):
            if not bool(rounds.overflow):
                candidates.append(
                    (
                        _kernel_rounds_to_list(rounds, groups.num_groups),
                        rounds.unschedulable[: groups.num_groups],
                    )
                )
        lp_candidate = self._realize_lp(lp_assignment, feasible_any, groups, fleet)
        if lp_candidate is not None:
            candidates.append(lp_candidate)
        if not candidates:
            return ffd.pack_groups(fleet, groups)

        # Score from rounds: a node's realized price is the cheapest of its
        # offered options, which for the CostSolver is the cheapest feasible
        # type for that fill. A candidate that leaves more pods unschedulable
        # never wins on price. The option sets are memoized per (t, fill) so
        # the winning candidate's decode reuses the scoring pass's work.
        options_memo: dict = {}

        def options_fn(t: int, fill: np.ndarray) -> List[int]:
            key = (t, fill.tobytes())
            options = options_memo.get(key)
            if options is None:
                options = _cheapest_feasible_options(fill, t, groups, fleet)
                options_memo[key] = options
            return options

        def score(candidate):
            round_list, unschedulable_counts = candidate
            nodes = sum(repl for _, _, repl in round_list)
            cost = sum(
                repl * float(fleet.prices[options_fn(t, fill)].min())
                for t, fill, repl in round_list
            )
            return (int(unschedulable_counts.sum()), cost, nodes)

        best_rounds, best_unschedulable = min(candidates, key=score)
        return _decode_rounds(
            best_rounds, best_unschedulable, groups, fleet, options_fn=options_fn
        )

    def _realize_lp(
        self,
        lp_assignment: np.ndarray,
        feasible_any: np.ndarray,
        groups: PodGroups,
        fleet: InstanceFleet,
    ) -> Optional[Tuple[List[Tuple[int, np.ndarray, int]], np.ndarray]]:
        """Integerize the relaxed [G, T] assignment (already fetched to host)
        and realize it as greedy per-type node fills."""
        num = groups.num_groups
        counts = groups.counts.astype(np.int64)
        unschedulable_counts = np.where(feasible_any[:num], 0, counts)
        solvable_counts = np.where(feasible_any[:num], counts, 0)
        if solvable_counts.sum() == 0:
            return None
        padded_solvable = np.zeros(lp_assignment.shape[0], dtype=np.int64)
        padded_solvable[:num] = solvable_counts
        assignment = round_assignment(lp_assignment, padded_solvable)

        # Realize the plan: per type, greedily fill nodes (pure greedy, no
        # quirk) with that type's assigned pods.
        round_list: List[Tuple[int, np.ndarray, int]] = []
        num_groups = groups.num_groups
        for t in range(fleet.num_types):
            counts_t = assignment[:num_groups, t].astype(np.int64).copy()
            guard = 0
            while counts_t.sum() > 0:
                fill = ffd.fill_node(
                    fleet.capacity[t],
                    fleet.total[t],
                    groups.vectors,
                    counts_t,
                    quirk=False,
                )
                if fill.sum() == 0:
                    # Should not happen (feasibility pre-checked); bail out.
                    return None
                repl_per_group = np.where(fill > 0, counts_t // np.maximum(fill, 1), np.iinfo(np.int64).max)
                repl = max(1, int(repl_per_group.min()))
                round_list.append((t, fill.copy(), repl))
                counts_t -= repl * fill
                guard += 1
                if guard > 4 * num_groups + 16:
                    return None
        return round_list, unschedulable_counts
