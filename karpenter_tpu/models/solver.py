"""Solver models — the pluggable "solver boundary" of the framework.

Ref: the north star's `pkg/cloudprovider/solver` plugin analogue (SURVEY.md
§2.7): the provisioning controller calls a Solver; TPUSolver runs the batched
JAX FFD kernel, CostSolver layers the price-aware strategies on top and keeps
the cheapest feasible packing, GreedySolver is the in-process fallback used
when no accelerator is available (and the correctness/cost oracle in tests
and benchmarks).
"""

from __future__ import annotations

import abc
import functools
import threading
from dataclasses import dataclass
from typing import Dict, Iterator, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider import InstanceType
from karpenter_tpu.ops import ffd
from karpenter_tpu.ops import mix_pack
from karpenter_tpu.ops.encode import InstanceFleet, PodGroups, build_fleet, group_pods
from karpenter_tpu.ops.pack_kernel import (  # noqa: F401 — fetch_bytes re-exported
    bucket_size,
    fetch_bytes,
    pack_kernel,
    pad_to,
)
from karpenter_tpu.ops import pallas_kernels
from karpenter_tpu.ops.pallas_kernels import dominance_prices
from karpenter_tpu.ops.score_kernel import (
    feasibility_mask,
    lp_relax_body,
    round_assignment,
)
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.metrics import REGISTRY
from karpenter_tpu.utils.tracing import TRACER, device_profile

# Which side of the adaptive dispatch a cost solve was ROUTED to — the
# first thing to check when solve latency looks wrong for the problem
# size. Counted at routing time: a device dispatch whose candidates all
# fail (rare — the caller then falls back to host greedy) still counts as
# "device", since the routing decision is what the metric explains.
SOLVE_DISPATCH_TOTAL = REGISTRY.counter(
    "solver_dispatch_total",
    "Cost solves by routed dispatch path (host|device)",
    ["path"],
)
# Boot-measured dispatch calibration (calibrate_break_even): the probed
# fetch floor, host solve rate, and the derived routing thresholds.
BREAK_EVEN_GAUGE = REGISTRY.gauge(
    "solver_break_even",
    "Host/device break-even calibration measured at boot",
    ["quantity"],
)
# Device-memory survival (CostSolver._solve_batch_survive): batch splits
# forced by HBM pressure. "estimate" = the pre-dispatch estimator chunked
# an oversized batch before it could OOM; "oom" = a live RESOURCE_EXHAUSTED
# bisected the batch and re-dispatched the halves; "floor" = a single
# schedule still OOMed, so the solve fell through to the BackendHealth CPU
# pin. A climbing "oom" rate with zero "estimate" means the estimator's
# budget read is wrong for this device.
SOLVER_BATCH_SPLIT_TOTAL = REGISTRY.counter(
    "solver_batch_split_total",
    "Solve-batch splits under device memory pressure (estimate|oom|floor)",
    ["reason"],
)


class Solver(abc.ABC):
    """The solver boundary. Pods must already share one schedule's
    constraints (the scheduler groups them; ref: scheduling/scheduler.go:67).
    `solve` densifies specs then delegates to `solve_encoded`, the
    tensor-level entry point the benchmark and sidecar call directly."""

    # Device-backed solvers carry XLA compile debt the first time each
    # (groups, types) bucket is hit; deployments that embed one warm the
    # bucket ladder at boot (models/warmup.py) behind their readiness gate.
    needs_device_warmup = False

    def solve(
        self,
        pods: Sequence[PodSpec],
        instance_types: Sequence[InstanceType],
        constraints: Constraints,
        daemons: Sequence[PodSpec] = (),
    ) -> ffd.PackResult:
        groups = group_pods(list(pods))
        fleet = build_fleet(
            instance_types, constraints, pods, daemons,
            pods_need=_groups_need(groups),
        )
        return self.solve_encoded(groups, fleet)

    @staticmethod
    def _encode_problems(
        problems: Sequence[
            Tuple[Sequence[PodSpec], Sequence[InstanceType], Constraints, Sequence[PodSpec]]
        ],
    ) -> List[Tuple[PodGroups, InstanceFleet]]:
        """THE spec->tensor encoding of a problem batch, shared by the
        barrier (solve_many) and pipelined (solve_many_pipelined) paths so
        they can never drift.

        Encoded-state fast path: a problem may arrive ALREADY encoded as a
        (PodGroups, InstanceFleet) pair — the incremental encoder
        (models/cluster_state.DeviceClusterState) hands these over when its
        delta-maintained tensors cover the batch, and group_pods/build_fleet
        are skipped entirely (per-sweep encode cost O(churn), not
        O(cluster)). The pair passes through untouched so the two sources
        stay interchangeable downstream."""
        encoded = []
        for item in problems:
            if len(item) == 2 and isinstance(item[0], PodGroups):
                encoded.append((item[0], item[1]))
                continue
            pods, instance_types, constraints, daemons = item
            groups = group_pods(list(pods))
            encoded.append(
                (
                    groups,
                    build_fleet(
                        instance_types, constraints, pods, daemons,
                        pods_need=_groups_need(groups),
                    ),
                )
            )
        return encoded

    def solve_many(
        self,
        problems: Sequence[
            Tuple[Sequence[PodSpec], Sequence[InstanceType], Constraints, Sequence[PodSpec]]
        ],
    ) -> List[ffd.PackResult]:
        """Solve a batch of independent schedule problems. Device-backed
        solvers override solve_encoded_many to share one device->host round
        trip across the whole batch (a pod batch regularly splits into many
        schedules — ref: provisioner.go solves them in a loop, paying the
        kernel per schedule)."""
        return self.solve_encoded_many(self._encode_problems(problems))

    def solve_encoded_many(
        self, items: Sequence[Tuple[PodGroups, InstanceFleet]]
    ) -> List[ffd.PackResult]:
        return [self.solve_encoded(groups, fleet) for groups, fleet in items]

    def solve_many_pipelined(
        self,
        problems: Sequence[
            Tuple[Sequence[PodSpec], Sequence[InstanceType], Constraints, Sequence[PodSpec]]
        ],
    ) -> Iterator[ffd.PackResult]:
        """solve_many as a generator: results come back one schedule at a
        time, in order, so the caller can bind schedule N while later
        schedules are still solving. Device-backed solvers override
        solve_encoded_pipelined to genuinely overlap the remaining kernels
        and device->host copies with the caller's bind work; the base
        implementation solves the whole batch up front and just yields."""
        return self.solve_encoded_pipelined(self._encode_problems(problems))

    def solve_encoded_pipelined(
        self, items: Sequence[Tuple[PodGroups, InstanceFleet]]
    ) -> Iterator[ffd.PackResult]:
        """Base implementation: solve each schedule ON DEMAND at its pull.
        Host solvers have no device work to overlap, but lazy per-pull
        solving keeps the caller's per-schedule timing honest (each
        SOLVE_DURATION sample in provisioning measures a real solve, not a
        pre-solved batch) and matches the pipelined contract: work for
        schedule N+1 happens after schedule N was handed over. Batching
        solvers (CostSolver, RemoteSolver) override this with genuinely
        overlapped implementations."""
        return (self.solve_encoded(groups, fleet) for groups, fleet in items)

    @abc.abstractmethod
    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        ...


def _groups_need(groups: PodGroups) -> Optional[np.ndarray]:
    """[R] max request vector from already-grouped pods (saves build_fleet a
    second 50k-pod walk)."""
    if groups.num_groups == 0:
        return None
    return groups.vectors.max(axis=0)


class GreedySolver(Solver):
    """Host-side grouped FFD in pure Python — reference-faithful oracle."""

    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        return ffd.pack_groups(fleet, groups)


class NativeSolver(Solver):
    """Compiled host FFD (native/ffd.cc via ctypes): same rounds as
    GreedySolver, at compiled-code speed — the fallback when no accelerator
    is attached, mirroring the role of the reference's compiled Go packer.
    Degrades to the pure-Python path when the library can't be built."""

    def __init__(self, quirk: bool = True):
        self.quirk = quirk

    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        from karpenter_tpu.ops import native

        if fleet.num_types == 0 or groups.num_groups == 0:
            return ffd.pack_groups(fleet, groups)
        result = native.ffd_pack_rounds(
            groups.vectors,
            groups.counts.astype(np.int64),
            fleet.capacity,
            fleet.total,
            quirk=self.quirk,
        )
        if result is None:
            return ffd.pack_groups(fleet, groups)
        round_list, unschedulable_counts = result
        return _decode_rounds(round_list, unschedulable_counts, groups, fleet)


def _cost_fused_body(
    vectors, counts, capacity, total, valid, prices, *, lp_steps: int,
    constrain=None, compact=None,
):
    """All three CostSolver candidates as ONE XLA computation: greedy-FFD
    rounds, cost-greedy rounds, and the LP relaxation. Fusing them means a
    single dispatch and a single device->host round trip per solve — on a
    tunneled accelerator the round trips cost more than the math. The
    outputs come back in FOUR leaves with very different fetch policies
    (see FusedHandle): a compacted int32 payload plus the scalar LP
    objective are fetched eagerly (a few KB — ops/pack_kernel.compact_plan);
    the dense round state is a spill fetched only when compaction overflows
    its COO entry budget; and the [G, T] LP assignment (the bulk of the old
    38KB payload) stays DEVICE-RESIDENT until the scoring pass actually
    decides to realize the LP plan. Few leaves still matters: each fetched
    leaf adds per-transfer overhead on the tunnel, so the eager payload is
    two leaves, not fifteen.

    Price model: a node packed for type t launches as the cheapest pool of
    ANY type whose capacity dominates t's (the plan offers the price-ranked
    feasible pools, _cheapest_feasible_pools), so the cost objective sees the
    dominating-type minimum price — the price the realization will actually
    pay, not t's own list price. The [T, T] dominance reduction is tensor
    math, so it rides along in the same compiled computation.

    `constrain` shards the LP's [G, T] tensors over a device mesh on the
    multi-chip path (see _sharded_fused_kernel); the sequential pack rounds
    stay replicated — they are lax.while_loop state machines with no
    parallelizable [G, T] bulk. `compact`, also supplied only by the
    sharded kernel, swaps the compaction for the shard-local one
    (ops/pack_kernel.compact_plan_sharded): each device compacts its own
    G block and only the compacted COO segments ride the collective. The
    hook replaces PR 6's force-replicate pin — letting GSPMD partition the
    plain prefix-sum + scatter produced corrupted COO entries (observed:
    shard-strided indices and a shard-multiplied nnz on an 8-way CPU
    mesh); shard_map's manual partitioning sidesteps that entirely."""
    valid_prices = jnp.where(valid, prices, jnp.inf)
    # [T, T'] dominance + masked min as a VMEM-resident pallas kernel on TPU
    # (ops/pallas_kernels.py), XLA formulation elsewhere.
    effective_prices = dominance_prices(capacity, valid_prices)
    rounds_ffd = pack_kernel(
        vectors, counts, capacity, total, valid, effective_prices,
        quirk=False, mode="ffd",
    )
    rounds_cost = pack_kernel(
        vectors, counts, capacity, total, valid, effective_prices,
        quirk=False, mode="cost",
    )
    feasible_any = feasibility_mask(vectors, capacity, valid).any(axis=1)
    solvable = jnp.where(feasible_any, counts, 0)
    lp = lp_relax_body(
        vectors, solvable, capacity, valid, effective_prices,
        steps=lp_steps, constrain=constrain,
    )

    def rounds_ints(r: "PackRounds"):
        return [
            r.round_type.ravel(),
            r.round_fill.ravel(),
            r.round_repl.ravel(),
            r.num_rounds.reshape(1),
            r.unschedulable.ravel(),
            r.overflow.astype(jnp.int32).reshape(1),
        ]

    from karpenter_tpu.ops.pack_kernel import compact_plan

    dense_ints = jnp.concatenate(
        rounds_ints(rounds_ffd)
        + rounds_ints(rounds_cost)
        + [feasible_any.astype(jnp.int32).ravel()]
    )
    compact_fn = compact_plan if compact is None else compact
    compacted = compact_fn(rounds_ffd, rounds_cost, feasible_any)
    objective = lp.objective.reshape(1).astype(jnp.float32)
    return compacted, objective, dense_ints, lp.assignment.ravel()


def unpack_dense(ints: np.ndarray, num_groups: int) -> Tuple:
    """Host-side inverse of the dense spill packing:
    (rounds_ffd, rounds_cost, feasible_any) from the flat int array, given
    the PADDED group count."""
    from karpenter_tpu.ops.pack_kernel import PackRounds, max_rounds

    mr = max_rounds(num_groups)
    cursor = 0

    def take(n):
        nonlocal cursor
        out = ints[cursor : cursor + n]
        cursor += n
        return out

    def take_rounds() -> PackRounds:
        return PackRounds(
            round_type=take(mr),
            round_fill=take(mr * num_groups).reshape(mr, num_groups),
            round_repl=take(mr),
            num_rounds=take(1)[0],
            unschedulable=take(num_groups),
            overflow=bool(take(1)[0]),
        )

    rounds_ffd = take_rounds()
    rounds_cost = take_rounds()
    feasible_any = take(num_groups).astype(bool)
    return rounds_ffd, rounds_cost, feasible_any


class FusedHandle(NamedTuple):
    """A dispatched fused solve: in-flight device arrays plus the static
    padded shapes needed to decode them after the fetch. Only `eager`
    (compact payload + LP objective, a few KB) is fetched on the hot path;
    `dense` is the spill for COO-budget overflow, and `lp` stays on device
    unless the scoring pass realizes the LP plan (fetch_plans)."""

    compact: object  # [NW] int32 (device array until fetched)
    objective: object  # [1] float32
    dense: object  # [NI] int32 — dense spill, fetched only on overflow
    lp: object  # [G*T] float32 — deferred LP assignment
    num_groups: int  # padded G
    num_types: int  # padded T
    shards: int = 1  # mesh device count of a sharded dispatch (compact layout)

    @property
    def eager(self):
        return (self.compact, self.objective)


_cost_fused_kernel = functools.partial(
    # vectors/counts donated: per-solve arrays nothing reads after dispatch
    # (ops/pack_kernel.pack_kernel documents the invariant). The fleet-side
    # args may be device_resident handles shared across sweeps and must
    # never be donated.
    jax.jit(
        _cost_fused_body,
        static_argnames=("lp_steps", "constrain", "compact"),
        donate_argnums=(0, 1),
    ),
    constrain=None,
    compact=None,
)

_cost_fused_kernel_nodonate = functools.partial(
    # The no-donation twin for encoded-state solves: pod tensors coming from
    # the incremental encode layer (models/cluster_state) are device arrays
    # a caller may still read after the dispatch (parity checks, a retry
    # against the same handle) — incremental buffers are NEVER donated
    # (docs/design/incremental-encode.md), so those solves route here.
    jax.jit(
        _cost_fused_body,
        static_argnames=("lp_steps", "constrain", "compact"),
    ),
    constrain=None,
    compact=None,
)


class FetchedPlan:
    """A fused solve's decoded eager payload plus deferred device handles.

    The compacted fetch helpers (fetch_plan / fetch_plans) produce these;
    cost_solve_finish consumes them. lp_assignment() triggers the deferred
    [G, T] fetch the first time the LP realization pass actually runs —
    solves whose kernel candidates win outright never transfer it."""

    def __init__(self, rounds_ffd, rounds_cost, feasible_any, lp_objective, handle):
        self.rounds_ffd = rounds_ffd
        self.rounds_cost = rounds_cost
        self.feasible_any = feasible_any
        self.lp_objective = lp_objective
        self._handle = handle
        self._lp: Optional[np.ndarray] = None

    def lp_assignment(self) -> np.ndarray:
        if self._lp is None:
            handle = self._handle
            self._lp = np.asarray(_to_host(handle.lp)).reshape(
                handle.num_groups, handle.num_types
            )
        return self._lp


def plan_start_fetch(handle: FusedHandle) -> None:
    """Queue the EAGER leaves' device->host copies (compact payload +
    objective) behind the dispatched kernel — the compacted analogue of
    calling _start_fetch on a whole output tree."""
    _start_fetch(handle.eager)


def fetch_plans(handles: Sequence[FusedHandle]) -> List["FetchedPlan"]:
    """THE compacted fetch: one device->host transfer for every handle's
    eager payload (a batch shares one round trip), then host-side decode.
    A plan that overflowed the COO entry budget falls back to its dense
    spill — correctness never depends on the budget."""
    from karpenter_tpu.ops.pack_kernel import decompact_plan_sharded

    try:
        eager = _to_host([handle.eager for handle in handles])
    except Exception as error:  # noqa: BLE001 — quarantine, then re-raise
        # The dispatch is async, so a chip that dies DURING execution
        # surfaces here, not at cost_solve_dispatch — without this hook the
        # mesh would never shrink and every subsequent solve would re-fail
        # on the dead chip. This solve still fails (the caller's fallback
        # ladder handles it); the quarantine makes the NEXT one re-lower
        # on the survivors. (An in-C hang is out of in-process reach —
        # that detection belongs to the killable probe + the runbook alert
        # on backend_wedged_chips; see docs/design/sharded-solve.md.)
        _quarantine_after_fetch_failure(handles, error)
        raise
    plans: List[FetchedPlan] = []
    for handle, (compact, objective) in zip(handles, eager):
        rounds_ffd, rounds_cost, feasible_any, ok = decompact_plan_sharded(
            np.asarray(compact), handle.num_groups, handle.shards
        )
        if not ok:  # pragma: no cover — entry budget sized to never trip
            rounds_ffd, rounds_cost, feasible_any = unpack_dense(
                np.asarray(_to_host(handle.dense)), handle.num_groups
            )
        plans.append(
            FetchedPlan(
                rounds_ffd,
                rounds_cost,
                feasible_any,
                float(np.asarray(objective)[0]),
                handle,
            )
        )
    return plans


def fetch_plan(handle: FusedHandle) -> "FetchedPlan":
    return fetch_plans([handle])[0]


def _quarantine_after_fetch_failure(
    handles: Sequence[FusedHandle], error: BaseException
) -> None:
    """A device->host fetch of sharded solve outputs failed: run the
    wedged-chip quarantine over the whole device set (the probe marks only
    non-responders, so passing every id is safe) so the next dispatch
    shrinks the mesh. No-op for purely single-device handles — a dead
    single device is the whole-device verdict's territory."""
    if not any(handle.shards > 1 for handle in handles):
        return
    quarantine_devices(error)


def quarantine_devices(error: BaseException) -> None:
    """Run the wedged-chip quarantine over the whole device set (best
    effort — diagnosis must never mask the original error). Shared by the
    fused-plan fetch hook above and the constrained [L, G, T] dispatch's
    fetch (constraints/solve), so a chip that dies during a sharded
    constrained solve also shrinks the mesh for the next dispatch."""
    try:
        from karpenter_tpu.utils import backend_health

        backend_health.quarantine_mesh(
            [int(d.id) for d in jax.devices()], error
        )
    except Exception:  # noqa: BLE001 — diagnosis must not mask the fetch error
        klog.named("solver").warning(
            "wedged-chip quarantine after fetch failure itself failed",
            exc_info=True,
        )


_SHARDED_KERNEL_CACHE: Dict[Tuple, Tuple] = {}


def _sharded_fused_kernel(mesh=None):
    """The fused kernel compiled for a multi-device mesh: identical math to
    _cost_fused_kernel, but every [G, T] LP tensor carries a
    with_sharding_constraint over the ("groups", "types") mesh so GSPMD
    partitions the softmax/einsum/Adam bulk across chips over ICI, while the
    sequential pack rounds replicate. Plan compaction runs SHARD-LOCAL
    (ops/pack_kernel.compact_plan_sharded): each device compacts its own G
    block and only the compacted COO segments — not the dense [MR, G] round
    state — ride the collective at the tail. Returns
    (kernel, (g_mult, t_mult), shards): callers must pad G/T to those
    multiples on top of the bucket ladder (g_mult is the TOTAL device count
    so the compaction blocks split evenly over every chip) and decode the
    compact payload with the `shards`-segment layout.

    One executable, one dispatch, one fetch — the multi-chip path keeps the
    single-round-trip property of the single-chip path (SURVEY.md §2.7:
    "sharded across TPU devices over ICI when the problem exceeds one chip")."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from karpenter_tpu.ops.pack_kernel import compact_plan_sharded
    from karpenter_tpu.parallel.mesh import GROUPS_AXIS, TYPES_AXIS, make_mesh

    mesh = mesh or make_mesh()
    key = tuple(d.id for d in mesh.devices.flat)
    cached = _SHARDED_KERNEL_CACHE.get(key)
    if cached is not None:
        return cached
    gt_sharding = NamedSharding(mesh, P(GROUPS_AXIS, TYPES_AXIS))
    replicated = NamedSharding(mesh, P())
    shards = int(mesh.devices.size)

    def constrain(x):
        return jax.lax.with_sharding_constraint(x, gt_sharding)

    # Eager leaves + dense spill replicate so every process of a multi-host
    # slice can fetch them without touching non-addressable shards
    # (parallel/spmd.py); the deferred [G*T] LP assignment STAYS SHARDED on
    # a single-host mesh — it is fetched rarely (only when the realization
    # pass runs), and replicating it would all-gather the one bulk tensor
    # the mesh exists to split. Multi-host keeps it replicated: rank 0 must
    # be able to fetch the whole array from addressable shards.
    lp_sharding = (
        replicated
        if jax.process_count() > 1
        else NamedSharding(mesh, P((GROUPS_AXIS, TYPES_AXIS)))
    )
    kernel = functools.partial(
        jax.jit(
            _cost_fused_body,
            static_argnames=("lp_steps", "constrain", "compact"),
            out_shardings=(replicated, replicated, replicated, lp_sharding),
        ),
        constrain=constrain,
        compact=functools.partial(compact_plan_sharded, mesh=mesh),
    )
    groups_size, types_size = mesh.devices.shape
    del groups_size  # g_mult is the total device count, not the groups axis
    cached = (kernel, (shards, int(types_size)), shards)
    _SHARDED_KERNEL_CACHE[key] = cached
    return cached


def sharded_solve_active() -> bool:
    """True iff solve_mesh() would return a mesh — THE sharded-solve
    predicate, mesh-free so gates can call it per solve. Shared by
    solve_mesh and host_solve_enabled so the dispatch gate can never drift
    from the actual mesh policy. A chip quarantined by BackendHealth
    (report_chip_wedged / quarantine_mesh) shrinks the usable set but the
    dispatch STAYS on the mesh machinery even at one survivor: a 1-device
    mesh pins the kernel to the healthy chip, whereas the plain
    single-device path would run on jax's default device — which may be
    the wedged chip itself. Only a fully dead device set leaves the mesh
    (and falls to the whole-device DEGRADED verdict's CPU pin)."""
    import os

    if os.environ.get("KARPENTER_SHARDED_SOLVE", "").lower() in (
        "0",
        "false",
        "off",
    ):
        return False
    if not _multi_device():
        return False
    from karpenter_tpu.utils import backend_health

    if not backend_health.mesh_degraded():
        return True
    return _device_count() - len(backend_health.wedged_chips()) >= 1


def solve_mesh():
    """The production mesh policy: shard the fused solve when the runtime
    has more than one accelerator (KARPENTER_SHARDED_SOLVE=0 forces the
    single-device path). Wedged chips are excluded by make_mesh, so a
    quarantined chip shrinks the mesh and the next dispatch re-lowers on
    the survivors — down to a 1-device mesh pinned to the last healthy
    chip (see sharded_solve_active). Returns a Mesh or None."""
    if not sharded_solve_active():
        return None
    from karpenter_tpu.parallel.mesh import make_mesh

    return make_mesh()


def constrained_level_hook(mesh=None):
    """(constrain, shards) for the constrained [L, G, T] dispatch
    (constraints/solve._dispatch_kernel): under the same mesh policy as the
    fused solve, the relaxation-level axis shards across every device
    (parallel/sharded_solver.constrained_level_sharding); on a single
    device the hook is None and the dispatch is the plain jit. Kept here so
    the constrained path can never disagree with solve_mesh about when the
    mesh is live (wedged-chip shrink included)."""
    if mesh is None:
        mesh = solve_mesh()
    if mesh is None:
        return None, 1
    from karpenter_tpu.parallel.sharded_solver import constrained_level_sharding

    return constrained_level_sharding(mesh)


_MULTI_DEVICE: Optional[bool] = None
_DEVICE_COUNT: Optional[int] = None


def _device_count() -> int:
    """Cached jax.device_count() — the device topology is fixed for the
    process lifetime, and probing it per solve would pay (on first call) a
    backend initialization inside the very gate whose host path exists to
    avoid touching the device. (Chip HEALTH is not cached here — wedged
    chips come from BackendHealth per call.)"""
    global _DEVICE_COUNT
    if _DEVICE_COUNT is None:
        _DEVICE_COUNT = jax.device_count()
    return _DEVICE_COUNT


def _multi_device() -> bool:
    global _MULTI_DEVICE
    if _MULTI_DEVICE is None:
        _MULTI_DEVICE = _device_count() > 1
    return _MULTI_DEVICE


def pad_kernel_args(vectors, counts, capacity, total, prices, g_mult=1, t_mult=1):
    """Bucket-pad the six dense kernel inputs — THE padding/valid-mask
    convention, shared by every dispatch site (in-process ffd/cost paths and
    the sidecar) so they can't drift apart. g_mult/t_mult round the buckets up
    to mesh-divisible sizes on the sharded path (power-of-two buckets already
    divide power-of-two mesh factors; the lcm covers odd device counts)."""
    g_pad = _pad_multiple(bucket_size(int(vectors.shape[0])), g_mult)
    t_pad = _pad_multiple(bucket_size(int(capacity.shape[0])), t_mult)
    return (
        pad_to(vectors, g_pad),
        pad_to(counts.astype(np.int32), g_pad),
        pad_to(capacity, t_pad),
        pad_to(total, t_pad),
        pad_to(np.ones(int(capacity.shape[0]), bool), t_pad),
        pad_to(prices, t_pad),
    )


def _pad_multiple(n: int, multiple: int) -> int:
    if multiple <= 1:
        return n
    return ((n + multiple - 1) // multiple) * multiple


def run_kernel_dense(vectors, counts, capacity, total, prices, mode: str, quirk: bool):
    return pack_kernel(
        *pad_kernel_args(vectors, counts, capacity, total, prices),
        quirk=quirk,
        mode=mode,
    )


def _run_kernel(groups: PodGroups, fleet: InstanceFleet, mode: str, quirk: bool):
    return run_kernel_dense(
        groups.vectors,
        groups.counts,
        fleet.capacity,
        fleet.total,
        fleet.prices,
        mode,
        quirk,
    )


# Row budget for one launch request: the reference offers MAX_INSTANCE_TYPES
# types, each crossed with ~3 zone subnets (instance.go:173-207) — we spend
# the same number of override rows on individually price-ranked pools.
MAX_POOL_ROWS = 3 * ffd.MAX_INSTANCE_TYPES
# Pools priced within this band of the cheapest feasible pool are offered;
# spot's capacity-optimized allocation picks freely among OFFERED rows, so the
# band bounds realized price for the in-band prefix.
POOL_PRICE_BAND = 0.05
# Never offer fewer than this many pools (when they exist): a single-pool
# request is one ICE away from failure (ref: the 45s blackout machinery,
# aws/instancetypes.go:174-183, exists because pools do run dry). Rows forced
# beyond the band for this floor are still price-capped (below) — past that,
# ICE-retry through the blackout cache beats overpaying.
MIN_POOL_ROWS = 4
# Hard ceiling on any offered row relative to the cheapest feasible pool:
# capacity-optimized allocation may land on ANY offered row, so every row is
# a price we are willing to pay. The ceiling OVERRIDES the MIN_POOL_ROWS
# floor — when the 2nd-cheapest feasible pool already exceeds it, we offer a
# single row and rely on the ICE blackout/retry machinery rather than
# overpay. 1.15 empirically dominates 1.3 across the bench's
# market-sensitivity grid (every cell mean improves, worst-seed realized
# ratio drops ~6pts).
MAX_POOL_PRICE_RATIO = 1.15


def _pool_zones(fleet: InstanceFleet) -> List[str]:
    """The zone axis of the fleet's pool matrix (stable order)."""
    return fleet.allowed_zones or sorted(
        {z for it in fleet.instance_types for z in it.zones()}
    )


def _pool_price_matrix(fleet: InstanceFleet) -> Tuple[List[str], np.ndarray]:
    """[T, Z] price of each type's pool per zone at the fleet's capacity type
    (inf where not offered), computed once per solve so per-round option
    ranking is pure vectorized numpy. Spot matrices carry the interruption-
    forecast penalty per POOL (price += price * risk * weight), so pinned
    launch rows rank away from pools trending toward interruption — the
    [T, Z] analogue of build_fleet's [T] penalty column."""
    zones = _pool_zones(fleet)
    matrix = np.full((fleet.num_types, len(zones)), np.inf, dtype=np.float64)
    zone_index = {zone: j for j, zone in enumerate(zones)}
    for ti, instance_type in enumerate(fleet.instance_types):
        for offering in instance_type.offerings:
            if offering.capacity_type != fleet.capacity_type:
                continue
            j = zone_index.get(offering.zone)
            if j is not None:
                matrix[ti, j] = min(matrix[ti, j], offering.price)
    if fleet.capacity_type == wellknown.CAPACITY_TYPE_SPOT:
        from karpenter_tpu.market.pricebook import active_book

        book = active_book()
        if book is not None and book.has_risk():
            from karpenter_tpu.market.forecast import (
                RISK_PRICE_WEIGHT,
                risk_matrix,
            )

            risks = risk_matrix(
                [it.name for it in fleet.instance_types], zones, book
            )
            # Multiplicative form so inf (unoffered) rows stay inf — the
            # additive prices + prices*risk*w form would produce inf*0=nan.
            matrix = matrix * (1.0 + risks * RISK_PRICE_WEIGHT)
    return zones, matrix


# A dense pool row: (type index, zone index, price) — the object-free form
# the sidecar streams back; priority is the row's position in the list.
PoolRow = Tuple[int, int, float]


def sort_pool_rows(pool_prices: np.ndarray):
    """Global price order of all (type, zone) pool rows — identical for every
    fill, so the O(TZ log TZ) sort is hoisted out of the per-fill option
    ranking: (row type, row zone, row price) each [N], price-ascending,
    non-offered (inf) rows dropped."""
    flat = pool_prices.ravel()
    finite = np.isfinite(flat)
    order = np.argsort(flat, kind="stable")
    order = order[finite[order]]
    num_zones = pool_prices.shape[1]
    return order // num_zones, order % num_zones, flat[order]


def _cheapest_feasible_pools(
    fill: np.ndarray,
    t: int,
    vectors: np.ndarray,
    capacity: np.ndarray,
    pool_prices: np.ndarray,
    pool_order=None,
) -> Tuple[List[int], Optional[List[PoolRow]]]:
    """Price-ranked launch options for a node with this fill (dense core).

    The reference offers the ascending-size window [t, t+20) as launch
    options (packer.go:178-180) with priority = window index — price-blind
    both across and within types. Any type whose usable capacity holds the
    node's demand can host it, so we instead rank individual (type, zone)
    pools by price at the fleet's capacity type, offer the cheapest rows
    within POOL_PRICE_BAND (at least MIN_POOL_ROWS, at most MAX_POOL_ROWS,
    distinct types capped at MAX_INSTANCE_TYPES to match the reference's
    request budget), and let the allocation strategy choose among
    near-cheapest pools only. Returns (type indices, pool rows)."""
    demand = (fill.astype(np.float64)[:, None] * vectors).sum(axis=0)
    feasible_mask = (capacity >= demand - 1e-6).all(axis=1)
    if pool_order is None:
        pool_order = sort_pool_rows(pool_prices)
    all_types, all_zones, all_prices = pool_order
    # The global price order restricted to feasible types keeps its sort.
    keep = feasible_mask[all_types]
    if not keep.any():
        # Degenerate: fall back to the feasibility anchor's type options.
        return [t], None
    row_types = all_types[keep]
    row_zones = all_zones[keep]
    prices_sorted = all_prices[keep]

    # Vectorized form of the sequential selection walk: rows of a type past
    # the MAX_INSTANCE_TYPES-th distinct one are skipped (not appended, not
    # counted); the walk stops at the first row where the appended-so-far
    # count hits the row budget, exits the price band past MIN_POOL_ROWS, or
    # exceeds the ceiling with anything appended.
    uniques, first_idx, inverse = np.unique(
        row_types, return_index=True, return_inverse=True
    )
    type_rank = np.argsort(np.argsort(first_idx))  # first-occurrence order
    admissible = type_rank[inverse] < ffd.MAX_INSTANCE_TYPES
    count_excl = np.concatenate(([0], np.cumsum(admissible)[:-1]))
    cheapest = prices_sorted[0]
    cutoff = cheapest * (1.0 + POOL_PRICE_BAND)
    ceiling = cheapest * MAX_POOL_PRICE_RATIO
    stop_mask = (
        (count_excl >= MAX_POOL_ROWS)
        | ((prices_sorted > cutoff) & (count_excl >= MIN_POOL_ROWS))
        | ((prices_sorted > ceiling) & (count_excl >= 1))
    )
    stops = np.nonzero(stop_mask)[0]
    stop = int(stops[0]) if stops.size else len(prices_sorted)
    selected = np.nonzero(admissible[:stop])[0]

    pool_rows: List[PoolRow] = [
        (int(row_types[i]), int(row_zones[i]), float(prices_sorted[i]))
        for i in selected
    ]
    sel_types = row_types[selected]
    _, sel_first = np.unique(sel_types, return_index=True)
    chosen_types = [int(sel_types[i]) for i in np.sort(sel_first)]
    return chosen_types, pool_rows


def _cheapest_feasible_options(
    fill: np.ndarray,
    t: int,
    groups: PodGroups,
    fleet: InstanceFleet,
    zones: Optional[List[str]] = None,
    pool_prices: Optional[np.ndarray] = None,
) -> Tuple[List[int], Optional[List[ffd.PoolOption]]]:
    """Object-level shell over _cheapest_feasible_pools."""
    if zones is None or pool_prices is None:
        zones, pool_prices = _pool_price_matrix(fleet)
    type_indices, rows = _cheapest_feasible_pools(
        fill, t, groups.vectors, fleet.capacity, pool_prices
    )
    return type_indices, pool_rows_to_options(rows, fleet, zones)


def pool_rows_to_options(
    rows: Optional[List[PoolRow]], fleet: InstanceFleet, zones: List[str]
) -> Optional[List[ffd.PoolOption]]:
    """Rehydrate dense pool rows into PoolOption objects on the fleet-holding
    side of the solver boundary."""
    if rows is None:
        return None
    return [
        ffd.PoolOption(
            instance_type=fleet.instance_types[ti],
            zone=zones[zi],
            price=price,
            priority=i,
        )
        for i, (ti, zi, price) in enumerate(rows)
    ]


def _decode_rounds(
    round_list: List[Tuple[int, np.ndarray, int]],
    unschedulable_counts: np.ndarray,
    groups: PodGroups,
    fleet: InstanceFleet,
    options_fn=None,
) -> ffd.PackResult:
    """Turn (type, fill, replication) rounds into Packing objects, merging by
    instance-option tuple (ref: packer.go:126-135 hashes options only).

    options_fn(t, fill) -> [type index] overrides the reference's
    ascending-size option window (the CostSolver passes its memoized
    cheapest-feasible selector).

    Per-node pod lists are LazyNodePods: decode records integer member
    windows only; the ~50k-ref Python materialization happens when the bind
    path iterates nodes, off the solve boundary's critical path."""
    cursors = [0] * groups.num_groups
    by_options = {}
    packings: List[ffd.Packing] = []
    for t, fill, repl in round_list:
        pool_opts = None
        if options_fn is not None:
            type_indices, pool_opts = options_fn(t, fill)
            options = [fleet.instance_types[i] for i in type_indices]
        else:
            options = fleet.instance_types[t : t + ffd.MAX_INSTANCE_TYPES]
        repl = int(repl)
        slices = []
        for g in np.nonzero(fill > 0)[0]:
            g, n = int(g), int(fill[g])
            slices.append((g, cursors[g], n))
            cursors[g] += n * repl
        key = (
            tuple(it.name for it in options),
            tuple((p.instance_type.name, p.zone) for p in pool_opts)
            if pool_opts
            else None,
        )
        existing = by_options.get(key)
        if existing is not None:
            existing.node_quantity += repl
            existing.pods_per_node.add_segment(repl, slices)
        else:
            lazy = ffd.LazyNodePods(groups.members)
            lazy.add_segment(repl, slices)
            packing = ffd.Packing(
                pods_per_node=lazy,
                instance_type_options=list(options),
                node_quantity=repl,
                pool_options=pool_opts,
            )
            by_options[key] = packing
            packings.append(packing)

    unschedulable: List[PodSpec] = []
    for g in np.nonzero(unschedulable_counts > 0)[0]:
        n = int(unschedulable_counts[g])
        unschedulable.extend(groups.members[g][cursors[g] : cursors[g] + n])
        cursors[g] += n
    return ffd.PackResult(packings=packings, unschedulable=unschedulable)


def _to_host(tree):
    """Device->host via jax.device_get, ONE call per kernel invocation.

    Every device_get is a full round trip to the accelerator (tens of ms over
    a tunneled device), and np.asarray on a jax Array is worse still (a slow
    element-protocol path). So kernel outputs are fetched as a single pytree
    transfer and everything downstream is plain numpy."""
    return jax.device_get(tree)


def _start_fetch(tree) -> None:
    """Begin the device->host copies of a dispatched kernel's outputs
    without blocking: the transfers queue behind the computation on the
    device stream and run while the host does overlap work (the pool matrix
    build + the entire mix-candidate pipeline), so the later _to_host finds
    the data already staged instead of starting the round trip then."""
    for leaf in jax.tree_util.tree_leaves(tree):
        copy_async = getattr(leaf, "copy_to_host_async", None)
        if copy_async is not None:
            try:
                copy_async()
            except Exception:  # pragma: no cover — backend-specific support
                return


# fetch_bytes — THE payload byte accounting — is re-exported from
# ops/pack_kernel (top-of-module import), where it lives next to the
# compact layout shape math, shared with consolidate's eager fetch.


def _kernel_rounds_to_list(host_rounds: "PackRounds", num_groups: int):
    # Defense against round-budget overflow (the kernel clamps the count,
    # but pre-packing tuple callers may hand over raw state): never read
    # past the static round buffer.
    num_rounds = min(
        int(host_rounds.num_rounds), int(host_rounds.round_type.shape[0])
    )
    return [
        (
            int(host_rounds.round_type[r]),
            host_rounds.round_fill[r, :num_groups],
            int(host_rounds.round_repl[r]),
        )
        for r in range(num_rounds)
    ]


class TPUSolver(Solver):
    """Batched solve on accelerator via ops.pack_kernel.

    mode="ffd" reproduces the reference packing (quirk=True bit-for-bit);
    mode="cost" picks price-efficient types each round. Shapes are bucketed to
    powers of two so repeat solves hit the jit cache.
    """

    needs_device_warmup = True

    def __init__(self, mode: str = "ffd", quirk: bool = False):
        self.mode = mode
        self.quirk = quirk

    def solve_encoded(self, groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        if fleet.num_types == 0 or groups.num_groups == 0:
            return ffd.pack_groups(fleet, groups)
        rounds = _to_host(_run_kernel(groups, fleet, self.mode, self.quirk))
        if bool(rounds.overflow):
            # Defensive: static round budget exhausted — fall back to host FFD
            # rather than return a partial packing.
            return ffd.pack_groups(fleet, groups)
        return _decode_rounds(
            _kernel_rounds_to_list(rounds, groups.num_groups),
            rounds.unschedulable[: groups.num_groups],
            groups,
            fleet,
        )


@dataclass
class DenseSolveResult:
    """Object-free cost-solve output — what crosses the solver boundary.

    rounds: (type index, fill[G], replication) per launch round;
    unschedulable: [G] pods per group that fit nowhere;
    options: fill-bytes -> (type indices, pool rows) launch options for each
    distinct fill appearing in rounds."""

    rounds: List[Tuple[int, np.ndarray, int]]
    unschedulable: np.ndarray
    options: Dict[bytes, Tuple[List[int], Optional[List[PoolRow]]]]


# Skip the host-side LP realization only when a kernel candidate beats the
# LP's fractional objective by this much. The two sides are priced in
# different models (round_price: mean offered pool row over all FEASIBLE
# types; lp_objective: min list price over capacity-DOMINATING types — a
# subset, so realized LP nodes can launch cheaper than the objective
# suggests); the slack absorbs that gap instead of letting a nominally
# dominated LP plan be skipped when it could still have won.
LP_REALIZE_SLACK = 0.8

# Per-priority-rank weight decay for the expected realized node price: row
# i of a fill's price-ranked pool options carries weight PRIORITY_DECAY**i
# (normalized). Models capacity-optimized-prioritized allocation honoring
# priority order with slack-bounded deviations (see round_price).
PRIORITY_DECAY = 0.5


def device_pod_args(groups: PodGroups):
    """The pod-side kernel tensors for a schedule: the encoded-state device
    arrays when the groups carry them (DeviceClusterState handles — already
    sorted + bucket-padded, and dispatched through the NON-donating kernel),
    None otherwise (caller uses the host numpy tensors)."""
    device_vectors = getattr(groups, "device_vectors", None)
    device_counts = getattr(groups, "device_counts", None)
    if device_vectors is None or device_counts is None:
        return None
    return device_vectors, device_counts


def cost_solve_dense(
    vectors: np.ndarray,
    counts: np.ndarray,
    capacity: np.ndarray,
    total: np.ndarray,
    prices: np.ndarray,
    pool_prices,
    lp_steps: int = 300,
    explain: Optional[dict] = None,
    device_pods=None,
) -> Optional[DenseSolveResult]:
    """The flagship solve on dense tensors only — shared by the in-process
    CostSolver and the gRPC sidecar (which has no PodSpec/InstanceType
    objects, just arrays off the wire). Returns None when no candidate packing
    exists (caller falls back to host greedy).

    Runs pure-greedy FFD, cost-greedy, and the LP-relaxation plan as ONE fused
    accelerator computation, scores each candidate by expected realized $/hr,
    and returns the winner's rounds + per-fill launch options.

    pool_prices may be the [T, Z] array itself or a zero-arg callable
    producing it: kernel dispatch is async, so a callable is evaluated while
    the device computes (the in-process path hides the pure-Python matrix
    build behind the kernel; the sidecar already has the array off the
    wire)."""
    num_groups = int(vectors.shape[0])
    num_types = int(capacity.shape[0])

    # Adaptive dispatch: below the device break-even (HOST_SOLVE_MAX_PODS —
    # one fetch costs a full, often-tunneled device round trip) the host
    # candidates answer in milliseconds and carry the cost win; the device
    # path owns scale, where its throughput and mesh sharding pay for the
    # trip. Falls through when the native library is unavailable.
    if host_solve_enabled(
        int(np.asarray(counts).sum())  # vet: host-array(dense inputs arrive as numpy)
    ):
        if callable(pool_prices):
            pool_prices = pool_prices()
        dense = cost_solve_host(
            vectors, counts, capacity, total, prices, pool_prices,
            explain=explain,
        )
        if dense is not None:
            return dense

    # device_profile is a no-op unless KARPENTER_JAX_PROFILE_DIR is set, in
    # which case each solve captures a jax.profiler device trace whose XLA
    # ops line up with the host spans via TraceAnnotation.
    with device_profile(TRACER), TRACER.span(
        "solve.device", groups=num_groups, types=num_types
    ):
        # Encoded-state solves hand the kernel the device-resident pod
        # tensors (skipping the host->device transfer AND donation); the
        # host numpy mirrors keep serving the gate above and the scoring
        # pass below — the two views are bit-identical by construction.
        pod_vectors, pod_counts = device_pods or (vectors, counts)
        fused = cost_solve_dispatch(
            pod_vectors, pod_counts, capacity, total, prices, lp_steps
        )
        # Overlap with the device AND the fetch: dispatch is async and the
        # blocking device_get releases the GIL while it waits on the (often
        # tunneled) transfer, so the pool matrix build and the entire
        # column-LP mix candidate (enumeration, pricing, covering LP,
        # integerization) run in a worker thread CONCURRENTLY with the
        # fetch — they add nothing to the solve's latency.
        plan_start_fetch(fused)
        overlap = _HostOverlap([(vectors, counts, capacity, pool_prices)])
        overlap.start()
        fetched = fetch_plan(fused)
        (pool_prices,), (mix_plan,) = overlap.join()

    return cost_solve_finish(
        fetched, vectors, counts, capacity, total, prices, pool_prices,
        mix_plan=mix_plan, explain=explain,
    )


class _HostOverlap:
    """THE fetch-overlap worker, shared by the single solve, the batched
    solve, and the sidecar's SolveStream: for each item
    (vectors, counts, capacity, pool_prices-or-thunk), evaluate the
    pool-price matrix then the mix candidate, in a thread that runs
    concurrently with the blocking device fetch (device_get releases the
    GIL while it waits on the transfer). Mix candidates are best-effort (an
    internal error degrades that item to no-mix); a pool-matrix failure
    re-raises on join, since the finish path cannot proceed without it.

    Items complete IN ORDER and each completion sets a per-item event, so
    the pipelined consumers (solve_encoded_pipelined, the sidecar's
    SolveStream) can wait(k) for just their item instead of joining the
    whole batch — the hand-off that lets schedule k's decode start while
    later schedules' host work is still running."""

    def __init__(self, items: Sequence[Tuple]):
        self._items = list(items)
        self.pool_prices: List = [None] * len(self._items)
        self.mix_plans: List = [None] * len(self._items)
        self._error: Optional[BaseException] = None
        self._error_index = len(self._items)
        self._done = [threading.Event() for _ in self._items]
        self._thread = threading.Thread(
            target=self._run, name="solve-host-overlap", daemon=True
        )

    def start(self) -> "_HostOverlap":
        self._thread.start()
        return self

    def _run(self):
        for index, (vectors, counts, capacity, pool_prices) in enumerate(
            self._items
        ):
            try:
                if callable(pool_prices):
                    pool_prices = pool_prices()
                self.pool_prices[index] = pool_prices
            except BaseException as error:  # noqa: BLE001 — re-raised on join
                self._error = error
                self._error_index = index
                for event in self._done[index:]:
                    event.set()
                return
            try:
                self.mix_plans[index] = compute_mix_candidate(
                    vectors, counts, capacity, pool_prices
                )
            except Exception:  # noqa: BLE001 — optional candidate, not fatal
                klog.named("solver").warning(
                    "mix candidate failed; solving without it", exc_info=True
                )
            self._done[index].set()

    def wait(self, index: int) -> None:
        """Block until item `index` is finished; re-raise the pool-matrix
        error iff it poisoned this item (items before the failure stay
        usable — their slots were already filled in order)."""
        self._done[index].wait()
        if self._error is not None and index >= self._error_index:
            raise self._error

    def join(self) -> Tuple[List, List]:
        self._thread.join()
        if self._error is not None:
            raise self._error
        return self.pool_prices, self.mix_plans


def compute_mix_candidate(
    vectors: np.ndarray,
    counts: np.ndarray,
    capacity: np.ndarray,
    pool_prices: np.ndarray,
    allow_single_group: bool = False,
) -> Optional[Tuple[List[Tuple[int, np.ndarray, int]], np.ndarray]]:
    """The column-LP candidate (ops/mix_pack.py) as (rounds, unschedulable),
    or None when no covering plan exists. Pure host work — callers run it
    while the fused kernel computes on the device (or as the whole cost
    engine on the cost_solve_host path, which sets allow_single_group)."""
    counts = counts.astype(np.int64)
    if int(vectors.shape[0]) < 2 and not allow_single_group:
        # On the DEVICE path a single request shape gains little from the
        # covering LP (the kernel's greedy candidates enumerate every
        # single-group fill) and the batched path (many small schedules
        # sharing one fetch) cannot afford per-schedule LP overhead
        # outlasting the fetch window. The host path has no fetch to hide
        # behind — there the LP's per-type max-fill columns pick the
        # cheapest per-pod type mix and DO improve on plain FFD.
        return None
    from karpenter_tpu.ops import native

    if (
        not native.available()
        and int(vectors.shape[0])
        * min(int(capacity.shape[0]), mix_pack.TYPES_BUDGET)
        > 256
    ):
        # Without the native enumeration the numpy fallback is ~15x slower
        # and would outlast the fetch window at scale, turning a free
        # candidate into a per-solve latency regression. Small problems
        # still get it (and the fallback stays covered by tests).
        return None
    pool_floor = np.where(
        np.isfinite(pool_prices), pool_prices, np.inf
    ).min(axis=1)
    feasible = (
        (capacity[None, :, :] >= vectors[:, None, :] - 1e-6).all(axis=2).any(axis=1)
    )
    solvable = np.where(feasible, counts, 0)
    unschedulable = counts - solvable
    if solvable.sum() == 0:
        return None
    rounds = mix_pack.mix_candidate(vectors, solvable, capacity, pool_floor)
    if rounds is None:
        return None
    return rounds, unschedulable


# Below this many pods a solve goes host-only: the device fetch costs a
# full (often tunneled) round trip — ~70ms on the bench rig for the OLD
# dense payload; the compacted payload (ops/pack_kernel.compact_plan) is a
# few KB and latency-bound, so on a recalibrated rig the probed floor is
# what a compacted fetch actually costs, not the dense 38KB one — while
# the host candidates (compiled FFD + the column-LP mix) answer faster
# with identical plans. Measured break-even on the bench rig (dense-era):
# 10k pods × 200 types host-solves in ~49ms vs ~94ms on device; at
# 50k × 400 the device wins and additionally scales via mesh sharding.
# 10k is the last measured point where host wins — it is also the CAP on
# boot calibration below: past it the host's own superlinear growth
# (types × pods FFD walk) is unvalidated territory regardless of how slow
# the fetch is. Boot calibration (calibrate_break_even) probes the
# COMPACTED fetch size and will derive a far lower break-even wherever
# the floor shrank — this constant is only the never-calibrated default.
HOST_SOLVE_MAX_PODS = 10_000
# The BATCHED paths (solve_encoded_many, the sidecar's SolveStream) share
# ONE device fetch across K schedules, so the per-schedule device cost is
# fetch/K + compute — far below the single-solve break-even. Host-solving
# there must clear a much lower bar (and it runs serially on the intake
# thread): only schedules whose host solve is a few ms qualify.
HOST_SOLVE_MAX_PODS_BATCHED = 2_000

# Device compute for the fused kernel once the fetch is paid: ~20-25ms and
# roughly flat across the ladder (the round loop, not the payload,
# dominates) — measured on the bench rig at 10k×200 (94ms total − 70ms
# floor) and 50k×400 (93ms − 70ms). Used by break-even calibration as the
# device-side cost the host must beat on top of the fetch floor. The
# compaction post-pass adds negligible compute (a prefix-sum + scatter over
# [MR×G] cells), so this estimate holds for the compacted pipeline; boot
# warmup measures the real value on the live backend anyway and only falls
# back to this constant when it can't.
DEVICE_COMPUTE_EST_MS = 22.0


@dataclass
class BreakEven:
    """Boot-measured host/device dispatch calibration (VERDICT r4 weak #4:
    the 10k constant encodes the bench rig's ~70ms tunnel floor; co-located
    TPUs have sub-ms floors and a far lower break-even)."""

    fetch_floor_ms: float
    host_ms_per_pod: float
    max_pods: int
    max_pods_batched: int


_break_even: Optional[BreakEven] = None
_break_even_lock = threading.Lock()


def _probe_fetch_floor_ms(reps: int = 3) -> float:
    """One device->host round trip with a COMPACT-sized payload — the same
    fetch path _to_host uses, sized to what a compacted plan fetch actually
    transfers at the headline group bucket (compact_words(16) int32s, a few
    KB) rather than a toy 8-int probe, so the break-even the calibration
    derives prices the real payload. min-of-reps: the floor, not the
    noise. bench.py publishes the identical probe as
    device_fetch_floor_ms."""
    import time as _time

    from karpenter_tpu.ops.pack_kernel import compact_words

    probe = jnp.zeros((compact_words(16),), jnp.int32) + 1
    jax.block_until_ready(probe)
    samples = []
    for _ in range(reps):
        start = _time.perf_counter()
        _to_host(probe)
        samples.append((_time.perf_counter() - start) * 1e3)
    return min(samples)


def _probe_host_rate_ms_per_pod(num_pods: int = 2_000, num_types: int = 64) -> float:
    """Measure the compiled host solve on a synthetic mid-ladder shape and
    return ms per pod. Returns inf when the native library is unavailable
    (host path can't run at all)."""
    import time as _time

    from karpenter_tpu.ops import native as native_mod

    if not native_mod.available():
        return float("inf")
    from karpenter_tpu.models.warmup import make_synthetic_problem

    vectors, counts, capacity = make_synthetic_problem(
        64, num_types, pods_per_group=num_pods // 64
    )
    counts = counts.astype(np.int64)
    start = _time.perf_counter()
    native_mod.ffd_pack_rounds(
        vectors, counts, capacity, capacity.copy(), quirk=False
    )
    elapsed_ms = (_time.perf_counter() - start) * 1e3
    return elapsed_ms / float(counts.sum())


def calibrate_break_even(
    fetch_floor_ms: Optional[float] = None,
    host_ms_per_pod: Optional[float] = None,
    device_compute_ms: Optional[float] = None,
) -> BreakEven:
    """Derive the host/device break-even from measured quantities instead
    of the baked-in rig constant. Host wins while
    host_ms_per_pod × n < fetch_floor + device_compute; the result is
    capped at HOST_SOLVE_MAX_PODS (the last point host-wins was ever
    validated) and floored at 0 (a sub-ms fetch floor routes everything
    but trivial solves to the device). Called from boot warmup
    (models/warmup.py), which also MEASURES device_compute_ms on the live
    backend (a warm mid-ladder solve minus the fetch floor) — the
    DEVICE_COMPUTE_EST_MS constant is only the fallback when no
    measurement is supplied. Explicit arguments override probes (unit
    tests stub timings this way); processes that never warm keep the
    measured-rig defaults.

    Both the calibration and the probes export through /metrics
    (karpenter_solver_break_even gauge family)."""
    global _break_even
    with _break_even_lock:
        floor = (
            _probe_fetch_floor_ms() if fetch_floor_ms is None else fetch_floor_ms
        )
        rate = (
            _probe_host_rate_ms_per_pod()
            if host_ms_per_pod is None
            else host_ms_per_pod
        )
        device_ms = (
            DEVICE_COMPUTE_EST_MS if device_compute_ms is None else device_compute_ms
        )
        if rate <= 0 or not np.isfinite(rate):
            max_pods = 0  # no host path at all
        else:
            max_pods = int((floor + device_ms) / rate)
        max_pods = min(max_pods, HOST_SOLVE_MAX_PODS)
        # The batched bar scales with the single-solve one (today's 2k is
        # 1/5 of 10k): those paths amortize one fetch over the whole batch.
        max_batched = min(max_pods // 5, HOST_SOLVE_MAX_PODS_BATCHED)
        _break_even = BreakEven(
            fetch_floor_ms=floor,
            host_ms_per_pod=rate,
            max_pods=max_pods,
            max_pods_batched=max_batched,
        )
        BREAK_EVEN_GAUGE.set(floor, "fetch_floor_ms")
        BREAK_EVEN_GAUGE.set(rate, "host_ms_per_pod")
        BREAK_EVEN_GAUGE.set(device_ms, "device_compute_ms")
        BREAK_EVEN_GAUGE.set(max_pods, "host_max_pods")
        BREAK_EVEN_GAUGE.set(max_batched, "host_max_pods_batched")
        return _break_even


def break_even() -> Optional[BreakEven]:
    return _break_even


def reset_break_even() -> None:
    """Test hook: return the gate to the uncalibrated defaults."""
    global _break_even
    with _break_even_lock:
        _break_even = None


def cost_solve_host(
    vectors: np.ndarray,
    counts: np.ndarray,
    capacity: np.ndarray,
    total: np.ndarray,
    prices: np.ndarray,
    pool_prices: np.ndarray,
    explain: Optional[dict] = None,
) -> Optional[DenseSolveResult]:
    """Host-only cost solve for problems under HOST_SOLVE_MAX_PODS: the
    compiled-C++ greedy FFD (reference-parity guarantee — greedy is always
    among the candidates) plus the column-LP mix, scored identically to the
    device path's candidates. Returns None when the native library is
    unavailable — callers fall through to the device path."""
    from karpenter_tpu.ops import native as native_mod

    ffd_result = native_mod.ffd_pack_rounds(
        vectors, counts.astype(np.int64), capacity, total, quirk=False
    )
    if ffd_result is None:
        return None
    SOLVE_DISPATCH_TOTAL.inc("host")
    mix_plan = compute_mix_candidate(
        vectors, counts, capacity, pool_prices, allow_single_group=True
    )
    return cost_solve_finish(
        None,
        vectors,
        counts,
        capacity,
        total,
        prices,
        pool_prices,
        mix_plan=mix_plan,
        host_candidates=[ffd_result],
        explain=explain,
    )


# While a deployment's boot warmup is compiling the bucket ladder, solves
# prefer the host path — identical plans at steady-state host latency
# instead of multi-second cold-compile stalls (the in-process Manager
# analogue of the sidecar's "warming" health state, where clients
# host-solve until grpc.health.v1 reports ok). Refcounted, not boolean:
# overlapping warmups (a Manager embedding CostSolver plus an in-process
# sidecar) must not have the first finisher cancel the second's window.
_WARMING_HOST_PREFERENCE = threading.Event()
_warming_refs = 0
_warming_lock = threading.Lock()

# The warming preference covers solves up to the largest host measurement
# on record — the stretch baselines run the compiled host packer at
# 100k×400 in ~245ms and 200k×800 in ~872ms (BASELINE.md), both far under
# a multi-second cold compile. Past that the host path is genuinely
# unmeasured, so warming solves fall through to the device and pay the
# compile rather than gamble.
HOST_WARMING_MAX_PODS = 200_000


def set_warming_host_preference(active: bool) -> None:
    global _warming_refs
    with _warming_lock:
        _warming_refs += 1 if active else -1
        _warming_refs = max(_warming_refs, 0)
        if _warming_refs > 0:
            _WARMING_HOST_PREFERENCE.set()
        else:
            _WARMING_HOST_PREFERENCE.clear()


def host_solve_enabled(num_pods: int, batched: bool = False) -> bool:
    """Policy gate for the host path (KARPENTER_HOST_SOLVE=0 forces the
    device path, =1 forces host regardless of size). Requires the native
    library: without it cost_solve_host cannot run, and callers that gate
    on this — notably the sidecar's SolveStream intake — would de-batch
    small requests into serial device round trips for nothing. batched=True
    applies the batch threshold: those paths amortize one fetch across the
    whole batch, so the device bar per schedule is K times lower."""
    import os

    from karpenter_tpu.ops import native as native_mod
    from karpenter_tpu.utils import backend_health

    flag = os.environ.get("KARPENTER_HOST_SOLVE", "").lower()
    if flag in ("0", "false", "off"):
        return False
    if not native_mod.available():
        return False
    if flag in ("1", "true", "on"):
        return True
    if backend_health.degraded() and num_pods <= HOST_WARMING_MAX_PODS:
        # DEGRADED backend verdict: the "device" is the jax-CPU fallback,
        # which loses to the compiled packer at every measured size (the
        # r05 stretch solves silently ran 5-13% behind their own baseline).
        # Deliberately route to the native hybrid (compiled C++ FFD + the
        # dominance-priced candidate scoring of cost_solve_host) up to the
        # largest measured host solve; past 200k pods the host path is
        # unvalidated territory and solves fall through to jax-CPU.
        return True
    if _WARMING_HOST_PREFERENCE.is_set() and num_pods <= HOST_WARMING_MAX_PODS:
        # Boot warmup in flight: every device bucket is potentially cold,
        # including the sharded one — host answers at steady state now.
        return True
    if sharded_solve_active():
        # Multi-chip runtime: the operator provisioned a mesh precisely so
        # solves ride it (and the sharded path is what dryrun/parity checks
        # must exercise) — the host path is a single-chip latency trade.
        return False
    calibrated = _break_even
    if calibrated is not None:
        limit = calibrated.max_pods_batched if batched else calibrated.max_pods
    else:
        limit = HOST_SOLVE_MAX_PODS_BATCHED if batched else HOST_SOLVE_MAX_PODS
    return num_pods <= limit


def cost_solve_dispatch(
    vectors, counts, capacity, total, prices, lp_steps: int = 300,
    count: bool = True,
):
    """Dispatch the fused kernel asynchronously; pair with a (batchable)
    fetch + cost_solve_finish. Splitting dispatch from finish lets a batch of
    schedules share ONE device->host round trip (the dominant latency on
    tunneled accelerators) instead of paying it per solve.

    On a multi-chip runtime (solve_mesh() non-None) the SAME entry dispatches
    the mesh-sharded fused kernel — production callers (CostSolver, the gRPC
    sidecar) get the sharded path with no code of their own. count=False
    keeps non-solve dispatches (boot warmup, bench probes) out of the
    dispatch-path metric."""
    if count:
        SOLVE_DISPATCH_TOTAL.inc("device")
    # Probe the pallas dominance kernel EAGERLY before the fused kernel
    # traces — under the trace the probe can't run and the XLA formulation
    # would be baked in untested (ops/pallas_kernels.ensure_probed).
    pallas_kernels.ensure_probed()
    mesh = solve_mesh()
    if mesh is None:
        padded = pad_kernel_args(vectors, counts, capacity, total, prices)
        # Fleet-side args ride device-resident handles: back-to-back sweeps
        # over the same encoded fleet (repeat batches, provision ->
        # consolidate in one reconcile turn) skip the host->device transfer
        # of the [T, R] state entirely. Pod-side args (vectors, counts) stay
        # host arrays — they change every solve and the kernel DONATES them.
        from karpenter_tpu.ops.pack_kernel import device_resident

        padded = padded[:2] + tuple(device_resident(a) for a in padded[2:])
        if isinstance(vectors, np.ndarray):
            out = _cost_fused_kernel(*padded, lp_steps=lp_steps)
        else:
            # Pod tensors already on device (the incremental encode layer's
            # sorted gather): same math, NO donation — the handle stays
            # readable after the solve.
            out = _cost_fused_kernel_nodonate(*padded, lp_steps=lp_steps)
        shards = 1
    else:
        out, padded, shards = _dispatch_sharded(
            vectors, counts, capacity, total, prices, lp_steps, mesh
        )
    compact, objective, dense_ints, lp_flat = out
    return FusedHandle(
        compact=compact,
        objective=objective,
        dense=dense_ints,
        lp=lp_flat,
        num_groups=int(padded[0].shape[0]),
        num_types=int(padded[2].shape[0]),
        shards=shards,
    )


def _dispatch_sharded(vectors, counts, capacity, total, prices, lp_steps, mesh):
    """Dispatch the mesh-sharded fused kernel, surviving a wedged chip:
    a dispatch-time failure quarantines the mesh through BackendHealth
    (per-chip killable probes mark the non-responders wedged), re-lowers on
    the shrunk mesh, and retries ONCE — the multi-chip analogue of the
    DEGRADED CPU pin, except the solve stays on the surviving chips
    (docs/design/sharded-solve.md). With no wedged chip found, or nothing
    left to shrink to, the original error propagates."""

    def attempt(mesh):
        kernel, (g_mult, t_mult), shards = _sharded_fused_kernel(mesh)
        padded = pad_kernel_args(
            vectors, counts, capacity, total, prices, g_mult=g_mult, t_mult=t_mult
        )
        if jax.process_count() > 1:
            # Multi-host slice: every process must dispatch the same program
            # (SPMD) — replicate this solve to the followers first.
            from karpenter_tpu.parallel import spmd

            out = spmd.lead_dispatch(kernel, padded, lp_steps, mesh=mesh)
        else:
            out = kernel(*padded, lp_steps=lp_steps)
        return out, padded, shards

    try:
        return attempt(mesh)
    except Exception as error:  # noqa: BLE001 — classified below
        from karpenter_tpu.parallel import spmd
        from karpenter_tpu.utils import backend_health

        if isinstance(error, spmd.SpmdUnsupportedError):
            # A backend-capability error, not a dead chip: probing the mesh
            # would waste the quarantine budget and mislabel healthy chips.
            raise
        wedged = backend_health.quarantine_mesh(
            [int(d.id) for d in mesh.devices.flat], error
        )
        if not wedged:
            raise
        retry_mesh = solve_mesh()
        if retry_mesh is None or jax.process_count() > 1:
            # No healthy chip left (or a multi-host slice, where a
            # one-sided shrink would desynchronize the collective order):
            # surface the failure to the caller's fallback ladder.
            raise
        klog.named("solver").warning(
            "sharded dispatch failed (%s); retrying on shrunk %d-device mesh",
            error,
            retry_mesh.devices.size,
        )
        return attempt(retry_mesh)


def _collect_candidates(fetched, num_groups: int, host_candidates, mix_plan):
    """Assemble the candidate pool for scoring — kernel outputs (decoded
    from the compacted fetch), host candidates, and the mix plan — in round
    form, with a parallel label list for explain output. Returns
    (candidates, labels, lp_supplier, feasible_any, lp_objective):
    lp_supplier is a zero-arg callable producing the [G, T] LP assignment —
    for a FetchedPlan it defers the device fetch until the realization pass
    actually runs."""
    lp_supplier = feasible_any = None
    lp_objective = np.inf
    candidates: List[Tuple[List[Tuple[int, np.ndarray, int]], np.ndarray]] = []
    labels: List[str] = []
    if fetched is not None:
        if isinstance(fetched, FetchedPlan):
            rounds_ffd = fetched.rounds_ffd
            rounds_cost = fetched.rounds_cost
            feasible_any = fetched.feasible_any
            lp_objective = fetched.lp_objective
            lp_supplier = fetched.lp_assignment
        else:  # pre-packing tuple form (kept for direct kernel callers)
            rounds_ffd, rounds_cost, lp_assignment, feasible_any, lp_objective = (
                fetched
            )
            lp_supplier = (lambda a=lp_assignment: a) if lp_assignment is not None else None
        for label, rounds in (("kernel_ffd", rounds_ffd), ("kernel_cost", rounds_cost)):
            if not bool(rounds.overflow):
                candidates.append(
                    (
                        _kernel_rounds_to_list(rounds, num_groups),
                        rounds.unschedulable[:num_groups],
                    )
                )
                labels.append(label)
    for index, host_candidate in enumerate(host_candidates or []):
        candidates.append(host_candidate)
        labels.append("host_ffd" if index == 0 else f"host_{index}")
    if mix_plan is not None:
        candidates.append(mix_plan)
        labels.append("mix")
    return candidates, labels, lp_supplier, feasible_any, lp_objective


def cost_solve_finish(
    fetched,
    vectors: np.ndarray,
    counts: np.ndarray,
    capacity: np.ndarray,
    total: np.ndarray,
    prices: np.ndarray,
    pool_prices: np.ndarray,
    mix_plan: Optional[
        Tuple[List[Tuple[int, np.ndarray, int]], np.ndarray]
    ] = None,
    host_candidates: Optional[
        List[Tuple[List[Tuple[int, np.ndarray, int]], np.ndarray]]
    ] = None,
    explain: Optional[dict] = None,
) -> Optional[DenseSolveResult]:
    """Host-side candidate scoring + LP realization over fetched kernel
    outputs (the second half of cost_solve_dense). mix_plan, when given, is
    the column-LP candidate computed in the dispatch-to-fetch overlap window
    (compute_mix_candidate) and competes on equal scoring terms. fetched may
    be None (the cost_solve_host path): scoring then runs over
    host_candidates + mix_plan only and the device-LP realization is
    skipped. An `explain` dict, when passed, is filled with every scored
    candidate — [(label, DenseSolveResult, score_tuple)] under
    "candidates" — so analysis tooling (tools/rank_consistency.py) can
    compare the expected-price ranking against realized market cost."""
    num_groups = int(vectors.shape[0])
    candidates, labels, lp_supplier, feasible_any, lp_objective = (
        _collect_candidates(fetched, num_groups, host_candidates, mix_plan)
    )

    # Score from rounds: a node's realized price is the cheapest of its
    # offered options, which for the cost solve is the cheapest feasible
    # type for that fill. A candidate that leaves more pods unschedulable
    # never wins on price. The option sets are memoized per fill so the
    # winning candidate's decode reuses the scoring pass's work; the whole
    # distinct-fill set is selected in ONE native batch call up front
    # (~100 per-fill numpy walks would cost ~20ms on the critical path).
    options_memo: Dict[bytes, Tuple[List[int], Optional[List[PoolRow]]]] = {}
    pool_order = sort_pool_rows(pool_prices)
    _batch_pool_options(candidates, vectors, capacity, pool_order, options_memo)

    def options_for(t: int, fill: np.ndarray):
        # The anchor t only matters on the degenerate no-finite-pool path;
        # keying by fill alone lets identical fills packed for different
        # types share one ranking pass.
        key = fill.tobytes()
        options = options_memo.get(key)
        if options is None:
            options = _cheapest_feasible_pools(
                fill, t, vectors, capacity, pool_prices, pool_order
            )
            options_memo[key] = options
        return options

    price_memo: Dict[bytes, float] = {}

    def round_price(t: int, fill: np.ndarray) -> float:
        """Expected realized price of one node. The fleet's
        capacity-optimized-prioritized allocation mostly honors the
        price-ranked priority order and deviates to deeper pools only
        within its slack, so the expectation is a geometric-decay weighted
        mean over the offered rows (PRIORITY_DECAY) — cheapest rows
        dominate, later rows hedge. Against the market simulator's full
        (seed × correlation × slack) grid this ranks candidate plans
        consistently with their realized cost in 22/24 cells, versus 19/24
        for the uniform mean it replaces. The two inconsistent cells are
        decay-INVARIANT (tools/rank_consistency.py sweeps 0.3→uniform):
        their realized order flips on market pool depth, unobservable in
        the advertised prices this model sees — bounded at 0.37% / 3.29%
        regret vs our own best candidate (docs/solver.md). Memoized per
        fill — the same fill recurs across candidates and replicated
        rounds."""
        key = fill.tobytes()
        price = price_memo.get(key)
        if price is None:
            type_indices, pool_rows = options_for(t, fill)
            if pool_rows:
                row_prices = np.array([p for _, _, p in pool_rows])
                weights = PRIORITY_DECAY ** np.arange(len(row_prices))
                price = float((weights / weights.sum()) @ row_prices)
            else:
                # Degenerate: no pool anywhere can host this fill, and the
                # anchor t may be a padded phantom type index past the real
                # catalog (kernel rounds keep the padded type axis). Price
                # it unhostable — never cheap, never an IndexError — so the
                # candidate loses on cost unless every rival is equally
                # degenerate.
                in_range = [i for i in type_indices if i < prices.shape[0]]
                price = (
                    float(prices[in_range].min()) if in_range else float("inf")
                )
            price_memo[key] = price
        return price

    def score(candidate):
        round_list, unschedulable_counts = candidate
        nodes = sum(repl for _, _, repl in round_list)
        cost = sum(
            repl * round_price(t, fill) for t, fill, repl in round_list
        )
        return (int(unschedulable_counts.sum()), cost, nodes)

    # The LP realization only adds fragmentation on top of the LP's own
    # relaxed cost, so a kernel candidate clearly under the LP's fractional
    # objective makes the (host-side, ~15ms) realization pass very unlikely
    # to win; LP_REALIZE_SLACK covers the price-model gap between the two.
    # Only HERE does the deferred [G, T] LP assignment get fetched off the
    # device (lp_supplier) — the common case, a kernel candidate beating the
    # objective outright, never transfers it.
    scores = {id(c): score(c) for c in candidates}
    best_kernel_cost = min(
        (s[1] for s in scores.values() if s[0] == 0), default=np.inf
    )
    if lp_supplier is not None and (
        not candidates
        or best_kernel_cost > float(lp_objective) * LP_REALIZE_SLACK
    ):
        lp_candidate = _realize_lp_dense(
            lp_supplier(), feasible_any, vectors, counts, capacity, total
        )
        if lp_candidate is not None:
            candidates.append(lp_candidate)
            labels.append("lp_realized")
            scores[id(lp_candidate)] = score(lp_candidate)
    if not candidates:
        return None

    def materialize(candidate) -> DenseSolveResult:
        rounds, unschedulable = candidate
        options: Dict[bytes, Tuple[List[int], Optional[List[PoolRow]]]] = {}
        for t, fill, _ in rounds:
            options[fill.tobytes()] = options_for(t, fill)
        return DenseSolveResult(
            rounds=rounds, unschedulable=unschedulable, options=options
        )

    if explain is not None:
        explain["candidates"] = [
            (label, materialize(candidate), scores[id(candidate)])
            for label, candidate in zip(labels, candidates)
        ]
    best = min(candidates, key=lambda c: scores[id(c)])
    # Materialize options for every round of the winner (scoring already
    # computed them; this is a dict lookup).
    return materialize(best)


def _batch_pool_options(
    candidates,
    vectors: np.ndarray,
    capacity: np.ndarray,
    pool_order,
    memo: Dict[bytes, Tuple[List[int], Optional[List[PoolRow]]]],
) -> None:
    """Pre-populate the per-fill options memo for every distinct fill across
    all candidates with one native ktpu_pool_select call (bit-identical to
    the per-fill _cheapest_feasible_pools walk). A missing native library
    leaves the memo empty — callers lazily fall back per fill."""
    from karpenter_tpu.ops import native as native_mod

    row_types, row_zones, row_prices = pool_order
    if len(row_types) == 0:
        return
    distinct: Dict[bytes, Tuple[int, np.ndarray]] = {}
    for round_list, _ in candidates:
        for t, fill, _ in round_list:
            fill = np.asarray(fill)  # vet: host-array(candidate rounds are post-fetch numpy)
            key = fill.tobytes()
            if key not in distinct and key not in memo:
                distinct[key] = (t, fill)
    if not distinct:
        return
    demand = np.stack(
        [fill for _, fill in distinct.values()]
    ).astype(np.float64) @ vectors
    out = native_mod.pool_select_batch(
        demand,
        capacity,
        row_types,
        row_prices,
        MAX_POOL_ROWS,
        MIN_POOL_ROWS,
        POOL_PRICE_BAND,
        MAX_POOL_PRICE_RATIO,
        ffd.MAX_INSTANCE_TYPES,
    )
    if out is None:
        return
    out_rows, out_counts = out
    for (key, (t, _)), selected, count in zip(
        distinct.items(), out_rows, out_counts
    ):
        if count < 0:
            memo[key] = ([int(t)], None)
            continue
        rows: List[PoolRow] = [
            (int(row_types[i]), int(row_zones[i]), float(row_prices[i]))
            for i in selected[:count]
        ]
        chosen: List[int] = []
        seen_types: set = set()
        for type_index, _, _ in rows:
            if type_index not in seen_types:
                seen_types.add(type_index)
                chosen.append(type_index)
        memo[key] = (chosen, rows)


def _realize_lp_dense(
    lp_assignment: np.ndarray,
    feasible_any: np.ndarray,
    vectors: np.ndarray,
    counts: np.ndarray,
    capacity: np.ndarray,
    total: np.ndarray,
) -> Optional[Tuple[List[Tuple[int, np.ndarray, int]], np.ndarray]]:
    """Integerize the relaxed [G, T] assignment (already fetched to host)
    and realize it as greedy per-type node fills."""
    num = int(vectors.shape[0])
    counts = counts.astype(np.int64)
    unschedulable_counts = np.where(feasible_any[:num], 0, counts)
    solvable_counts = np.where(feasible_any[:num], counts, 0)
    if solvable_counts.sum() == 0:
        return None
    padded_solvable = np.zeros(lp_assignment.shape[0], dtype=np.int64)
    padded_solvable[:num] = solvable_counts
    # Concentrate before rounding: softmax leaves a long tail of tiny
    # per-type shards that round into poorly-filled single nodes. Keep
    # each group's heaviest types (up to 8) and renormalize — the
    # realized node count drops sharply at negligible objective cost.
    lp_assignment = np.asarray(  # vet: host-array(already fetched by the caller)
        lp_assignment, dtype=np.float64
    ).copy()
    for g in range(num):
        row = lp_assignment[g]
        total_mass = row.sum()
        if total_mass <= 0:
            continue
        keep = np.argsort(-row)[:8]
        kept = np.zeros_like(row)
        kept[keep] = row[keep]
        kept_mass = kept.sum()
        if kept_mass > 0:
            lp_assignment[g] = kept * (total_mass / kept_mass)
    assignment = round_assignment(lp_assignment, padded_solvable)

    # Realize the plan: per type, greedily fill nodes (pure greedy, no
    # quirk) with that type's assigned pods. The compiled path does all
    # types in one call; pure Python below is the no-toolchain fallback.
    from karpenter_tpu.ops import native

    native_rounds = native.lp_realize(
        vectors, assignment[:num, : capacity.shape[0]], capacity, total
    )
    if native_rounds is native.INFEASIBLE:
        return None  # proven unrealizable — don't redo the work in Python
    if native_rounds is not None:
        return native_rounds, unschedulable_counts

    round_list: List[Tuple[int, np.ndarray, int]] = []
    num_types = int(capacity.shape[0])
    for t in range(num_types):
        counts_t = assignment[:num, t].astype(np.int64).copy()
        guard = 0
        while counts_t.sum() > 0:
            fill = ffd.fill_node(
                capacity[t],
                total[t],
                vectors,
                counts_t,
                quirk=False,
            )
            if fill.sum() == 0:
                # Should not happen (feasibility pre-checked); bail out.
                return None
            repl_per_group = np.where(fill > 0, counts_t // np.maximum(fill, 1), np.iinfo(np.int64).max)
            repl = max(1, int(repl_per_group.min()))
            round_list.append((t, fill.copy(), repl))
            counts_t -= repl * fill
            guard += 1
            if guard > 4 * num + 16:
                return None
    return round_list, unschedulable_counts


# --- device-memory survival --------------------------------------------------
#
# A batch of schedules can exceed device HBM even though every schedule fits
# alone: the batched path dispatches all K fused kernels before the first
# fetch, so their [G, T] LP states are live simultaneously. Rather than let
# one oversized sweep crash provisioning (or silently dump the WHOLE batch
# onto the CPU pin), CostSolver bisects on RESOURCE_EXHAUSTED and
# re-dispatches the halves — each half re-runs the identical per-schedule
# math, so the recovered plans are bit-identical to the unsplit solve.

# Markers scanned (case-insensitively) over the error text. XLA surfaces
# allocation failure as XlaRuntimeError("RESOURCE_EXHAUSTED: ..."); older
# jaxlibs and the PJRT CPU client phrase it as "Out of memory" or "Failed
# to allocate N bytes".
_RESOURCE_EXHAUSTED_MARKERS = (
    "resource_exhausted",
    "out of memory",
    "failed to allocate",
)


def _is_resource_exhausted(error: BaseException) -> bool:
    """True when `error` is a device allocation failure — the recoverable
    kind the bisect ladder retries. Message-scan, not type-check: the
    concrete exception class differs across jaxlib versions and the injected
    fault, but the status phrase is stable."""
    text = f"{type(error).__name__}: {error}".lower()
    return any(marker in text for marker in _RESOURCE_EXHAUSTED_MARKERS)


# Live [G, T] float32 copies per in-flight solve: LP assignment + Adam m/v +
# gradient + softmax activations + compaction scratch. A deliberate
# overestimate — the pre-split only has to be conservative enough that the
# bisect path stays the rare fallback, not a per-sweep tax.
_LIVE_TENSOR_COPIES = 6
# Fraction of the device budget the pre-split packs to — headroom for the
# runtime's own allocations and fetch staging buffers.
HBM_SAFETY_FACTOR = 0.8


def _hbm_budget_bytes() -> Optional[float]:
    """Device memory budget for the pre-dispatch estimator, or None to skip
    pre-splitting (CPU backends report no limit — the bisect ladder still
    covers them). KARPENTER_HBM_BYTES overrides for tests and for devices
    whose PJRT client misreports bytes_limit."""
    import os

    raw = os.environ.get("KARPENTER_HBM_BYTES", "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            return None
    try:
        stats = jax.devices()[0].memory_stats()
        limit = (stats or {}).get("bytes_limit")
        return float(limit) if limit else None
    except Exception:  # noqa: BLE001 — estimator absence must never fail a solve
        return None


def _estimate_solve_bytes(groups: PodGroups, fleet: InstanceFleet) -> float:
    """Rough HBM footprint of one schedule's fused solve: the padded [G, T]
    LP tensors dominate (the dense plan state is [MR, G] int8 — noise next
    to float32 [G, T] at scale). Bucketed dims, because that's what the
    kernel actually allocates."""
    g = bucket_size(max(1, int(groups.num_groups)))
    t = bucket_size(max(1, int(fleet.num_types)))
    return float(g) * float(t) * 4.0 * _LIVE_TENSOR_COPIES


def _presplit_for_hbm(
    items: Sequence[Tuple[PodGroups, InstanceFleet]],
) -> List[List[Tuple[PodGroups, InstanceFleet]]]:
    """Greedily chunk a batch so each chunk's estimated footprint fits the
    device budget — the cheap pre-check that spares the common oversized
    sweep a guaranteed OOM + bisect round trip. One chunk (no split) when
    the budget is unknown or everything fits."""
    budget = _hbm_budget_bytes()
    if budget is None or len(items) <= 1:
        return [list(items)]
    cap = budget * HBM_SAFETY_FACTOR
    chunks: List[List[Tuple[PodGroups, InstanceFleet]]] = []
    current: List[Tuple[PodGroups, InstanceFleet]] = []
    current_bytes = 0.0
    for item in items:
        cost = _estimate_solve_bytes(*item)
        if current and current_bytes + cost > cap:
            chunks.append(current)
            current, current_bytes = [], 0.0
        current.append(item)
        current_bytes += cost
    chunks.append(current)
    return chunks


def _maybe_inject_device_oom() -> None:
    """The solver.dispatch faultpoint: chaos harnesses arm "oom" here to
    prove the bisect ladder recovers (count=N forces N failures, i.e. N
    split depths, before a dispatch goes through)."""
    from karpenter_tpu.utils import faultpoints

    if faultpoints.draw("solver.dispatch") is not None:
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: injected device allocation failure "
            "(faultpoint solver.dispatch)"
        )


class CostSolver(Solver):
    """The flagship: runs pure-greedy FFD, cost-greedy, and the LP-relaxation
    plan on TPU, returns the cheapest feasible packing. Because greedy is
    always among the candidates, projected $/hr can only match or beat the
    baseline. Thin object shell over cost_solve_dense — the same core the
    gRPC sidecar serves."""

    needs_device_warmup = True

    def __init__(self, lp_steps: int = 300):
        self.lp_steps = lp_steps

    @staticmethod
    def host_fallback_available() -> bool:
        """True when the warming-time host path can serve solves (native
        FFD present) — lets the Manager keep provisioning during boot
        warmup instead of holding batches."""
        from karpenter_tpu.ops import native as native_mod

        return native_mod.available()

    def solve_encoded(
        self,
        groups: PodGroups,
        fleet: InstanceFleet,
        explain: Optional[dict] = None,
    ) -> ffd.PackResult:
        if fleet.num_types == 0 or groups.num_groups == 0:
            return ffd.pack_groups(fleet, groups)

        # The matrix build is handed down as a thunk so it runs while the
        # fused kernel computes on the device. cost_solve_dense guarantees
        # the thunk runs before it returns non-None; the sentinel check
        # below makes that contract explicit rather than an IndexError later.
        pool_zones: Optional[List[str]] = None

        def pool_prices_fn():
            nonlocal pool_zones
            pool_zones, matrix = _pool_price_matrix(fleet)
            return matrix

        dense = cost_solve_dense(
            groups.vectors,
            groups.counts,
            fleet.capacity,
            fleet.total,
            fleet.prices,
            pool_prices_fn,
            lp_steps=self.lp_steps,
            explain=explain,
            device_pods=device_pod_args(groups),
        )
        if dense is None:
            return ffd.pack_groups(fleet, groups)
        if pool_zones is None:
            raise AssertionError(
                "cost_solve_dense returned a plan without evaluating pool_prices"
            )
        return decode_dense_result(dense, groups, fleet, pool_zones)

    def _dispatch_batch(self, items, batched: Optional[bool] = None):
        """Shared first stage of the batched and pipelined paths: host-solve
        or dispatch every schedule (async, device->host copies queued), and
        start ONE overlap worker for the pending schedules' host work.
        Returns (results, pending, zones_box, overlap) where `results` holds
        the already-finished slots and pending the in-flight ones.

        `batched` pins the host-gate threshold independently of len(items):
        the OOM bisect re-dispatches HALVES of a batch, and a singleton half
        re-gated as unary would flip host/device routing — the recovered
        plan must be bit-identical to the unsplit solve's."""
        if batched is None:
            batched = len(items) > 1
        results: List[Optional[ffd.PackResult]] = [None] * len(items)
        pending = []  # (index, groups, fleet, fused, prebuilt_pool)
        for i, (groups, fleet) in enumerate(items):
            if fleet.num_types == 0 or groups.num_groups == 0:
                results[i] = ffd.pack_groups(fleet, groups)
                continue
            prebuilt_pool = None  # (zones, matrix) when the host gate ran
            if host_solve_enabled(int(groups.counts.sum()), batched=batched):
                # Small schedule: the host path answers in milliseconds —
                # cheaper than even a SHARED device fetch's slice of work.
                # A single-item "batch" has no fetch to amortize, so it uses
                # the unary threshold.
                prebuilt_pool = _pool_price_matrix(fleet)
                dense = cost_solve_host(
                    groups.vectors,
                    groups.counts,
                    fleet.capacity,
                    fleet.total,
                    fleet.prices,
                    prebuilt_pool[1],
                )
                if dense is not None:
                    results[i] = decode_dense_result(
                        dense, groups, fleet, prebuilt_pool[0]
                    )
                    continue
            pod_vectors, pod_counts = device_pod_args(groups) or (
                groups.vectors,
                groups.counts,
            )
            fused = cost_solve_dispatch(
                pod_vectors,
                pod_counts,
                fleet.capacity,
                fleet.total,
                fleet.prices,
                self.lp_steps,
            )
            plan_start_fetch(fused)
            pending.append((i, groups, fleet, fused, prebuilt_pool))

        overlap = None
        zones_box: List[Optional[List[str]]] = [None] * len(pending)
        if pending:
            # Per-schedule host work (pool matrices + mix candidates) runs in
            # a worker thread concurrently with the blocking fetches, exactly
            # like the single-solve path. The thunks stash each fleet's zone
            # axis so the finish loop doesn't rebuild it, and reuse a matrix
            # the host-gate branch already built (rare fallthrough: native
            # overflow after the gate passed).
            def _matrix_thunk(
                fleet: InstanceFleet, slot: int, prebuilt
            ) -> np.ndarray:
                zones, matrix = prebuilt or _pool_price_matrix(fleet)
                zones_box[slot] = zones
                return matrix

            overlap = _HostOverlap(
                [
                    (
                        groups.vectors,
                        groups.counts,
                        fleet.capacity,
                        functools.partial(_matrix_thunk, fleet, k, prebuilt),
                    )
                    for k, (_, groups, fleet, _, prebuilt) in enumerate(pending)
                ]
            ).start()
        return results, pending, zones_box, overlap

    def _finish_one(self, entry, zones, pool_prices, mix_plan, plan):
        """Score + decode one pending schedule from its fetched plan."""
        _, groups, fleet, _, _ = entry
        dense = cost_solve_finish(
            plan,
            groups.vectors,
            groups.counts,
            fleet.capacity,
            fleet.total,
            fleet.prices,
            pool_prices,
            mix_plan=mix_plan,
        )
        return (
            ffd.pack_groups(fleet, groups)
            if dense is None
            else decode_dense_result(dense, groups, fleet, zones)
        )

    def solve_encoded_many(
        self, items: Sequence[Tuple[PodGroups, InstanceFleet]]
    ) -> List[ffd.PackResult]:
        """Batch path: dispatch every schedule's fused kernel first (async),
        build all pool matrices while the device works, then fetch ALL
        compacted payloads in one device->host transfer — K schedules cost
        one round trip instead of K (the round trip dominates on tunneled
        devices). Rides the OOM-survival ladder: oversized batches are
        pre-split by the HBM estimator, and a live RESOURCE_EXHAUSTED
        bisects and re-dispatches instead of crashing the sweep."""
        return self._solve_batch_survive(list(items), batched=len(items) > 1)

    def _solve_batch_fetch(
        self,
        items: Sequence[Tuple[PodGroups, InstanceFleet]],
        batched: bool,
    ) -> List[ffd.PackResult]:
        """One dispatch->fetch->finish round for `items` — the unit the
        bisect retries. Raises (RESOURCE_EXHAUSTED included) instead of
        falling back; _solve_batch_survive owns recovery."""
        results, pending, zones_box, overlap = self._dispatch_batch(
            items, batched=batched
        )
        if pending:
            _maybe_inject_device_oom()
            with device_profile(TRACER), TRACER.span(
                "solve.device.batch", solves=len(pending)
            ):
                plans = fetch_plans([entry[3] for entry in pending])
            pool_matrices, mix_plans = overlap.join()
            for entry, zones, pool_prices, mix_plan, plan in zip(
                pending, zones_box, pool_matrices, mix_plans, plans
            ):
                results[entry[0]] = self._finish_one(
                    entry, zones, pool_prices, mix_plan, plan
                )
        return results

    def _solve_batch_survive(
        self,
        items: List[Tuple[PodGroups, InstanceFleet]],
        batched: bool,
        depth: int = 0,
    ) -> List[ffd.PackResult]:
        """Device-memory survival ladder around the batched solve:

        1. depth 0 pre-splits by the HBM estimator — a batch whose estimated
           footprint exceeds the device budget never reaches the device
           whole (reason="estimate").
        2. A RESOURCE_EXHAUSTED from dispatch/fetch bisects the batch and
           re-dispatches the halves sequentially (reason="oom") — each half
           re-runs the identical per-schedule math under the ORIGINAL
           batched gate, so recovered plans are bit-identical to the
           unsplit solve's.
        3. A singleton that still OOMs is the floor (reason="floor"): fall
           through to the existing BackendHealth CPU pin and answer from
           the host path — degraded latency, never a crash.

        Any non-memory error propagates unchanged: retrying a batch around
        a logic error would just re-fail, and the caller's fallback ladder
        (provisioning's serial re-solve, the sidecar's status mapping) owns
        those.
        """
        if not items:
            return []
        if depth == 0:
            chunks = _presplit_for_hbm(items)
            if len(chunks) > 1:
                SOLVER_BATCH_SPLIT_TOTAL.inc("estimate", amount=len(chunks) - 1)
                klog.named("solver").info(
                    "HBM estimator pre-split solve batch: %d schedules -> "
                    "%d chunks", len(items), len(chunks),
                )
                out: List[ffd.PackResult] = []
                for chunk in chunks:
                    out.extend(self._solve_batch_survive(chunk, batched, depth=1))
                return out
        try:
            return self._solve_batch_fetch(items, batched)
        except Exception as error:  # noqa: BLE001 — classifier gates the catch
            if not _is_resource_exhausted(error):
                raise
            if len(items) == 1:
                SOLVER_BATCH_SPLIT_TOTAL.inc("floor")
                klog.named("solver").warning(
                    "single schedule exhausted device memory (%s); pinning "
                    "CPU backend and answering from the host path", error,
                )
                from karpenter_tpu.utils import backend_health

                backend_health.pin_cpu()
                return [self._floor_solve(*items[0])]
            SOLVER_BATCH_SPLIT_TOTAL.inc("oom")
            mid = len(items) // 2
            klog.named("solver").warning(
                "device memory exhausted (%s); bisecting %d-schedule batch "
                "at depth %d", error, len(items), depth + 1,
            )
            # Sequential, not parallel: the halves must not be in flight
            # together — co-residency is exactly what just OOMed.
            return self._solve_batch_survive(
                items[:mid], batched, depth=depth + 1
            ) + self._solve_batch_survive(
                items[mid:], batched, depth=depth + 1
            )

    @staticmethod
    def _floor_solve(groups: PodGroups, fleet: InstanceFleet) -> ffd.PackResult:
        """The bisect floor's answer: host cost solve (compiled FFD + mix
        candidates — same scoring as the device candidates), or plain FFD
        when the native library is absent. Cannot touch the device, so it
        cannot re-OOM."""
        zones, matrix = _pool_price_matrix(fleet)
        dense = cost_solve_host(
            groups.vectors, groups.counts, fleet.capacity,
            fleet.total, fleet.prices, matrix,
        )
        if dense is None:
            return ffd.pack_groups(fleet, groups)
        return decode_dense_result(dense, groups, fleet, zones)

    def solve_encoded_pipelined(
        self, items: Sequence[Tuple[PodGroups, InstanceFleet]]
    ) -> Iterator[ffd.PackResult]:
        """The solve->bind pipeline: every schedule's kernel is dispatched
        and its compacted device->host copy queued UP FRONT (double-buffered
        — the copies stream behind the kernels on the device queue), then
        results yield in schedule order. While the caller binds/launches
        result N, schedules N+1.. are still computing and copying; each
        fetch here finds its payload already staged instead of starting a
        round trip. Crash-consistency note: provisioning only takes this
        path when no crashpoint is armed (armed runs use the serial
        solve-then-bind flow so mid-bind kills stay deterministic —
        controllers/provisioning._solve_results).

        Dispatch happens EAGERLY at the call (not at the first pull): the
        caller's dispatch-stage timing stays honest, and the device starts
        working before the first bind regardless of when iteration
        begins."""
        results, pending, zones_box, overlap = self._dispatch_batch(items)

        def _results() -> Iterator[ffd.PackResult]:
            next_pending = 0
            # After a mid-stream RESOURCE_EXHAUSTED, the not-yet-fetched
            # tail is re-solved through the bisect ladder; `recovered`
            # holds those plans, indexed from pending slot `recovered_base`.
            recovered: Optional[List[ffd.PackResult]] = None
            recovered_base = 0
            for i in range(len(items)):
                if results[i] is not None:
                    yield results[i]
                    continue
                entry = pending[next_pending]
                k = next_pending
                next_pending += 1
                if recovered is not None:
                    yield recovered[k - recovered_base]
                    continue
                # Wait for THIS schedule's host work only — later schedules'
                # mix candidates keep computing while this one decodes/binds.
                overlap.wait(k)
                try:
                    _maybe_inject_device_oom()
                    with device_profile(TRACER), TRACER.span(
                        "solve.device.pipelined", solve=k
                    ):
                        plan = fetch_plan(entry[3])
                except Exception as error:  # noqa: BLE001 — classifier gates
                    if not _is_resource_exhausted(error):
                        raise
                    # The in-flight tail just proved it doesn't fit next to
                    # whatever else holds HBM: abandon those handles and
                    # re-solve pending[k:] through the bisect ladder, under
                    # the SAME batched gate so plans stay bit-identical.
                    SOLVER_BATCH_SPLIT_TOTAL.inc("oom")
                    klog.named("solver").warning(
                        "device memory exhausted mid-pipeline (%s); "
                        "re-solving %d remaining schedules via bisect",
                        error, len(pending) - k,
                    )
                    recovered = self._solve_batch_survive(
                        [(e[1], e[2]) for e in pending[k:]],
                        batched=len(items) > 1,
                        depth=1,
                    )
                    recovered_base = k
                    yield recovered[0]
                    continue
                yield self._finish_one(
                    entry, zones_box[k], overlap.pool_prices[k],
                    overlap.mix_plans[k], plan,
                )

        return _results()


def decode_dense_result(
    dense: DenseSolveResult,
    groups: PodGroups,
    fleet: InstanceFleet,
    zones: List[str],
) -> ffd.PackResult:
    """Rehydrate a DenseSolveResult into a PackResult on the object-holding
    side of the solver boundary (in-process or the sidecar's client)."""

    def options_fn(t: int, fill: np.ndarray):
        type_indices, rows = dense.options[fill.tobytes()]
        return type_indices, pool_rows_to_options(rows, fleet, zones)

    return _decode_rounds(
        dense.rounds, dense.unschedulable, groups, fleet, options_fn=options_fn
    )
