"""Device-mesh parallelism for solver scale-out.

The reference's "distributed fabric" is goroutines + the kube watch plane
(SURVEY.md §2.7); this framework's scale axis is the (pod-groups × instance
-types) score tensor, sharded over a jax.sharding.Mesh with XLA collectives
riding ICI (SURVEY.md §5 long-context analogue).
"""

from karpenter_tpu.parallel.mesh import make_mesh, solver_shardings
from karpenter_tpu.parallel.sharded_solver import sharded_lp_train_step, sharded_lp_solve

__all__ = ["make_mesh", "solver_shardings", "sharded_lp_train_step", "sharded_lp_solve"]
