"""Mesh construction and sharding layouts for the solver.

Axes:
  "groups" (data-parallel axis): pod groups — each shard owns a slice of the
      pod-group dimension; gradients reduce across it (psum inserted by GSPMD).
  "types" (model-parallel axis): instance types — the score/assignment matrix
      [G, T] is sharded across both axes; per-type reductions ride ICI.

For a single host this is a flat mesh over local devices; multi-host keeps
the same named axes over the global device set (jax.distributed handles
process bootstrap), so the solver code is topology-agnostic.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

GROUPS_AXIS = "groups"
TYPES_AXIS = "types"


def _factor(n: int) -> Tuple[int, int]:
    """Split n into (groups, types) factors, as square as possible with the
    types axis at least as large (type counts dominate group counts)."""
    best = (1, n)
    a = 1
    while a * a <= n:
        if n % a == 0:
            best = (a, n // a)
        a += 1
    return best


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    groups_size, types_size = _factor(len(devices))
    grid = np.array(devices).reshape(groups_size, types_size)
    return Mesh(grid, (GROUPS_AXIS, TYPES_AXIS))


def solver_shardings(mesh: Mesh):
    """NamedShardings for the LP solver operands."""
    return {
        "logits": NamedSharding(mesh, P(GROUPS_AXIS, TYPES_AXIS)),  # [G, T]
        "vectors": NamedSharding(mesh, P(GROUPS_AXIS, None)),  # [G, R]
        "counts": NamedSharding(mesh, P(GROUPS_AXIS)),  # [G]
        "capacity": NamedSharding(mesh, P(TYPES_AXIS, None)),  # [T, R]
        "prices": NamedSharding(mesh, P(TYPES_AXIS)),  # [T]
        "valid": NamedSharding(mesh, P(TYPES_AXIS)),  # [T]
        "replicated": NamedSharding(mesh, P()),
    }


def pad_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple
