"""Mesh construction and sharding layouts for the solver.

Axes:
  "groups" (data-parallel axis): pod groups — each shard owns a slice of the
      pod-group dimension; gradients reduce across it (psum inserted by GSPMD).
  "types" (model-parallel axis): instance types — the score/assignment matrix
      [G, T] is sharded across both axes; per-type reductions ride ICI.

For a single host this is a flat mesh over local devices; multi-host keeps
the same named axes over the global device set (jax.distributed handles
process bootstrap), so the solver code is topology-agnostic.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

GROUPS_AXIS = "groups"
TYPES_AXIS = "types"


def _factor(n: int) -> Tuple[int, int]:
    """Split n into (groups, types) factors, as square as possible with the
    types axis at least as large (type counts dominate group counts)."""
    best = (1, n)
    a = 1
    while a * a <= n:
        if n % a == 0:
            best = (a, n // a)
        a += 1
    return best


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """The solver mesh over the HEALTHY device set: chips quarantined by
    BackendHealth (utils/backend_health.report_chip_wedged — the "1 of N
    chips wedged" verdict) are excluded, so the mesh shrinks and the next
    kernel lowering spans only the survivors instead of the process falling
    back to CPU. An explicit `devices` argument bypasses the filter (tests
    and the dryrun build meshes over exact device sets)."""
    if devices is None:
        from karpenter_tpu.utils import backend_health

        wedged = backend_health.wedged_chips()
        devices = [d for d in jax.devices() if int(d.id) not in wedged]
        if not devices:
            # Every chip quarantined: the caller's gate (solve_mesh) should
            # have routed away already; fail loudly rather than build an
            # empty mesh.
            raise RuntimeError(
                f"no healthy devices left (wedged: {sorted(wedged)})"
            )
    devices = list(devices)
    groups_size, types_size = _factor(len(devices))
    grid = np.array(devices).reshape(groups_size, types_size)
    return Mesh(grid, (GROUPS_AXIS, TYPES_AXIS))


def solver_shardings(mesh: Mesh):
    """NamedShardings for the LP solver operands."""
    return {
        "logits": NamedSharding(mesh, P(GROUPS_AXIS, TYPES_AXIS)),  # [G, T]
        "vectors": NamedSharding(mesh, P(GROUPS_AXIS, None)),  # [G, R]
        "counts": NamedSharding(mesh, P(GROUPS_AXIS)),  # [G]
        "capacity": NamedSharding(mesh, P(TYPES_AXIS, None)),  # [T, R]
        "prices": NamedSharding(mesh, P(TYPES_AXIS)),  # [T]
        "valid": NamedSharding(mesh, P(TYPES_AXIS)),  # [T]
        "replicated": NamedSharding(mesh, P()),
    }


def pad_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple
