"""SPMD work distribution for the multi-host solver.

Multi-process JAX is single-program-multiple-data: a computation over a
global mesh must be dispatched by EVERY process, or the first collective
deadlocks. Only rank 0 receives solve RPCs (the chart pins the Service to
pod 0), so each solve is replicated to the slice through this module:

  rank 0   SpmdDispatcher.lead_dispatch(): broadcast a fixed-shape header
           [op, G, T, lp_steps], then the mesh device-mask, then the padded
           operand arrays, then run the mesh-sharded fused kernel — the
           same call every follower makes.
  rank >0  follower_loop(): block on the next header broadcast, rebuild the
           mesh from the device-mask and the operand shapes from the
           header, receive the arrays, run the SAME kernel, and wait for
           the next header. An OP_STOP header exits the loop (lead_stop()
           on clean shutdown; a dead coordinator surfaces as a collective
           error, which also exits).

The device-mask leg keeps a DEGRADED mesh coherent across the slice: when
BackendHealth quarantines a wedged chip on the lead, the mask names the
surviving devices and every follower lowers the kernel over the identical
shrunk mesh — a one-sided shrink would desynchronize collective order.

Broadcasts ride jax.experimental.multihost_utils.broadcast_one_to_all —
XLA collectives over ICI/DCN, the same fabric as the solve itself; there is
no side-channel RPC layer to operate. Solves are serialized under the
dispatcher's lock on rank 0 because collectives must be issued in the same
order on every process.

Not every jaxlib can host this: XLA:CPU (as shipped in some builds) rejects
multi-process computations outright. That surfaces as an XlaRuntimeError at
the FIRST broadcast — detect it (collectives_unsupported) and fail fast
with a named error instead of letting a half-initialized slice hang; the
spmd test skips on the same signature.

Ref: SURVEY.md §5 — "a distributed communication backend (XLA collectives
over ICI/DCN) that scales to multi-host the way the reference's NCCL/MPI
backend does". The reference distributes work by running many independent
EC2 calls; this framework's scale axis is one solve spanning many hosts.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.tracing import (
    TRACER,
    trace_id_to_words,
    words_to_trace_id,
)

log = klog.named("parallel.spmd")

OP_STOP = 0
OP_SOLVE = 1

# Header layout: [op, g_pad, t_pad, lp_steps, trace_lo, trace_hi]. The two
# trace words carry the provisioning batch's trace id (tracing.new_trace_id,
# split into 31-bit halves for the int32 transport) so follower-side spans
# land under the SAME trace as the host and sidecar spans — a merged Chrome
# trace stitches all three processes. (0, 0) means "no trace current".
HEADER_WORDS = 6

# The backend-capability signature: jaxlib's CPU client raises this when a
# multi-process program reaches it. Shared with tests/test_spmd.py so the
# skip reason and the runtime error can never drift apart.
COLLECTIVES_UNSUPPORTED_MSG = (
    "Multiprocess computations aren't implemented on the CPU backend"
)


class SpmdUnsupportedError(RuntimeError):
    """The runtime cannot host multi-process collectives (see
    COLLECTIVES_UNSUPPORTED_MSG) — raised instead of deadlocking the slice."""


def collectives_unsupported(error: BaseException) -> bool:
    return COLLECTIVES_UNSUPPORTED_MSG in str(error)


def _broadcast(value):
    from jax.experimental import multihost_utils

    try:
        return multihost_utils.broadcast_one_to_all(value)
    except Exception as error:  # noqa: BLE001 — classify, then re-raise
        if collectives_unsupported(error):
            raise SpmdUnsupportedError(
                "multi-process dispatch needs cross-process collectives, "
                f"which this jaxlib build lacks: {error}"
            ) from error
        raise


def is_multiprocess() -> bool:
    import jax

    return jax.process_count() > 1


def _broadcast_operands(padded):
    """Broadcast the six padded kernel operands as ONE pytree collective
    (the follower knows every shape from the header). Bool masks ride as
    uint8 — collective backends are numeric."""
    vectors, counts, capacity, total, valid, prices = padded
    out = _broadcast(
        (
            np.asarray(vectors, np.float32),  # vet: host-array(padded numpy operands)
            np.asarray(counts, np.int32),  # vet: host-array(padded numpy operands)
            np.asarray(capacity, np.float32),  # vet: host-array(padded numpy operands)
            np.asarray(total, np.float32),  # vet: host-array(padded numpy operands)
            np.asarray(valid, np.uint8),  # vet: host-array(padded numpy operands)
            np.asarray(prices, np.float32),  # vet: host-array(padded numpy operands)
        )
    )
    vectors, counts, capacity, total, valid, prices = (
        # The broadcast result is a committed device array and this IS a
        # deliberate fetch: every process must feed the sharded kernel
        # identical host operands, and the collective is the only transport.
        np.asarray(leaf)  # vet: host-array(SPMD replication fetch, deliberate)
        for leaf in out
    )
    return vectors, counts, capacity, total, valid.astype(bool), prices


def _device_mask(mesh) -> np.ndarray:
    """[device_count] uint8 membership mask of the mesh's devices — the
    fixed-shape leg that replicates a (possibly shrunk) mesh to followers."""
    import jax

    mask = np.zeros(jax.device_count(), np.uint8)
    for device in mesh.devices.flat:
        mask[int(device.id)] = 1
    return mask


def _mesh_from_mask(mask: np.ndarray):
    import jax

    from karpenter_tpu.parallel.mesh import make_mesh

    by_id = {int(d.id): d for d in jax.devices()}
    return make_mesh([by_id[i] for i in np.nonzero(mask)[0]])


class SpmdDispatcher:
    """Rank 0's dispatch serializer. Collectives must be issued in the same
    order on every process, so every lead-side broadcast round — dispatch
    and stop alike — runs under one lock, held through device completion
    (the follower blocks on ITS kernel before the next header, so a second
    lead dispatch racing ahead would desynchronize the collective order).
    That lock-across-dispatch is the documented blocking-under-lock
    allowance (tools/vet/checkers/locks.py ALLOWED)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stopped = False  # vet: guarded-by(self._lock) — no dispatch after stop
        self._dispatched = 0  # vet: guarded-by(self._lock) — solves replicated so far

    def lead_dispatch(self, kernel, padded, lp_steps: int, mesh=None):
        """Rank 0: replicate one solve to every process, then dispatch it.
        Returns the kernel's outputs, ALREADY device-complete (unlike the
        single-host path's async dispatch) — multi-host solves serialize
        and the batch path's one-fetch amortization degrades to per-solve
        round trips; acceptable, since a pod slice's solve throughput
        dwarfs any realistic schedule rate."""
        g_pad = int(padded[0].shape[0])
        t_pad = int(padded[2].shape[0])
        trace_lo, trace_hi = trace_id_to_words(TRACER.current_trace())
        with self._lock:
            if self._stopped:
                raise RuntimeError("SPMD dispatcher already stopped")
            _broadcast(
                np.array(
                    [OP_SOLVE, g_pad, t_pad, lp_steps, trace_lo, trace_hi],
                    np.int32,
                )
            )
            if mesh is not None:
                _broadcast(_device_mask(mesh))
            else:  # pragma: no cover — every production caller passes a mesh
                import jax

                _broadcast(np.ones(jax.device_count(), np.uint8))
            operands = _broadcast_operands(padded)
            out = kernel(*operands, lp_steps=lp_steps)
            self._dispatched += 1
            # Hold the lock until device completion: see the class docstring.
            import jax

            jax.block_until_ready(out)
        return out

    def lead_stop(self) -> None:
        """Rank 0, clean shutdown: release every follower from its header
        wait. Idempotent — a second stop must not issue a second collective
        no follower is waiting for."""
        if not is_multiprocess():
            return
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            _broadcast(np.zeros(HEADER_WORDS, np.int32))


DISPATCHER = SpmdDispatcher()


def lead_dispatch(kernel, padded, lp_steps: int, mesh=None):
    return DISPATCHER.lead_dispatch(kernel, padded, lp_steps, mesh=mesh)


def lead_stop() -> None:
    DISPATCHER.lead_stop()


def follower_step(dims: int):
    """One follower protocol round: header, device-mask, operands, kernel.
    Returns the kernel's (device-complete) outputs, or None on OP_STOP.
    Split from follower_loop so the loopback test (tests/test_spmd.py
    TestSpmdCpuMesh) can drive the REAL follower code through an injected
    transport on the single-process virtual mesh."""
    import jax

    from karpenter_tpu.models.solver import _sharded_fused_kernel

    header = np.asarray(  # vet: host-array(fixed-shape SPMD header, deliberate fetch)
        _broadcast(np.zeros(HEADER_WORDS, np.int32))
    )
    op, g_pad, t_pad, lp_steps, trace_lo, trace_hi = (int(x) for x in header)
    if op == OP_STOP:
        return None
    mask = np.asarray(  # vet: host-array(device-mask leg, deliberate fetch)
        _broadcast(np.zeros(jax.device_count(), np.uint8))
    )
    padded = (
        np.zeros((g_pad, dims), np.float32),
        np.zeros(g_pad, np.int32),
        np.zeros((t_pad, dims), np.float32),
        np.zeros((t_pad, dims), np.float32),
        np.zeros(t_pad, bool),
        np.zeros(t_pad, np.float32),
    )
    operands = _broadcast_operands(padded)
    kernel, _, _ = _sharded_fused_kernel(_mesh_from_mask(mask))
    # The follower's span carries the lead's batch trace id (header words),
    # so its lane stitches into the same cross-process timeline.
    with TRACER.trace(words_to_trace_id(trace_lo, trace_hi)), TRACER.span(
        "spmd.follower.step", g_pad=g_pad, t_pad=t_pad
    ):
        out = kernel(*operands, lp_steps=lp_steps)
        jax.block_until_ready(out)
    return out


def follower_loop() -> None:
    """Ranks > 0: mirror every lead dispatch until OP_STOP."""
    import jax

    from karpenter_tpu.api import wellknown
    from karpenter_tpu.ops import pallas_kernels

    # Probe before the first trace, exactly like the lead's dispatch path —
    # the traced program must be identical on every process.
    pallas_kernels.ensure_probed()

    log.info(
        "SPMD follower %d/%d up (%d global devices)",
        jax.process_index(), jax.process_count(), jax.device_count(),
    )
    while follower_step(wellknown.NUM_RESOURCE_DIMS) is not None:
        pass
    log.info("SPMD follower %d stopping", jax.process_index())
