"""SPMD work distribution for the multi-host solver.

Multi-process JAX is single-program-multiple-data: a computation over a
global mesh must be dispatched by EVERY process, or the first collective
deadlocks. Only rank 0 receives solve RPCs (the chart pins the Service to
pod 0), so each solve is replicated to the slice through this module:

  rank 0   lead_dispatch(): broadcast a fixed-shape header
           [op, G, T, lp_steps], then the padded operand arrays, then run
           the mesh-sharded fused kernel — the same call every follower
           makes.
  rank >0  follower_loop(): block on the next header broadcast, rebuild the
           operand shapes from it, receive the arrays, run the SAME kernel,
           and wait for the next header. An OP_STOP header exits the loop
           (lead_stop() on clean shutdown; a dead coordinator surfaces as a
           collective error, which also exits).

Broadcasts ride jax.experimental.multihost_utils.broadcast_one_to_all —
XLA collectives over ICI/DCN, the same fabric as the solve itself; there is
no side-channel RPC layer to operate. Solves are serialized under a lock on
rank 0 because collectives must be issued in the same order on every
process.

Ref: SURVEY.md §5 — "a distributed communication backend (XLA collectives
over ICI/DCN) that scales to multi-host the way the reference's NCCL/MPI
backend does". The reference distributes work by running many independent
EC2 calls; this framework's scale axis is one solve spanning many hosts.
"""

from __future__ import annotations

import threading

import numpy as np

from karpenter_tpu.utils import logging as klog

log = klog.named("parallel.spmd")

OP_STOP = 0
OP_SOLVE = 1

_LEAD_LOCK = threading.Lock()


def _broadcast(value):
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(value)


def is_multiprocess() -> bool:
    import jax

    return jax.process_count() > 1


def _broadcast_operands(padded):
    """Broadcast the six padded kernel operands as ONE pytree collective
    (the follower knows every shape from the header). Bool masks ride as
    uint8 — collective backends are numeric."""
    vectors, counts, capacity, total, valid, prices = padded
    out = _broadcast(
        (
            np.asarray(vectors, np.float32),  # vet: host-array(padded numpy operands)
            np.asarray(counts, np.int32),  # vet: host-array(padded numpy operands)
            np.asarray(capacity, np.float32),  # vet: host-array(padded numpy operands)
            np.asarray(total, np.float32),  # vet: host-array(padded numpy operands)
            np.asarray(valid, np.uint8),  # vet: host-array(padded numpy operands)
            np.asarray(prices, np.float32),  # vet: host-array(padded numpy operands)
        )
    )
    vectors, counts, capacity, total, valid, prices = (
        # The broadcast result is a committed device array and this IS a
        # deliberate fetch: every process must feed the sharded kernel
        # identical host operands, and the collective is the only transport.
        np.asarray(leaf)  # vet: host-array(SPMD replication fetch, deliberate)
        for leaf in out
    )
    return vectors, counts, capacity, total, valid.astype(bool), prices


def lead_dispatch(kernel, padded, lp_steps: int):
    """Rank 0: replicate one solve to every process, then dispatch it.
    Returns the kernel's outputs, ALREADY device-complete (unlike the
    single-host path's async dispatch): the lock must cover execution so a
    concurrent second solve can't desynchronize collective order, which
    means multi-host solves serialize and the batch path's one-fetch
    amortization degrades to per-solve round trips — acceptable, since a
    pod slice's solve throughput dwarfs any realistic schedule rate."""
    g_pad = int(padded[0].shape[0])
    t_pad = int(padded[2].shape[0])
    with _LEAD_LOCK:
        _broadcast(np.array([OP_SOLVE, g_pad, t_pad, lp_steps], np.int32))
        operands = _broadcast_operands(padded)
        out = kernel(*operands, lp_steps=lp_steps)
        # Hold the lock until device completion: the follower blocks on ITS
        # kernel before the next header, so a second lead dispatch racing
        # ahead would desynchronize the collective order.
        import jax

        jax.block_until_ready(out)
    return out


def lead_stop() -> None:
    """Rank 0, clean shutdown: release every follower from its header wait."""
    if not is_multiprocess():
        return
    with _LEAD_LOCK:
        _broadcast(np.zeros(4, np.int32))


def follower_loop() -> None:
    """Ranks > 0: mirror every lead dispatch until OP_STOP."""
    import jax

    from karpenter_tpu.api import wellknown
    from karpenter_tpu.ops import pallas_kernels

    # Probe before the first trace, exactly like the lead's dispatch path —
    # the traced program must be identical on every process.
    pallas_kernels.ensure_probed()
    from karpenter_tpu.models.solver import _sharded_fused_kernel

    dims = wellknown.NUM_RESOURCE_DIMS
    log.info(
        "SPMD follower %d/%d up (%d global devices)",
        jax.process_index(), jax.process_count(), jax.device_count(),
    )
    while True:
        header = np.asarray(  # vet: host-array(4-int SPMD header, deliberate fetch)
            _broadcast(np.zeros(4, np.int32))
        )
        op, g_pad, t_pad, lp_steps = (int(x) for x in header)
        if op == OP_STOP:
            log.info("SPMD follower %d stopping", jax.process_index())
            return
        padded = (
            np.zeros((g_pad, dims), np.float32),
            np.zeros(g_pad, np.int32),
            np.zeros((t_pad, dims), np.float32),
            np.zeros((t_pad, dims), np.float32),
            np.zeros(t_pad, bool),
            np.zeros(t_pad, np.float32),
        )
        operands = _broadcast_operands(padded)
        kernel, _ = _sharded_fused_kernel()
        jax.block_until_ready(kernel(*operands, lp_steps=lp_steps))
