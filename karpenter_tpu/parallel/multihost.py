"""Multi-host bootstrap: jax.distributed process initialization.

Ref: the reference scales its control plane with one process and leans on
EC2 Fleet for scale-out; this framework's scale axis is the solver, and a
TPU pod slice spans HOSTS (e.g. v4-16 = 2 hosts × 4 chips). SURVEY.md §5
mandates "a distributed communication backend (XLA collectives over
ICI/DCN) that scales to multi-host the way the reference's NCCL/MPI
backend does" — in JAX that is `jax.distributed.initialize`: every process
contacts the coordinator, and `jax.devices()` becomes the GLOBAL device
set, so `parallel.mesh.make_mesh()` and the mesh-sharded fused kernel
(models/solver.py) span hosts with zero further code — GSPMD routes
collectives over ICI within a slice and DCN across slices.

Environment contract (the chart's solver StatefulSet sets these; any
launcher can):
  KARPENTER_COORDINATOR        host:port of process 0 (absent = single host)
  KARPENTER_NUM_PROCESSES      total process count
  KARPENTER_PROCESS_ID         this process's rank, 0-based
  KARPENTER_MULTIHOST=auto     instead of the three above: call
                               jax.distributed.initialize() with no
                               arguments, which autodetects coordinator and
                               ranks from the TPU pod-slice metadata
                               service (only meaningful on TPU pods).
With none of these set, the process runs single-host.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from karpenter_tpu.utils import logging as klog

log = klog.named("parallel.multihost")


@dataclass(frozen=True)
class DistributedConfig:
    coordinator: str
    num_processes: int
    process_id: int

    @staticmethod
    def from_env(environ=None) -> Optional["DistributedConfig"]:
        """None when multi-host is not configured (the common single-host
        case). Raises ValueError on a partial/inconsistent configuration —
        silently falling back to single-host would deadlock the other
        processes of the slice at their first collective."""
        environ = os.environ if environ is None else environ
        coordinator = environ.get("KARPENTER_COORDINATOR", "")
        num_processes = environ.get("KARPENTER_NUM_PROCESSES", "")
        process_id = environ.get("KARPENTER_PROCESS_ID", "")
        if not coordinator and not num_processes and not process_id:
            return None
        if not (coordinator and num_processes and process_id != ""):
            raise ValueError(
                "partial multi-host config: KARPENTER_COORDINATOR, "
                "KARPENTER_NUM_PROCESSES and KARPENTER_PROCESS_ID must all "
                f"be set (got coordinator={coordinator!r}, "
                f"num_processes={num_processes!r}, process_id={process_id!r})"
            )
        config = DistributedConfig(
            coordinator=coordinator,
            num_processes=int(num_processes),
            process_id=int(process_id),
        )
        if config.num_processes < 1:
            raise ValueError(f"num_processes must be >= 1, got {config.num_processes}")
        if not 0 <= config.process_id < config.num_processes:
            raise ValueError(
                f"process_id {config.process_id} out of range for "
                f"{config.num_processes} processes"
            )
        return config


def init_distributed(environ=None) -> bool:
    """Initialize jax.distributed from the environment. Returns True when a
    multi-host runtime came up (jax.devices() is now the global set), False
    for the single-host case. Idempotent per process (jax raises if
    initialized twice; we guard)."""
    import jax

    env = os.environ if environ is None else environ
    auto = env.get("KARPENTER_MULTIHOST", "").lower() == "auto"
    config = DistributedConfig.from_env(environ)
    if config is None and not auto:
        return False
    if getattr(init_distributed, "_initialized", False):
        return True
    if config is None:
        # TPU pod slice: coordinator/ranks from the metadata service.
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(
            coordinator_address=config.coordinator,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )
    init_distributed._initialized = True
    # Read rank/size back from jax: on the auto path there is no config.
    log.info(
        "multi-host runtime up: process %d/%d, %d global devices",
        jax.process_index(), jax.process_count(), jax.device_count(),
    )
    return True
