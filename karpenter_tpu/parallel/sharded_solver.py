"""Sharded LP solve: the multi-chip path.

The [G, T] assignment problem shards over the ("groups", "types") mesh; all
operands carry NamedShardings and GSPMD inserts the collectives (psum of the
objective partial-sums across both axes, all-gathers on the softmax axis).
This is this framework's context-parallelism: when 50k-pod batches with
hundreds of types exceed one chip, the score tensor splits over ICI
(SURVEY.md §5: "sharding the (pods × instance-types) score tensor ... is
this project's context parallelism").
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from karpenter_tpu.ops.score_kernel import LPResult, lp_objective, feasibility_mask
from karpenter_tpu.parallel.mesh import make_mesh, pad_multiple, solver_shardings

_OPTIMIZER = optax.adam(0.25)


class LPTrainState(NamedTuple):
    """Optimizer state for the assignment-logits 'model'."""

    logits: jnp.ndarray  # [G, T]
    opt_state: tuple


def lp_train_init(logits0: jnp.ndarray) -> LPTrainState:
    return LPTrainState(logits=logits0, opt_state=_OPTIMIZER.init(logits0))


def lp_train_step(
    state: LPTrainState,
    vectors: jnp.ndarray,
    counts: jnp.ndarray,
    capacity: jnp.ndarray,
    prices: jnp.ndarray,
    feasible: jnp.ndarray,
) -> Tuple[LPTrainState, jnp.ndarray]:
    """One optimization step on the assignment logits — the framework's
    'training step': loss, grad, Adam update."""
    loss, grads = jax.value_and_grad(lp_objective)(
        state.logits, vectors, counts, capacity, prices, feasible
    )
    updates, opt_state = _OPTIMIZER.update(grads, state.opt_state, state.logits)
    return (
        LPTrainState(
            logits=optax.apply_updates(state.logits, updates), opt_state=opt_state
        ),
        loss,
    )


def _state_shardings(shardings):
    """LPTrainState shardings: Adam's mu/nu mirror the [G, T] logits sharding;
    scalar leaves (step count) stay replicated."""
    template_opt_state = _OPTIMIZER.init(jnp.zeros((1, 1)))
    return LPTrainState(
        logits=shardings["logits"],
        opt_state=jax.tree_util.tree_map(
            lambda leaf: shardings["logits"]
            if getattr(leaf, "ndim", 0) == 2
            else shardings["replicated"],
            template_opt_state,
        ),
    )


def sharded_lp_train_step(mesh=None):
    """Build a jitted train step with solver shardings over `mesh`.

    Returns (step_fn, shardings). step_fn(state, vectors, counts, capacity,
    prices, feasible) -> (state, loss), with the [G, T] logits sharded over
    (groups, types) and every collective compiled by GSPMD.
    """
    mesh = mesh or make_mesh()
    shardings = solver_shardings(mesh)
    state_sharding = _state_shardings(shardings)
    step = jax.jit(
        lp_train_step,
        in_shardings=(
            state_sharding,
            shardings["vectors"],
            shardings["counts"],
            shardings["capacity"],
            shardings["prices"],
            shardings["logits"],  # feasible is [G, T]
        ),
        out_shardings=(state_sharding, shardings["replicated"]),
    )
    return step, shardings


def sharded_lp_solve(
    vectors,
    counts,
    capacity,
    valid_types,
    prices,
    steps: int = 300,
    mesh=None,
) -> LPResult:
    """Multi-chip LP solve: pads G and T to mesh-divisible sizes, places
    operands with NamedShardings, and runs the optimization loop."""
    mesh = mesh or make_mesh()
    groups_size, types_size = mesh.devices.shape
    g = pad_multiple(vectors.shape[0], max(groups_size, 1))
    t = pad_multiple(capacity.shape[0], max(types_size, 1))

    vectors = jnp.asarray(_pad(vectors, g, 0))
    counts = jnp.asarray(_pad(counts, g, 0)).astype(jnp.float32)
    capacity = jnp.asarray(_pad(capacity, t, 0))
    valid_types = jnp.asarray(_pad(valid_types, t, 0))
    prices = jnp.asarray(_pad(prices, t, 0))

    shardings = solver_shardings(mesh)
    vectors = jax.device_put(vectors, shardings["vectors"])
    counts = jax.device_put(counts, shardings["counts"])
    capacity = jax.device_put(capacity, shardings["capacity"])
    valid_types = jax.device_put(valid_types, shardings["valid"])
    prices = jax.device_put(prices, shardings["prices"])

    feasible = feasibility_mask(vectors, capacity, valid_types)
    feasible = jax.device_put(feasible, shardings["logits"])
    density = prices / jnp.maximum(jnp.max(capacity, axis=1), 1.0)
    logits0 = jnp.broadcast_to(-jnp.log(density + 1e-9), feasible.shape).astype(
        jnp.float32
    )
    logits0 = jax.device_put(logits0, shardings["logits"])

    # The whole optimization runs in ONE sharded executable (lax.scan over
    # steps): one dispatch, one run-id. Many small dispatches of a collective
    # program can starve XLA:CPU's in-process rendezvous on low-core hosts
    # (observed: AllReduce deadlock with 8 virtual devices on 1 core); a
    # single scan executable avoids that and is also the efficient shape for
    # real ICI.
    state_shardings = _state_shardings(shardings)

    def optimize(vectors, counts, capacity, prices, feasible, logits0):
        state0 = lp_train_init(logits0)

        def body(state, _):
            state, loss = lp_train_step(
                state, vectors, counts, capacity, prices, feasible
            )
            return state, loss

        state, losses = jax.lax.scan(body, state0, None, length=steps)
        return state, losses[-1]

    optimize_jit = jax.jit(
        optimize,
        in_shardings=(
            shardings["vectors"],
            shardings["counts"],
            shardings["capacity"],
            shardings["prices"],
            shardings["logits"],
            shardings["logits"],
        ),
        out_shardings=(state_shardings, shardings["replicated"]),
    )
    state, _ = optimize_jit(vectors, counts, capacity, prices, feasible, logits0)

    masked = jnp.where(feasible, state.logits, -1e9)
    x = counts[:, None] * jax.nn.softmax(masked, axis=1)
    x = jnp.where(feasible, x, 0.0)
    demand = jnp.einsum("gt,gr->tr", x, vectors)
    nodes = jnp.max(demand / jnp.maximum(capacity, 1e-3), axis=1)
    return LPResult(assignment=x, fractional_nodes=nodes, objective=jnp.sum(prices * nodes))


def _pad(array, size, value):
    import numpy as np

    array = np.asarray(array)  # vet: host-array(padding runs on host inputs)
    if array.shape[0] >= size:
        return array
    widths = [(0, size - array.shape[0])] + [(0, 0)] * (array.ndim - 1)
    return np.pad(array, widths, constant_values=value)


# --- constrained [L, G, T] level sharding ------------------------------------
#
# The constrained pack dispatch (ops/pack_kernel.pack_kernel_levels) vmaps a
# sequential round loop over the relaxation-level axis. The round loops are
# lax.while_loop state machines — the same reason the PR 6/9 pack rounds
# replicate instead of sharding [G, T] — but LEVELS are embarrassingly
# parallel: each level is an independent solve over the same fleet. So the
# multi-chip lowering shards the L axis across every device of the
# ("groups", "types") mesh (both axes flattened), each chip solves its own
# levels, and the only collective is the tiny cross-level argmin + the
# chosen level's round-state gather at the tail. Decode is bit-identical to
# the single-device dispatch: the per-level math never sees the mesh.

_LEVEL_HOOK_CACHE: dict = {}


def constrained_level_sharding(mesh=None):
    """(constrain, shards) for pack_kernel_levels: `constrain` pins every
    [L, ...] operand's leading axis over the whole mesh; cached per device
    set so the jitted dispatch (which hashes the hook as a static arg)
    compiles once per mesh, not once per call."""
    from karpenter_tpu.parallel.mesh import GROUPS_AXIS, TYPES_AXIS

    mesh = mesh or make_mesh()
    if mesh is None or mesh.devices.size <= 1:
        return None, 1
    key = tuple(int(d.id) for d in mesh.devices.flat)
    cached = _LEVEL_HOOK_CACHE.get(key)
    if cached is None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(mesh, P((GROUPS_AXIS, TYPES_AXIS)))

        def constrain(x):
            return jax.lax.with_sharding_constraint(x, sharding)

        cached = (constrain, int(mesh.devices.size))
        _LEVEL_HOOK_CACHE[key] = cached
    return cached
