"""ApiServerCluster — the Cluster verb set against a real kube-apiserver.

Ref: pkg/controllers/manager.go:33-66 — controller-runtime gives the
reference cached reads (informers), direct writes, and watch-driven
reconciles. This class is that architecture on our verb surface:

- READS come from the inherited in-memory Cluster, which acts as the
  informer cache. Watch pump threads keep it synced with the apiserver.
- WRITES go through to the apiserver REST API first (binding and eviction
  use their subresources, exactly the RPCs the reference issues), then
  update the cache so same-thread read-after-write is consistent — the
  watch event that follows is deduplicated by resourceVersion.
- The leader-election lease is a real coordination.k8s.io/v1 Lease with
  optimistic-concurrency CAS, so mutual exclusion spans every replica
  (cmd/controller/main.go:80-81).

Controllers cannot tell the backends apart; the test suites run against
both (tests/test_backend_parity.py).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import Provisioner
from karpenter_tpu.cloudprovider import NodeSpec
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.controllers.errors import PDBViolationError
from karpenter_tpu.utils.metrics import REGISTRY
from karpenter_tpu.kubeapi import convert
from karpenter_tpu.kubeapi.client import ApiError, KubeClient, critical_lane
from karpenter_tpu.utils import faultpoints
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.clock import Clock

log = klog.named("kubeapi")

PODS = "/api/v1/pods"
NODES = "/api/v1/nodes"
DAEMONSETS = "/apis/apps/v1/daemonsets"
PROVISIONERS = f"/apis/{convert.GROUP}/{convert.VERSION}/provisioners"
LEASES = "/apis/coordination.k8s.io/v1/namespaces/kube-system/leases"
PDBS = "/apis/policy/v1/namespaces/default/poddisruptionbudgets"


def _pod_path(namespace: str, name: str = "") -> str:
    base = f"/api/v1/namespaces/{namespace}/pods"
    return f"{base}/{name}" if name else base


# Watch-plane health: a re-list means a watch gap outlived the apiserver's
# history window (410 Gone) — rare in steady state; a rising rate signals
# network trouble or an undersized watch cache.
WATCH_RELIST_TOTAL = REGISTRY.counter(
    "watch_relist_total", "410-triggered re-LISTs per resource kind", ["kind"]
)


class ApiServerCluster(Cluster):
    """The in-memory Cluster as informer cache + write-through REST verbs."""

    WATCHES = (
        ("pod", PODS),
        ("node", NODES),
        ("provisioner", PROVISIONERS),
        ("daemonset", DAEMONSETS),
    )

    # How long a deletion tombstone suppresses late events for its key.
    # Must exceed any plausible delivery delay of an in-flight stale event
    # (watch replays after reconnects); pruned opportunistically on delete.
    TOMBSTONE_TTL_S = 120.0

    def __init__(self, client: KubeClient, clock: Optional[Clock] = None):
        super().__init__(clock)
        self.api = client
        self._rv: Dict[Tuple[str, object], int] = {}  # vet: guarded-by(self._rv_lock)
        # Deletion tombstones: key -> (deletion rv, monotonic stamp). A
        # deleted key's rv entry can't just be popped — a stale MODIFIED
        # replayed after the DELETED event would pass _newer and resurrect
        # the object in the cache (the client-go informer solves this with
        # DeletedFinalStateUnknown tombstones).
        self._tombstones: Dict[Tuple[str, object], Tuple[int, float]] = {}  # vet: guarded-by(self._rv_lock)
        self._rv_lock = threading.Lock()
        # Serializes the PDB gate + displacement write (reschedule_pod):
        # the interruption and consolidation drain loops displace
        # concurrently, and two gates passing on the same budget instant
        # would jointly overspend it.
        self._disruption_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list = []
        self.resync_count = 0  # 410-triggered re-LISTs (observability + tests)
        # On this backend the inherited store is ONLY the informer cache —
        # the watch pump must keep syncing it even for a deposed leader —
        # so the write fence moves from the base verbs to the write-through
        # verbs below (checked before the remote call goes out).
        self._fence_is_store = False

    # --- lifecycle ----------------------------------------------------------

    def start(self) -> "ApiServerCluster":
        """Initial LIST of every watched resource, then start watch pumps.
        Controllers constructed after start() see a warm cache. Each watch
        resumes from its LIST's collection resourceVersion so no event in
        the list-to-watch window is lost (the client-go reflector contract,
        ref: pkg/controllers/manager.go:33-40 via controller-runtime)."""
        for kind, path in self.WATCHES:
            items, rv = self.api.list_with_rv(path)
            for obj in items:
                self._apply_remote(kind, obj)
            thread = threading.Thread(  # vet: fence-exempt(informer sync: pump writes land in the cache only — _fence_is_store is False, the write-through verbs fence directly — and a deposed leader MUST keep its cache syncing)
                target=self._pump,
                args=(kind, path, rv),
                name=f"watch-{kind}",
                daemon=True,
            )
            thread.start()
            self._threads.append(thread)
        # PDBs seed from the server too: a RESTARTED controller that only
        # re-listed pods/nodes would hold an empty budget table, and every
        # post-restart drain would displace unbudgeted (the market-storm
        # smoke caught exactly this — one interruption sweep took all four
        # replicas behind a PDB down at once).
        for item in self.api.list(PDBS):
            spec = item.get("spec") or {}
            selector = (spec.get("selector") or {}).get("matchLabels") or {}
            Cluster.apply_pdb(
                self,
                (item.get("metadata") or {}).get("name", ""),
                selector,
                int(spec.get("minAvailable", 0)),
            )
        return self

    def close(self) -> None:
        self._stop.set()
        self.api.transport.close()  # unblock watch streams
        for thread in self._threads:
            thread.join(timeout=2.0)
        self._threads.clear()

    def _pump(self, kind: str, path: str, resource_version: str) -> None:
        self.api.watch(
            path,
            lambda event_type, obj: self._on_watch(kind, event_type, obj),
            self._stop,
            resource_version=resource_version,
            relist=lambda: self._relist(kind, path),
        )

    def _relist(self, kind: str, path: str) -> str:
        """410-recovery: replace the cache snapshot for `kind` from a fresh
        LIST — apply every live object, delete cached objects that vanished
        during the watch gap — and return the new collection rv to resume
        the watch from."""
        items, rv = self.api.list_with_rv(path)
        live = {self._key(kind, obj) for obj in items}
        try:
            list_rv = int(rv)
        except (TypeError, ValueError):
            list_rv = 0
        with self._lock:
            if kind == "pod":
                cached = list(self._pods.keys())
            elif kind == "node":
                cached = list(self._nodes.keys())
            elif kind == "provisioner":
                cached = list(self._provisioners.keys())
            else:
                cached = list(self._daemonsets.keys())
        for key in cached:
            if key in live:
                continue
            # The guard and the removal must be one atomic step: holding
            # _rv_lock across both means a write-through re-create either
            # fully precedes the guard (its newer rv skips the sweep) or
            # blocks at _record_rv until the sweep is done and then
            # re-inserts the object — no interleaving can delete a live
            # object and then have its watch replay suppressed by _newer.
            with self._rv_lock:
                # Write-through can land an object between our LIST and this
                # sweep; its rv is newer than the LIST's collection rv, so it
                # is not a ghost — leave it for the resumed watch to confirm.
                if list_rv and self._rv.get((kind, key), 0) > list_rv:
                    continue
                # Tombstone at the LIST's rv: any event predating the LIST
                # is a stale replay of this vanished object.
                self._entomb_locked((kind, key), list_rv)
                if kind == "pod":
                    namespace, name = key
                    ghost = {"metadata": {"namespace": namespace, "name": name}}
                else:
                    ghost = {"metadata": {"name": key}}
                self._remove_local(kind, ghost)
        for obj in items:
            # Gate on rv like _on_watch does: a write-through landing between
            # our LIST and this apply has a newer rv, and overwriting it with
            # the LIST's older copy would stick (the watch echo of the newer
            # write is deduplicated by _newer).
            if self._newer(kind, obj):
                self._apply_remote(kind, obj)
        self.resync_count += 1
        WATCH_RELIST_TOTAL.inc(kind)
        log.warning("watch for %s expired (410); re-listed %d objects", kind, len(items))
        return rv

    # --- cache application ---------------------------------------------------

    @staticmethod
    def _key(kind: str, obj: dict):
        metadata = obj.get("metadata") or {}
        if kind == "pod":
            return (metadata.get("namespace", "default"), metadata.get("name", ""))
        return metadata.get("name", "")

    def _newer(self, kind: str, obj: dict) -> bool:
        """resourceVersion gate: a watch event at-or-below what write-through
        already put in the cache is an echo of our own write — skipping it
        keeps cached object INSTANCES stable (controllers and tests hold
        references), while genuinely external changes (higher rv) re-sync.
        Events at-or-below a deletion tombstone are stale replays of a dead
        object and must not resurrect it."""
        metadata = obj.get("metadata") or {}
        try:
            rv = int(metadata.get("resourceVersion", 0))
        except (TypeError, ValueError):
            return True
        key = (kind, self._key(kind, obj))
        # Locked check-then-set: watch pumps and write-through callers (incl.
        # the bind fan-out) race on this dict; unlocked, an older event could
        # be applied after a newer one.
        with self._rv_lock:
            tombstone = self._tombstones.get(key)
            if tombstone is not None:
                if rv <= tombstone[0]:
                    return False
                self._tombstones.pop(key, None)  # genuine re-creation
            if rv <= self._rv.get(key, 0):
                return False
            self._rv[key] = rv
        return True

    def _entomb_locked(self, key, rv: int) -> None:
        """Record a deletion tombstone (caller holds _rv_lock). The rv map
        entry goes with the object (pod churn must not leak an entry per pod
        ever observed); the tombstone carries the deletion rv forward for
        TOMBSTONE_TTL_S so late replays can't resurrect the object, and the
        TTL bounds the tombstone map the same way popping bounded _rv.

        Prune cost: insertion order IS stamp order (appended with a fresh
        monotonic stamp), so expiry pops from the front and stops at the
        first live entry — O(expired) per delete, never a full scan."""
        now = self.clock.monotonic()
        self._rv.pop(key, None)
        cutoff = now - self.TOMBSTONE_TTL_S
        while self._tombstones:
            oldest = next(iter(self._tombstones))
            if self._tombstones[oldest][1] >= cutoff:
                break
            del self._tombstones[oldest]
        # Re-entombing an existing key must keep stamp order (drop the old
        # slot so the new entry appends at the back) and must NEVER lower
        # the rv — a stale replayed DELETED of an older incarnation would
        # otherwise reopen the gate for stale events of a newer one.
        old = self._tombstones.pop(key, None)
        if old is not None and old[0] > rv:
            rv = old[0]
        self._tombstones[key] = (rv, now)

    def _on_watch(self, kind: str, event_type: str, obj: dict) -> None:
        try:
            if event_type == "DELETED":
                key = (kind, self._key(kind, obj))
                metadata = obj.get("metadata") or {}
                try:
                    delete_rv = int(metadata.get("resourceVersion", 0))
                except (TypeError, ValueError):
                    delete_rv = 0
                with self._rv_lock:
                    # DELETED needs the same staleness gate as every other
                    # event: a replayed DELETED of a PRIOR incarnation must
                    # not evict a live re-created object (cache rv newer)
                    # nor lower an existing tombstone.
                    tombstone = self._tombstones.get(key)
                    if (
                        delete_rv
                        and tombstone is not None
                        and delete_rv <= tombstone[0]
                    ):
                        return  # replay of a deletion already tombstoned
                    if delete_rv and delete_rv < self._rv.get(key, 0):
                        return  # the live object is a newer incarnation
                    # The DELETED event's rv is >= every prior event of the
                    # object; fall back to the last rv we applied.
                    self._entomb_locked(
                        key, max(delete_rv, self._rv.get(key, 0))
                    )
                self._remove_local(kind, obj)
            elif self._newer(kind, obj):
                self._apply_remote(kind, obj)
        except Exception:  # noqa: BLE001 — one bad event must not kill the pump
            log.exception("applying %s %s event failed", kind, event_type)

    def _apply_remote(self, kind: str, obj: dict) -> None:
        self._newer(kind, obj)  # record rv on initial LIST too
        if kind == "pod":
            super().apply_pod(convert.pod_from_kube(obj))
        elif kind == "node":
            node = convert.node_from_kube(obj)
            existing = super().try_get_node(node.name)
            if existing is None or node.deletion_timestamp is None:
                super().apply_node(node)
            else:
                # Deletion flows through the finalizer protocol locally too.
                existing.deletion_timestamp = node.deletion_timestamp
                existing.finalizers = node.finalizers
                super().update_node(existing)
        elif kind == "provisioner":
            super().apply_provisioner(convert.provisioner_from_kube(obj))
        elif kind == "daemonset":
            metadata = obj.get("metadata") or {}
            super().apply_daemonset(
                metadata.get("name", ""), convert.daemonset_template_from_kube(obj)
            )

    def _remove_local(self, kind: str, obj: dict) -> None:
        key = self._key(kind, obj)
        if kind == "pod":
            super().delete_pod(*key)
        elif kind == "node":
            with self._lock:
                node = self._nodes.pop(key, None)
            if node is not None:
                self._notify("node", node, verb="delete")
        elif kind == "provisioner":
            with self._lock:
                provisioner = self._provisioners.pop(key, None)
            if provisioner is not None:
                provisioner.deletion_timestamp = (
                    provisioner.deletion_timestamp or self.clock.now()
                )
                self._notify("provisioner", provisioner, verb="delete")
        elif kind == "daemonset":
            with self._lock:
                self._daemonsets.pop(key, None)

    def _record_rv(self, kind: str, obj: dict) -> None:
        self._newer(kind, obj)

    # --- pods ---------------------------------------------------------------

    def _create_or_update(self, collection_path: str, obj_path: str, body: dict):
        """Create-first apply: POST, and only on 409 (already exists) GET the
        current resourceVersion and PUT. The common case (new object — every
        pod of a storm) is one RPC instead of the GET-then-POST pair, which
        at 10k-pod scale halves the write-plane round trips."""
        try:
            return self.api.create(collection_path, body)
        except ApiError as error:
            if error.status != 409:
                raise
        existing = self.api.try_get(obj_path)
        if existing is None:
            # Deleted between our 409 and the GET: retry the create once.
            return self.api.create(collection_path, body)
        body.setdefault("metadata", {})["resourceVersion"] = (
            existing.get("metadata", {}).get("resourceVersion")
        )
        return self.api.update(obj_path, body)

    def apply_pod(self, pod: PodSpec) -> PodSpec:
        self.fence.check("apply_pod")
        created = self._create_or_update(
            _pod_path(pod.namespace),
            _pod_path(pod.namespace, pod.name),
            convert.pod_to_kube(pod),
        )
        self._record_rv("pod", created)
        return super().apply_pod(pod)

    def bind_pod(self, pod: PodSpec, node: NodeSpec) -> None:
        self.fence.check("bind_pod")
        # The actual Binding RPC the reference issues per pod
        # (provisioner.go:239-247 → coreV1Client.Pods(...).Bind).
        try:
            self.api.create(
                _pod_path(pod.namespace, pod.name) + "/binding",
                {
                    "apiVersion": "v1",
                    "kind": "Binding",
                    "metadata": {"name": pod.name, "namespace": pod.namespace},
                    "target": {"kind": "Node", "name": node.name},
                },
            )
        except ApiError as error:
            if error.status != 409:
                raise
            # 409 "already bound": either the retry envelope re-POSTed a
            # Binding whose first attempt committed (response lost to a
            # timeout), or a rival bound the pod first. Ask the server WHOSE
            # bind won — ours is a success, anyone else's stays a conflict.
            live = self.api.try_get(_pod_path(pod.namespace, pod.name))
            bound_to = ((live or {}).get("spec") or {}).get("nodeName")
            if bound_to != node.name:
                raise
        super().bind_pod(pod, node)

    def delete_pod(
        self, namespace: str, name: str, uid: Optional[str] = None
    ) -> bool:
        self.fence.check("delete_pod")
        try:
            self.api.delete(_pod_path(namespace, name), uid=uid)
        except ApiError as error:
            if error.status == 409 and uid:
                # UID precondition failed: the name now belongs to a new
                # incarnation — the pod the caller observed is already gone.
                return False
            if error.status != 404:
                raise
            super().delete_pod(namespace, name, uid=uid)
            return False  # someone else already deleted it
        super().delete_pod(namespace, name, uid=uid)
        return True

    def evict_pod(self, namespace: str, name: str) -> None:
        """POST the Eviction subresource; the apiserver enforces PDBs and
        answers 429 (ref: termination/eviction.go:90-109)."""
        self.fence.check("evict_pod")
        try:
            self.api.create(
                _pod_path(namespace, name) + "/eviction",
                {
                    "apiVersion": "policy/v1",
                    "kind": "Eviction",
                    "metadata": {"name": name, "namespace": namespace},
                },
            )
        except ApiError as error:
            if error.status == 429 or error.status == 500:
                raise PDBViolationError(f"pod {namespace}/{name} blocked by PDB")
            if error.status == 404:
                return
            raise
        pod = super().try_get_pod(namespace, name)
        if pod is not None:
            pod.deletion_timestamp = self.clock.now()
            self._notify("pod", pod, verb="update")

    def reschedule_pod(self, namespace: str, name: str, override_pdb: bool = False):
        self.fence.check("reschedule_pod")
        # One displacement in flight at a time: the server-truth gate below
        # reads a fresh LIST, and two concurrent drains passing on the same
        # healthy count would jointly overspend the budget. The gate runs
        # ONLY here, on the actual displacement — nomination pre-checks
        # (consolidation's _drainable_pods) keep the cache-based
        # _pdb_allows, or every sweep would pay O(candidates x pods) full
        # server LISTs.
        with self._disruption_lock:
            if not override_pdb:
                pod = self.try_get_pod(namespace, name)
                if (
                    pod is not None
                    and pod.node_name is not None
                    and not self._pdb_allows_server(pod)
                ):
                    from karpenter_tpu.controllers.errors import PDBViolationError

                    raise PDBViolationError(
                        f"pod {namespace}/{name} blocked by PDB"
                    )
            return super().reschedule_pod(namespace, name, override_pdb)

    def _pdb_allows_server(self, pod) -> bool:
        """Server-truth budget check — the displacement analogue of the
        server-gated Eviction subresource. The cache-based _pdb_allows
        rides the chaos-mangled watch streams: a duplicated/reordered event
        from BEFORE a displacement can resurrect the victim's bound state,
        the stale count over-reports, and one polite drain sweep displaces
        every replica behind the PDB (the market-storm smoke caught exactly
        this). So the budget is counted from a fresh server LIST — the
        un-mangled truth — with the victim's own bound state read from the
        same snapshot."""
        with self._lock:
            pdbs = list(self._pdbs.values())
        matching = [
            (labels, min_available)
            for labels, min_available in pdbs
            if all(pod.labels.get(k) == v for k, v in labels.items())
        ]
        if not matching:
            return True
        healthy_labels, victim_counts = self._server_healthy_pods(pod)
        for match_labels, min_available in matching:
            healthy = sum(
                1
                for labels in healthy_labels
                if all(labels.get(k) == v for k, v in match_labels.items())
            )
            if healthy - (1 if victim_counts else 0) < min_available:
                return False
        return True

    def _server_healthy_pods(self, victim):
        """One fresh server LIST -> (label dicts of every healthy BOUND
        non-terminating pod, whether the victim itself is among them)."""
        victim_counts = False
        healthy_labels = []
        for item in self.api.list(PODS):
            meta = item.get("metadata") or {}
            if meta.get("deletionTimestamp"):
                continue
            if not (item.get("spec") or {}).get("nodeName"):
                continue
            healthy_labels.append(meta.get("labels") or {})
            if (
                meta.get("namespace", "default") == victim.namespace
                and meta.get("name") == victim.name
            ):
                victim_counts = True
        return healthy_labels, victim_counts

    def _reschedule_local(self, namespace: str, name: str):
        """Write-through displacement: clear spec.nodeName (merge-patch null
        removes the key), restore the Unschedulable condition so a re-list
        sees the pod as provisionable again, and persist the bumped
        reschedule epoch (launch-identity input); then update the cache. The
        PDB gate already ran in reschedule_pod against the SERVER's pod list
        (_pdb_allows above; PDBs write through both sides)."""
        from karpenter_tpu.controllers.cluster import reschedule_epoch

        pod = self.try_get_pod(namespace, name)
        epoch = reschedule_epoch(pod) + 1 if pod is not None else 1
        try:
            updated = self.api.patch(
                _pod_path(namespace, name),
                {
                    "metadata": {
                        "annotations": {
                            wellknown.RESCHEDULE_EPOCH_ANNOTATION: str(epoch)
                        }
                    },
                    "spec": {"nodeName": None},
                    "status": {
                        "conditions": [
                            {
                                "type": "PodScheduled",
                                "status": "False",
                                "reason": "Unschedulable",
                            }
                        ]
                    },
                },
            )
            self._record_rv("pod", updated)
        except ApiError as error:
            if error.status != 404:
                raise
        return super()._reschedule_local(namespace, name)

    def apply_pdb(self, name: str, match_labels, min_available: int):
        self.fence.check("apply_pdb")
        path = "/apis/policy/v1/namespaces/default/poddisruptionbudgets"
        body = {
            "apiVersion": "policy/v1",
            "kind": "PodDisruptionBudget",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {
                "minAvailable": min_available,
                "selector": {"matchLabels": dict(match_labels)},
            },
        }
        self._create_or_update(path, f"{path}/{name}", body)
        super().apply_pdb(name, match_labels, min_available)

    # --- nodes --------------------------------------------------------------

    def create_node(self, node: NodeSpec) -> NodeSpec:
        self.fence.check("create_node")
        if not node.created_at:
            node.created_at = self.clock.now()
        # The apiserver is the strictness authority here (duplicate names
        # come back as ApiError 409 from the create); the local cache update
        # is an upsert so a watch event racing our own write can't trip the
        # in-memory duplicate check.
        try:
            created = self.api.create(NODES, convert.node_to_kube(node))
        except ApiError as error:
            if error.status != 409:
                raise
            # Verify the conflict before letting it become the adoption
            # signal upstream: a 409 for a node a GET cannot find is either
            # a conflict-storm artifact or a delete racing our create —
            # adopting a ghost would bind pods to a node that doesn't
            # exist. Retry the create once; a REAL AlreadyExists re-raises.
            if self.api.try_get(f"{NODES}/{node.name}") is not None:
                raise
            created = self.api.create(NODES, convert.node_to_kube(node))
        self._record_rv("node", created)
        return super().apply_node(node)

    def update_node(self, node: NodeSpec) -> None:
        self.fence.check("update_node")
        # PATCH (merge) only the fields controllers own; a full PUT would
        # clobber concurrent kubelet status updates.
        patch = {
            "metadata": {
                "labels": dict(node.labels),
                "annotations": dict(node.annotations),
                "finalizers": list(node.finalizers),
            },
            "spec": {
                "unschedulable": node.unschedulable,
                "taints": [
                    {"key": t.key, "value": t.value, "effect": t.effect}
                    for t in node.taints
                ],
            },
        }
        try:
            updated = self.api.patch(f"{NODES}/{node.name}", patch)
            self._record_rv("node", updated)
        except ApiError as error:
            if error.status != 404:
                raise
        super().update_node(node)

    def heartbeat_node(self, name: str, ready: bool = True):
        # Status-only merge-patch — the write a real kubelet's status loop
        # issues. Deliberately disjoint from update_node's metadata/spec
        # patch so neither side clobbers the other. Unfenced (see base):
        # the reporter is the node, not the controller leader. Critical
        # lane: a heartbeat parked behind a bulk bind storm reads as a
        # gone-dark node and trips the health ladder for no reason.
        try:
            with critical_lane():
                updated = self.api.patch(
                    f"{NODES}/{name}",
                    {
                        "status": {
                            "conditions": [
                                {
                                    "type": "Ready",
                                    "status": "True" if ready else "False",
                                    "lastHeartbeatTime": convert.rfc3339(
                                        self.clock.now()
                                    ),
                                }
                            ]
                        }
                    },
                )
            self._record_rv("node", updated)
        except ApiError as error:
            if error.status != 404:
                raise
            return None
        return super().heartbeat_node(name, ready)

    def remove_node_annotation(self, node: NodeSpec, key: str) -> None:
        self.fence.check("remove_node_annotation")
        # Merge-patch null is the only way to DELETE a key server-side
        # (RFC 7386); sending the remaining map would leave it in place and
        # the watch pump would resurrect it into the cache.
        try:
            updated = self.api.patch(
                f"{NODES}/{node.name}", {"metadata": {"annotations": {key: None}}}
            )
            self._record_rv("node", updated)
        except ApiError as error:
            if error.status != 404:
                raise
        super().remove_node_annotation(node, key)

    def delete_node(self, name: str) -> None:
        self.fence.check("delete_node")
        # Critical lane (with remove_finalizer below): the drain path's
        # teardown verbs — parking them behind a bulk storm holds reclaimed
        # capacity (and its cost) alive for the storm's duration.
        try:
            with critical_lane():
                self.api.delete(f"{NODES}/{name}")
        except ApiError as error:
            if error.status != 404:
                raise
        super().delete_node(name)

    def remove_finalizer(self, node: NodeSpec, finalizer: str) -> None:
        self.fence.check("remove_finalizer")
        remaining = [f for f in node.finalizers if f != finalizer]
        try:
            with critical_lane():
                updated = self.api.patch(
                    f"{NODES}/{node.name}",
                    {"metadata": {"finalizers": remaining}},
                )
            self._record_rv("node", updated)
        except ApiError as error:
            if error.status != 404:
                raise
        super().remove_finalizer(node, finalizer)

    # --- provisioners --------------------------------------------------------

    def apply_provisioner(self, provisioner: Provisioner) -> Provisioner:
        self.fence.check("apply_provisioner")
        created = self._create_or_update(
            PROVISIONERS,
            f"{PROVISIONERS}/{provisioner.name}",
            convert.provisioner_to_kube(provisioner),
        )
        self._record_rv("provisioner", created)
        return super().apply_provisioner(provisioner)

    def update_provisioner_status(self, provisioner: Provisioner) -> None:
        self.fence.check("update_provisioner_status")
        status = convert.provisioner_to_kube(provisioner).get("status", {})
        try:
            updated = self.api.patch(
                f"{PROVISIONERS}/{provisioner.name}/status", {"status": status}
            )
            self._record_rv("provisioner", updated)
        except ApiError as error:
            if error.status != 404:
                raise
        super().update_provisioner_status(provisioner)

    def delete_provisioner(self, name: str) -> None:
        self.fence.check("delete_provisioner")
        try:
            self.api.delete(f"{PROVISIONERS}/{name}")
        except ApiError as error:
            if error.status != 404:
                raise
        super().delete_provisioner(name)

    # --- daemonsets -----------------------------------------------------------

    def apply_daemonset(self, name: str, pod_template: PodSpec) -> None:
        self.fence.check("apply_daemonset")
        body = {
            "apiVersion": "apps/v1",
            "kind": "DaemonSet",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"template": convert.pod_to_kube(pod_template)},
        }
        path = f"{DAEMONSETS.replace('/daemonsets', '')}/namespaces/default/daemonsets"
        self._create_or_update(path, f"{path}/{name}", body)
        super().apply_daemonset(name, pod_template)

    # --- leases ---------------------------------------------------------------

    def acquire_lease(
        self,
        name: str,
        holder: str,
        duration_s: float,
        *,
        transitions: Optional[int] = None,
    ) -> int:
        """CAS over a real coordination.k8s.io Lease: optimistic-concurrency
        update keyed on resourceVersion; a 409 means a rival won the race.

        Returns the committed ``leaseTransitions`` (the fencing generation,
        bumped only on holder change) or 0 on a lost CAS, mirroring the
        server's counter into the in-memory cache so both backends report
        the identical generation. The ``lease.cas`` faultpoint flaps this
        verb for the chaos smokes: ``conflict`` loses the CAS outright,
        ``commit-lost`` performs the server write but reports it lost —
        the split-brain seed, which the next campaign must absorb by
        observing itself as holder WITHOUT a transitions bump.
        """
        fault = faultpoints.draw("lease.cas")
        if fault is not None and fault.kind == "conflict":
            return 0
        commit_lost = fault is not None and fault.kind == "commit-lost"
        # Critical lane for the whole read-CAS round: a lease renew queued
        # behind a bulk LIST/bind storm past the TTL deposes the leader —
        # the exact failure the reserved token budget exists to prevent.
        with critical_lane():
            now = self.clock.now()
            current = self.api.try_get(f"{LEASES}/{name}")
            if current is None:
                committed = int(transitions) if transitions is not None else 1
                won = self._lease_create(name, holder, duration_s, now, committed)
            else:
                committed = self._lease_next_transitions(
                    current, holder, now, transitions
                )
                won = committed > 0 and self._lease_update(
                    name, holder, duration_s, now, committed, current
                )
        if not won or commit_lost:
            return 0
        return super().acquire_lease(name, holder, duration_s, transitions=committed)

    def _lease_create(self, name, holder, duration_s, now, committed) -> bool:
        try:
            self.api.create(
                LEASES,
                convert.lease_to_kube(name, holder, duration_s, now, committed),
            )
        except ApiError as error:
            if error.status == 409:
                return False
            raise
        return True

    def _lease_next_transitions(self, current, holder, now, transitions):
        """The generation this acquire would commit, or 0 when the CAS is
        already lost (a rival holds an unexpired term)."""
        state = convert.lease_from_kube(current)
        # A vacated Lease (released holder) still carries its counter; read
        # it from the raw spec so the next generation doesn't restart at 1.
        prior_transitions = int(
            (current.get("spec") or {}).get("leaseTransitions", 0)
        )
        same_holder = False
        if state is not None:
            current_holder, renew, held_duration, prior_transitions = state
            if current_holder != holder and now < renew + held_duration:
                return 0
            same_holder = current_holder == holder
        if transitions is not None:
            return int(transitions)
        return prior_transitions if same_holder else prior_transitions + 1

    def _lease_update(
        self, name, holder, duration_s, now, committed, current
    ) -> bool:
        body = convert.lease_to_kube(name, holder, duration_s, now, committed)
        body["metadata"]["resourceVersion"] = current.get("metadata", {}).get(
            "resourceVersion"
        )
        try:
            self.api.update(f"{LEASES}/{name}", body)
        except ApiError as error:
            if error.status == 409:
                return False  # rival CAS'd first
            raise
        return True

    def release_lease(self, name: str, holder: str) -> bool:
        path = f"{LEASES}/{name}"
        with critical_lane():
            current = self.api.try_get(path)
            state = convert.lease_from_kube(current) if current else None
            if state is None or state[0] != holder:
                return False
            # Vacate by clearing holderIdentity instead of deleting the
            # object: leaseTransitions must survive a voluntary release, or
            # the next holder's generation would alias the first one's
            # fence token.
            body = convert.lease_to_kube(name, "", 0, self.clock.now(), state[3])
            body["metadata"]["resourceVersion"] = current.get("metadata", {}).get(
                "resourceVersion"
            )
            try:
                self.api.update(path, body)
            except ApiError as error:
                if error.status not in (404, 409):
                    raise
        return super().release_lease(name, holder)

    def get_lease(self, name: str):
        with critical_lane():
            current = self.api.try_get(f"{LEASES}/{name}")
        state = convert.lease_from_kube(current) if current else None
        if state is None:
            return None
        holder, renew, duration, lease_transitions = state
        if self.clock.now() >= renew + duration:
            return None
        return holder, renew + duration, lease_transitions
