"""Kubernetes apiserver backend for the cluster store.

Ref: pkg/controllers/manager.go:33-66 + cmd/controller/main.go:61-99 — the
reference's controllers reconcile a live apiserver through controller-runtime
(informer cache for reads, direct client writes, watch-driven requeues).
This package is that architecture for the TPU rebuild: `ApiServerCluster`
mirrors watched objects into the in-memory `Cluster` (the informer cache),
writes through to the apiserver REST API, and pumps watch streams so the
runtime's reconcile loops fire on live cluster changes. The in-memory store
stays the envtest analogue for tests; production selects the backend with
--kube-api-server (cmd/controller.py).
"""

from karpenter_tpu.kubeapi.client import (
    ApiError,
    KubeClient,
    RetryPolicy,
    Transport,
    TransportError,
    critical_lane,
)
from karpenter_tpu.kubeapi.cluster import ApiServerCluster

__all__ = [
    "ApiError",
    "ApiServerCluster",
    "KubeClient",
    "RetryPolicy",
    "Transport",
    "TransportError",
    "critical_lane",
]
