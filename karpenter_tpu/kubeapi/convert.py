"""Kubernetes API object ↔ framework dataclass converters.

Ref: the reference operates directly on client-go typed objects; our
controllers operate on the trimmed dataclasses in api/pods.py and
cloudprovider.NodeSpec. These converters are the boundary: kube Pod/Node/
DaemonSet/Lease JSON (what an apiserver serves) to and from those
dataclasses, with the same semantics the reference reads —
requests folded per pkg/utils/resources (max(init) ⌄ sum(containers)),
unschedulable from the PodScheduled condition, node identity labels from the
well-known keys.
"""

from __future__ import annotations

import datetime
from typing import Dict, List, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec, PreferredTerm, TopologySpreadConstraint
from karpenter_tpu.api.provisioner import Provisioner
from karpenter_tpu.api.requirements import Requirement
from karpenter_tpu.api.resources import ResourceList, parse_resource_list
from karpenter_tpu.api.serialization import provisioner_from_dict, provisioner_to_dict
from karpenter_tpu.api.taints import Taint, Toleration
from karpenter_tpu.cloudprovider import NodeSpec

GROUP = "karpenter.tpu"
VERSION = "v1alpha1"

# kube well-known node labels (the apiserver-side spellings).
NODE_INSTANCE_TYPE_LABEL = "node.kubernetes.io/instance-type"


# --- time ------------------------------------------------------------------


def rfc3339(epoch: Optional[float]) -> Optional[str]:
    if epoch is None:
        return None
    return (
        datetime.datetime.fromtimestamp(epoch, tz=datetime.timezone.utc)
        .isoformat()
        .replace("+00:00", "Z")
    )


def from_rfc3339(text: Optional[str]) -> Optional[float]:
    if not text:
        return None
    return datetime.datetime.fromisoformat(text.replace("Z", "+00:00")).timestamp()


# --- quantities ------------------------------------------------------------


def quantity_str(resource: str, value: float) -> str:
    """Render a parsed quantity back into kube syntax: millicores for cpu,
    Mi for memory-sized byte counts, plain integers otherwise."""
    if resource == "cpu":
        millis = round(value * 1000)
        if millis % 1000 == 0:
            return str(millis // 1000)
        return f"{millis}m"
    if value >= 1024**2 and value % (1024**2) == 0:
        return f"{int(value // 1024**2)}Mi"
    if value == int(value):
        return str(int(value))
    return repr(value)


def resource_list_to_kube(resources: ResourceList) -> Dict[str, str]:
    # NodeSpec.capacity may carry raw quantity strings (callers pass them
    # through unparsed); normalize before rendering.
    return {
        key: quantity_str(key, parse_resource_list({key: value})[key])
        for key, value in resources.items()
    }


# --- requirements / affinity ----------------------------------------------


def _expr_to_requirement(expr: dict) -> Requirement:
    return Requirement(
        key=expr.get("key", ""),
        operator=expr.get("operator", "In"),
        values=tuple(expr.get("values", ())),
    )


def _requirement_to_expr(requirement: Requirement) -> dict:
    return {
        "key": requirement.key,
        "operator": requirement.operator,
        "values": list(requirement.values),
    }


# --- pods ------------------------------------------------------------------


def pod_requests(spec: dict) -> ResourceList:
    """Effective pod requests (ref: pkg/utils/resources RequestsForPods —
    per resource, max(any single init container, sum of app containers))."""
    totals: Dict[str, float] = {}
    for container in spec.get("containers", []) or []:
        requests = parse_resource_list(
            (container.get("resources") or {}).get("requests") or {}
        )
        for key, value in requests.items():
            totals[key] = totals.get(key, 0.0) + value
    for container in spec.get("initContainers", []) or []:
        requests = parse_resource_list(
            (container.get("resources") or {}).get("requests") or {}
        )
        for key, value in requests.items():
            totals[key] = max(totals.get(key, 0.0), value)
    return totals


def _node_affinity_from_kube(spec: dict):
    """(required_terms, match_fields_terms, preferred_terms) from the kube
    nodeAffinity stanza."""
    affinity = (spec.get("affinity") or {}).get("nodeAffinity") or {}
    required = affinity.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    required_terms: List[List[Requirement]] = []
    match_fields_terms: List[dict] = []
    for term in required.get("nodeSelectorTerms", []) or []:
        exprs = term.get("matchExpressions") or []
        if exprs:
            required_terms.append([_expr_to_requirement(e) for e in exprs])
        for field_expr in term.get("matchFields") or []:
            match_fields_terms.append(dict(field_expr))
    return required_terms, match_fields_terms, _preferred_terms_from_kube(affinity)


def _preferred_terms_from_kube(affinity: dict) -> List[PreferredTerm]:
    return [
        PreferredTerm(
            weight=int(item.get("weight", 1)),
            requirements=[
                _expr_to_requirement(e)
                for e in (item.get("preference") or {}).get("matchExpressions") or []
            ],
        )
        for item in affinity.get("preferredDuringSchedulingIgnoredDuringExecution")
        or []
    ]


def _pod_affinity_from_kube(spec: dict):
    """(pod_affinity_terms, pod_anti_affinity_terms) — raw kube term dicts,
    the scheduler consumes them directly."""
    pod_aff = (spec.get("affinity") or {}).get("podAffinity") or {}
    pod_anti = (spec.get("affinity") or {}).get("podAntiAffinity") or {}
    return (
        list(pod_aff.get("requiredDuringSchedulingIgnoredDuringExecution") or []),
        list(pod_anti.get("requiredDuringSchedulingIgnoredDuringExecution") or []),
    )


def _tolerations_from_kube(spec: dict) -> List[Toleration]:
    return [
        Toleration(
            key=t.get("key", ""),
            operator=t.get("operator", "Equal"),
            value=t.get("value", ""),
            effect=t.get("effect", ""),
        )
        for t in spec.get("tolerations", []) or []
    ]


def _topology_spread_from_kube(spec: dict) -> List[TopologySpreadConstraint]:
    return [
        TopologySpreadConstraint(
            max_skew=int(c.get("maxSkew", 1)),
            topology_key=c.get("topologyKey", ""),
            when_unsatisfiable=c.get("whenUnsatisfiable", "DoNotSchedule"),
            match_labels=dict(
                (c.get("labelSelector") or {}).get("matchLabels") or {}
            ),
        )
        for c in spec.get("topologySpreadConstraints", []) or []
    ]


def _unschedulable_from_kube(status: dict) -> bool:
    """The PodScheduled=False/Unschedulable condition the reference keys
    provisioning on."""
    for condition in status.get("conditions", []) or []:
        if (
            condition.get("type") == "PodScheduled"
            and condition.get("status") == "False"
            and condition.get("reason") == "Unschedulable"
        ):
            return True
    return False


def _owner_kind_from_kube(metadata: dict) -> Optional[str]:
    """The controlling owner's kind; first owner's kind as fallback."""
    owner_kind = None
    for owner in metadata.get("ownerReferences", []) or []:
        if owner.get("controller"):
            return owner.get("kind")
        owner_kind = owner_kind or owner.get("kind")
    return owner_kind


def pod_from_kube(obj: dict) -> PodSpec:
    metadata = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    required_terms, match_fields_terms, preferred_terms = _node_affinity_from_kube(spec)
    pod_affinity_terms, pod_anti_affinity_terms = _pod_affinity_from_kube(spec)

    pod = PodSpec(
        name=metadata.get("name", ""),
        namespace=metadata.get("namespace", "default"),
        labels=dict(metadata.get("labels") or {}),
        annotations=dict(metadata.get("annotations") or {}),
        requests=pod_requests(spec),
        node_selector=dict(spec.get("nodeSelector") or {}),
        required_terms=required_terms,
        match_fields_terms=match_fields_terms,
        preferred_terms=preferred_terms,
        tolerations=_tolerations_from_kube(spec),
        topology_spread=_topology_spread_from_kube(spec),
        pod_affinity_terms=pod_affinity_terms,
        pod_anti_affinity_terms=pod_anti_affinity_terms,
        owner_kind=_owner_kind_from_kube(metadata),
        priority_class_name=spec.get("priorityClassName", ""),
        phase=status.get("phase", "Pending"),
        node_name=spec.get("nodeName") or None,
        unschedulable=_unschedulable_from_kube(status),
        deletion_timestamp=from_rfc3339(metadata.get("deletionTimestamp")),
        created_at=from_rfc3339(metadata.get("creationTimestamp")),
    )
    if metadata.get("uid"):
        pod.uid = metadata["uid"]
    return pod


def _pod_metadata_to_kube(pod: PodSpec) -> dict:
    metadata: dict = {
        "name": pod.name,
        "namespace": pod.namespace,
        "uid": pod.uid,
        "labels": dict(pod.labels),
        "annotations": dict(pod.annotations),
    }
    if pod.owner_kind:
        metadata["ownerReferences"] = [
            {
                "apiVersion": "apps/v1",
                "kind": pod.owner_kind,
                "name": f"{pod.name}-owner",
                "controller": True,
            }
        ]
    if pod.deletion_timestamp is not None:
        metadata["deletionTimestamp"] = rfc3339(pod.deletion_timestamp)
    if pod.created_at is not None:
        metadata["creationTimestamp"] = rfc3339(pod.created_at)
    return metadata


def pod_to_kube(pod: PodSpec) -> dict:
    """PodSpec → kube Pod JSON (one synthetic container carrying the folded
    requests — enough for tests and tooling to seed an apiserver; production
    pods arrive from the apiserver, not from this direction)."""
    requests = {
        k: quantity_str(k, v)
        for k, v in pod.requests.items()
        if k != wellknown.RESOURCE_PODS
    }
    affinity: dict = {}
    node_affinity: dict = {}
    if pod.required_terms or pod.match_fields_terms:
        terms = [
            {"matchExpressions": [_requirement_to_expr(r) for r in term]}
            for term in pod.required_terms
        ]
        if pod.match_fields_terms:
            terms.append({"matchFields": [dict(t) for t in pod.match_fields_terms]})
        node_affinity["requiredDuringSchedulingIgnoredDuringExecution"] = {
            "nodeSelectorTerms": terms
        }
    if pod.preferred_terms:
        node_affinity["preferredDuringSchedulingIgnoredDuringExecution"] = [
            {
                "weight": term.weight,
                "preference": {
                    "matchExpressions": [
                        _requirement_to_expr(r) for r in term.requirements
                    ]
                },
            }
            for term in pod.preferred_terms
        ]
    if node_affinity:
        affinity["nodeAffinity"] = node_affinity
    if pod.pod_affinity_terms:
        affinity["podAffinity"] = {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                dict(t) for t in pod.pod_affinity_terms
            ]
        }
    if pod.pod_anti_affinity_terms:
        affinity["podAntiAffinity"] = {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                dict(t) for t in pod.pod_anti_affinity_terms
            ]
        }

    spec: dict = {
        "containers": [{"name": "main", "resources": {"requests": requests}}],
    }
    if pod.node_selector:
        spec["nodeSelector"] = dict(pod.node_selector)
    if affinity:
        spec["affinity"] = affinity
    if pod.tolerations:
        spec["tolerations"] = [
            {
                "key": t.key,
                "operator": t.operator,
                "value": t.value,
                "effect": t.effect,
            }
            for t in pod.tolerations
        ]
    if pod.topology_spread:
        spec["topologySpreadConstraints"] = [
            {
                "maxSkew": c.max_skew,
                "topologyKey": c.topology_key,
                "whenUnsatisfiable": c.when_unsatisfiable,
                "labelSelector": {"matchLabels": dict(c.match_labels)},
            }
            for c in pod.topology_spread
        ]
    if pod.priority_class_name:
        spec["priorityClassName"] = pod.priority_class_name
    if pod.node_name:
        spec["nodeName"] = pod.node_name

    metadata = _pod_metadata_to_kube(pod)

    status: dict = {"phase": pod.phase}
    if pod.unschedulable:
        status["conditions"] = [
            {
                "type": "PodScheduled",
                "status": "False",
                "reason": "Unschedulable",
            }
        ]
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": metadata,
        "spec": spec,
        "status": status,
    }


# --- nodes -----------------------------------------------------------------


def node_from_kube(obj: dict) -> NodeSpec:
    metadata = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    labels = dict(metadata.get("labels") or {})

    ready = False
    status_reported_at: Optional[float] = None
    for condition in status.get("conditions", []) or []:
        if condition.get("type") == "Ready":
            ready = condition.get("status") == "True"
            status_reported_at = from_rfc3339(
                condition.get("lastHeartbeatTime")
            ) or from_rfc3339(condition.get("lastTransitionTime"))

    return NodeSpec(
        name=metadata.get("name", ""),
        labels=labels,
        annotations=dict(metadata.get("annotations") or {}),
        taints=[
            Taint(
                key=t.get("key", ""),
                value=t.get("value", ""),
                effect=t.get("effect", "NoSchedule"),
            )
            for t in spec.get("taints", []) or []
        ],
        capacity=parse_resource_list(status.get("allocatable") or status.get("capacity") or {}),
        instance_type=labels.get(NODE_INSTANCE_TYPE_LABEL)
        or labels.get(wellknown.INSTANCE_TYPE_LABEL, ""),
        zone=labels.get(wellknown.ZONE_LABEL, ""),
        capacity_type=labels.get(wellknown.CAPACITY_TYPE_LABEL, ""),
        provider_id=spec.get("providerID", ""),
        ready=ready,
        unschedulable=bool(spec.get("unschedulable", False)),
        finalizers=list(metadata.get("finalizers") or []),
        created_at=from_rfc3339(metadata.get("creationTimestamp")) or 0.0,
        deletion_timestamp=from_rfc3339(metadata.get("deletionTimestamp")),
        status_reported_at=status_reported_at,
    )


def node_to_kube(node: NodeSpec) -> dict:
    labels = dict(node.labels)
    if node.instance_type:
        labels.setdefault(NODE_INSTANCE_TYPE_LABEL, node.instance_type)
        labels.setdefault(wellknown.INSTANCE_TYPE_LABEL, node.instance_type)
    if node.zone:
        labels.setdefault(wellknown.ZONE_LABEL, node.zone)
    if node.capacity_type:
        labels.setdefault(wellknown.CAPACITY_TYPE_LABEL, node.capacity_type)

    metadata: dict = {
        "name": node.name,
        "labels": labels,
        "annotations": dict(node.annotations),
        "finalizers": list(node.finalizers),
    }
    if node.created_at:
        metadata["creationTimestamp"] = rfc3339(node.created_at)
    if node.deletion_timestamp is not None:
        metadata["deletionTimestamp"] = rfc3339(node.deletion_timestamp)

    spec: dict = {}
    if node.taints:
        spec["taints"] = [
            {"key": t.key, "value": t.value, "effect": t.effect} for t in node.taints
        ]
    if node.unschedulable:
        spec["unschedulable"] = True
    if node.provider_id:
        spec["providerID"] = node.provider_id

    status: dict = {}
    if node.capacity:
        status["capacity"] = resource_list_to_kube(node.capacity)
        status["allocatable"] = resource_list_to_kube(node.capacity)
    conditions = [
        {
            "type": "Ready",
            "status": "True" if node.ready else "False",
        }
    ]
    if node.status_reported_at is not None:
        conditions[0]["lastHeartbeatTime"] = rfc3339(node.status_reported_at)
    status["conditions"] = conditions

    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": metadata,
        "spec": spec,
        "status": status,
    }


# --- provisioners (CRD) ----------------------------------------------------


def provisioner_from_kube(obj: dict) -> Provisioner:
    """The CRD schema matches api/serialization.py field-for-field (see
    deploy/crds) — only the envelope differs."""
    provisioner = provisioner_from_dict(obj)
    metadata = obj.get("metadata") or {}
    provisioner.deletion_timestamp = from_rfc3339(metadata.get("deletionTimestamp"))
    return provisioner


def provisioner_to_kube(provisioner: Provisioner) -> dict:
    obj = provisioner_to_dict(provisioner)
    obj["apiVersion"] = f"{GROUP}/{VERSION}"
    obj["kind"] = "Provisioner"
    if provisioner.deletion_timestamp is not None:
        obj.setdefault("metadata", {})["deletionTimestamp"] = rfc3339(
            provisioner.deletion_timestamp
        )
    return obj


# --- daemonsets ------------------------------------------------------------


def daemonset_template_from_kube(obj: dict) -> PodSpec:
    """DaemonSet → its pod template as a PodSpec (the scheduler only needs
    the template's requests/constraints for overhead reservation,
    ref: binpacking/packer.go getDaemons:144-158)."""
    metadata = obj.get("metadata") or {}
    template = ((obj.get("spec") or {}).get("template")) or {}
    pod = pod_from_kube(
        {
            "metadata": {
                "name": f"{metadata.get('name', 'daemonset')}-template",
                "namespace": metadata.get("namespace", "default"),
                **(template.get("metadata") or {}),
            },
            "spec": template.get("spec") or {},
        }
    )
    pod.owner_kind = "DaemonSet"
    return pod


# --- leases (coordination.k8s.io/v1) ---------------------------------------


def lease_to_kube(
    name: str,
    holder: str,
    duration_s: float,
    acquired_at: float,
    transitions: int = 1,
) -> dict:
    return {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": {"name": name, "namespace": "kube-system"},
        "spec": {
            "holderIdentity": holder,
            "leaseDurationSeconds": int(duration_s),
            "renewTime": rfc3339(acquired_at),
            # The real coordination.k8s.io field: bumped only on holder
            # change. This IS the fencing token (utils/fence.py) — a stale
            # leader's generation can never equal its successor's.
            "leaseTransitions": int(transitions),
        },
    }


def lease_from_kube(obj: dict) -> Optional[tuple]:
    """(holder, renew_epoch, duration_s, transitions) or None for a vacant
    lease."""
    spec = obj.get("spec") or {}
    holder = spec.get("holderIdentity")
    if not holder:
        return None
    renew = from_rfc3339(spec.get("renewTime")) or 0.0
    return (
        holder,
        renew,
        float(spec.get("leaseDurationSeconds", 15)),
        int(spec.get("leaseTransitions", 1)),
    )
