"""ChaosTransport — fault injection between the kube client and any
Transport (the socket-free fake-apiserver DirectTransport or the real
HttpTransport alike).

Ref: the reference survives a degraded apiserver via client-go's retrying,
rate-limited reflector stack (cmd/controller/main.go:66-69); this wrapper
exists to *prove* the analogous envelope here (kubeapi/client.py) actually
absorbs every fault class instead of assuming it. Sites and rates come from
utils/faultpoints (armed by tests and `make chaos-smoke`); with nothing
armed every call is a straight passthrough plus one dict read.

Request faults (site ``api.request.<verb>``):

- ``latency``       sleep delay_s through the Clock, then forward
- ``reset``         TransportError(reset) WITHOUT forwarding — the request
                    never reached the server (connection refused/reset)
- ``timeout``       forward the request, then TransportError(timeout) — the
                    server may have COMMITTED the write and the response
                    died; the fault class the per-verb idempotency story
                    exists for
- ``throttle``      429 Status carrying details.retryAfterSeconds, without
                    forwarding
- ``server-error``  5xx Status (fault.status) without forwarding
- ``conflict``      409 Status without forwarding — from the client's view
                    this is exactly the delete-between-409-and-GET race
                    shape (a 409 for an object a subsequent GET cannot find)

Watch faults (``watch.open`` / ``watch.event``):

- ``tear``        TransportError mid-open / mid-stream (socket died)
- ``gone``        ApiError 410 at open (compacted resume point)
- ``latency``     delayed delivery
- ``duplicate``   the same event delivered twice (at-least-once watch)
- ``reorder``     event held and delivered AFTER its successor
- ``drop-410``    event silently swallowed, then the stream errors 410 —
                  the only cure is the re-list rebuild path, which is the
                  point of the fault
"""

from __future__ import annotations

import copy
from typing import Iterator, Optional, Tuple

from karpenter_tpu.kubeapi.client import ApiError, Transport, TransportError
from karpenter_tpu.utils import faultpoints
from karpenter_tpu.utils.clock import Clock, SYSTEM_CLOCK


def _status_body(code: int, reason: str, message: str, **details) -> dict:
    body = {"kind": "Status", "code": code, "reason": reason, "message": message}
    if details:
        body["details"] = dict(details)
    return body


# Literal site names per HTTP method (LIST is a collection GET) — spelled
# out, not an f-string, so the chaos site-inventory lint
# (tests/test_chaos.py) can hold these literals to faultpoints.SITES the
# same way the crashpoint lint pins crash sites to instrumented code.
SITE_BY_METHOD = {
    "GET": "api.request.get",
    "POST": "api.request.post",
    "PUT": "api.request.put",
    "PATCH": "api.request.patch",
    "DELETE": "api.request.delete",
}


class ChaosTransport(Transport):
    """Wrap `inner`, consulting faultpoints on every request and delivered
    watch event. Faults are ordinary Exceptions / status codes — they must
    travel the retry and reconnect paths, never punch through them."""

    def __init__(self, inner: Transport, clock: Optional[Clock] = None):
        self.inner = inner
        self.clock = clock or SYSTEM_CLOCK

    # --- requests -----------------------------------------------------------

    def request(self, method, path, query="", body=None, timeout_s=None) -> Tuple[int, dict]:
        fault = faultpoints.draw(SITE_BY_METHOD.get(method, "api.request.get"))
        if fault is None:
            return self.inner.request(method, path, query, body, timeout_s=timeout_s)
        if fault.kind == "latency":
            self.clock.sleep(fault.delay_s)
            return self.inner.request(method, path, query, body, timeout_s=timeout_s)
        if fault.kind == "reset":
            raise TransportError(
                f"injected connection reset before {method} {path}",
                reason="reset",
            )
        if fault.kind == "timeout":
            # The dangerous half of a timeout: the server did the work, the
            # response never arrived.
            self.inner.request(method, path, query, body, timeout_s=timeout_s)
            raise TransportError(
                f"injected timeout after {method} {path} executed",
                reason="timeout",
            )
        if fault.kind == "throttle":
            return 429, _status_body(
                429, "TooManyRequests", "injected throttle",
                retryAfterSeconds=fault.retry_after_s,
            )
        if fault.kind == "server-error":
            return fault.status, _status_body(
                fault.status, "InternalError", "injected server error"
            )
        # conflict
        return 409, _status_body(409, "Conflict", "injected conflict")

    # --- watch streams ------------------------------------------------------

    def stream(self, path, query="") -> Iterator[dict]:
        fault = faultpoints.draw("watch.open")
        if fault is not None:
            if fault.kind == "gone":
                raise ApiError(410, "injected watch expiry")
            raise TransportError(
                f"injected watch-open reset for {path}", reason="reset"
            )
        inner = self.inner.stream(path, query)
        held: Optional[dict] = None  # reorder buffer
        try:
            for event in inner:
                fault = faultpoints.draw("watch.event")
                if fault is not None:
                    if fault.kind == "reorder":
                        if held is not None:
                            yield held  # one deep: release the older hold
                        held = event  # delivered after its successor
                        continue
                    if fault.kind == "tear":
                        # A torn socket loses in-flight data (any held event
                        # included); the reconnect replays from the last rv.
                        raise TransportError(
                            "injected watch stream tear", reason="reset"
                        )
                    if fault.kind == "drop-410":
                        # Silent drop, then the compaction verdict: the
                        # client cannot resume past the hole — only the
                        # re-list rebuild converges.
                        raise ApiError(410, "injected expiry after dropped event")
                    if fault.kind == "latency":
                        self.clock.sleep(fault.delay_s)
                yield event
                if fault is not None and fault.kind == "duplicate":
                    yield copy.deepcopy(event)
                if held is not None:
                    yield held  # the reorder: successor first, held second
                    held = None
            if held is not None:
                # Stream ended with an event still held: deliver it late
                # rather than silently losing it (reorder, not drop).
                yield held
        finally:
            close = getattr(inner, "close", None)
            if close is not None:
                close()

    def close(self) -> None:
        self.inner.close()
