"""Minimal Kubernetes REST client.

Ref: cmd/controller/main.go:66-69 — the reference builds a rate-limited
client-go client (200 qps / 300 burst token bucket). Here the same envelope
over a pluggable Transport:

- `HttpTransport` speaks real HTTPS to an apiserver with bearer-token auth
  and the cluster CA (in-cluster serviceaccount files by default).
- tests inject a direct-call transport into the fake apiserver (no sockets),
  and exercise the HTTP path separately.

Only the verbs the controllers use exist: get/list/create/update/patch/
delete, the binding and eviction subresources, and line-delimited watch
streams.
"""

from __future__ import annotations

import json
import ssl
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Iterator, Optional, Tuple

from karpenter_tpu.utils.clock import Clock, SYSTEM_CLOCK

SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ApiError(Exception):
    def __init__(self, status: int, message: str = ""):
        super().__init__(f"apiserver {status}: {message}")
        self.status = status
        self.message = message


class Transport:
    """request() returns (status, parsed-JSON body); stream() yields parsed
    JSON objects from a line-delimited watch response until closed."""

    def request(
        self, method: str, path: str, query: str = "", body: Optional[dict] = None
    ) -> Tuple[int, dict]:
        raise NotImplementedError

    def stream(self, path: str, query: str = "") -> Iterator[dict]:
        raise NotImplementedError

    def close(self) -> None:
        """Terminate open streams so watch pumps can exit."""


class HttpTransport(Transport):
    def __init__(
        self,
        base_url: str,
        token: str = "",
        ca_file: Optional[str] = None,
        insecure: bool = False,
        timeout_s: float = 30.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout_s = timeout_s
        if insecure:
            self.ssl_context: Optional[ssl.SSLContext] = ssl._create_unverified_context()
        elif ca_file:
            self.ssl_context = ssl.create_default_context(cafile=ca_file)
        else:
            self.ssl_context = None

    @classmethod
    def in_cluster(cls) -> "HttpTransport":
        """The in-cluster configuration every kube client defaults to:
        serviceaccount token + CA against kubernetes.default.svc."""
        with open(f"{SERVICEACCOUNT_DIR}/token") as f:
            token = f.read().strip()
        return cls(
            "https://kubernetes.default.svc",
            token=token,
            ca_file=f"{SERVICEACCOUNT_DIR}/ca.crt",
        )

    @classmethod
    def for_store(cls, store: str) -> Optional["HttpTransport"]:
        """THE --cluster-store selection, shared by every binary (controller,
        webhook): "memory" -> None (in-memory store), "incluster" ->
        serviceaccount transport, anything else -> an apiserver URL with
        KUBE_TOKEN / KUBE_CA_FILE / KUBE_INSECURE env credentials."""
        if store == "memory":
            return None
        if store == "incluster":
            return cls.in_cluster()
        import os

        return cls(
            store,
            token=os.environ.get("KUBE_TOKEN", ""),
            ca_file=os.environ.get("KUBE_CA_FILE") or None,
            insecure=os.environ.get("KUBE_INSECURE", "") == "true",
        )

    def _request(self, method: str, url: str, body: Optional[dict], timeout: float):
        data = None if body is None else json.dumps(body).encode()
        request = urllib.request.Request(url, data=data, method=method)
        request.add_header("Accept", "application/json")
        if body is not None:
            content_type = "application/json"
            if method == "PATCH":
                content_type = "application/merge-patch+json"
            request.add_header("Content-Type", content_type)
        if self.token:
            request.add_header("Authorization", f"Bearer {self.token}")
        return urllib.request.urlopen(
            request, timeout=timeout, context=self.ssl_context
        )

    def request(self, method, path, query="", body=None):
        url = self.base_url + path + (f"?{query}" if query else "")
        try:
            with self._request(method, url, body, self.timeout_s) as response:
                payload = response.read()
                return response.status, json.loads(payload) if payload else {}
        except urllib.error.HTTPError as error:
            detail = error.read().decode(errors="replace")
            try:
                return error.code, json.loads(detail)
            except (ValueError, json.JSONDecodeError):
                return error.code, {"message": detail}

    def stream(self, path, query=""):
        url = self.base_url + path + (f"?{query}" if query else "")
        try:
            response = self._request("GET", url, None, timeout=None)
        except urllib.error.HTTPError as error:
            # A watch opened with an expired resourceVersion answers 410 Gone
            # at the HTTP layer; surface it so the reflector can re-LIST.
            detail = error.read().decode(errors="replace")
            raise ApiError(error.code, detail) from None
        try:
            for line in response:
                line = line.strip()
                if line:
                    yield json.loads(line)
        finally:
            response.close()


class RateLimiter:
    """Token bucket matching the reference's client-side throttle
    (ref: cmd/controller/main.go:67, options qps/burst)."""

    def __init__(self, qps: float, burst: int, clock: Optional[Clock] = None):
        self.qps = qps
        self.burst = burst
        self.clock = clock or SYSTEM_CLOCK
        self._tokens = float(burst)  # vet: guarded-by(self._lock)
        self._last = self.clock.monotonic()  # vet: guarded-by(self._lock)
        self._lock = threading.Lock()

    def wait(self) -> None:
        while True:
            with self._lock:
                now = self.clock.monotonic()
                self._tokens = min(
                    self.burst, self._tokens + (now - self._last) * self.qps
                )
                self._last = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                needed = (1.0 - self._tokens) / self.qps
            # Deliberately OUTSIDE the bucket lock (the blocking-under-lock
            # checker enforces this shape): a throttled caller must not hold
            # up token refill arithmetic for everyone else while it sleeps.
            self.clock.sleep(needed)


class KubeClient:
    """Typed-path helpers over a Transport. Raises ApiError for non-2xx."""

    def __init__(
        self,
        transport: Transport,
        qps: float = 200.0,
        burst: int = 300,
        clock: Optional[Clock] = None,
    ):
        self.transport = transport
        self.limiter = RateLimiter(qps, burst, clock)

    def _call(self, method, path, query="", body=None) -> dict:
        self.limiter.wait()
        status, payload = self.transport.request(method, path, query, body)
        if status >= 300:
            raise ApiError(status, str(payload.get("message", payload)))
        return payload

    # --- generic resource verbs -------------------------------------------

    def get(self, path: str) -> dict:
        return self._call("GET", path)

    def list(self, path: str) -> list:
        return self._call("GET", path).get("items", [])

    def list_with_rv(self, path: str) -> Tuple[list, str]:
        """LIST returning (items, collection resourceVersion). The collection
        rv is what the first watch must resume from — resuming from '' (or
        from an item rv) loses events in the list-to-watch window."""
        payload = self._call("GET", path)
        rv = (payload.get("metadata") or {}).get("resourceVersion", "")
        return payload.get("items", []), rv

    def create(self, path: str, obj: dict) -> dict:
        return self._call("POST", path, body=obj)

    def update(self, path: str, obj: dict) -> dict:
        return self._call("PUT", path, body=obj)

    def patch(self, path: str, patch: dict) -> dict:
        return self._call("PATCH", path, body=patch)

    def delete(self, path: str, uid: Optional[str] = None) -> dict:
        """DELETE, optionally UID-preconditioned (DeleteOptions.preconditions):
        the server answers 409 when the live object is a different incarnation
        than the one the caller observed."""
        body = {"preconditions": {"uid": uid}} if uid else None
        return self._call("DELETE", path, body=body)

    def try_get(self, path: str) -> Optional[dict]:
        try:
            return self.get(path)
        except ApiError as error:
            if error.status == 404:
                return None
            raise

    # --- watch -------------------------------------------------------------

    def watch(
        self,
        path: str,
        on_event: Callable[[str, dict], None],
        stop: threading.Event,
        resource_version: str = "",
        relist: Optional[Callable[[], str]] = None,
    ) -> None:
        """Consume watch events ({type, object} lines) until stop is set —
        the reflector loop of a client-go informer:

        - reconnect from the last seen resourceVersion on stream drops;
        - on 410 Gone (an in-stream ERROR Status event or an HTTP 410 on
          reconnect — what the apiserver sends once etcd compaction has
          discarded the resumption point), call `relist` to rebuild state
          from a fresh LIST and resume from the collection rv it returns.
          Without a relist callback the watch restarts from 'now' ('' rv),
          accepting the gap rather than hot-looping on 410 forever.
        """
        rv = resource_version
        while not stop.is_set():
            # Bookmarks keep rv fresh on idle kinds, shrinking the 410 window.
            query = "watch=true&allowWatchBookmarks=true" + (
                f"&resourceVersion={rv}" if rv else ""
            )
            expired = False
            try:
                for event in self.transport.stream(path, query):
                    if stop.is_set():
                        return
                    event_type = event.get("type", "")
                    obj = event.get("object") or {}
                    if event_type == "ERROR":
                        # k8s signals watch errors in-band as a Status object.
                        try:
                            code = int(obj.get("code", 0) or 0)
                        except (TypeError, ValueError):
                            code = 0
                        expired = code == 410
                        break
                    if event_type == "BOOKMARK":
                        new_rv = (obj.get("metadata") or {}).get("resourceVersion")
                        if new_rv:
                            rv = new_rv
                        continue
                    new_rv = (obj.get("metadata") or {}).get("resourceVersion")
                    if new_rv:
                        rv = new_rv
                    on_event(event_type, obj)
            except ApiError as error:
                expired = error.status == 410
            except Exception:  # noqa: BLE001 — watch drop: back off, re-watch
                pass
            if expired:
                if relist is not None:
                    try:
                        rv = relist()
                    except Exception:  # noqa: BLE001 — apiserver flake: retry
                        if stop.wait(timeout=0.5):
                            return
                else:
                    rv = ""
            elif stop.wait(timeout=0.2):
                # Non-410 stream end (incl. a non-410 ERROR Status): back off
                # before reconnecting from the last rv, so a persistently
                # erroring server isn't hot-looped.
                return
