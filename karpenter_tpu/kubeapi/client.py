"""Minimal Kubernetes REST client.

Ref: cmd/controller/main.go:66-69 — the reference builds a rate-limited
client-go client (200 qps / 300 burst token bucket). Here the same envelope
over a pluggable Transport:

- `HttpTransport` speaks real HTTPS to an apiserver with bearer-token auth
  and the cluster CA (in-cluster serviceaccount files by default).
- tests inject a direct-call transport into the fake apiserver (no sockets),
  and exercise the HTTP path separately.
- `ChaosTransport` (kubeapi/chaos.py) wraps either and injects faults at
  named faultpoint sites — the substrate `make chaos-smoke` storms with.

Only the verbs the controllers use exist: get/list/create/update/patch/
delete, the binding and eviction subresources, and line-delimited watch
streams.

Every request crosses ONE retry envelope (`KubeClient._request_enveloped`,
pinned by the vet transport-discipline checker): per-verb deadlines, capped
exponential backoff with jitter through the Clock abstraction, Retry-After
honored on 429. Idempotency rationale per verb (docs/design/chaos.md):

- GET/LIST/DELETE/PATCH/PUT are retried freely — re-executing any of them
  converges (DELETE answers 404, PATCH re-merges, PUT either lands or
  answers a 409 CAS conflict the caller already handles).
- POST (create/binding/eviction) is retried too, but its safety leans on
  the strict-409 semantics the write paths already carry: a retried create
  whose first attempt committed answers 409, which callers treat as
  already-exists (node adoption, _create_or_update's GET+PUT, bind_pod's
  bound-to-whom check) — nothing double-creates.

Network faults surface as a typed `TransportError` (retryable) instead of
raw urllib/socket exceptions, so callers — and the envelope — can tell a
connection reset from an apiserver verdict (`ApiError`).
"""

from __future__ import annotations

import http.client
import json
import random
import ssl
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, Iterator, Optional, Tuple

from karpenter_tpu.utils.backoff import capped_backoff_s
from karpenter_tpu.utils.clock import Clock, SYSTEM_CLOCK
from karpenter_tpu.utils.metrics import REGISTRY

SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# Control-plane client health: a rising retry rate is the first symptom of
# apiserver degradation (docs/operations.md, "API degradation" runbook).
KUBE_API_RETRY_TOTAL = REGISTRY.counter(
    "kube_api_retry_total",
    "Kube API request retries by verb and fault reason",
    ["verb", "reason"],
)
KUBE_API_REQUEST_DURATION = REGISTRY.histogram(
    "kube_api_request_duration_seconds",
    "Kube API request latency per attempt (failed attempts included)",
    ["verb"],
)
KUBE_API_LANE_WAIT = REGISTRY.histogram(
    "kube_api_lane_wait_seconds",
    "Time a request waited for a rate-limiter token, by priority lane "
    "(critical lane waits spiking means the reserve is sized wrong)",
    ["lane"],
)

# --- priority lanes ----------------------------------------------------------
#
# The kube analogue of API Priority & Fairness, client-side: the token
# bucket keeps a small reserve only CRITICAL requests may drain, so a bulk
# LIST/bind storm saturating the limiter cannot park the control-plane
# heartbeat traffic behind it. Critical today: lease renew/acquire (losing
# the lease mid-storm deposes the leader and trips the write fence),
# node heartbeat status writes, and finalizer removal/node deletes (a
# stuck drain holds capacity). The lane rides a thread-local so call
# sites stay signature-free: kubeapi/cluster.py wraps its critical verbs
# in `with critical_lane():` and every nested request inherits it.

# Fraction of the bucket's burst reserved for the critical lane.
CRITICAL_RESERVE_FRACTION = 0.1

_lane_local = threading.local()


def current_lane() -> str:
    """The calling thread's lane: "critical" inside a critical_lane() block,
    else "bulk"."""
    return getattr(_lane_local, "lane", "bulk")


class critical_lane:
    """Context manager marking every kube request on this thread critical
    (reserved-token lane) for the duration. Re-entrant; restores the prior
    lane on exit so a critical section nested in another stays critical."""

    def __enter__(self) -> "critical_lane":
        self._prior = getattr(_lane_local, "lane", "bulk")
        _lane_local.lane = "critical"
        return self

    def __exit__(self, *exc_info) -> None:
        _lane_local.lane = self._prior


class ApiError(Exception):
    """The apiserver answered with a non-2xx verdict."""

    def __init__(self, status: int, message: str = ""):
        super().__init__(f"apiserver {status}: {message}")
        self.status = status
        self.message = message


class TransportError(Exception):
    """A network-layer fault: the request may or may not have reached the
    server (a timeout can follow a committed write). `retryable` says the
    fault is transient; `reason` labels the retry metric
    (timeout | reset | network | idle-timeout)."""

    def __init__(self, message: str, retryable: bool = True, reason: str = "network"):
        super().__init__(message)
        self.retryable = retryable
        self.reason = reason


def _as_transport_error(error: Exception) -> TransportError:
    """Classify a raw urllib/socket/http.client fault. URLError wraps its
    cause in .reason; unwrap so a connection reset inside a URLError still
    labels as a reset."""
    cause = error
    if isinstance(error, urllib.error.URLError) and isinstance(
        error.reason, Exception
    ):
        cause = error.reason
    if isinstance(cause, TimeoutError):  # socket.timeout is an alias
        reason = "timeout"
    elif isinstance(cause, (ConnectionResetError, ConnectionAbortedError,
                            BrokenPipeError, http.client.RemoteDisconnected)):
        reason = "reset"
    else:
        reason = "network"
    return TransportError(f"{type(cause).__name__}: {cause}", reason=reason)


def _status_code(obj: dict) -> int:
    """The integer .code of an in-band Status object, 0 when unparsable."""
    try:
        return int(obj.get("code", 0) or 0)
    except (TypeError, ValueError):
        return 0


class Transport:
    """request() returns (status, parsed-JSON body); stream() yields parsed
    JSON objects from a line-delimited watch response until closed.
    Network-layer faults raise TransportError; HTTP-layer error Statuses on
    a stream open raise ApiError. `timeout_s` is the per-request deadline
    the retry envelope selects per verb (socketless transports ignore it)."""

    def request(
        self,
        method: str,
        path: str,
        query: str = "",
        body: Optional[dict] = None,
        timeout_s: Optional[float] = None,
    ) -> Tuple[int, dict]:
        raise NotImplementedError

    def stream(self, path: str, query: str = "") -> Iterator[dict]:
        raise NotImplementedError

    def close(self) -> None:
        """Terminate open streams so watch pumps can exit."""


class HttpTransport(Transport):
    def __init__(
        self,
        base_url: str,
        token: str = "",
        ca_file: Optional[str] = None,
        insecure: bool = False,
        timeout_s: float = 30.0,
        watch_idle_s: float = 300.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout_s = timeout_s
        # Watch read-deadline: an apiserver that stops sending bytes without
        # closing the connection would otherwise hang the watch pump forever
        # (the stream used to open with timeout=None). Each blocking read is
        # bounded by this; a quiet-too-long stream tears with a retryable
        # idle-timeout TransportError and the pump reconnects from its last
        # rv. Must exceed the server's bookmark cadence (~1/min) by a wide
        # margin so healthy-idle watches don't churn.
        self.watch_idle_s = watch_idle_s
        if insecure:
            self.ssl_context: Optional[ssl.SSLContext] = ssl._create_unverified_context()
        elif ca_file:
            self.ssl_context = ssl.create_default_context(cafile=ca_file)
        else:
            self.ssl_context = None

    @classmethod
    def in_cluster(cls) -> "HttpTransport":
        """The in-cluster configuration every kube client defaults to:
        serviceaccount token + CA against kubernetes.default.svc."""
        with open(f"{SERVICEACCOUNT_DIR}/token") as f:
            token = f.read().strip()
        return cls(
            "https://kubernetes.default.svc",
            token=token,
            ca_file=f"{SERVICEACCOUNT_DIR}/ca.crt",
        )

    @classmethod
    def for_store(cls, store: str) -> Optional["HttpTransport"]:
        """THE --cluster-store selection, shared by every binary (controller,
        webhook): "memory" -> None (in-memory store), "incluster" ->
        serviceaccount transport, anything else -> an apiserver URL with
        KUBE_TOKEN / KUBE_CA_FILE / KUBE_INSECURE env credentials."""
        if store == "memory":
            return None
        if store == "incluster":
            return cls.in_cluster()
        import os

        return cls(
            store,
            token=os.environ.get("KUBE_TOKEN", ""),
            ca_file=os.environ.get("KUBE_CA_FILE") or None,
            insecure=os.environ.get("KUBE_INSECURE", "") == "true",
        )

    def _request(self, method: str, url: str, body: Optional[dict], timeout: float):
        data = None if body is None else json.dumps(body).encode()
        request = urllib.request.Request(url, data=data, method=method)
        request.add_header("Accept", "application/json")
        if body is not None:
            content_type = "application/json"
            if method == "PATCH":
                content_type = "application/merge-patch+json"
            request.add_header("Content-Type", content_type)
        if self.token:
            request.add_header("Authorization", f"Bearer {self.token}")
        return urllib.request.urlopen(
            request, timeout=timeout, context=self.ssl_context
        )

    def request(self, method, path, query="", body=None, timeout_s=None):
        url = self.base_url + path + (f"?{query}" if query else "")
        try:
            with self._request(
                method, url, body, timeout_s or self.timeout_s
            ) as response:
                payload = response.read()
                return response.status, json.loads(payload) if payload else {}
        except urllib.error.HTTPError as error:
            detail = error.read().decode(errors="replace")
            try:
                parsed = json.loads(detail)
            except (ValueError, json.JSONDecodeError):
                parsed = {"message": detail}
            # Surface the throttle header where the apiserver used it instead
            # of (or in addition to) Status.details — the retry envelope reads
            # details.retryAfterSeconds.
            retry_after = error.headers.get("Retry-After") if error.headers else None
            if retry_after and isinstance(parsed, dict):
                try:
                    parsed.setdefault("details", {}).setdefault(
                        "retryAfterSeconds", float(retry_after)
                    )
                except (TypeError, ValueError):
                    pass
            return error.code, parsed
        except (urllib.error.URLError, http.client.HTTPException, OSError) as error:
            # Raw network faults (connection reset/refused, socket timeout,
            # torn keep-alive) become a typed retryable TransportError — a
            # bare URLError escaping into a controller thread was the
            # pre-chaos failure mode (ISSUE 10 satellite).
            raise _as_transport_error(error) from error

    def stream(self, path, query=""):
        url = self.base_url + path + (f"?{query}" if query else "")
        try:
            # Read-deadline, not a request deadline: timeout bounds each
            # blocking socket read, so a stalled-but-open stream tears after
            # watch_idle_s instead of hanging the pump forever.
            response = self._request("GET", url, None, timeout=self.watch_idle_s)
        except urllib.error.HTTPError as error:
            # A watch opened with an expired resourceVersion answers 410 Gone
            # at the HTTP layer; surface it so the reflector can re-LIST.
            detail = error.read().decode(errors="replace")
            raise ApiError(error.code, detail) from None
        except (urllib.error.URLError, http.client.HTTPException, OSError) as error:
            raise _as_transport_error(error) from error
        try:
            for line in self._stream_lines(response):
                line = line.strip()
                if line:
                    yield json.loads(line)
        finally:
            response.close()

    @staticmethod
    def _stream_lines(response):
        """Iterate the response, mapping mid-stream socket faults (incl. the
        idle-timeout read deadline) to TransportError so the watch pump's
        reconnect path — not a raw socket.timeout — sees them."""
        while True:
            try:
                line = response.readline()
            except (TimeoutError, OSError, http.client.HTTPException) as error:
                mapped = _as_transport_error(error)
                if mapped.reason == "timeout":
                    raise TransportError(
                        "watch stream idle past the read deadline",
                        reason="idle-timeout",
                    ) from error
                raise mapped from error
            if not line:
                return
            yield line


class RateLimiter:
    """Token bucket matching the reference's client-side throttle
    (ref: cmd/controller/main.go:67, options qps/burst), with a critical
    reserve: bulk callers may not drain the bucket below `critical_reserve`
    tokens — only critical-lane callers take the bucket to zero, so a bulk
    storm's worst case delays a lease renew by refill arithmetic, never by
    the storm's own queue."""

    def __init__(
        self,
        qps: float,
        burst: int,
        clock: Optional[Clock] = None,
        critical_reserve: int = 0,
    ):
        self.qps = qps
        self.burst = burst
        # Reserve clamped inside the bucket: a reserve >= burst would
        # starve bulk entirely.
        self.critical_reserve = max(0, min(int(critical_reserve), burst - 1))
        self.clock = clock or SYSTEM_CLOCK
        self._tokens = float(burst)  # vet: guarded-by(self._lock)
        self._last = self.clock.monotonic()  # vet: guarded-by(self._lock)
        self._lock = threading.Lock()

    # Shortest throttle sleep: refill arithmetic can leave a sub-ULP token
    # deficit (tokens + (deficit/qps)*qps rounds just below the grant line),
    # and the matching sub-nanosecond sleep is absorbed by double-precision
    # rounding on any clock with a large absolute value (1e6 + 1e-18 == 1e6)
    # — the refill never lands and wait() livelocks. One scheduler quantum
    # is the floor; the overshoot is noise against a >= 1-token wait.
    MIN_SLEEP_S = 0.0005

    def wait(self, critical: bool = False) -> float:
        """Block until a token is available in the caller's lane; returns
        the seconds slept (0.0 for an unthrottled call) so the envelope can
        publish per-lane wait."""
        floor = 0.0 if critical else float(self.critical_reserve)
        waited = 0.0
        while True:
            with self._lock:
                now = self.clock.monotonic()
                self._tokens = min(
                    self.burst, self._tokens + (now - self._last) * self.qps
                )
                self._last = now
                if self._tokens >= floor + 1.0:
                    self._tokens -= 1.0
                    return waited
                needed = max(
                    (floor + 1.0 - self._tokens) / self.qps, self.MIN_SLEEP_S
                )
            # Deliberately OUTSIDE the bucket lock (the blocking-under-lock
            # checker enforces this shape): a throttled caller must not hold
            # up token refill arithmetic for everyone else while it sleeps.
            self.clock.sleep(needed)
            waited += needed


# Per-verb request deadlines (the envelope passes these to the transport).
# LIST gets the long deadline — a 50k-pod collection takes real time to
# serialize; point reads and writes should fail fast and retry instead.
DEFAULT_VERB_TIMEOUTS_S: Dict[str, float] = {
    "GET": 15.0,
    "LIST": 120.0,
    "POST": 30.0,
    "PUT": 30.0,
    "PATCH": 30.0,
    "DELETE": 30.0,
}

# Statuses the envelope retries with backoff (besides 429-with-Retry-After):
# transient server-side trouble, per client-go's default retry set.
RETRYABLE_STATUSES = frozenset({500, 502, 503, 504})


class RetryPolicy:
    """The envelope's tuning knobs (Options --kube-retry-* flags): attempt
    budget, capped exponential backoff with 0.5x-1.5x jitter, a cap on how
    long a server-sent Retry-After can park the client, and the per-verb
    deadline table."""

    def __init__(
        self,
        max_attempts: int = 5,
        backoff_base_s: float = 0.1,
        backoff_cap_s: float = 5.0,
        retry_after_cap_s: float = 30.0,
        timeouts_s: Optional[Dict[str, float]] = None,
        jitter: Optional[random.Random] = None,
    ):
        self.max_attempts = max(1, max_attempts)
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.retry_after_cap_s = retry_after_cap_s
        self.timeouts_s = dict(DEFAULT_VERB_TIMEOUTS_S)
        if timeouts_s:
            self.timeouts_s.update(timeouts_s)
        self._jitter = jitter or random.Random()

    def timeout_for(self, verb: str) -> float:
        return self.timeouts_s.get(verb, 30.0)

    def backoff_s(self, attempt: int) -> float:
        """Capped exponential with jitter: attempt 1 -> ~base, doubling to
        the cap. Jitter de-synchronizes a fleet of controllers retrying the
        same outage (client-go's DefaultBackoff shape)."""
        base = capped_backoff_s(self.backoff_base_s, self.backoff_cap_s, attempt)
        return base * (0.5 + self._jitter.random())

    def retry_after_s(self, payload: dict) -> Optional[float]:
        """The server-directed delay of a 429, from Status
        details.retryAfterSeconds (where the apiserver mirrors the
        Retry-After header). None when absent — a 429 WITHOUT it is a
        semantic rejection (the eviction subresource's PDB verdict), which
        must surface immediately, not spin in the envelope."""
        details = payload.get("details") if isinstance(payload, dict) else None
        value = (details or {}).get("retryAfterSeconds")
        if value is None:
            return None
        try:
            return min(float(value), self.retry_after_cap_s)
        except (TypeError, ValueError):
            return None


class KubeClient:
    """Typed-path helpers over a Transport. Raises ApiError for non-2xx
    verdicts and TransportError for network faults that outlived the retry
    budget."""

    # Watch reconnect backoff: base doubles per consecutive failed
    # connection (no event received), capped; reset by any delivered event.
    WATCH_BACKOFF_BASE_S = 0.2
    WATCH_BACKOFF_CAP_S = 5.0

    def __init__(
        self,
        transport: Transport,
        qps: float = 200.0,
        burst: int = 300,
        clock: Optional[Clock] = None,
        retry: Optional[RetryPolicy] = None,
        critical_reserve: Optional[int] = None,
    ):
        self.transport = transport
        self.clock = clock or SYSTEM_CLOCK
        if critical_reserve is None:
            critical_reserve = int(burst * CRITICAL_RESERVE_FRACTION)
        self.limiter = RateLimiter(
            qps, burst, self.clock, critical_reserve=critical_reserve
        )
        self.retry = retry or RetryPolicy()

    def _call(self, verb, path, query="", body=None) -> dict:
        status, payload = self._request_enveloped(verb, path, query, body)
        if status >= 300:
            raise ApiError(status, str(payload.get("message", payload)))
        return payload

    def _request_enveloped(
        self, verb: str, path: str, query: str, body: Optional[dict]
    ) -> Tuple[int, dict]:
        """THE retry envelope — the only transport.request caller in the
        tree (vet: transport-discipline). Loops attempts under the rate
        limiter; each failed attempt costs a backoff sleep through the
        Clock. See the module docstring for the per-verb idempotency
        rationale that makes uniform retry safe."""
        method = "GET" if verb == "LIST" else verb
        label = verb.lower()
        lane = current_lane()
        timeout_s = self.retry.timeout_for(verb)
        attempt = 0
        while True:
            attempt += 1
            waited = self.limiter.wait(critical=lane == "critical")
            KUBE_API_LANE_WAIT.observe(waited, lane)
            began = self.clock.monotonic()
            try:
                status, payload = self.transport.request(
                    method, path, query, body, timeout_s=timeout_s
                )
            except TransportError as error:
                KUBE_API_REQUEST_DURATION.observe(
                    self.clock.monotonic() - began, label
                )
                if not error.retryable or attempt >= self.retry.max_attempts:
                    raise
                KUBE_API_RETRY_TOTAL.inc(label, error.reason)
                self._flight_record_retry(label, error.reason, attempt)
                self.clock.sleep(self.retry.backoff_s(attempt))
                continue
            KUBE_API_REQUEST_DURATION.observe(self.clock.monotonic() - began, label)
            delay = self._status_retry_delay(status, payload, attempt)
            if delay is None:
                return status, payload
            reason = "throttled" if status == 429 else "server-error"
            KUBE_API_RETRY_TOTAL.inc(label, reason)
            self._flight_record_retry(label, reason, attempt)
            self.clock.sleep(delay)

    @staticmethod
    def _flight_record_retry(verb: str, reason: str, attempt: int) -> None:
        """Every envelope retry lands in the flight recorder: a breach dump
        must show whether the budget went to a misbehaving apiserver."""
        from karpenter_tpu.utils.obs import RECORDER

        RECORDER.record("retry", verb=verb, reason=reason, attempt=attempt)

    def _status_retry_delay(
        self, status: int, payload: dict, attempt: int
    ) -> Optional[float]:
        """Backoff before retrying `status`, or None to surface it now."""
        if attempt >= self.retry.max_attempts:
            return None
        if status == 429:
            # Honor Retry-After; a 429 without one is a semantic verdict
            # (PDB eviction rejection), not a throttle — never retried here.
            return self.retry.retry_after_s(payload)
        if status in RETRYABLE_STATUSES:
            return self.retry.backoff_s(attempt)
        return None

    # --- generic resource verbs -------------------------------------------

    def get(self, path: str) -> dict:
        return self._call("GET", path)

    def list(self, path: str) -> list:
        return self._call("LIST", path).get("items", [])

    def list_with_rv(self, path: str) -> Tuple[list, str]:
        """LIST returning (items, collection resourceVersion). The collection
        rv is what the first watch must resume from — resuming from '' (or
        from an item rv) loses events in the list-to-watch window."""
        payload = self._call("LIST", path)
        rv = (payload.get("metadata") or {}).get("resourceVersion", "")
        return payload.get("items", []), rv

    def create(self, path: str, obj: dict) -> dict:
        return self._call("POST", path, body=obj)

    def update(self, path: str, obj: dict) -> dict:
        return self._call("PUT", path, body=obj)

    def patch(self, path: str, patch: dict) -> dict:
        return self._call("PATCH", path, body=patch)

    def delete(self, path: str, uid: Optional[str] = None) -> dict:
        """DELETE, optionally UID-preconditioned (DeleteOptions.preconditions):
        the server answers 409 when the live object is a different incarnation
        than the one the caller observed."""
        body = {"preconditions": {"uid": uid}} if uid else None
        return self._call("DELETE", path, body=body)

    def try_get(self, path: str) -> Optional[dict]:
        try:
            return self.get(path)
        except ApiError as error:
            if error.status == 404:
                return None
            raise

    # --- watch -------------------------------------------------------------

    def _watch_backoff_s(self, failures: int) -> float:
        return capped_backoff_s(
            self.WATCH_BACKOFF_BASE_S, self.WATCH_BACKOFF_CAP_S, failures
        )

    def _consume_stream(self, path, query, on_event, stop, progress):
        """One watch connection: deliver events until the stream ends.
        Returns (expired, stopped). `progress` ({"rv", "delivered"}) is
        mutated in place so a mid-stream tear keeps the resume point and
        backoff credit of the events already applied."""
        expired = False
        for event in self.transport.stream(path, query):
            if stop.is_set():
                return False, True
            progress["delivered"] = True
            event_type = event.get("type", "")
            obj = event.get("object") or {}
            if event_type == "ERROR":
                # k8s signals watch errors in-band as a Status object.
                expired = _status_code(obj) == 410
                break
            new_rv = (obj.get("metadata") or {}).get("resourceVersion")
            if new_rv:
                progress["rv"] = new_rv
            if event_type != "BOOKMARK":
                # Bookmarks only advance rv (shrinking the 410 window on
                # idle kinds); everything else is delivered.
                on_event(event_type, obj)
        return expired, False

    def watch(
        self,
        path: str,
        on_event: Callable[[str, dict], None],
        stop: threading.Event,
        resource_version: str = "",
        relist: Optional[Callable[[], str]] = None,
    ) -> None:
        """Consume watch events ({type, object} lines) until stop is set —
        the reflector loop of a client-go informer:

        - reconnect from the last seen resourceVersion on stream drops,
          with capped exponential backoff per consecutive dead connection
          (a torn socket and a persistently erroring server must not be
          hot-looped; any delivered event resets the backoff);
        - on 410 Gone (an in-stream ERROR Status event or an HTTP 410 on
          reconnect — what the apiserver sends once etcd compaction has
          discarded the resumption point), call `relist` to rebuild state
          from a fresh LIST and resume from the collection rv it returns.
          Without a relist callback the watch restarts from 'now' ('' rv),
          accepting the gap rather than hot-looping on 410 forever.
        """
        rv = resource_version
        failures = 0
        while not stop.is_set():
            query = "watch=true&allowWatchBookmarks=true" + (
                f"&resourceVersion={rv}" if rv else ""
            )
            expired = False
            progress = {"rv": rv, "delivered": False}
            try:
                expired, stopped = self._consume_stream(
                    path, query, on_event, stop, progress
                )
                if stopped:
                    return
            except ApiError as error:
                expired = error.status == 410
            except TransportError as error:
                # Socket-layer tear (reset, idle deadline, refused reconnect)
                # — retryable by definition, but distinctly counted so a
                # flapping network shows up in the watch retry series.
                KUBE_API_RETRY_TOTAL.inc("watch", error.reason)
            except Exception:  # noqa: BLE001 — watch drop: back off, re-watch
                KUBE_API_RETRY_TOTAL.inc("watch", "stream-error")
            rv = progress["rv"]
            if progress["delivered"]:
                failures = 0
            if expired:
                if relist is not None:
                    try:
                        rv = relist()
                        failures = 0
                        continue
                    except Exception:  # noqa: BLE001 — apiserver flake: retry
                        failures += 1
                        if stop.wait(timeout=self._watch_backoff_s(failures)):
                            return
                else:
                    rv = ""
            else:
                # Non-410 stream end (incl. a non-410 ERROR Status): back off
                # before reconnecting from the last rv.
                failures += 1
                if stop.wait(timeout=self._watch_backoff_s(failures)):
                    return
