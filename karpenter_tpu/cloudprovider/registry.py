"""Cloud-provider registry.

Ref: pkg/cloudprovider/registry/register.go — the reference selects the
provider at compile time via build tags and installs its Default/Validate
hooks into the API package. We select at runtime (config/env) and do the same
hook installation.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from karpenter_tpu.api import validation
from karpenter_tpu.cloudprovider import CloudProvider

_factories: Dict[str, Callable[[], CloudProvider]] = {}
_active: Optional[CloudProvider] = None


def register_factory(name: str, factory: Callable[[], CloudProvider]) -> None:
    _factories[name] = factory


def new_cloud_provider(name: str = "fake") -> CloudProvider:
    """Instantiate and install API hooks (ref: register.go:24-37)."""
    global _active
    if name not in _factories:
        raise KeyError(f"unknown cloud provider {name!r}; known: {sorted(_factories)}")
    provider = _factories[name]()
    validation.DEFAULT_HOOK = provider.default
    validation.VALIDATE_HOOK = provider.validate
    _active = provider
    return provider


def active() -> Optional[CloudProvider]:
    return _active


def _register_builtins() -> None:
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider

    register_factory("fake", FakeCloudProvider)

    def _ec2_factory():
        from karpenter_tpu.cloudprovider.ec2 import Ec2CloudProvider

        return Ec2CloudProvider()

    register_factory("ec2", _ec2_factory)


_register_builtins()
