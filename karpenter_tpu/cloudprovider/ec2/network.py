"""Subnet and security-group discovery by tag selector.

Ref: pkg/cloudprovider/aws/{subnets.go,securitygroups.go} — tag-selector
lookup ("*" value = key existence), cached; security groups keep at most one
cluster-tagged group (the load-balancer-controller workaround,
securitygroups.go:44-66).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.cloudprovider.ec2.api import (
    SETUP_CACHE_TTL,
    Ec2Api,
    SecurityGroup,
    Subnet,
)
from karpenter_tpu.cloudprovider.ec2.vendor import (
    CLUSTER_TAG_KEY_FORMAT,
    Ec2Provider,
)
from karpenter_tpu.utils.cache import TtlCache
from karpenter_tpu.utils.clock import Clock, SYSTEM_CLOCK

class NoMatchError(Exception):
    """Selector matched nothing (ref: subnets.go:43-45, securitygroups.go:47)."""


def _selector_key(selector: Dict[str, str]) -> Tuple:
    return tuple(sorted(selector.items()))


class SubnetProvider:
    """Ref: aws/subnets.go SubnetProvider:18-49."""

    def __init__(self, api: Ec2Api, clock: Optional[Clock] = None):
        self.api = api
        self._cache = TtlCache(SETUP_CACHE_TTL, clock or SYSTEM_CLOCK)
        self._lock = threading.Lock()

    def get(self, provider: Ec2Provider) -> List[Subnet]:
        selector = provider.subnet_selector or {}
        key = _selector_key(selector)
        with self._lock:
            cached = self._cache.get(key)
            if cached is not None:
                return cached
            subnets = self.api.describe_subnets(selector)
            if not subnets:
                raise NoMatchError(f"no subnets matched selector {selector}")
            self._cache.set(key, subnets)
            return subnets


class SecurityGroupProvider:
    """Ref: aws/securitygroups.go SecurityGroupProvider:19-99."""

    def __init__(
        self, api: Ec2Api, cluster_name: str, clock: Optional[Clock] = None
    ):
        self.api = api
        self.cluster_name = cluster_name
        self._cache = TtlCache(SETUP_CACHE_TTL, clock or SYSTEM_CLOCK)
        self._lock = threading.Lock()

    def get(self, provider: Ec2Provider) -> List[str]:
        selector = provider.security_group_selector or {}
        key = _selector_key(selector)
        with self._lock:
            cached = self._cache.get(key)
            if cached is None:
                cached = self.api.describe_security_groups(selector)
                self._cache.set(key, cached)
        groups = self._drop_extra_cluster_tagged(cached)
        if not groups:
            raise NoMatchError(f"no security groups matched selector {selector}")
        return [group.group_id for group in groups]

    def _drop_extra_cluster_tagged(
        self, groups: List[SecurityGroup]
    ) -> List[SecurityGroup]:
        """Keep at most one group carrying the cluster discovery tag
        (ref: securitygroups.go filterClusterTaggedGroups:44-66)."""
        cluster_tag = CLUSTER_TAG_KEY_FORMAT.format(self.cluster_name)
        kept, found = [], False
        for group in groups:
            if cluster_tag in group.tags:
                if found:
                    continue
                found = True
            kept.append(group)
        return kept
