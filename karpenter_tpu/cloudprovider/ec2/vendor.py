"""EC2 vendor extension: the opaque `Constraints.provider` blob.

Ref: pkg/cloudprovider/aws/apis/v1alpha1/ — the reference nests a vendor CRD
(`AWS{InstanceProfile, LaunchTemplate, SubnetSelector,
SecurityGroupSelector, Tags}`) inside the Provisioner as raw JSON
(provider.go:31-79), defaults it from the cluster name
(provider_defaults.go:29-52), validates it (provider_validation.go), and
merges cluster-discovery tags onto every created resource (tags.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Mapping, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.provisioner import Constraints, Provisioner
from karpenter_tpu.api.requirements import Requirement

# Tag key set on all cluster-owned resources (ref: tags.go ClusterTagKeyFormat).
CLUSTER_TAG_KEY_FORMAT = "kubernetes.io/cluster/{}"
# Tag key marking resources this framework owns (ref: tags.go KarpenterTagKeyFormat).
FRAMEWORK_TAG_KEY_FORMAT = "karpenter.tpu/cluster/{}"


class VendorValidationError(Exception):
    """Invalid provider blob (ref: provider_validation.go FieldErrors)."""


@dataclass
class Ec2Provider:
    """Typed view of the vendor blob (ref: provider.go:33-52)."""

    instance_profile: str = ""
    launch_template: Optional[str] = None
    subnet_selector: Optional[Dict[str, str]] = None
    security_group_selector: Optional[Dict[str, str]] = None
    tags: Dict[str, str] = field(default_factory=dict)

    @staticmethod
    def deserialize(constraints: Constraints) -> "Ec2Provider":
        """Ref: provider.go Deserialize:54-67 — the blob must exist (the
        defaulting hook installs it)."""
        if constraints.provider is None:
            raise VendorValidationError(
                "spec.provider is not defined; is the defaulting hook installed?"
            )
        blob: Mapping[str, Any] = constraints.provider
        unknown = set(blob) - {
            "instanceProfile",
            "launchTemplate",
            "subnetSelector",
            "securityGroupSelector",
            "tags",
        }
        if unknown:
            raise VendorValidationError(f"unknown provider fields: {sorted(unknown)}")
        return Ec2Provider(
            instance_profile=blob.get("instanceProfile", ""),
            launch_template=blob.get("launchTemplate"),
            subnet_selector=dict(blob["subnetSelector"])
            if blob.get("subnetSelector") is not None
            else None,
            security_group_selector=dict(blob["securityGroupSelector"])
            if blob.get("securityGroupSelector") is not None
            else None,
            tags=dict(blob.get("tags") or {}),
        )

    def serialize(self) -> Dict[str, Any]:
        blob: Dict[str, Any] = {"instanceProfile": self.instance_profile}
        if self.launch_template is not None:
            blob["launchTemplate"] = self.launch_template
        if self.subnet_selector is not None:
            blob["subnetSelector"] = dict(self.subnet_selector)
        if self.security_group_selector is not None:
            blob["securityGroupSelector"] = dict(self.security_group_selector)
        if self.tags:
            blob["tags"] = dict(self.tags)
        return blob

    def validate(self) -> None:
        """Ref: provider_validation.go:24-83."""
        errors = []
        if not self.instance_profile:
            errors.append("provider.instanceProfile is required")
        for name, selector in (
            ("subnetSelector", self.subnet_selector),
            ("securityGroupSelector", self.security_group_selector),
        ):
            if selector is None:
                errors.append(f"provider.{name} is required")
                continue
            for key, value in selector.items():
                if key == "" or value == "":
                    errors.append(f"provider.{name}[{key!r}] must be non-empty")
        for key in self.tags:
            if key == "":
                errors.append("provider.tags: empty tag keys are not supported")
        if errors:
            raise VendorValidationError("; ".join(errors))


def default_provider_blob(provisioner: Provisioner, cluster_name: str) -> None:
    """The vendor defaulting hook (ref: provider_defaults.go Default:18-23):
    arch defaults to amd64, capacity type to on-demand, and subnet/SG
    selectors to the cluster discovery tag."""
    constraints = provisioner.spec.constraints
    blob = dict(constraints.provider or {})
    discovery = {CLUSTER_TAG_KEY_FORMAT.format(cluster_name): "*"}
    blob.setdefault("subnetSelector", discovery)
    blob.setdefault("securityGroupSelector", dict(discovery))
    constraints.provider = blob

    existing_keys = set(constraints.requirements.keys()) | set(constraints.labels)
    if wellknown.ARCH_LABEL not in existing_keys:
        constraints.requirements = constraints.requirements.add(
            Requirement.in_(wellknown.ARCH_LABEL, ["amd64"])
        )
    if wellknown.CAPACITY_TYPE_LABEL not in existing_keys:
        constraints.requirements = constraints.requirements.add(
            Requirement.in_(
                wellknown.CAPACITY_TYPE_LABEL, [wellknown.CAPACITY_TYPE_ON_DEMAND]
            )
        )


def merge_tags(
    cluster_name: str, provisioner_name: str, custom_tags: Mapping[str, str]
) -> Dict[str, str]:
    """Managed tags, overridable by user tags (ref: tags.go MergeTags:27-40)."""
    merged = {
        "Name": f"{wellknown.GROUP}/cluster/{cluster_name}/provisioner/{provisioner_name}",
        CLUSTER_TAG_KEY_FORMAT.format(cluster_name): "owned",
        FRAMEWORK_TAG_KEY_FORMAT.format(cluster_name): "owned",
    }
    merged.update(custom_tags)
    return merged
