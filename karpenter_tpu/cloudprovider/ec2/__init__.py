"""EC2-backed cloud provider: the full discovery→template→fleet stack.

Ref: pkg/cloudprovider/aws/cloudprovider.go — the facade wiring
instance-type / subnet / security-group / launch-template / instance
providers behind the generic CloudProvider interface, with the fleet call
throttled at 2 qps / 100 burst (cloudprovider.go:40-56) and the vendor
`provider` blob deserialized per call (:118,137).

By default the stack runs against the in-memory FakeEc2 backend — the whole
provider logic (capacity-type choice, ICE blackouts, launch-template
hashing, override pricing) is real; only the wire calls are simulated. A
production deployment implements `Ec2Api` over the AWS SDK and passes it in.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import datetime
import json
import threading

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.provisioner import Constraints, Provisioner
from karpenter_tpu.cloudprovider import (
    DEFAULT_INTERRUPTION_DEADLINE_SECONDS,
    INTERRUPTION_REBALANCE,
    INTERRUPTION_SPOT,
    INTERRUPTION_STOPPING,
    CloudInstance,
    CloudProvider,
    CloudProviderError,
    InstanceType,
    InterruptionEvent,
    NodeSpec,
)
from karpenter_tpu.cloudprovider.ec2.api import Ec2Api
from karpenter_tpu.cloudprovider.ec2.fake import FakeEc2
from karpenter_tpu.cloudprovider.ec2.instances import (
    InstanceProvider,
    parse_instance_id,
)
from karpenter_tpu.cloudprovider.ec2.instancetypes import InstanceTypeProvider
from karpenter_tpu.cloudprovider.ec2.launchtemplates import (
    AmiProvider,
    LaunchTemplateProvider,
)
from karpenter_tpu.cloudprovider.ec2.network import (
    SecurityGroupProvider,
    SubnetProvider,
)
from karpenter_tpu.cloudprovider.ec2.vendor import (
    Ec2Provider,
    default_provider_blob,
)
from karpenter_tpu.utils.clock import Clock, SYSTEM_CLOCK
from karpenter_tpu.utils.workqueue import RateLimiter

# Fleet-call throttle (ref: aws/cloudprovider.go:41-46).
FLEET_QPS = 2.0
FLEET_BURST = 100

# EventBridge detail-type -> interruption kind (ref: the reference ecosystem's
# interruption controller consumes exactly these rule streams via SQS).
_DETAIL_TYPE_KINDS = {
    "EC2 Spot Instance Interruption Warning": INTERRUPTION_SPOT,
    "EC2 Instance Rebalance Recommendation": INTERRUPTION_REBALANCE,
    "EC2 Instance State-change Notification": INTERRUPTION_STOPPING,
}
# State-change notifications that actually mean "capacity going away".
_STOPPING_STATES = frozenset({"stopping", "shutting-down"})


def _parse_event_time(value: str) -> float:
    """EventBridge ISO-8601 `time` -> epoch seconds; 0.0 when unparseable
    (the caller falls back to its own observation time)."""
    if not value:
        return 0.0
    try:
        return datetime.datetime.fromisoformat(
            value.replace("Z", "+00:00")
        ).timestamp()
    except ValueError:
        return 0.0


class Ec2CloudProvider(CloudProvider):
    """Ref: aws/cloudprovider.go CloudProvider:38-168."""

    def __init__(
        self,
        api: Optional[Ec2Api] = None,
        cluster_name: str = "test-cluster",
        cluster_endpoint: str = "https://cluster.test",
        kube_version: str = "1.21",
        ca_bundle: Optional[str] = None,
        clock: Optional[Clock] = None,
    ):
        self.clock = clock or SYSTEM_CLOCK
        self.cluster_name = cluster_name
        self.api: Ec2Api = api if api is not None else FakeEc2(cluster_name=cluster_name)
        self.subnets = SubnetProvider(self.api, self.clock)
        self.security_groups = SecurityGroupProvider(
            self.api, cluster_name, self.clock
        )
        self.instance_types = InstanceTypeProvider(
            self.api, self.subnets, self.clock
        )
        self.amis = AmiProvider(self.api, kube_version, self.clock)
        self.launch_templates = LaunchTemplateProvider(
            self.api,
            self.amis,
            self.security_groups,
            cluster_name,
            cluster_endpoint,
            ca_bundle,
            self.clock,
        )
        self.instances = InstanceProvider(
            self.api,
            self.instance_types,
            self.subnets,
            self.launch_templates,
            cluster_name,
            self.clock,
        )
        self._fleet_limiter = RateLimiter(FLEET_QPS, FLEET_BURST, self.clock)
        # Market tick numbering (poll_market_events): DescribeSpotPriceHistory
        # is a SLIDING window, so a row's rank in any one poll is not a
        # stable identity — old rows age out and renumber everything after
        # them. Seqs are therefore assigned from this process-local counter
        # as rows first cross each POOL's sort-key cursor (per-pool, so a
        # late-published row for a quiet pool is not shadowed by a busier
        # pool's newer cursor), and emitted ticks are retained (bounded —
        # see _compact_market_history_locked) so a re-fold from seq 0
        # replays the sequence. A restarted process starts both a fresh
        # numbering and a fresh PriceBook, so the two can never disagree.
        self._market_lock = threading.Lock()
        self._market_seq = 0  # vet: guarded-by(self._market_lock)
        self._market_cursors: dict = {}  # vet: guarded-by(self._market_lock)
        self._market_history: List = []  # vet: guarded-by(self._market_lock)
        # The controller's folded PriceBook (attach_market), read by the
        # sustained-ICE drift check. Plain slot (GIL-atomic swap, read-only
        # use): attach happens once at Manager boot.
        self._market_book = None

    # --- CloudProvider interface ------------------------------------------

    def create(
        self,
        constraints: Constraints,
        instance_types: Sequence[InstanceType],
        quantity: int,
        callback: Callable[[NodeSpec], None],
        pool_options: Optional[Sequence] = None,
        launch_id: Optional[str] = None,
    ) -> List[Exception]:
        """Ref: aws/cloudprovider.go Create:111-133 — one throttled fleet
        launch per packing; each launched node flows through the callback.
        `launch_id` propagates to deterministic CreateFleet ClientTokens
        (restart-safe launches; see instances._launch)."""
        errors: List[Exception] = []
        try:
            provider = Ec2Provider.deserialize(constraints)
            self._throttle()
            nodes = self.instances.create(
                constraints, provider, instance_types, quantity,
                pool_options=pool_options, launch_id=launch_id,
            )
        except Exception as error:  # noqa: BLE001 — reported, not raised
            return [error] * quantity
        for node in nodes:
            callback(node)
        shortfall = quantity - len(nodes)
        if shortfall > 0:
            errors.extend(
                [RuntimeError("fleet under-fulfilled the request")] * shortfall
            )
        return errors

    def delete(self, node: NodeSpec) -> None:
        self.instances.terminate(node)

    def list_instances(self) -> List[CloudInstance]:
        """Everything tagged as ours and not already terminating — the
        leaked-capacity GC's ground truth (DescribeInstances by the
        framework ownership tag that merge_tags stamps on every launch)."""
        from karpenter_tpu.cloudprovider.ec2.instances import PROVIDER_ID_FORMAT
        from karpenter_tpu.cloudprovider.ec2.vendor import FRAMEWORK_TAG_KEY_FORMAT

        filters = {FRAMEWORK_TAG_KEY_FORMAT.format(self.cluster_name): "owned"}
        out: List[CloudInstance] = []
        for instance in self.api.describe_instances_by_tag(filters):
            if instance.state in ("terminated", "shutting-down"):
                continue
            out.append(
                CloudInstance(
                    instance_id=instance.instance_id,
                    provider_id=PROVIDER_ID_FORMAT.format(
                        zone=instance.zone, instance_id=instance.instance_id
                    ),
                    instance_type=instance.instance_type,
                    zone=instance.zone,
                    capacity_type="spot" if instance.spot else "on-demand",
                    state=instance.state,
                    launched_at=instance.launched_at,
                )
            )
        return out

    def terminate_instance(self, instance: CloudInstance) -> None:
        self.instances.terminate_by_id(instance.instance_id)

    def poll_interruptions(self) -> List[InterruptionEvent]:
        """Drain one poll of the EventBridge-fed queue into typed events.
        Messages that map to an event are left on the queue (at-least-once —
        the controller acks after durably recording the interruption);
        messages that map to nothing (state changes we don't act on, foreign
        sources) are deleted here so noise can't clog the queue."""
        events: List[InterruptionEvent] = []
        for message in self.api.receive_queue_messages():
            event = self._to_interruption(message)
            if event is None:
                self.api.delete_queue_message(message.receipt_handle)
                continue
            events.append(event)
        return events

    def _to_interruption(self, message) -> Optional[InterruptionEvent]:
        # Anything can land on an SQS queue. EVERY malformed shape — invalid
        # JSON, a non-object body, a non-dict detail, a numeric time — must
        # map to None (and therefore deletion) rather than raise: an
        # exception here would abort the whole poll before the message is
        # deleted, and the poison re-delivery would starve every real
        # reclaim warning behind it forever.
        try:
            body = json.loads(message.body)
            kind = _DETAIL_TYPE_KINDS.get(body.get("detail-type", ""))
            detail = body.get("detail") or {}
            instance_id = detail.get("instance-id")
            state = detail.get("state")
            observed = _parse_event_time(body.get("time", ""))
        except (ValueError, AttributeError, TypeError):
            return None
        if kind is None or not instance_id or not isinstance(instance_id, str):
            return None
        if kind == INTERRUPTION_STOPPING and state not in _STOPPING_STATES:
            return None
        deadline = None
        if kind != INTERRUPTION_REBALANCE:
            deadline = (
                observed or self.clock.now()
            ) + DEFAULT_INTERRUPTION_DEADLINE_SECONDS
        return InterruptionEvent(
            kind=kind,
            instance_id=instance_id,
            deadline=deadline,
            event_id=message.receipt_handle,
            detail=body.get("detail-type", ""),
        )

    def ack_interruption(self, event: InterruptionEvent) -> None:
        self.api.delete_queue_message(event.event_id)

    def attach_market(self, book) -> None:
        """Advertised spot offering prices track the controller's folded
        market (instancetypes applies the book's discounts at get); the
        book is also retained for the sustained-ICE drift verdict."""
        self.instance_types.attach_market(book)
        self._market_book = book

    # Sustained-ICE drift window, in FEED time: a spot pool must stay
    # ICE-closed this long before its nodes count as provider-drifted —
    # far past the 45s blackout TTL, so ordinary capacity wobble (the ICE
    # open/close churn every storm produces) never rolls a fleet.
    DRIFT_ICE_SUSTAINED_S = 600.0

    def instance_drifted(self, node: NodeSpec) -> Optional[str]:
        """Provider-side drift verdicts, cheapest check first:
        (1) the node's instance type dropped out of the RAW catalog (the
        undiscounted DescribeInstanceTypes view — the blackout/market-
        filtered catalog would flip on every transient ICE);
        (2) its spot pool has been ICE-closed past DRIFT_ICE_SUSTAINED_S of
        feed time in the folded PriceBook;
        (3) the live instance's AMI no longer matches what a launch today
        would resolve — one DescribeInstances over the shared retry
        envelope, compared against the AmiProvider's current resolution
        (content-hashed launch-template names make AMI divergence the same
        fact as template-version divergence).
        Read-only; an API failure returns None (no verdict — drift is
        voluntary, so the conservative answer is "not drifted")."""
        try:
            infos = self.instance_types._get_infos()
        except Exception:  # noqa: BLE001 — coded API errors only
            return None
        if node.instance_type and node.instance_type not in infos:
            return f"instance type {node.instance_type} no longer advertised"
        verdict = self._ice_closed_verdict(node)
        if verdict is not None:
            return verdict
        return self._ami_drift_verdict(node)

    def _ami_drift_verdict(self, node: NodeSpec) -> Optional[str]:
        if not node.provider_id:
            return None
        try:
            instance_id = parse_instance_id(node.provider_id)
            described = self.instances._describe_with_retry([instance_id])
        except CloudProviderError:
            return None
        live = [i for i in described if i.instance_id == instance_id]
        if not live or not live[0].image_id:
            return None  # gone/unknown: the GC's problem, not drift's
        catalog_type = next(
            (
                t
                for t in self.get_instance_types()
                if t.name == node.instance_type
            ),
            None,
        )
        if catalog_type is None:
            return None  # no offerings right now: transient, not drift
        try:
            current_amis = self.amis.get([catalog_type])
        except Exception:  # noqa: BLE001 — SSM faults are not a verdict
            return None
        if live[0].image_id not in current_amis:
            return (
                f"ami {live[0].image_id} superseded by "
                f"{'/'.join(sorted(current_amis))}"
            )
        return None

    def _ice_closed_verdict(self, node: NodeSpec) -> Optional[str]:
        book = self._market_book
        if book is None or node.capacity_type != wellknown.CAPACITY_TYPE_SPOT:
            return None
        closed_at = book.closed_since((node.instance_type, node.zone))
        newest = book.last_tick_at()
        if closed_at is None or newest is None:
            return None
        closed_for = newest - closed_at
        if closed_for < self.DRIFT_ICE_SUSTAINED_S:
            return None
        return (
            f"spot pool ({node.instance_type}, {node.zone}) ICE-closed "
            f"for {closed_for:.0f}s"
        )

    # Retained-tick budget: past this the oldest half of the history
    # collapses to its newest tick per pool (exactly the snapshot a
    # from-0 re-fold needs) so a weeks-long controller doesn't hoard
    # every price change ever seen.
    MARKET_HISTORY_MAX = 50_000
    # Safe market-sweep cadence when --market-poll-interval is left at
    # auto: every poll is a paginated DescribeSpotPriceHistory, so 1 Hz
    # (the in-memory fake's cadence) would burn ~86k calls/day against
    # the API throttle shared with fleet/catalog calls.
    MARKET_POLL_DEFAULT_S = 15.0

    def poll_market_events(self, after_seq: int = 0) -> List:
        """DescribeSpotPriceHistory rows as a strictly-ordered, replayable
        tick stream. Rows sort on (timestamp, type, zone, price) — a total
        deterministic order — and each row is assigned a seq from a
        process-local counter the first time it crosses its POOL's sort-key
        cursor, then retained: seqs stay stable when the API's sliding
        window drops old rows, a late-published row for one pool is never
        shadowed by another pool's newer rows (eventual consistency), and a
        re-fold from seq 0 replays the in-process sequence (see __init__).
        A row at or below its own pool's cursor is stale information by
        construction (the book only folds forward) and is dropped.
        Discounts derive from the offering catalog's on-demand prices;
        rows for unknown pools are skipped (no anchor = no discount)."""
        from karpenter_tpu.market.feed import TICK_PRICE, MarketTick

        rows = sorted(
            self.api.describe_spot_price_history(),
            key=lambda r: (r.timestamp, r.instance_type, r.zone, r.price),
        )
        od_prices = self.instance_types.on_demand_prices()
        with self._market_lock:
            for row in rows:
                pool = (row.instance_type, row.zone)
                key = (row.timestamp, row.price)
                cursor = self._market_cursors.get(pool)
                if cursor is not None and key <= cursor:
                    continue
                self._market_cursors[pool] = key
                od = od_prices.get(pool, 0.0)
                if od <= 0:
                    continue
                self._market_seq += 1
                discount = row.price / od
                self._market_history.append(
                    MarketTick(
                        seq=self._market_seq,
                        kind=TICK_PRICE,
                        instance_type=row.instance_type,
                        zone=row.zone,
                        discount=discount,
                        # EC2 never reveals pool depth, but the forecast's
                        # trend leg is computed from depth deltas — so proxy
                        # it as 1/discount (spot price climbing toward
                        # on-demand = the pool draining), the same inverse
                        # price/depth coupling the simulated walk produces.
                        # A sustained price climb then raises hazard BEFORE
                        # any interruption lands, on the real backend too.
                        depth=1.0 / discount,
                        at=row.timestamp,
                    )
                )
            if len(self._market_history) > self.MARKET_HISTORY_MAX:
                self._compact_market_history_locked()
            # Ordered by seq but not necessarily dense after compaction.
            return [t for t in self._market_history if t.seq > after_seq]

    def _compact_market_history_locked(self) -> None:
        """Bound the replay history: the oldest half collapses to its
        newest tick per pool — the snapshot a from-0 re-fold needs to
        anchor quiet pools — and pools superseded in the kept tail drop
        out entirely. Seqs are preserved (the fold keys on them), so the
        stream stays strictly ordered, just no longer dense."""
        half = len(self._market_history) // 2
        prefix, tail = (
            self._market_history[:half],
            self._market_history[half:],
        )
        newest_by_pool = {tick.pool: tick for tick in prefix}
        tail_pools = {tick.pool for tick in tail}
        snapshot = sorted(
            (
                tick
                for pool, tick in newest_by_pool.items()
                if pool not in tail_pools
            ),
            key=lambda tick: tick.seq,
        )
        self._market_history = snapshot + tail

    def blackout_offering(
        self, instance_type: str, zone: str, capacity_type: str
    ) -> None:
        """Interruption-driven exclusion rides the ICE blackout cache, so a
        reclaimed pool vanishes from get_instance_types for the TTL and the
        replacement re-solve picks other pools."""
        self.instance_types.cache_unavailable(instance_type, zone, capacity_type)

    def get_instance_types(
        self, constraints: Optional[Constraints] = None
    ) -> List[InstanceType]:
        if constraints is not None and constraints.provider is not None:
            provider = Ec2Provider.deserialize(constraints)
        else:
            provider = self._discovery_provider()
        return self.instance_types.get(provider)

    def default(self, provisioner: Provisioner) -> None:
        default_provider_blob(provisioner, self.cluster_name)

    def validate(self, provisioner: Provisioner) -> None:
        Ec2Provider.deserialize(provisioner.spec.constraints).validate()

    # --- helpers -----------------------------------------------------------

    def _discovery_provider(self) -> Ec2Provider:
        from karpenter_tpu.cloudprovider.ec2.vendor import CLUSTER_TAG_KEY_FORMAT

        discovery = {CLUSTER_TAG_KEY_FORMAT.format(self.cluster_name): "*"}
        return Ec2Provider(
            instance_profile="discovery",
            subnet_selector=discovery,
            security_group_selector=dict(discovery),
        )

    def _throttle(self) -> None:
        while not self._fleet_limiter.try_acquire():
            self.clock.sleep(max(self._fleet_limiter.wait_time(), 0.001))
