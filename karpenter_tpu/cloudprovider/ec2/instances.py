"""Instance launch/terminate through the fleet API.

Ref: pkg/cloudprovider/aws/instance.go — capacity-type choice (spot iff
allowed and offered), launch-template config assembly, the
(instance type × zone × subnet) override cross-product with spot priority,
instant-fleet launch with partial-fulfillment tolerance, recording
insufficient-capacity pools into the blackout cache, eventually-consistent
describe with retry, and instance → node conversion.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider import (
    CloudProviderError,
    InstanceType,
    NodeSpec,
)
from karpenter_tpu.cloudprovider.ec2.api import (
    INSUFFICIENT_CAPACITY_ERROR_CODE,
    Ec2Api,
    FleetOverride,
    FleetRequest,
    FleetResult,
    Instance,
    derive_client_token,
    is_not_found,
)
from karpenter_tpu.cloudprovider.ec2.instancetypes import InstanceTypeProvider
from karpenter_tpu.cloudprovider.ec2.launchtemplates import LaunchTemplateProvider
from karpenter_tpu.cloudprovider.ec2.network import SubnetProvider
from karpenter_tpu.cloudprovider.ec2.vendor import Ec2Provider, merge_tags
from karpenter_tpu.utils.clock import Clock, SYSTEM_CLOCK
from karpenter_tpu.utils.crashpoints import crashpoint

DESCRIBE_RETRY_ATTEMPTS = 3  # ref: instance.go:57-61
DESCRIBE_RETRY_DELAY = 1.0

# States in which the instance is not (or will not stay) usable capacity.
# EC2 keeps terminated instances DESCRIBABLE for about an hour, and a
# ClientToken replay hands back the ORIGINAL instance ids regardless of
# their state — so adoption must filter on liveness or it would register
# Nodes backed by corpses.
DEAD_INSTANCE_STATES = frozenset(
    {"shutting-down", "terminated", "stopping", "stopped"}
)

# When a replayed token yields ONLY corpses (controller down past the GC
# grace: the sweep terminated the orphans, then the restart re-issued the
# launch), the launch walks to the next token generation and buys fresh.
# The walk is deterministic — generation g derives from (launch_id, g) — so
# a crash mid-walk replays the same sequence. The cap bounds the pathology
# of every prior generation having been bought and reaped.
MAX_LAUNCH_GENERATIONS = 4

PROVIDER_ID_FORMAT = "aws:///{zone}/{instance_id}"


class FleetLaunchError(CloudProviderError):
    """CreateFleet produced zero instances (ref: instance.go
    combineFleetErrors:302-311)."""

    def __init__(self, errors):
        unique = sorted({f"{e.code}: {e.message}" for e in errors})
        super().__init__(
            "with fleet error(s), " + ("; ".join(unique) or "no usable capacity pools")
        )
        self.fleet_errors = list(errors)


class InstanceProvider:
    """Ref: aws/instance.go InstanceProvider:38-146."""

    def __init__(
        self,
        api: Ec2Api,
        instance_type_provider: InstanceTypeProvider,
        subnet_provider: SubnetProvider,
        launch_template_provider: LaunchTemplateProvider,
        cluster_name: str,
        clock: Optional[Clock] = None,
    ):
        self.api = api
        self.instance_type_provider = instance_type_provider
        self.subnet_provider = subnet_provider
        self.launch_template_provider = launch_template_provider
        self.cluster_name = cluster_name
        self.clock = clock or SYSTEM_CLOCK

    def create(
        self,
        constraints: Constraints,
        provider: Ec2Provider,
        instance_types: Sequence[InstanceType],
        quantity: int,
        pool_options=None,
        launch_id: Optional[str] = None,
    ) -> List[NodeSpec]:
        """Launch up to `quantity` nodes; partial fulfillment returns fewer
        (ref: instance.go Create:49-89). instance_types should be sorted
        smallest-first — spot priority derives from that order. `pool_options`
        (price-ranked PoolOption rows) pins per-pool override rows instead.
        `launch_id` makes the fleet calls restart-idempotent (deterministic
        ClientTokens; see _launch)."""
        instances: List[Instance] = []
        for generation in range(MAX_LAUNCH_GENERATIONS):
            generation_id = launch_id
            if launch_id and generation:
                generation_id = f"{launch_id}|g{generation}"
            instance_ids = self._launch(
                constraints, provider, instance_types, quantity, pool_options,
                launch_id=generation_id,
            )
            # Capacity is bought (instance ids in hand); nothing upstream
            # knows yet — the canonical crash/leak window the GC +
            # idempotent tokens exist for.
            crashpoint("cloud.after-create-fleet")
            described = self._describe_with_retry(instance_ids)
            instances = [
                i for i in described if i.state not in DEAD_INSTANCE_STATES
            ]
            if instances or not launch_id:
                break
            # Every id the fleet calls handed back is a corpse: the token
            # replayed a pre-crash purchase whose capacity was since
            # terminated. Walk to the next deterministic generation.
        by_name = {t.name: t for t in instance_types}
        nodes, strays = [], []
        for instance in instances:
            instance_type = by_name.get(instance.instance_type)
            if instance_type is None:
                # Fleet launched a type we didn't offer: terminate it rather
                # than leak a running, untracked instance.
                strays.append(instance.instance_id)
                continue
            nodes.append(self._to_node(instance, instance_type))
        if strays:
            self.api.terminate_instances(strays)
        if not nodes:
            raise CloudProviderError("zero nodes were created")
        return nodes

    def terminate(self, node: NodeSpec) -> None:
        """Ref: instance.go Terminate:91-105 — not-found is success."""
        self.terminate_by_id(parse_instance_id(node.provider_id))

    def terminate_by_id(self, instance_id: str) -> None:
        """Not-found is success (raced normal termination / already gone) —
        the one terminate contract, shared by node deletion and the
        leaked-capacity GC."""
        try:
            self.api.terminate_instances([instance_id])
        except Exception as error:  # noqa: BLE001 — coded errors only
            if is_not_found(error):
                return
            raise

    # --- launch ------------------------------------------------------------

    def _launch(
        self,
        constraints: Constraints,
        provider: Ec2Provider,
        instance_types: Sequence[InstanceType],
        quantity: int,
        pool_options=None,
        launch_id: Optional[str] = None,
    ) -> List[str]:
        """Ref: instance.go launchInstances:107-146.

        With `launch_id`, every CreateFleet call in the template walk gets a
        ClientToken derived from (cluster, launch_id, call index, and the
        FULL request content — template, capacity type, quantity, override
        rows, tags): the walk is deterministic (templates is
        insertion-ordered from the same inputs), so a controller that
        crashed after a fleet call and re-issues the same logical launch
        replays the identical token sequence and ADOPTS the instances the
        first attempt bought instead of buying twice. Binding the token to
        the request content matters for the OTHER restart path: the ICE
        blackout cache empties on restart (and subnets/offerings drift), so
        a re-solve can rebuild DIFFERENT override rows for the same logical
        launch — EC2 rejects a reused token whose parameters changed
        (IdempotentParameterMismatch), which would wedge the launch loop
        until the idempotency window expires. A drifted request instead
        mints a fresh token and buys fresh; the first attempt's orphans are
        the leaked-capacity GC's job."""
        capacity_type = self.pick_capacity_type(constraints, instance_types)
        templates = self.launch_template_provider.get(
            constraints,
            provider,
            instance_types,
            {wellknown.CAPACITY_TYPE_LABEL: capacity_type},
        )
        subnets = self.subnet_provider.get(provider)
        allowed_zones = constraints.effective_requirements().zones()
        result = FleetResult()
        fleet_call_index = 0
        for template_name, template_types in templates.items():
            if pool_options:
                overrides = self.build_pool_overrides(
                    pool_options, template_types, subnets, allowed_zones,
                    capacity_type,
                )
            else:
                overrides = self.build_overrides(
                    template_types, subnets, allowed_zones, capacity_type
                )
            if not overrides:
                continue
            request = FleetRequest(
                launch_template_name=template_name,
                overrides=overrides,
                capacity_type=capacity_type,
                quantity=quantity - len(result.instance_ids),
                tags=merge_tags(self.cluster_name, "", dict(provider.tags)),
            )
            if launch_id:
                request.client_token = derive_client_token(
                    "CreateFleet",
                    self.cluster_name,
                    launch_id,
                    str(fleet_call_index),
                    request.idempotency_payload(),
                )
            fleet_call_index += 1
            fleet = self.api.create_fleet(request)
            self._record_unavailable(fleet, capacity_type)
            result.instance_ids.extend(fleet.instance_ids)
            result.errors.extend(fleet.errors)
            if len(result.instance_ids) >= quantity:
                break
        if not result.instance_ids:
            raise FleetLaunchError(result.errors)
        return result.instance_ids

    def pick_capacity_type(
        self, constraints: Constraints, instance_types: Sequence[InstanceType]
    ) -> str:
        """Spot iff the constraints allow spot AND some offering has it in an
        allowed zone; otherwise on-demand (ref: instance.go
        getCapacityType:281-292)."""
        requirements = constraints.effective_requirements()
        allowed = requirements.capacity_types()
        if allowed is not None and wellknown.CAPACITY_TYPE_SPOT not in allowed:
            return wellknown.CAPACITY_TYPE_ON_DEMAND
        if allowed is None:
            # Unconstrained capacity type defaults to on-demand (the vendor
            # defaulting hook normally pins this; this is the backstop).
            return wellknown.CAPACITY_TYPE_ON_DEMAND
        zones = requirements.zones()
        for instance_type in instance_types:
            for offering in instance_type.offerings:
                if offering.capacity_type != wellknown.CAPACITY_TYPE_SPOT:
                    continue
                if zones is None or offering.zone in zones:
                    return wellknown.CAPACITY_TYPE_SPOT
        return wellknown.CAPACITY_TYPE_ON_DEMAND

    def build_overrides(
        self,
        instance_types: Sequence[InstanceType],
        subnets,
        allowed_zones,
        capacity_type: str,
    ) -> List[FleetOverride]:
        """Cross product of instance types × offerings × subnets, one subnet
        per zone, spot priority = smallest-first index (ref: instance.go
        getOverrides:173-207)."""
        subnet_by_zone: Dict[str, str] = {}
        for subnet in subnets:
            subnet_by_zone.setdefault(subnet.zone, subnet.subnet_id)
        overrides = []
        for index, instance_type in enumerate(instance_types):
            for offering in instance_type.offerings:
                if offering.capacity_type != capacity_type:
                    continue
                if allowed_zones is not None and offering.zone not in allowed_zones:
                    continue
                subnet_id = subnet_by_zone.get(offering.zone)
                if subnet_id is None:
                    continue
                overrides.append(
                    FleetOverride(
                        instance_type=instance_type.name,
                        subnet_id=subnet_id,
                        zone=offering.zone,
                        priority=float(index)
                        if capacity_type == wellknown.CAPACITY_TYPE_SPOT
                        else None,
                    )
                )
        return overrides

    def build_pool_overrides(
        self,
        pool_options,
        template_types: Sequence[InstanceType],
        subnets,
        allowed_zones,
        capacity_type: str,
    ) -> List[FleetOverride]:
        """Override rows from a cost-aware plan's pinned pools: per-POOL
        priority (price rank) instead of the reference's per-type index —
        same row budget, strictly finer control over what spot's
        capacity-optimized-prioritized allocation may pick."""
        template_names = {t.name for t in template_types}
        subnet_by_zone: Dict[str, str] = {}
        for subnet in subnets:
            subnet_by_zone.setdefault(subnet.zone, subnet.subnet_id)
        overrides = []
        for pool in pool_options:
            if pool.instance_type.name not in template_names:
                continue
            if allowed_zones is not None and pool.zone not in allowed_zones:
                continue
            subnet_id = subnet_by_zone.get(pool.zone)
            if subnet_id is None:
                continue
            offered = any(
                o.zone == pool.zone and o.capacity_type == capacity_type
                for o in pool.instance_type.offerings
            )
            if not offered:
                continue
            overrides.append(
                FleetOverride(
                    instance_type=pool.instance_type.name,
                    subnet_id=subnet_id,
                    zone=pool.zone,
                    priority=float(pool.priority)
                    if capacity_type == wellknown.CAPACITY_TYPE_SPOT
                    else None,
                )
            )
        return overrides

    def _record_unavailable(self, fleet: FleetResult, capacity_type: str) -> None:
        """Feed ICE pools into the blackout cache (ref: instance.go
        updateUnavailableOfferingsCache:270-276)."""
        for error in fleet.errors:
            if error.code == INSUFFICIENT_CAPACITY_ERROR_CODE:
                self.instance_type_provider.cache_unavailable(
                    error.instance_type, error.zone, capacity_type
                )

    # --- describe / convert ------------------------------------------------

    def _describe_with_retry(self, instance_ids: List[str]) -> List[Instance]:
        """EC2 is eventually consistent (ref: instance.go:55-65)."""
        last_error: Optional[Exception] = None
        for attempt in range(DESCRIBE_RETRY_ATTEMPTS):
            try:
                return self.api.describe_instances(instance_ids)
            except Exception as error:  # noqa: BLE001 — coded errors only
                last_error = error
                if attempt < DESCRIBE_RETRY_ATTEMPTS - 1:
                    self.clock.sleep(DESCRIBE_RETRY_DELAY)
        raise CloudProviderError(f"describing instances: {last_error}")

    def _to_node(self, instance: Instance, instance_type: InstanceType) -> NodeSpec:
        """Ref: instance.go instanceToNode:232-268."""
        capacity_type = (
            wellknown.CAPACITY_TYPE_SPOT
            if instance.spot
            else wellknown.CAPACITY_TYPE_ON_DEMAND
        )
        return NodeSpec(
            name=instance.private_dns_name or instance.instance_id,
            labels={
                wellknown.ZONE_LABEL: instance.zone,
                wellknown.INSTANCE_TYPE_LABEL: instance.instance_type,
                wellknown.CAPACITY_TYPE_LABEL: capacity_type,
            },
            capacity=dict(instance_type.capacity),
            instance_type=instance.instance_type,
            zone=instance.zone,
            capacity_type=capacity_type,
            provider_id=PROVIDER_ID_FORMAT.format(
                zone=instance.zone, instance_id=instance.instance_id
            ),
            created_at=self.clock.now(),
        )


def parse_instance_id(provider_id: str) -> str:
    """Ref: instance.go getInstanceID:294-300."""
    parts = provider_id.split("/")
    if len(parts) < 5:
        raise CloudProviderError(f"parsing instance id from {provider_id!r}")
    return parts[4]
