"""In-memory fake of the EC2-shaped API, with fault injection.

Ref: pkg/cloudprovider/aws/fake/ec2api.go — records CreateFleet /
CreateLaunchTemplate inputs, simulates instances, injects
InsufficientInstanceCapacity per (type, zone, capacity-type) pool, and ships
a canned instance-type table (ec2api.go:214-388). fake/ssmapi.go fakes AMI
parameters. This fake is the test double for the whole provider stack and
the default backend when no real cloud is configured.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import threading
from dataclasses import replace
from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

from karpenter_tpu.cloudprovider.ec2.api import (
    INSUFFICIENT_CAPACITY_ERROR_CODE,
    ApiError,
    Ec2Api,
    FleetError,
    FleetRequest,
    FleetResult,
    Instance,
    InstanceTypeInfo,
    InstanceTypeOffering,
    LaunchTemplate,
    QueueMessage,
    SecurityGroup,
    SpotPrice,
    Subnet,
    match_tags,
)

ZONES = ("test-zone-1a", "test-zone-1b", "test-zone-1c")

SPOT_DISCOUNT = 0.6  # flat fake spot market: spot = 60% of on-demand


def default_instance_type_infos() -> List[InstanceTypeInfo]:
    """Canned table (ref: fake/ec2api.go:214-388): general purpose sizes,
    burstable, GPU, neuron, ARM, pod-ENI trunking, plus rows the opinionated
    filter must drop (bare metal, FPGA, unsupported family)."""
    return [
        InstanceTypeInfo(
            name="m5.large", vcpus=2, memory_mib=8 * 1024, price_on_demand=0.096,
            max_network_interfaces=3, ipv4_addresses_per_interface=10,
        ),
        InstanceTypeInfo(
            name="m5.xlarge", vcpus=4, memory_mib=16 * 1024, price_on_demand=0.192,
            max_network_interfaces=4, ipv4_addresses_per_interface=15,
        ),
        InstanceTypeInfo(
            name="m5.2xlarge", vcpus=8, memory_mib=32 * 1024, price_on_demand=0.384,
            max_network_interfaces=4, ipv4_addresses_per_interface=15,
        ),
        InstanceTypeInfo(
            name="c5.large", vcpus=2, memory_mib=4 * 1024, price_on_demand=0.085,
            max_network_interfaces=3, ipv4_addresses_per_interface=10,
        ),
        InstanceTypeInfo(
            name="r5.large", vcpus=2, memory_mib=16 * 1024, price_on_demand=0.126,
            max_network_interfaces=3, ipv4_addresses_per_interface=10,
        ),
        InstanceTypeInfo(
            name="t3.medium", vcpus=2, memory_mib=4 * 1024, price_on_demand=0.0416,
            max_network_interfaces=3, ipv4_addresses_per_interface=6,
        ),
        InstanceTypeInfo(
            name="p3.8xlarge", vcpus=32, memory_mib=244 * 1024, price_on_demand=12.24,
            nvidia_gpus=4, max_network_interfaces=8, ipv4_addresses_per_interface=30,
        ),
        InstanceTypeInfo(
            name="g4dn.8xlarge", vcpus=32, memory_mib=128 * 1024, price_on_demand=2.176,
            nvidia_gpus=1, max_network_interfaces=4, ipv4_addresses_per_interface=15,
        ),
        InstanceTypeInfo(
            name="inf1.6xlarge", vcpus=24, memory_mib=48 * 1024, price_on_demand=1.18,
            neurons=4, max_network_interfaces=8, ipv4_addresses_per_interface=30,
        ),
        InstanceTypeInfo(
            name="m6g.large", vcpus=2, memory_mib=8 * 1024, price_on_demand=0.077,
            architectures=("arm64",), max_network_interfaces=3,
            ipv4_addresses_per_interface=10,
        ),
        InstanceTypeInfo(
            name="m5.metal", vcpus=96, memory_mib=384 * 1024, price_on_demand=4.608,
            bare_metal=True, max_network_interfaces=15,
            ipv4_addresses_per_interface=50,
        ),
        InstanceTypeInfo(
            name="f1.2xlarge", vcpus=8, memory_mib=122 * 1024, price_on_demand=1.65,
            fpga=True,
        ),
        InstanceTypeInfo(
            name="d3.xlarge", vcpus=4, memory_mib=32 * 1024, price_on_demand=0.499,
        ),
        # Pod-ENI / trunking capable (security-groups-for-pods).
        InstanceTypeInfo(
            name="m5.4xlarge", vcpus=16, memory_mib=64 * 1024, price_on_demand=0.768,
            max_network_interfaces=8, ipv4_addresses_per_interface=30,
            pod_eni_branch_interfaces=54,
        ),
    ]


class FakeEc2(Ec2Api):
    """Thread-safe in-memory cloud. All mutating calls are recorded for
    assertions (ref: fake/ec2api.go CalledWithCreateFleetInput etc.)."""

    def __init__(
        self,
        instance_type_infos: Optional[List[InstanceTypeInfo]] = None,
        zones: Sequence[str] = ZONES,
        cluster_name: str = "test-cluster",
    ):
        self.zones = tuple(zones)
        self.instance_type_infos = (
            default_instance_type_infos()
            if instance_type_infos is None
            else list(instance_type_infos)
        )
        cluster_tag = f"kubernetes.io/cluster/{cluster_name}"
        self.subnets: List[Subnet] = [
            Subnet(
                subnet_id=f"subnet-{i + 1}",
                zone=zone,
                tags={cluster_tag: "owned", "Name": f"private-{zone}"},
            )
            for i, zone in enumerate(self.zones)
        ]
        self.security_groups: List[SecurityGroup] = [
            SecurityGroup(group_id="sg-test1", tags={cluster_tag: "owned"}),
            SecurityGroup(group_id="sg-test2", tags={cluster_tag: "owned"}),
            SecurityGroup(group_id="sg-test3", tags={"other-tag": "yes"}),
        ]
        self.ami_parameters: Dict[str, str] = {}  # path -> ami id; see get_ami_parameter
        # Fault injection: pools that report InsufficientInstanceCapacity
        # (ref: fake/ec2api.go InsufficientCapacityPools:54).
        self.insufficient_capacity_pools: Set[Tuple[str, str, str]] = set()

        self.launch_templates: Dict[str, LaunchTemplate] = {}
        self.instances: Dict[str, Instance] = {}
        # Terminated instances stay DESCRIBABLE with state="terminated",
        # exactly like EC2 (corpses linger in DescribeInstances for about an
        # hour): the launch path's liveness filter and corpse-replay
        # recovery only exist on the real wire surface, so the fake must
        # not hide dead instances for them to be testable.
        self.corpses: Dict[str, Instance] = {}
        # ClientToken -> (request fingerprint, instance ids) of the fleet
        # that token bought. A repeated token replays those ids instead of
        # launching again — INCLUDING since-terminated ones, which is what
        # EC2 does (idempotency replays the recorded result, not a liveness
        # check) — the server-side half of restart-safe launches. A reused
        # token with DIFFERENT request parameters is rejected, also like
        # EC2 (IdempotentParameterMismatch).
        self._fleet_tokens: Dict[str, Tuple[str, List[str]]] = {}
        # Injectable interruption queue: receipt_handle -> message, delivered
        # until deleted (the SQS visibility model, so record-then-ack crash
        # consistency is testable against this fake too).
        self.interruption_messages: Dict[str, QueueMessage] = {}
        # Injectable spot-price history (DescribeSpotPriceHistory rows):
        # append-only, re-served in full on every poll — the replayable
        # cursorless history the market controller re-folds after a restart.
        self.spot_price_history: List[SpotPrice] = []
        self.calls: Dict[str, List] = {
            "create_fleet": [],
            "create_launch_template": [],
            "terminate_instances": [],
            "delete_queue_message": [],
        }
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    # --- discovery ---------------------------------------------------------

    def describe_instance_types(self) -> List[InstanceTypeInfo]:
        return list(self.instance_type_infos)

    def describe_instance_type_offerings(self) -> List[InstanceTypeOffering]:
        offerings = []
        for info in self.instance_type_infos:
            for zone in self.zones:
                for capacity_type in info.supported_usage_classes:
                    price = info.price_on_demand
                    if capacity_type == "spot":
                        price *= SPOT_DISCOUNT
                    offerings.append(
                        InstanceTypeOffering(
                            instance_type=info.name,
                            zone=zone,
                            capacity_type=capacity_type,
                            price=price,
                        )
                    )
        return offerings

    def inject_spot_price(
        self, instance_type: str, zone: str, price: float, timestamp: float = 0.0
    ) -> SpotPrice:
        """Test hook: append one DescribeSpotPriceHistory row."""
        row = SpotPrice(
            instance_type=instance_type,
            zone=zone,
            price=price,
            timestamp=timestamp,
        )
        self.spot_price_history.append(row)
        return row

    def describe_spot_price_history(self) -> List[SpotPrice]:
        return list(self.spot_price_history)

    def describe_subnets(self, filters: Mapping[str, str]) -> List[Subnet]:
        return [s for s in self.subnets if match_tags(s.tags, filters)]

    def describe_security_groups(self, filters: Mapping[str, str]) -> List[SecurityGroup]:
        return [g for g in self.security_groups if match_tags(g.tags, filters)]

    # --- launch templates --------------------------------------------------

    def describe_launch_template(self, name: str) -> LaunchTemplate:
        with self._lock:
            if name not in self.launch_templates:
                raise ApiError("InvalidLaunchTemplateName.NotFoundException", name)
            return self.launch_templates[name]

    def create_launch_template(self, template: LaunchTemplate) -> LaunchTemplate:
        with self._lock:
            created = LaunchTemplate(
                name=template.name,
                template_id=f"lt-{next(self._ids):08d}",
                image_id=template.image_id,
                instance_profile=template.instance_profile,
                security_group_ids=tuple(template.security_group_ids),
                user_data=template.user_data,
                tags=dict(template.tags),
            )
            self.launch_templates[template.name] = created
            self.calls["create_launch_template"].append(created)
            return created

    # --- fleet -------------------------------------------------------------

    def create_fleet(self, request: FleetRequest) -> FleetResult:
        """Instant-fleet semantics: walk override pools in priority order,
        launching until quantity is met; ICE pools contribute errors instead
        (ref: fake/ec2api.go CreateFleetWithContext:90-136)."""
        with self._lock:
            self.calls["create_fleet"].append(request)
            if request.launch_template_name not in self.launch_templates:
                raise ApiError(
                    "InvalidLaunchTemplateName.NotFoundException",
                    request.launch_template_name,
                )
            if request.client_token and request.client_token in self._fleet_tokens:
                fingerprint, replay = self._fleet_tokens[request.client_token]
                if fingerprint != request.idempotency_payload():
                    raise ApiError(
                        "IdempotentParameterMismatch",
                        "client token reused with different parameters",
                    )
                return FleetResult(instance_ids=list(replay))
            template = self.launch_templates[request.launch_template_name]
            result = FleetResult()
            pools = sorted(
                request.overrides,
                key=lambda o: o.priority if o.priority is not None else 0.0,
            )
            seen_bad: Set[Tuple[str, str, str]] = set()
            usable = []
            for override in pools:
                pool = (override.instance_type, override.zone, request.capacity_type)
                if pool in self.insufficient_capacity_pools:
                    if pool not in seen_bad:
                        seen_bad.add(pool)
                        result.errors.append(
                            FleetError(
                                code=INSUFFICIENT_CAPACITY_ERROR_CODE,
                                message=f"no capacity in pool {pool}",
                                instance_type=override.instance_type,
                                zone=override.zone,
                            )
                        )
                    continue
                usable.append(override)
            if not usable:
                return result
            for n in range(request.quantity):
                override = usable[n % len(usable)] if request.capacity_type == "spot" else usable[0]
                instance_id = f"i-{next(self._ids):017d}"
                info = self._info(override.instance_type)
                instance = Instance(
                    instance_id=instance_id,
                    instance_type=override.instance_type,
                    zone=override.zone,
                    private_dns_name=f"ip-192-168-{(next(self._ids)) % 256}-{n % 256}."
                    f"{override.zone}.compute.internal",
                    image_id=template.image_id,
                    architecture=info.architectures[0] if info else "x86_64",
                    spot=request.capacity_type == "spot",
                    tags=dict(request.tags),
                )
                self.instances[instance_id] = instance
                result.instance_ids.append(instance_id)
            if request.client_token:
                self._fleet_tokens[request.client_token] = (
                    request.idempotency_payload(),
                    list(result.instance_ids),
                )
            return result

    def _info(self, name: str) -> Optional[InstanceTypeInfo]:
        for info in self.instance_type_infos:
            if info.name == name:
                return info
        return None

    # --- instances ---------------------------------------------------------

    def describe_instances(self, instance_ids: Sequence[str]) -> List[Instance]:
        with self._lock:
            known = {**self.corpses, **self.instances}
            missing = [i for i in instance_ids if i not in known]
            if missing:
                raise ApiError("InvalidInstanceID.NotFound", ",".join(missing))
            return [known[i] for i in instance_ids]

    def describe_instances_by_tag(
        self, filters: Mapping[str, str]
    ) -> List[Instance]:
        # Corpses show up here too — callers (the leaked-capacity GC's
        # listing) are expected to filter on state, as with real EC2.
        with self._lock:
            return [
                instance
                for instance in list(self.instances.values())
                + list(self.corpses.values())
                if match_tags(instance.tags, filters)
            ]

    def terminate_instances(self, instance_ids: Sequence[str]) -> None:
        with self._lock:
            self.calls["terminate_instances"].append(list(instance_ids))
            for instance_id in instance_ids:
                if instance_id in self.corpses:
                    continue  # terminating a terminated instance is a no-op
                if instance_id not in self.instances:
                    raise ApiError("InvalidInstanceID.NotFound", instance_id)
                live = self.instances.pop(instance_id)
                self.corpses[instance_id] = replace(live, state="terminated")

    # --- interruption queue --------------------------------------------------

    def inject_interruption_message(
        self, detail_type: str, instance_id: str, time_iso: str = "",
        detail: Optional[Dict] = None,
    ) -> QueueMessage:
        """Enqueue an EventBridge-shaped notice (the exact envelope the real
        queue carries) for the interruption poll to consume."""
        body = {
            "version": "0",
            "detail-type": detail_type,
            "source": "aws.ec2",
            "time": time_iso,
            "detail": {"instance-id": instance_id, **(detail or {})},
        }
        with self._lock:
            handle = f"rh-{next(self._ids):08d}"
            message = QueueMessage(
                message_id=f"mid-{handle}",
                receipt_handle=handle,
                body=json.dumps(body),
            )
            self.interruption_messages[handle] = message
            return message

    def receive_queue_messages(self) -> List[QueueMessage]:
        with self._lock:
            return list(self.interruption_messages.values())

    def delete_queue_message(self, receipt_handle: str) -> None:
        with self._lock:
            self.calls["delete_queue_message"].append(receipt_handle)
            self.interruption_messages.pop(receipt_handle, None)

    # --- ssm ---------------------------------------------------------------

    def get_ami_parameter(self, path: str) -> str:
        """Any recommended-image path resolves (ref: fake/ssmapi.go returns a
        deterministic fake AMI per parameter); explicit entries win."""
        if path in self.ami_parameters:
            return self.ami_parameters[path]
        if "recommended/image_id" in path:
            digest = hashlib.sha256(path.encode()).hexdigest()[:12]
            return f"ami-{digest}"
        raise ApiError("ParameterNotFound", path)
