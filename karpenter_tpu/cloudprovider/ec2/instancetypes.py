"""Instance-type discovery: raw catalog → solver-ready InstanceTypes.

Ref: pkg/cloudprovider/aws/{instancetype.go,instancetypes.go} — adapts raw
instance-type records (VM memory factor, ENI pod formula, allocatable
overhead model) and assembles offerings as
(subnet zones ∩ offered zones) × usage classes, minus the
insufficient-capacity blackout cache, all behind a 5-minute catalog cache.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.cloudprovider import ARCH_AMD64, ARCH_ARM64, InstanceType, Offering
from karpenter_tpu.cloudprovider.ec2.api import Ec2Api, InstanceTypeInfo
from karpenter_tpu.cloudprovider.ec2.network import SubnetProvider
from karpenter_tpu.cloudprovider.ec2.vendor import Ec2Provider
from karpenter_tpu.utils.cache import TtlCache
from karpenter_tpu.utils.clock import Clock, SYSTEM_CLOCK

# The VM consumes <7.5% of machine memory (ref: instancetype.go:31-32).
VM_AVAILABLE_MEMORY_FACTOR = 0.925

CATALOG_CACHE_TTL = 5 * 60.0  # ref: instancetypes.go:36
ICE_BLACKOUT_TTL = 45.0  # ref: instancetypes.go:37

_ARCH_MAP = {"x86_64": ARCH_AMD64, "arm64": ARCH_ARM64}

# Families useful for Kubernetes (ref: instancetypes.go filter:157-170):
# standard (m,c,r,a), burstable (t3,t4), accelerators (p,inf,g).
_USEFUL_PREFIXES = ("m", "c", "r", "a", "t3", "t4", "p", "inf", "g")


def pods_per_node(info: InstanceTypeInfo) -> int:
    """ENI formula: max ENIs × (IPv4 addrs per ENI − 1) + 2
    (ref: instancetype.go:72-77)."""
    return info.max_network_interfaces * (info.ipv4_addresses_per_interface - 1) + 2


def kube_reserved_cpu_millis(vcpus: int) -> int:
    """Piecewise kube-reserved CPU (ref: instancetype.go Overhead:140-157,
    the Bottlerocket formula): 6% of the first core, 1% of the second,
    0.5% of cores 3-4, 0.25% of the rest — plus 100m system-reserved."""
    millis = vcpus * 1000
    reserved = 100.0  # system-reserved
    for start, end, percentage in (
        (0, 1000, 0.06),
        (1000, 2000, 0.01),
        (2000, 4000, 0.005),
        (4000, 1 << 31, 0.0025),
    ):
        if millis >= start:
            covered = min(millis, end) - start
            reserved += covered * percentage
    return int(reserved)


def overhead_for(info: InstanceTypeInfo) -> Dict[str, str]:
    """Allocatable overhead: kube-reserved + system-reserved + eviction
    threshold (ref: instancetype.go Overhead:124-159)."""
    pods = pods_per_node(info)
    memory_mib = (11 * pods + 255) + 100 + 100
    return {
        "cpu": f"{kube_reserved_cpu_millis(info.vcpus)}m",
        "memory": f"{memory_mib}Mi",
    }


def adapt_instance_type(
    info: InstanceTypeInfo, offerings: List[Offering]
) -> InstanceType:
    """Raw record → solver InstanceType with allocatable-view capacity."""
    capacity = {
        wellknown.RESOURCE_CPU: info.vcpus,
        wellknown.RESOURCE_MEMORY: f"{int(info.memory_mib * VM_AVAILABLE_MEMORY_FACTOR)}Mi",
        wellknown.RESOURCE_PODS: pods_per_node(info),
    }
    if info.nvidia_gpus:
        capacity[wellknown.RESOURCE_NVIDIA_GPU] = info.nvidia_gpus
    if info.amd_gpus:
        capacity[wellknown.RESOURCE_AMD_GPU] = info.amd_gpus
    if info.neurons:
        capacity[wellknown.RESOURCE_AWS_NEURON] = info.neurons
    if info.tpus:
        capacity[wellknown.RESOURCE_GOOGLE_TPU] = info.tpus
    if info.pod_eni_branch_interfaces:
        capacity[wellknown.RESOURCE_AWS_POD_ENI] = info.pod_eni_branch_interfaces
    architecture = ARCH_AMD64
    for raw_arch in info.architectures:
        if raw_arch in _ARCH_MAP:
            architecture = _ARCH_MAP[raw_arch]
            break
    return InstanceType(
        name=info.name,
        capacity=capacity,
        overhead=overhead_for(info),
        architecture=architecture,
        offerings=offerings,
    )


def useful_for_kubernetes(info: InstanceTypeInfo) -> bool:
    """Opinionated filter (ref: instancetypes.go filter:157-170)."""
    if info.fpga or info.bare_metal:
        return False
    if "hvm" not in info.supported_virtualization_types:
        return False
    return info.name.startswith(_USEFUL_PREFIXES)


class InstanceTypeProvider:
    """Ref: aws/instancetypes.go InstanceTypeProvider:41-104."""

    def __init__(
        self,
        api: Ec2Api,
        subnet_provider: SubnetProvider,
        clock: Optional[Clock] = None,
    ):
        clock = clock or SYSTEM_CLOCK
        self.api = api
        self.subnet_provider = subnet_provider
        # Catalog cached *before* ICE filtering so blackouts apply instantly
        # (ref: instancetypes.go:44-46).
        self._cache = TtlCache(CATALOG_CACHE_TTL, clock)
        self._unavailable = TtlCache(ICE_BLACKOUT_TTL, clock)
        # The controller's PriceBook (attach_market): advertised spot prices
        # track its folded market; ICE-closed pools drop their spot
        # offering. Plain slot, GIL-atomic swap at boot.
        self._market_book = None
        self._lock = threading.Lock()

    def attach_market(self, book) -> None:
        self._market_book = book

    def get(self, provider: Ec2Provider) -> List[InstanceType]:
        """All instance types purchasable in the provider's subnet zones,
        with per-offering prices, minus blacked-out pools
        (ref: instancetypes.go Get:61-104)."""
        infos = self._get_infos()
        offerings_by_type = self._get_offerings()
        subnet_zones = {
            subnet.zone for subnet in self.subnet_provider.get(provider)
        }
        # One on-demand anchor map per get(), not per offering: with a
        # book attached every spot offering reprices against it, and
        # rebuilding it inside the loop would make the catalog quadratic
        # in offerings.
        od_prices = (
            self.on_demand_prices() if self._market_book is not None else {}
        )
        result = []
        for info in infos.values():
            offerings = []
            for offering in offerings_by_type.get(info.name, []):
                if offering.zone not in subnet_zones:
                    continue
                if offering.capacity_type not in info.supported_usage_classes:
                    continue
                if self.is_unavailable(
                    info.name, offering.zone, offering.capacity_type
                ):
                    continue
                priced = self._market_priced(info.name, offering, od_prices)
                if priced is not None:
                    offerings.append(priced)
            if offerings:
                result.append(adapt_instance_type(info, offerings))
        return result

    def _market_priced(self, name: str, offering, od_prices) -> Optional[Offering]:
        """One offering under the attached PriceBook, priced by the SHARED
        rule (market.pricebook.advertised_price — the fake provider calls
        the same function, so the backends cannot drift): spot follows the
        folded market (on-demand anchor x live discount), ICE-closed pools
        vanish, anything unpriced keeps the wire/catalog price."""
        from karpenter_tpu.market.pricebook import advertised_price

        pool = (name, offering.zone)
        price = advertised_price(
            self._market_book,
            pool,
            offering.capacity_type,
            offering.price,
            od_prices.get(pool),
        )
        if price is None:
            return None
        return Offering(
            zone=offering.zone, capacity_type=offering.capacity_type, price=price
        )

    def on_demand_prices(self) -> Dict[tuple, float]:
        """{(type, zone): on-demand $/hr} from the cached offering listing —
        the anchor spot discounts are computed against."""
        out: Dict[tuple, float] = {}
        for name, offerings in self._get_offerings().items():
            for offering in offerings:
                if offering.capacity_type == "on-demand":
                    out[(name, offering.zone)] = offering.price
        return out

    def _get_infos(self) -> Dict[str, InstanceTypeInfo]:
        with self._lock:
            cached = self._cache.get("types")
            if cached is not None:
                return cached
            infos = {
                info.name: info
                for info in self.api.describe_instance_types()
                if useful_for_kubernetes(info)
            }
            self._cache.set("types", infos)
            return infos

    def _get_offerings(self):
        with self._lock:
            cached = self._cache.get("offerings")
            if cached is not None:
                return cached
            by_type: Dict[str, list] = {}
            for offering in self.api.describe_instance_type_offerings():
                by_type.setdefault(offering.instance_type, []).append(offering)
            self._cache.set("offerings", by_type)
            return by_type

    # --- ICE blackout (ref: instancetypes.go CacheUnavailable:174-187) -----

    def cache_unavailable(
        self, instance_type: str, zone: str, capacity_type: str
    ) -> None:
        """Record a temporary capacity shortage; the offering disappears from
        get() for ICE_BLACKOUT_TTL so retries pick another pool."""
        self._unavailable.set((capacity_type, instance_type, zone))

    def is_unavailable(
        self, instance_type: str, zone: str, capacity_type: str
    ) -> bool:
        return (capacity_type, instance_type, zone) in self._unavailable
