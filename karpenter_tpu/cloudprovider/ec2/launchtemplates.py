"""Machine-image discovery and deterministic launch templates.

Ref: pkg/cloudprovider/aws/{ami.go,launchtemplate.go} — the AMI provider
resolves the recommended image for (k8s version, architecture, accelerator)
via a parameter-store query; the launch-template provider derives a
deterministic template name from a content hash of everything that affects
boot (cluster, user-data, instance profile, SGs, AMI, tags), discovers or
creates it under a lock, and generates hash-stable bootstrap user-data with
sorted kubelet label/taint args.
"""

from __future__ import annotations

import base64
import hashlib
import json
import threading
from typing import Dict, List, Mapping, Optional, Sequence

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.api.taints import Taint
from karpenter_tpu.cloudprovider import ARCH_ARM64, InstanceType
from karpenter_tpu.cloudprovider.ec2.api import (
    SETUP_CACHE_TTL,
    Ec2Api,
    LaunchTemplate,
    is_not_found,
)
from karpenter_tpu.cloudprovider.ec2.network import SecurityGroupProvider
from karpenter_tpu.cloudprovider.ec2.vendor import Ec2Provider, merge_tags
from karpenter_tpu.utils.cache import TtlCache
from karpenter_tpu.utils.clock import Clock, SYSTEM_CLOCK

LAUNCH_TEMPLATE_NAME_FORMAT = "KarpenterTPU-{cluster}-{hash}"


class AmiProvider:
    """Ref: aws/ami.go AMIProvider:25-110. Groups instance types by the image
    query they need (accelerator image for GPU/neuron types, arm64 image for
    ARM), then resolves each query through the parameter store, cached."""

    def __init__(
        self,
        api: Ec2Api,
        kube_version: str = "1.21",
        clock: Optional[Clock] = None,
    ):
        self.api = api
        self.kube_version = kube_version
        self._cache = TtlCache(SETUP_CACHE_TTL, clock or SYSTEM_CLOCK)
        self._lock = threading.Lock()

    def get(
        self, instance_types: Sequence[InstanceType]
    ) -> Dict[str, List[InstanceType]]:
        """ami id -> instance types bootable from it (ref: ami.go Get:35-57)."""
        by_query: Dict[str, List[InstanceType]] = {}
        for instance_type in instance_types:
            by_query.setdefault(self._query_for(instance_type), []).append(
                instance_type
            )
        by_ami: Dict[str, List[InstanceType]] = {}
        for query, types in by_query.items():
            by_ami.setdefault(self._resolve(query), []).extend(types)
        return by_ami

    def _query_for(self, instance_type: InstanceType) -> str:
        """Ref: ami.go getSSMQuery:75-83."""
        suffix = ""
        if instance_type.get(wellknown.RESOURCE_NVIDIA_GPU) or instance_type.get(
            wellknown.RESOURCE_AWS_NEURON
        ):
            suffix = "-gpu"
        elif instance_type.architecture == ARCH_ARM64:
            suffix = "-arm64"
        return (
            f"/aws/service/eks/optimized-ami/{self.kube_version}"
            f"/amazon-linux-2{suffix}/recommended/image_id"
        )

    def _resolve(self, query: str) -> str:
        with self._lock:
            cached = self._cache.get(query)
            if cached is not None:
                return cached
            ami = self.api.get_ami_parameter(query)
            self._cache.set(query, ami)
            return ami


def _needs_legacy_runtime(instance_types: Sequence[InstanceType]) -> bool:
    """GPU/neuron types can't use containerd directly in the reference's AMI
    (ref: launchtemplate.go needsDocker:163-171)."""
    return any(
        t.get(wellknown.RESOURCE_NVIDIA_GPU) or t.get(wellknown.RESOURCE_AWS_NEURON)
        for t in instance_types
    )


def _sorted_taint_args(taints: Sequence[Taint]) -> str:
    ordered = sorted(taints, key=lambda t: (t.key, t.value, t.effect))
    return ",".join(f"{t.key}={t.value}:{t.effect}" for t in ordered)


def build_user_data(
    cluster_name: str,
    cluster_endpoint: str,
    constraints: Constraints,
    instance_types: Sequence[InstanceType],
    additional_labels: Mapping[str, str],
    ca_bundle: Optional[str] = None,
) -> str:
    """Bootstrap script, byte-stable for equivalent inputs so the launch
    template hash is stable (ref: launchtemplate.go getUserData:225-285 —
    labels and taints are emitted in sorted order for exactly this reason)."""
    lines = [
        "#!/bin/bash -xe",
        "exec > >(tee /var/log/user-data.log|logger -t user-data -s 2>/dev/console) 2>&1",
    ]
    bootstrap = f"/etc/eks/bootstrap.sh '{cluster_name}'"
    if not _needs_legacy_runtime(instance_types):
        bootstrap += " --container-runtime containerd"
    bootstrap += f" \\\n    --apiserver-endpoint '{cluster_endpoint}'"
    if ca_bundle:
        bootstrap += f" \\\n    --b64-cluster-ca '{ca_bundle}'"
    labels = {**additional_labels, **constraints.labels}
    kubelet_args = []
    if labels:
        pairs = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
        kubelet_args.append(f"--node-labels={pairs}")
    if constraints.taints:
        kubelet_args.append(
            f"--register-with-taints={_sorted_taint_args(constraints.taints)}"
        )
    if kubelet_args:
        bootstrap += f" \\\n    --kubelet-extra-args '{' '.join(kubelet_args)}'"
    lines.append(bootstrap)
    return base64.b64encode("\n".join(lines).encode()).decode()


class LaunchTemplateProvider:
    """Ref: aws/launchtemplate.go LaunchTemplateProvider:47-157."""

    def __init__(
        self,
        api: Ec2Api,
        ami_provider: AmiProvider,
        security_group_provider: SecurityGroupProvider,
        cluster_name: str,
        cluster_endpoint: str = "",
        ca_bundle: Optional[str] = None,
        clock: Optional[Clock] = None,
    ):
        self.api = api
        self.ami_provider = ami_provider
        self.security_group_provider = security_group_provider
        self.cluster_name = cluster_name
        self.cluster_endpoint = cluster_endpoint
        self.ca_bundle = ca_bundle
        self._cache = TtlCache(SETUP_CACHE_TTL, clock or SYSTEM_CLOCK)
        self._lock = threading.Lock()

    def get(
        self,
        constraints: Constraints,
        provider: Ec2Provider,
        instance_types: Sequence[InstanceType],
        additional_labels: Mapping[str, str],
    ) -> Dict[str, List[InstanceType]]:
        """launch template name -> instance types it can boot
        (ref: launchtemplate.go Get:85-125). A user-specified template
        bypasses generation entirely."""
        if provider.launch_template is not None:
            return {provider.launch_template: list(instance_types)}
        security_group_ids = self.security_group_provider.get(provider)
        result: Dict[str, List[InstanceType]] = {}
        for ami_id, types in self.ami_provider.get(instance_types).items():
            user_data = build_user_data(
                self.cluster_name,
                self.cluster_endpoint,
                constraints,
                types,
                additional_labels,
                self.ca_bundle,
            )
            template = self._ensure(
                LaunchTemplate(
                    name=self._template_name(
                        ami_id, user_data, security_group_ids, provider
                    ),
                    image_id=ami_id,
                    instance_profile=provider.instance_profile,
                    security_group_ids=tuple(security_group_ids),
                    user_data=user_data,
                    tags=merge_tags(self.cluster_name, "", provider.tags),
                )
            )
            result[template.name] = types
        return result

    def _template_name(
        self,
        ami_id: str,
        user_data: str,
        security_group_ids: Sequence[str],
        provider: Ec2Provider,
    ) -> str:
        """Deterministic content-hash name (ref: launchtemplate.go
        launchTemplateName:64-83 — same inputs must produce the same
        template so templates are reused, not multiplied)."""
        payload = json.dumps(
            {
                "cluster": self.cluster_name,
                "userData": user_data,
                "instanceProfile": provider.instance_profile,
                "securityGroups": sorted(security_group_ids),
                "ami": ami_id,
                "tags": dict(sorted(provider.tags.items())),
            },
            sort_keys=True,
        )
        digest = hashlib.sha256(payload.encode()).hexdigest()[:16]
        return LAUNCH_TEMPLATE_NAME_FORMAT.format(
            cluster=self.cluster_name, hash=digest
        )

    def _ensure(self, desired: LaunchTemplate) -> LaunchTemplate:
        """Cache → describe → create (ref: ensureLaunchTemplate:127-157)."""
        with self._lock:
            cached = self._cache.get(desired.name)
            if cached is not None:
                return cached
            try:
                template = self.api.describe_launch_template(desired.name)
            except Exception as error:  # noqa: BLE001 — coded errors only
                if not is_not_found(error):
                    raise
                template = self.api.create_launch_template(desired)
            self._cache.set(desired.name, template)
            return template
