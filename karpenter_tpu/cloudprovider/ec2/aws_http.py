"""Real AWS binding for the Ec2Api boundary: SigV4-signed HTTP against the
EC2 Query API and SSM JSON API, stdlib only (no boto3 in the image).

Ref: the reference performs these exact calls through the AWS SDK —
CreateFleet type=instant with allocation strategies
(pkg/cloudprovider/aws/instance.go:116-133), DescribeInstanceTypes/
DescribeInstanceTypeOfferings paginated (aws/instancetypes.go:61-104),
DescribeSubnets/DescribeSecurityGroups by tag filter (aws/subnets.go:52-69,
securitygroups.go), launch-template CRUD (aws/launchtemplate.go), SSM
GetParameter for AMI discovery (aws/ami.go:49-110). This module is the same
wire surface hand-rolled: one class, `AwsHttpEc2Api`, implementing the typed
`Ec2Api` protocol over an injectable `HttpTransport` so tests drive it with
recorded/stub responses and production uses urllib with real credentials.

Prices: the EC2 control-plane API carries no prices; the reference ships a
generated static price table (aws/zz_generated.pricing.go). `price_catalog`
plays that role here — a mapping of instance type -> on-demand $/hr, with a
flat `spot_price_ratio` for spot rows (or `spot_prices` per (type, zone)).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import json
import os
import random
import urllib.error
import urllib.parse
import urllib.request
import uuid
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

from karpenter_tpu.cloudprovider.ec2.api import (
    ApiError,
    Ec2Api,
    derive_client_token,
    FleetError,
    FleetRequest,
    FleetResult,
    Instance,
    InstanceTypeInfo,
    InstanceTypeOffering,
    LaunchTemplate,
    QueueMessage,
    SecurityGroup,
    SpotPrice,
    Subnet,
)

from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.clock import SYSTEM_CLOCK
from karpenter_tpu.utils.metrics import REGISTRY

log = klog.named("aws")

EC2_API_VERSION = "2016-11-15"
_SSM_TARGET_PREFIX = "AmazonSSM"
_SQS_TARGET_PREFIX = "AmazonSQS"
# One poll's message budget (the SQS per-call maximum). The controller sweeps
# every couple of seconds, so a reclaim storm drains across a few polls.
SQS_MAX_MESSAGES = 10

# Retries by action and error code: a rising rate is the first visible sign
# of throttling or a flaky NAT path, well before calls start exhausting
# their budget and failing outright.
AWS_RETRY_TOTAL = REGISTRY.counter(
    "aws_retry_total",
    "AWS call attempts retried, by API action and error code",
    ["action", "code"],
)


# --- HTTP layer -------------------------------------------------------------


@dataclass
class HttpResponse:
    status: int
    body: bytes
    headers: Mapping[str, str] = field(default_factory=dict)


class HttpTransport:
    """Boundary for the actual socket I/O — tests inject a stub that replays
    recorded responses; production uses UrllibTransport."""

    def send(
        self, method: str, url: str, headers: Mapping[str, str], body: bytes
    ) -> HttpResponse:
        raise NotImplementedError


class UrllibTransport(HttpTransport):
    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout

    def send(self, method, url, headers, body) -> HttpResponse:
        request = urllib.request.Request(
            url, data=body, headers=dict(headers), method=method
        )
        try:
            with urllib.request.urlopen(request, timeout=self.timeout) as resp:
                return HttpResponse(
                    status=resp.status, body=resp.read(), headers=dict(resp.headers)
                )
        except urllib.error.HTTPError as err:  # non-2xx still has a body
            return HttpResponse(
                status=err.code, body=err.read(), headers=dict(err.headers or {})
            )
        except (urllib.error.URLError, OSError) as err:
            # Socket-level failures (DNS, reset, timeout) are normalized to a
            # coded ApiError so upstream classification — and the retryer —
            # behave identically against the real cloud and the fakes.
            raise ApiError("TransportError", str(err)) from err


# --- Retry ------------------------------------------------------------------

# Throttle codes back off harder than generic transient failures, mirroring
# the SDK's throttle/retryable split (Go SDK shouldRetry / throttle lists).
THROTTLE_CODES = frozenset(
    {
        "RequestLimitExceeded",
        "Throttling",
        "ThrottlingException",
        "RequestThrottled",
        "RequestThrottledException",
        "TooManyRequestsException",
        "EC2ThrottledException",
    }
)
_TRANSIENT_CODES = frozenset(
    {
        "TransportError",
        "RequestTimeout",
        "RequestTimeoutException",
        "InternalError",
        "InternalFailure",
        "ServiceUnavailable",
        "Unavailable",
        "InternalServiceError",
        "InternalServerError",
    }
)


@dataclass
class RetryPolicy:
    """Jittered exponential backoff with a bounded attempt budget.

    Ref: the reference's AWS session installs
    `client.DefaultRetryer{NumMaxRetries: DefaultRetryerMaxNumRetries}`
    (pkg/cloudprovider/aws/cloudprovider.go:67-69), so every EC2/SSM call
    there absorbs throttles (`RequestLimitExceeded`), 5xx, and connection
    errors for free. This is that retryer for the hand-rolled binding: equal
    jitter over an exponentially growing window, with throttle codes backing
    off from a larger base than generic transient failures (the SDK's 500ms
    vs 30ms minimums).
    """

    max_retries: int = 3
    base_delay: float = 0.03
    throttle_base: float = 0.5
    max_delay: float = 20.0
    sleep: Callable[[float], None] = SYSTEM_CLOCK.sleep
    rng: Callable[[], float] = random.random

    def is_retryable(self, code: str) -> bool:
        if code in THROTTLE_CODES or code in _TRANSIENT_CODES:
            return True
        # Synthesized codes for proxy/LB failures with no parseable envelope:
        # all 5xx, plus bare 429 (throttle) and 408 (timeout), the statuses
        # the SDK DefaultRetryer retries on without an error code.
        if code in ("HTTP429", "HTTP408"):
            return True
        if code.startswith("HTTP5") and code[4:].isdigit():
            return True
        return False

    def is_throttle(self, code: str) -> bool:
        return code in THROTTLE_CODES or code == "HTTP429"

    def delay(self, attempt: int, code: str) -> float:
        base = self.throttle_base if self.is_throttle(code) else self.base_delay
        window = min(self.max_delay, base * (2.0 ** attempt))
        return window / 2.0 + self.rng() * (window / 2.0)


# --- SigV4 ------------------------------------------------------------------


@dataclass(frozen=True)
class Credentials:
    access_key_id: str
    secret_access_key: str
    session_token: str = ""

    @staticmethod
    def from_env() -> "Credentials":
        return Credentials(
            access_key_id=os.environ.get("AWS_ACCESS_KEY_ID", ""),
            secret_access_key=os.environ.get("AWS_SECRET_ACCESS_KEY", ""),
            session_token=os.environ.get("AWS_SESSION_TOKEN", ""),
        )


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sign_request(
    method: str,
    url: str,
    headers: Dict[str, str],
    body: bytes,
    region: str,
    service: str,
    credentials: Credentials,
    now: Optional[datetime.datetime] = None,
) -> Dict[str, str]:
    """AWS Signature Version 4. Returns the headers dict with Host,
    X-Amz-Date, optional X-Amz-Security-Token, and Authorization added.
    Deterministic given `now`, so a known-answer test can pin the output."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date_stamp = now.strftime("%Y%m%d")
    parsed = urllib.parse.urlsplit(url)
    headers = dict(headers)
    headers["Host"] = parsed.netloc
    headers["X-Amz-Date"] = amz_date
    if credentials.session_token:
        headers["X-Amz-Security-Token"] = credentials.session_token

    canonical_uri = urllib.parse.quote(parsed.path or "/", safe="/")
    query_pairs = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    # Spec: sort by URI-encoded name/value — encode FIRST, then sort, so keys
    # whose encodings order differently than their raw forms sign correctly.
    encoded_pairs = sorted(
        (
            urllib.parse.quote(k, safe="-_.~"),
            urllib.parse.quote(v, safe="-_.~"),
        )
        for k, v in query_pairs
    )
    canonical_query = "&".join(f"{k}={v}" for k, v in encoded_pairs)
    signed_names = sorted(headers, key=str.lower)
    canonical_headers = "".join(
        f"{name.lower()}:{' '.join(headers[name].split())}\n" for name in signed_names
    )
    signed_headers = ";".join(name.lower() for name in signed_names)
    payload_hash = hashlib.sha256(body).hexdigest()
    canonical_request = "\n".join(
        [method, canonical_uri, canonical_query, canonical_headers, signed_headers,
         payload_hash]
    )
    scope = f"{date_stamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        ["AWS4-HMAC-SHA256", amz_date, scope,
         hashlib.sha256(canonical_request.encode()).hexdigest()]
    )
    key = _hmac(
        _hmac(
            _hmac(
                _hmac(("AWS4" + credentials.secret_access_key).encode(), date_stamp),
                region,
            ),
            service,
        ),
        "aws4_request",
    )
    signature = hmac.new(key, string_to_sign.encode(), hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={credentials.access_key_id}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}"
    )
    return headers


# --- XML helpers ------------------------------------------------------------


def _strip_ns(element: ET.Element) -> ET.Element:
    """EC2 responses carry a version namespace; strip it so callers use bare
    tag names regardless of API version."""
    for node in element.iter():
        if "}" in node.tag:
            node.tag = node.tag.split("}", 1)[1]
    return element


def _text(element: Optional[ET.Element], path: str, default: str = "") -> str:
    found = element.find(path) if element is not None else None
    return found.text.strip() if found is not None and found.text else default


def _items(element: Optional[ET.Element], path: str) -> List[ET.Element]:
    return element.findall(path) if element is not None else []


def _tags(element: Optional[ET.Element]) -> Dict[str, str]:
    return {
        _text(item, "key"): _text(item, "value")
        for item in _items(element, "tagSet/item")
    }


def _parse_launch_time(value: str) -> float:
    """ISO-8601 launchTime -> epoch seconds; 0.0 when absent/unparseable
    (the GC treats 0.0 as unknown and falls back to sighting age)."""
    if not value:
        return 0.0
    try:
        return datetime.datetime.fromisoformat(
            value.replace("Z", "+00:00")
        ).timestamp()
    except ValueError:
        return 0.0


# --- The binding ------------------------------------------------------------


class AwsHttpEc2Api(Ec2Api):
    """Ec2Api over real AWS wire protocols (EC2 Query XML + SSM JSON 1.1).

    Pagination: every Describe* call follows nextToken until exhausted.
    Errors: non-2xx responses are parsed (XML <Errors><Error><Code> for EC2,
    JSON __type for SSM) and raised as the boundary's ApiError, so upstream
    classification (is_not_found, ICE handling) works identically against the
    real cloud and the in-memory fake.
    """

    def __init__(
        self,
        region: str = "",
        credentials: Optional[Credentials] = None,
        transport: Optional[HttpTransport] = None,
        ec2_endpoint: str = "",
        ssm_endpoint: str = "",
        sqs_endpoint: str = "",
        interruption_queue_url: str = "",
        price_catalog: Optional[Mapping[str, float]] = None,
        spot_price_ratio: float = 0.6,
        spot_prices: Optional[Mapping[Tuple[str, str], float]] = None,
        branch_interfaces: Optional[Mapping[str, int]] = None,
        clock: Callable[[], datetime.datetime] = None,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self.region = region or os.environ.get(
            "AWS_REGION", os.environ.get("AWS_DEFAULT_REGION", "us-east-1")
        )
        self.credentials = credentials or Credentials.from_env()
        self.transport = transport or UrllibTransport()
        self.ec2_endpoint = ec2_endpoint or f"https://ec2.{self.region}.amazonaws.com/"
        self.ssm_endpoint = ssm_endpoint or f"https://ssm.{self.region}.amazonaws.com/"
        self.sqs_endpoint = sqs_endpoint or f"https://sqs.{self.region}.amazonaws.com/"
        # EventBridge-fed interruption queue (spot-interruption-warning /
        # rebalance-recommendation / instance-state-change rules). Empty =
        # no interruption feed; receive_queue_messages returns [] without a
        # wire call.
        self.interruption_queue_url = interruption_queue_url or os.environ.get(
            "KARPENTER_INTERRUPTION_QUEUE_URL", ""
        )
        self.price_catalog = dict(price_catalog or {})
        self.spot_price_ratio = spot_price_ratio
        self.spot_prices = dict(spot_prices or {})
        # Pod-ENI branch-interface counts come from a static limits table in
        # the reference (vpc-resource-controller data), not the EC2 API.
        self.branch_interfaces = dict(branch_interfaces or {})
        self._clock = clock
        self.retry = retry_policy or RetryPolicy()
        # type name -> supported usage classes, from the last
        # DescribeInstanceTypes response (see describe_instance_type_offerings).
        self._usage_classes: Optional[Dict[str, Sequence[str]]] = None

    # --- protocol plumbing --------------------------------------------------

    def _with_retries(self, attempt_fn: Callable[[], "ET.Element | Dict"], what: str):
        """Run one signed call with the retry budget: throttles, 5xx, and
        transport failures back off and re-sign (fresh X-Amz-Date per attempt);
        everything else — and budget exhaustion — propagates."""
        attempt = 0
        while True:
            try:
                return attempt_fn()
            except ApiError as error:
                if attempt >= self.retry.max_retries or not self.retry.is_retryable(
                    error.code
                ):
                    raise
                delay = self.retry.delay(attempt, error.code)
                attempt += 1
                AWS_RETRY_TOTAL.inc(what, error.code)
                log.debug(
                    "%s attempt %d failed (%s); retrying in %.2fs",
                    what, attempt, error.code, delay,
                )
                self.retry.sleep(delay)

    def _ec2_call(self, action: str, params: Mapping[str, str]) -> ET.Element:
        body_params = {"Action": action, "Version": EC2_API_VERSION}
        body_params.update(params)
        body = urllib.parse.urlencode(sorted(body_params.items())).encode()
        return self._with_retries(
            lambda: self._ec2_attempt(body), what=action
        )

    def _ec2_attempt(self, body: bytes) -> ET.Element:
        headers = {"Content-Type": "application/x-www-form-urlencoded; charset=utf-8"}
        headers = sign_request(
            "POST", self.ec2_endpoint, headers, body, self.region, "ec2",
            self.credentials, now=self._clock() if self._clock else None,
        )
        response = self.transport.send("POST", self.ec2_endpoint, headers, body)
        if response.status >= 300:
            # Parse AFTER the status check: a proxy/LB 5xx may carry HTML or
            # an empty body, which must still surface as a coded ApiError so
            # upstream classification works, not as a bare XML ParseError.
            try:
                root = _strip_ns(ET.fromstring(response.body))
                error = root.find("Errors/Error")
            except ET.ParseError:
                error = None
            code = _text(error, "Code", f"HTTP{response.status}")
            message = _text(error, "Message") or response.body[:200].decode(
                "utf-8", "replace"
            )
            raise ApiError(code, message)
        try:
            root = _strip_ns(ET.fromstring(response.body))
        except ET.ParseError as err:
            # A 2xx with a non-XML body (misbehaving proxy) must still be a
            # coded error for upstream classification, not a raw ParseError.
            raise ApiError(
                "MalformedResponse",
                f"{err}: {response.body[:200].decode('utf-8', 'replace')}",
            ) from None
        if not root.tag.endswith("Response"):
            # Well-formed XML that is not an EC2 envelope (an XHTML error
            # page) would otherwise parse as an EMPTY result set.
            raise ApiError(
                "MalformedResponse", f"unexpected root element <{root.tag}>"
            )
        return root

    def _ec2_paginated(
        self, action: str, params: Mapping[str, str], item_path: str
    ) -> List[ET.Element]:
        items: List[ET.Element] = []
        token = ""
        while True:
            page_params = dict(params)
            if token:
                page_params["NextToken"] = token
            root = self._ec2_call(action, page_params)
            items.extend(root.findall(item_path))
            token = _text(root, "nextToken")
            if not token:
                return items

    def _ssm_call(self, target: str, payload: Mapping) -> Dict:
        body = json.dumps(payload).encode()
        return self._with_retries(
            lambda: self._json_attempt(
                self.ssm_endpoint, "ssm", f"{_SSM_TARGET_PREFIX}.{target}",
                "application/x-amz-json-1.1", body,
            ),
            what=target,
        )

    def _sqs_call(self, target: str, payload: Mapping) -> Dict:
        """SQS speaks the same signed JSON-RPC shape as SSM (json 1.0 rather
        than 1.1); retries ride the shared budget and count aws_retry_total
        by action like every other call."""
        body = json.dumps(payload).encode()
        return self._with_retries(
            lambda: self._json_attempt(
                self.sqs_endpoint, "sqs", f"{_SQS_TARGET_PREFIX}.{target}",
                "application/x-amz-json-1.0", body,
            ),
            what=target,
        )

    def _json_attempt(
        self, endpoint: str, service: str, target: str, content_type: str,
        body: bytes,
    ) -> Dict:
        headers = {
            "Content-Type": content_type,
            "X-Amz-Target": target,
        }
        headers = sign_request(
            "POST", endpoint, headers, body, self.region, service,
            self.credentials, now=self._clock() if self._clock else None,
        )
        response = self.transport.send("POST", endpoint, headers, body)
        try:
            data = json.loads(response.body or b"{}")
        except ValueError:
            data = None
        if response.status >= 300:
            data = data if isinstance(data, dict) else {}
            code = str(data.get("__type", f"HTTP{response.status}")).split("#")[-1]
            raise ApiError(code, str(data.get("message", data.get("Message", ""))))
        if not isinstance(data, dict):
            # 2xx with a non-JSON body: a transient proxy glitch must not be
            # coerced into {} and misread as ParameterNotFound downstream.
            raise ApiError(
                "MalformedResponse",
                response.body[:200].decode("utf-8", "replace"),
            )
        return data

    # --- discovery ----------------------------------------------------------

    def describe_instance_types(self) -> List[InstanceTypeInfo]:
        items = self._ec2_paginated(
            "DescribeInstanceTypes", {"MaxResults": "100"}, "instanceTypeSet/item"
        )
        infos = []
        for item in items:
            name = _text(item, "instanceType")
            gpus = {"nvidia": 0, "amd": 0}
            for gpu in _items(item, "gpuInfo/gpus/item"):
                maker = _text(gpu, "manufacturer").lower()
                count = int(_text(gpu, "count", "0") or 0)
                if maker in gpus:
                    gpus[maker] += count
            neurons = sum(
                int(_text(acc, "count", "0") or 0)
                for acc in _items(item, "inferenceAcceleratorInfo/accelerators/item")
            )
            infos.append(
                InstanceTypeInfo(
                    name=name,
                    vcpus=int(_text(item, "vCpuInfo/defaultVCpus", "0") or 0),
                    memory_mib=int(_text(item, "memoryInfo/sizeInMiB", "0") or 0),
                    architectures=tuple(
                        node.text
                        for node in _items(
                            item, "processorInfo/supportedArchitectures/item"
                        )
                        if node.text
                    )
                    or ("x86_64",),
                    supported_usage_classes=tuple(
                        node.text
                        for node in _items(item, "supportedUsageClasses/item")
                        if node.text
                    )
                    or ("on-demand",),
                    max_network_interfaces=int(
                        _text(item, "networkInfo/maximumNetworkInterfaces", "4") or 4
                    ),
                    ipv4_addresses_per_interface=int(
                        _text(item, "networkInfo/ipv4AddressesPerInterface", "15") or 15
                    ),
                    nvidia_gpus=gpus["nvidia"],
                    amd_gpus=gpus["amd"],
                    neurons=neurons,
                    pod_eni_branch_interfaces=self.branch_interfaces.get(name, 0),
                    bare_metal=_text(item, "bareMetal", "false") == "true",
                    fpga=item.find("fpgaInfo") is not None,
                    supported_virtualization_types=tuple(
                        node.text
                        for node in _items(item, "supportedVirtualizationTypes/item")
                        if node.text
                    )
                    or ("hvm",),
                    price_on_demand=float(self.price_catalog.get(name, 0.0)),
                )
            )
        self._usage_classes = {
            info.name: info.supported_usage_classes for info in infos
        }
        return infos

    def describe_instance_type_offerings(self) -> List[InstanceTypeOffering]:
        """Wire rows are (type, zone); capacity types come from the type's
        supportedUsageClasses and prices from the static catalog (the wire has
        no prices — see module docstring). Usage classes reuse the last
        DescribeInstanceTypes result (refreshed by describe_instance_types,
        which the provider's own 5-minute catalog cache already drives) —
        ~8 paginated signed calls saved per offerings refresh on the real
        ~700-type EC2 catalog."""
        if self._usage_classes is None:
            self.describe_instance_types()
        usage_classes = self._usage_classes or {}
        items = self._ec2_paginated(
            "DescribeInstanceTypeOfferings",
            {"LocationType": "availability-zone", "MaxResults": "1000"},
            "instanceTypeOfferingSet/item",
        )
        offerings = []
        for item in items:
            name = _text(item, "instanceType")
            zone = _text(item, "location")
            od_price = float(self.price_catalog.get(name, 0.0))
            for capacity_type in usage_classes.get(name, ("on-demand",)):
                if capacity_type == "spot":
                    price = self.spot_prices.get(
                        (name, zone), od_price * self.spot_price_ratio
                    )
                else:
                    price = od_price
                offerings.append(
                    InstanceTypeOffering(
                        instance_type=name,
                        zone=zone,
                        capacity_type=capacity_type,
                        price=price,
                    )
                )
        return offerings

    def describe_spot_price_history(self) -> List[SpotPrice]:
        """DescribeSpotPriceHistory over the signed Query API with the
        shared retry envelope — the polling leg of the live market feed
        (karpenter_tpu/market): rows become a replayable tick stream in
        Ec2CloudProvider.poll_market_events."""
        items = self._ec2_paginated(
            "DescribeSpotPriceHistory",
            {"ProductDescription.1": "Linux/UNIX", "MaxResults": "1000"},
            "spotPriceHistorySet/item",
        )
        rows: List[SpotPrice] = []
        for item in items:
            name = _text(item, "instanceType")
            zone = _text(item, "availabilityZone")
            try:
                price = float(_text(item, "spotPrice") or "0")
            except ValueError:
                continue  # a malformed row must not poison the whole poll
            if not name or not zone or price <= 0:
                continue
            rows.append(
                SpotPrice(
                    instance_type=name,
                    zone=zone,
                    price=price,
                    timestamp=_parse_launch_time(_text(item, "timestamp")),
                )
            )
        return rows

    @staticmethod
    def _filter_params(filters: Mapping[str, str]) -> Dict[str, str]:
        """Tag selector -> EC2 Filter.N params: value "*"/"" filters on key
        existence (tag-key), else exact tag:KEY=value
        (ref: aws/subnets.go getFilters:52-69)."""
        params: Dict[str, str] = {}
        for index, (key, value) in enumerate(sorted(filters.items()), start=1):
            if value in ("*", ""):
                params[f"Filter.{index}.Name"] = "tag-key"
                params[f"Filter.{index}.Value.1"] = key
            else:
                params[f"Filter.{index}.Name"] = f"tag:{key}"
                params[f"Filter.{index}.Value.1"] = value
        return params

    def describe_subnets(self, filters: Mapping[str, str]) -> List[Subnet]:
        items = self._ec2_paginated(
            "DescribeSubnets", self._filter_params(filters), "subnetSet/item"
        )
        return [
            Subnet(
                subnet_id=_text(item, "subnetId"),
                zone=_text(item, "availabilityZone"),
                tags=_tags(item),
            )
            for item in items
        ]

    def describe_security_groups(
        self, filters: Mapping[str, str]
    ) -> List[SecurityGroup]:
        items = self._ec2_paginated(
            "DescribeSecurityGroups",
            self._filter_params(filters),
            "securityGroupInfo/item",
        )
        return [
            SecurityGroup(group_id=_text(item, "groupId"), tags=_tags(item))
            for item in items
        ]

    # --- launch templates ---------------------------------------------------

    def describe_launch_template(self, name: str) -> LaunchTemplate:
        root = self._ec2_call(
            "DescribeLaunchTemplateVersions",
            {"LaunchTemplateName": name, "LaunchTemplateVersion.1": "$Latest"},
        )
        versions = root.findall("launchTemplateVersionSet/item")
        if not versions:
            raise ApiError("InvalidLaunchTemplateName.NotFoundException", name)
        version = versions[0]
        data = version.find("launchTemplateData")
        return LaunchTemplate(
            name=_text(version, "launchTemplateName", name),
            template_id=_text(version, "launchTemplateId"),
            image_id=_text(data, "imageId"),
            instance_profile=_text(data, "iamInstanceProfile/name"),
            security_group_ids=tuple(
                node.text
                for node in _items(data, "securityGroupIdSet/item")
                if node.text
            ),
            user_data=_text(data, "userData"),
        )

    def create_launch_template(self, template: LaunchTemplate) -> LaunchTemplate:
        params: Dict[str, str] = {
            "LaunchTemplateName": template.name,
            # Same idempotency rationale as CreateFleet, strengthened to
            # survive a controller RESTART: the token derives from the
            # template's content identity (the name already embeds the
            # content hash — launchtemplates._template_name), so a retried
            # attempt re-sends the identical token (one body per logical
            # call in _ec2_call) AND a restarted controller re-ensuring the
            # same template is a server-side no-op rather than an
            # AlreadyExists surprise.
            "ClientToken": derive_client_token(
                "CreateLaunchTemplate", template.name, template.image_id
            ),
            "LaunchTemplateData.ImageId": template.image_id,
            "LaunchTemplateData.UserData": template.user_data,
        }
        if template.instance_profile:
            params["LaunchTemplateData.IamInstanceProfile.Name"] = (
                template.instance_profile
            )
        for index, group_id in enumerate(template.security_group_ids, start=1):
            params[f"LaunchTemplateData.SecurityGroupId.{index}"] = group_id
        for index, (key, value) in enumerate(sorted(template.tags.items()), start=1):
            params["LaunchTemplateData.TagSpecification.1.ResourceType"] = "instance"
            params[f"LaunchTemplateData.TagSpecification.1.Tag.{index}.Key"] = key
            params[f"LaunchTemplateData.TagSpecification.1.Tag.{index}.Value"] = value
        root = self._ec2_call("CreateLaunchTemplate", params)
        created = root.find("launchTemplate")
        return LaunchTemplate(
            name=_text(created, "launchTemplateName", template.name),
            template_id=_text(created, "launchTemplateId"),
            image_id=template.image_id,
            instance_profile=template.instance_profile,
            security_group_ids=tuple(template.security_group_ids),
            user_data=template.user_data,
            tags=dict(template.tags),
        )

    # --- fleet --------------------------------------------------------------

    def create_fleet(self, request: FleetRequest) -> FleetResult:
        """CreateFleet type=instant with the reference's allocation
        strategies: lowest-price on-demand, capacity-optimized-prioritized
        spot (ref: instance.go:116-133)."""
        params: Dict[str, str] = {
            "Type": "instant",
            # Idempotency token: a retried CreateFleet (5xx whose first
            # attempt may have executed server-side) must not double-launch.
            # The whole retry loop re-sends ONE token since the body is built
            # once per logical call in _ec2_call. When the caller supplies a
            # deterministic token (restart-safe launches, see FleetRequest),
            # it is forwarded verbatim; otherwise a random per-call token
            # preserves the retry-only guarantee.
            "ClientToken": request.client_token or str(uuid.uuid4()),
            "LaunchTemplateConfigs.1.LaunchTemplateSpecification.LaunchTemplateName":
                request.launch_template_name,
            "LaunchTemplateConfigs.1.LaunchTemplateSpecification.Version": "$Latest",
            "TargetCapacitySpecification.TotalTargetCapacity": str(request.quantity),
            "TargetCapacitySpecification.DefaultTargetCapacityType":
                request.capacity_type,
        }
        if request.capacity_type == "spot":
            params["SpotOptions.AllocationStrategy"] = "capacity-optimized-prioritized"
        else:
            params["OnDemandOptions.AllocationStrategy"] = "lowest-price"
        for index, override in enumerate(request.overrides, start=1):
            prefix = f"LaunchTemplateConfigs.1.Overrides.{index}"
            params[f"{prefix}.InstanceType"] = override.instance_type
            params[f"{prefix}.SubnetId"] = override.subnet_id
            if override.priority is not None:
                params[f"{prefix}.Priority"] = str(override.priority)
        for index, (key, value) in enumerate(sorted(request.tags.items()), start=1):
            params["TagSpecification.1.ResourceType"] = "instance"
            params[f"TagSpecification.1.Tag.{index}.Key"] = key
            params[f"TagSpecification.1.Tag.{index}.Value"] = value

        root = self._ec2_call("CreateFleet", params)
        result = FleetResult()
        for item in root.findall("fleetInstanceSet/item"):
            for node in _items(item, "instanceIds/item"):
                if node.text:
                    result.instance_ids.append(node.text)
        for item in root.findall("errorSet/item"):
            overrides = item.find("launchTemplateAndOverrides/overrides")
            result.errors.append(
                FleetError(
                    code=_text(item, "errorCode"),
                    message=_text(item, "errorMessage"),
                    instance_type=_text(overrides, "instanceType"),
                    zone=_text(overrides, "availabilityZone"),
                )
            )
        return result

    # --- instances ----------------------------------------------------------

    def describe_instances(self, instance_ids: Sequence[str]) -> List[Instance]:
        params = {
            f"InstanceId.{index}": instance_id
            for index, instance_id in enumerate(instance_ids, start=1)
        }
        return self._describe_instances(params)

    def describe_instances_by_tag(
        self, filters: Mapping[str, str]
    ) -> List[Instance]:
        """DescribeInstances with tag filters — the leaked-capacity GC's
        sweep over everything this cluster is paying for, Node or not."""
        return self._describe_instances(self._filter_params(filters))

    def _describe_instances(self, params: Mapping[str, str]) -> List[Instance]:
        items = self._ec2_paginated(
            "DescribeInstances", params, "reservationSet/item"
        )
        instances = []
        for reservation in items:
            for item in _items(reservation, "instancesSet/item"):
                instances.append(
                    Instance(
                        instance_id=_text(item, "instanceId"),
                        instance_type=_text(item, "instanceType"),
                        zone=_text(item, "placement/availabilityZone"),
                        private_dns_name=_text(item, "privateDnsName"),
                        image_id=_text(item, "imageId"),
                        architecture=_text(item, "architecture", "x86_64"),
                        spot=_text(item, "instanceLifecycle") == "spot",
                        state=_text(item, "instanceState/name", "running"),
                        tags=_tags(item),
                        launched_at=_parse_launch_time(
                            _text(item, "launchTime")
                        ),
                    )
                )
        return instances

    def terminate_instances(self, instance_ids: Sequence[str]) -> None:
        params = {
            f"InstanceId.{index}": instance_id
            for index, instance_id in enumerate(instance_ids, start=1)
        }
        self._ec2_call("TerminateInstances", params)

    # --- ssm ----------------------------------------------------------------

    def get_ami_parameter(self, path: str) -> str:
        data = self._ssm_call("GetParameter", {"Name": path})
        value = data.get("Parameter", {}).get("Value", "")
        if not value:
            raise ApiError("ParameterNotFound", path)
        return value

    # --- interruption queue (sqs) -------------------------------------------

    def receive_queue_messages(self) -> List[QueueMessage]:
        """One short poll of the EventBridge-fed interruption queue. Messages
        are NOT deleted here — they stay invisible for the queue's visibility
        timeout and re-deliver unless delete_queue_message confirms them, so
        a controller that dies after receiving loses nothing."""
        if not self.interruption_queue_url:
            return []
        data = self._sqs_call(
            "ReceiveMessage",
            {
                "QueueUrl": self.interruption_queue_url,
                "MaxNumberOfMessages": SQS_MAX_MESSAGES,
                "WaitTimeSeconds": 0,
            },
        )
        return [
            QueueMessage(
                message_id=str(item.get("MessageId", "")),
                receipt_handle=str(item.get("ReceiptHandle", "")),
                body=str(item.get("Body", "")),
            )
            for item in data.get("Messages", []) or []
        ]

    def delete_queue_message(self, receipt_handle: str) -> None:
        if not self.interruption_queue_url or not receipt_handle:
            return
        try:
            self._sqs_call(
                "DeleteMessage",
                {
                    "QueueUrl": self.interruption_queue_url,
                    "ReceiptHandle": receipt_handle,
                },
            )
        except ApiError as error:
            # An expired/unknown handle means the message already re-surfaced
            # or was deleted — ack semantics make that success.
            if error.code not in (
                "ReceiptHandleIsInvalid", "InvalidParameterValue",
            ):
                raise
