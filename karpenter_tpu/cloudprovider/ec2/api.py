"""The EC2-shaped cloud API model — the framework's process boundary to the
cloud control plane.

Ref: the reference talks to AWS through ec2iface.EC2API + ssmiface.SSMAPI
(aws/cloudprovider.go:40-56, aws/ami.go:28). We define the equivalent
boundary as a small typed protocol (`Ec2Api`) with plain dataclasses instead
of the AWS SDK's pointer-heavy request/response structs. Two deliberate
departures from the EC2 wire API:

- `InstanceTypeOffering` carries a price. The reference delegates price choice
  to EC2 Fleet's allocation strategy; our TPU cost solver optimizes projected
  $/hr jointly with packing, so the pricing surface must cross the boundary.
- Pagination is elided: implementations return full lists (the fake is
  in-memory; a real implementation would page internally).

Everything the controllers know about "the cloud" flows through this file, so
a real AWS/GCP binding is one class implementing `Ec2Api`.
"""

from __future__ import annotations

import abc
import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence

# Setup-resource cache TTL shared by subnet/SG/AMI/launch-template
# discovery (ref: aws/cloudprovider.go:53 CacheTTL 60s).
SETUP_CACHE_TTL = 60.0


def derive_client_token(*parts: str) -> str:
    """Deterministic idempotency token from the logical call's identity.
    Two processes (or one process before and after a crash) issuing the
    same logical call derive the SAME token, so the second execution is a
    server-side no-op instead of a duplicate purchase. 64-char budget per
    the EC2 ClientToken contract; 32 hex chars of SHA-256 is comfortably
    collision-free at fleet-call volumes."""
    digest = hashlib.sha256("|".join(parts).encode()).hexdigest()[:32]
    return f"ktpu-{digest}"

# --- Error model (ref: aws/errors.go:22-43) --------------------------------

INSUFFICIENT_CAPACITY_ERROR_CODE = "InsufficientInstanceCapacity"

_NOT_FOUND_CODES = frozenset(
    {
        "InvalidInstanceID.NotFound",
        "InvalidLaunchTemplateName.NotFoundException",
        "InvalidLaunchTemplateId.NotFound",
        "ParameterNotFound",
    }
)


class ApiError(Exception):
    """A coded cloud-API error (ref: awserr.Error)."""

    def __init__(self, code: str, message: str = ""):
        super().__init__(f"{code}: {message}" if message else code)
        self.code = code
        self.api_message = message


def is_not_found(error: Optional[BaseException]) -> bool:
    """Ref: aws/errors.go isNotFound:28-39."""
    return isinstance(error, ApiError) and error.code in _NOT_FOUND_CODES


# --- Catalog / discovery types ---------------------------------------------


@dataclass(frozen=True)
class InstanceTypeInfo:
    """Raw instance-type record (ref: ec2.InstanceTypeInfo as consumed by
    aws/instancetype.go). Memory is the *machine* size; the adapter applies
    the VM-available factor."""

    name: str
    vcpus: int
    memory_mib: int
    architectures: Sequence[str] = ("x86_64",)
    supported_usage_classes: Sequence[str] = ("on-demand", "spot")
    # ENI model for the pods-per-node formula (instancetype.go:72-77).
    max_network_interfaces: int = 4
    ipv4_addresses_per_interface: int = 15
    nvidia_gpus: int = 0
    amd_gpus: int = 0
    neurons: int = 0
    tpus: int = 0
    pod_eni_branch_interfaces: int = 0
    bare_metal: bool = False
    fpga: bool = False
    supported_virtualization_types: Sequence[str] = ("hvm",)
    # On-demand list price, $/hr (price surface; see module docstring).
    price_on_demand: float = 0.0


@dataclass(frozen=True)
class InstanceTypeOffering:
    """One purchasable (type, zone, capacity-type) with its current price
    (ref: ec2.InstanceTypeOffering from DescribeInstanceTypeOfferings,
    aws/instancetypes.go:106-126, extended with price)."""

    instance_type: str
    zone: str
    capacity_type: str
    price: float = 0.0


@dataclass(frozen=True)
class Subnet:
    subnet_id: str
    zone: str
    tags: Mapping[str, str] = field(default_factory=dict)


@dataclass(frozen=True)
class SecurityGroup:
    group_id: str
    tags: Mapping[str, str] = field(default_factory=dict)


# --- Launch types ----------------------------------------------------------


@dataclass(frozen=True)
class LaunchTemplate:
    name: str
    template_id: str = ""
    image_id: str = ""
    instance_profile: str = ""
    security_group_ids: Sequence[str] = ()
    user_data: str = ""
    tags: Mapping[str, str] = field(default_factory=dict)


@dataclass(frozen=True)
class FleetOverride:
    """One (instance type, subnet) candidate in a fleet request
    (ref: ec2.FleetLaunchTemplateOverridesRequest, aws/instance.go:173-207).
    Zone is recorded redundantly so capacity errors can name the zone without
    a subnet lookup; priority orders spot candidates (smallest first)."""

    instance_type: str
    subnet_id: str
    zone: str
    priority: Optional[float] = None


@dataclass
class FleetRequest:
    """Ref: ec2.CreateFleetInput (instance.go:116-133). type=instant
    semantics: the call synchronously returns launched ids + per-pool
    errors; partial fulfillment is allowed.

    `client_token` is the EC2 idempotency token. Empty = the binding mints
    a random one per logical call (retries of that call still reuse it).
    Callers that need RESTART idempotency (a re-issued launch after a crash
    or ambiguous 5xx must be a server-side no-op) derive it deterministically
    from the launch content — see instances.InstanceProvider._launch."""

    launch_template_name: str
    overrides: List[FleetOverride]
    capacity_type: str
    quantity: int
    tags: Dict[str, str] = field(default_factory=dict)
    client_token: str = ""

    def idempotency_payload(self) -> str:
        """Canonical serialization of everything EC2 compares under a reused
        ClientToken. Token derivation (instances._launch) and the fake's
        IdempotentParameterMismatch check both key on this one method, so
        the two sides of the contract cannot drift apart."""
        rows = sorted(
            f"{o.instance_type}/{o.subnet_id}/{o.zone}/{o.priority}"
            for o in self.overrides
        )
        tags = sorted(f"{k}={v}" for k, v in self.tags.items())
        return "|".join(
            [self.launch_template_name, self.capacity_type, str(self.quantity)]
            + rows
            + tags
        )


@dataclass(frozen=True)
class FleetError:
    """Per-pool launch failure (ref: ec2.CreateFleetError)."""

    code: str
    message: str
    instance_type: str = ""
    zone: str = ""


@dataclass
class FleetResult:
    instance_ids: List[str] = field(default_factory=list)
    errors: List[FleetError] = field(default_factory=list)


@dataclass(frozen=True)
class QueueMessage:
    """One message from the cluster's interruption queue (ref: the reference
    ecosystem's interruption controller consumes an SQS queue fed by
    EventBridge rules for spot-interruption-warning, rebalance-recommendation
    and instance-state-change). `body` is the raw EventBridge JSON envelope;
    `receipt_handle` is the delete token — a message stays re-deliverable
    (visibility timeout) until deleted, which is what makes the interruption
    pipeline crash-consistent: record first, delete after."""

    message_id: str
    receipt_handle: str
    body: str


@dataclass(frozen=True)
class SpotPrice:
    """One DescribeSpotPriceHistory row: the spot $/hr one pool advertised
    at `timestamp` (epoch seconds). The market feed sorts rows into a
    strictly-ordered tick stream — the poll IS the replayable history, so
    the controller's PriceBook can always re-fold from zero."""

    instance_type: str
    zone: str
    price: float
    timestamp: float = 0.0


@dataclass(frozen=True)
class Instance:
    """Ref: ec2.Instance fields read by instanceToNode (instance.go:232-268).
    `tags` and `launched_at` (epoch seconds, 0.0 = unknown) feed the
    leaked-capacity GC's by-cluster-tag listing."""

    instance_id: str
    instance_type: str
    zone: str
    private_dns_name: str = ""
    image_id: str = ""
    architecture: str = "x86_64"
    spot: bool = False
    state: str = "running"
    tags: Mapping[str, str] = field(default_factory=dict)
    launched_at: float = 0.0


# --- The boundary ----------------------------------------------------------


class Ec2Api(abc.ABC):
    """Everything the provider stack may ask of the cloud. One RPC-ish method
    per EC2/SSM call the reference makes."""

    @abc.abstractmethod
    def describe_instance_types(self) -> List[InstanceTypeInfo]:
        ...

    @abc.abstractmethod
    def describe_instance_type_offerings(self) -> List[InstanceTypeOffering]:
        ...

    @abc.abstractmethod
    def describe_subnets(self, filters: Mapping[str, str]) -> List[Subnet]:
        """filters: tag-key -> value, value "*" = key existence only
        (ref: aws/subnets.go getFilters:52-69)."""

    @abc.abstractmethod
    def describe_security_groups(self, filters: Mapping[str, str]) -> List[SecurityGroup]:
        ...

    @abc.abstractmethod
    def describe_launch_template(self, name: str) -> LaunchTemplate:
        """Raises ApiError(NotFound) when absent."""

    @abc.abstractmethod
    def create_launch_template(self, template: LaunchTemplate) -> LaunchTemplate:
        ...

    @abc.abstractmethod
    def create_fleet(self, request: FleetRequest) -> FleetResult:
        ...

    @abc.abstractmethod
    def describe_instances(self, instance_ids: Sequence[str]) -> List[Instance]:
        ...

    @abc.abstractmethod
    def describe_instances_by_tag(
        self, filters: Mapping[str, str]
    ) -> List[Instance]:
        """Every instance matching a tag selector (same filter grammar as
        describe_subnets), terminated ones included with their state — the
        leaked-capacity GC's DescribeInstances-by-cluster-tag sweep."""

    @abc.abstractmethod
    def terminate_instances(self, instance_ids: Sequence[str]) -> None:
        ...

    @abc.abstractmethod
    def get_ami_parameter(self, path: str) -> str:
        """SSM GetParameter for AMI discovery (ref: aws/ami.go:62-72).
        Raises ApiError(ParameterNotFound) when absent."""

    def receive_queue_messages(self) -> List[QueueMessage]:
        """Poll the cluster's interruption queue (SQS ReceiveMessage).
        Messages remain re-deliverable until delete_queue_message — the
        at-least-once contract the interruption controller's record-then-ack
        discipline depends on. Default: no queue configured, nothing to
        receive."""
        return []

    def delete_queue_message(self, receipt_handle: str) -> None:
        """Ack one received message (SQS DeleteMessage). Deleting an unknown
        or already-deleted handle is success."""

    def describe_spot_price_history(self) -> List[SpotPrice]:
        """Spot price history for this account's pools (EC2
        DescribeSpotPriceHistory), oldest-first is NOT guaranteed — callers
        sort. Default: no spot-price feed, the market controller is inert."""
        return []


def match_tags(tags: Mapping[str, str], filters: Mapping[str, str]) -> bool:
    """Evaluate a tag-selector against a resource's tags. Empty filters match
    nothing-specified = everything (callers decide whether empty is legal)."""
    for key, value in filters.items():
        if key not in tags:
            return False
        if value not in ("*", "") and tags[key] != value:
            return False
    return True
