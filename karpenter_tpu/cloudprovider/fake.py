"""Fake cloud provider: instant nodes, canned catalogs, fault injection.

Ref: pkg/cloudprovider/fake/cloudprovider.go (instant fake nodes honoring
requested zone/capacity-type; canned instance-type catalog) and
pkg/cloudprovider/aws/fake/ec2api.go (InsufficientCapacityPools to exercise
ICE blackout fallback). Used by tests and by the runtime when no real cloud
is configured.
"""

from __future__ import annotations

import copy
import itertools
import threading
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.provisioner import Constraints, Provisioner
from karpenter_tpu.cloudprovider import (
    DEFAULT_INTERRUPTION_DEADLINE_SECONDS,
    INTERRUPTION_SPOT,
    CloudInstance,
    CloudProvider,
    InstanceType,
    InsufficientCapacityError,
    InterruptionEvent,
    NodeSpec,
    Offering,
)
from karpenter_tpu.utils.clock import SYSTEM_CLOCK
from karpenter_tpu.utils.crashpoints import crashpoint

ZONES = ("test-zone-1", "test-zone-2", "test-zone-3")

_node_counter = itertools.count(1)

# ICE blackout TTL (ref: aws/instancetypes.go:37 — 45s).
UNAVAILABLE_OFFERING_TTL = 45.0


def _offerings(price: float, zones=ZONES) -> List[Offering]:
    return [
        Offering(zone=zone, capacity_type=ct, price=price * (0.6 if ct == "spot" else 1.0))
        for zone in zones
        for ct in (wellknown.CAPACITY_TYPE_ON_DEMAND, wellknown.CAPACITY_TYPE_SPOT)
    ]


def default_instance_types() -> List[InstanceType]:
    """Canned catalog mirroring the reference's fake fixtures
    (ref: fake/cloudprovider.go:36-116): default, small, gpu, arm."""
    return [
        InstanceType(
            name="default-instance-type",
            capacity={"cpu": 16, "memory": "64Gi", "pods": 110},
            offerings=_offerings(0.8),
        ),
        InstanceType(
            name="small-instance-type",
            capacity={"cpu": 2, "memory": "4Gi", "pods": 110},
            offerings=_offerings(0.1),
        ),
        InstanceType(
            name="nvidia-gpu-instance-type",
            capacity={
                "cpu": 16,
                "memory": "64Gi",
                "pods": 110,
                wellknown.RESOURCE_NVIDIA_GPU: 2,
            },
            offerings=_offerings(2.4),
        ),
        InstanceType(
            name="amd-gpu-instance-type",
            capacity={
                "cpu": 16,
                "memory": "64Gi",
                "pods": 110,
                wellknown.RESOURCE_AMD_GPU: 2,
            },
            offerings=_offerings(2.0),
        ),
        InstanceType(
            name="tpu-instance-type",
            capacity={
                "cpu": 96,
                "memory": "192Gi",
                "pods": 110,
                wellknown.RESOURCE_GOOGLE_TPU: 4,
            },
            offerings=_offerings(4.8),
        ),
        InstanceType(
            name="arm-instance-type",
            capacity={"cpu": 16, "memory": "64Gi", "pods": 110},
            architecture="arm64",
            offerings=_offerings(0.7),
        ),
        InstanceType(
            name="pod-eni-instance-type",
            capacity={
                "cpu": 4,
                "memory": "16Gi",
                "pods": 110,
                wellknown.RESOURCE_AWS_POD_ENI: 38,
            },
            offerings=_offerings(0.3),
        ),
    ]


def consolidation_instance_types() -> List[InstanceType]:
    """Utilization fixtures for the consolidation sweep: a size ladder with
    an unambiguous cheaper-replacement structure (big-instance-type strictly
    dominates mid and small on capacity while costing proportionally more,
    so a drained-down big node always has a strictly cheaper feasible
    replacement), plus a reserved pool whose offerings are marked
    consolidatable=False — capacity bought there must never be nominated."""
    return [
        InstanceType(
            name="small-consolidation-type",
            capacity={"cpu": 4, "memory": "16Gi", "pods": 110},
            offerings=_offerings(0.2),
        ),
        InstanceType(
            name="mid-consolidation-type",
            capacity={"cpu": 8, "memory": "32Gi", "pods": 110},
            offerings=_offerings(0.4),
        ),
        InstanceType(
            name="big-consolidation-type",
            capacity={"cpu": 16, "memory": "64Gi", "pods": 110},
            offerings=_offerings(0.8),
        ),
        InstanceType(
            name="reserved-consolidation-type",
            capacity={"cpu": 16, "memory": "64Gi", "pods": 110},
            offerings=[
                Offering(
                    zone=zone,
                    capacity_type=wellknown.CAPACITY_TYPE_ON_DEMAND,
                    price=0.5,
                    consolidatable=False,
                )
                for zone in ZONES
            ],
        ),
    ]


def instance_type_ladder(n: int) -> List[InstanceType]:
    """Linear size ladder for benchmarks (ref: fake/instancetype.go:69-80)."""
    return [
        InstanceType(
            name=f"fake-ladder-{i + 1}",
            capacity={"cpu": 2 * (i + 1), "memory": f"{4 * (i + 1)}Gi", "pods": 110},
            offerings=_offerings(0.05 * (i + 1)),
        )
        for i in range(n)
    ]


class FakeCloudProvider(CloudProvider):
    """Instant node launches honoring the tightened constraints; records all
    launch calls; injectable insufficient-capacity pools."""

    def __init__(
        self,
        instance_types: Optional[List[InstanceType]] = None,
        clock=None,
    ):
        self._instance_types = (
            list(instance_types) if instance_types is not None else default_instance_types()
        )
        self.clock = clock or SYSTEM_CLOCK
        self.create_calls: List[Tuple[Constraints, List[str], int]] = []
        self.deleted_nodes: List[str] = []
        # Crash-consistency surfaces: every live instance this cloud is
        # "billing" for (provider_id -> CloudInstance), the NodeSpecs each
        # launch_id bought (replayed on a re-issued launch so a restarted
        # controller ADOPTS instead of re-buying), and a per-call log of
        # (launch_id, quantity, adopted, launched) — the ClientToken
        # analogue the crash battletest asserts determinism on.
        self.instances: Dict[str, CloudInstance] = {}  # vet: guarded-by(self._lock)
        self.terminated_instances: List[str] = []
        self._launches: Dict[str, List[NodeSpec]] = {}  # vet: guarded-by(self._lock)
        self.launch_log: List[Dict] = []
        # (instance_type, zone, capacity_type) triples that fail with ICE
        # (ref: aws/fake/ec2api.go InsufficientCapacityPools:54).
        self.insufficient_capacity_pools: Set[Tuple[str, str, str]] = set()
        # Offering blackout cache (ref: aws/instancetypes.go:174-183).
        self._unavailable: Dict[Tuple[str, str, str], float] = {}  # vet: guarded-by(self._lock)
        # Injectable interruption feed: event_id -> event, delivered by
        # poll_interruptions until acked (the SQS at-least-once model), so
        # crash tests can kill the controller between observing and
        # recording an event and still see it re-delivered.
        self._interruptions: Dict[str, InterruptionEvent] = {}  # vet: guarded-by(self._lock)
        self._event_ids = itertools.count(1)
        self.acked_interruptions: List[str] = []
        # Injectable provider-side drift set: provider_id -> reason, served
        # by instance_drifted until cleared — drift storms are scriptable
        # the same way interruption storms are.
        self._drifted: Dict[str, str] = {}  # vet: guarded-by(self._lock)
        # Live market wiring (karpenter_tpu/market): the feed generates the
        # tick stream poll_market_events serves; the attached PriceBook (the
        # controller's fold of that stream) reprices ADVERTISED spot
        # offerings and drops ICE-closed pools, so every catalog consumer
        # sees the market the controller folded. Plain slots (GIL-atomic
        # swaps, read-only use): attach happens at harness/Manager boot.
        self._market_feed = None
        self._market_book = None
        self._lock = threading.Lock()

    # --- helpers ------------------------------------------------------------

    def _now(self) -> float:
        return self.clock.now()

    def set_instance_types(self, instance_types: List[InstanceType]) -> None:
        self._instance_types = list(instance_types)

    def cache_unavailable(self, instance_type: str, zone: str, capacity_type: str):
        with self._lock:
            self._unavailable[(instance_type, zone, capacity_type)] = (
                self._now() + UNAVAILABLE_OFFERING_TTL
            )

    def blackout_offering(
        self, instance_type: str, zone: str, capacity_type: str
    ) -> None:
        """Interruption-driven pool exclusion rides the same blackout cache
        as ICE feedback: the pool vanishes from get_instance_types for the
        TTL, so replacement capacity re-solves away from it."""
        self.cache_unavailable(instance_type, zone, capacity_type)

    # --- market feed --------------------------------------------------------

    def attach_market_feed(self, feed) -> None:
        """Wire a karpenter_tpu.market.feed.MarketFeed as this cloud's tick
        source; poll_market_events advances it on the provider clock. An
        un-stepped feed is re-anchored to that clock here — a feed built
        with the default epoch anchor would otherwise owe one step per
        elapsed second since 0 at the first poll (FakeClock starts at
        1e6)."""
        feed.rebase(self._now())
        self._market_feed = feed

    def attach_market(self, book) -> None:
        self._market_book = book

    def poll_market_events(self, after_seq: int = 0) -> List:
        feed = self._market_feed
        if feed is None:
            return []
        feed.advance(self._now())
        return feed.ticks_after(after_seq)

    def _market_offering(self, name: str, offering: Offering, od_price):
        """One offering under the attached book, priced by the SHARED rule
        (market.pricebook.advertised_price — the EC2 catalog path calls the
        same function, so the backends cannot drift): spot prices track the
        folded market (od * discount), ICE-closed pools drop their spot
        offering, anything unpriced keeps the catalog price."""
        from karpenter_tpu.market.pricebook import advertised_price

        price = advertised_price(
            self._market_book,
            (name, offering.zone),
            offering.capacity_type,
            offering.price,
            od_price,
        )
        if price is None:
            return None
        if price == offering.price:
            return offering
        return Offering(
            zone=offering.zone,
            capacity_type=offering.capacity_type,
            price=price,
            consolidatable=offering.consolidatable,
        )

    def _priced_offerings(self, it: InstanceType) -> List[Offering]:
        """The type's available offerings under blackouts + the live market."""
        od_by_zone = {
            o.zone: o.price
            for o in it.offerings
            if o.capacity_type == wellknown.CAPACITY_TYPE_ON_DEMAND
        }
        out = []
        for o in it.offerings:
            if not self._offering_available(it.name, o):
                continue
            priced = self._market_offering(it.name, o, od_by_zone.get(o.zone))
            if priced is not None:
                out.append(priced)
        return out

    # --- interruption feed --------------------------------------------------

    def inject_interruption(
        self,
        node: NodeSpec,
        kind: str = INTERRUPTION_SPOT,
        deadline_in: Optional[float] = DEFAULT_INTERRUPTION_DEADLINE_SECONDS,
    ) -> InterruptionEvent:
        """Test hook: enqueue an interruption notice for `node`'s instance.
        `deadline_in` is seconds from now (None = soft, no deadline)."""
        with self._lock:
            event_id = f"fake-event-{next(self._event_ids)}"
            event = InterruptionEvent(
                kind=kind,
                instance_id=node.provider_id.rsplit("/", 1)[-1],
                provider_id=node.provider_id,
                deadline=(
                    self._now() + deadline_in if deadline_in is not None else None
                ),
                event_id=event_id,
            )
            self._interruptions[event_id] = event
            return event

    def poll_interruptions(self) -> List[InterruptionEvent]:
        with self._lock:
            return list(self._interruptions.values())

    def ack_interruption(self, event: InterruptionEvent) -> None:
        with self._lock:
            if self._interruptions.pop(event.event_id, None) is not None:
                self.acked_interruptions.append(event.event_id)

    # --- drift feed ---------------------------------------------------------

    def inject_drift(self, node: NodeSpec, reason: str = "template-moved") -> None:
        """Test hook: mark `node`'s instance as provider-drifted. The drift
        sweep sees it on its next pass via instance_drifted."""
        with self._lock:
            self._drifted[node.provider_id] = reason

    def clear_drift(self, node: NodeSpec) -> None:
        with self._lock:
            self._drifted.pop(node.provider_id, None)

    def instance_drifted(self, node: NodeSpec) -> Optional[str]:
        with self._lock:
            return self._drifted.get(node.provider_id)

    def _offering_available(self, name: str, offering: Offering) -> bool:
        key = (name, offering.zone, offering.capacity_type)
        with self._lock:
            expiry = self._unavailable.get(key)
            if expiry is None:
                return True
            if self._now() >= expiry:
                del self._unavailable[key]
                return True
            return False

    # --- CloudProvider ------------------------------------------------------

    def get_instance_types(self, constraints: Optional[Constraints] = None) -> List[InstanceType]:
        """Catalog with blacked-out offerings filtered (ref: instancetypes.go
        Get:61-104 subtracts the unavailable-offerings cache)."""
        out = []
        for it in self._instance_types:
            offerings = self._priced_offerings(it)
            if not offerings:
                continue
            out.append(
                InstanceType(
                    name=it.name,
                    capacity=dict(it.capacity),
                    overhead=dict(it.overhead),
                    architecture=it.architecture,
                    operating_systems=it.operating_systems,
                    offerings=offerings,
                )
            )
        return out

    def _adopt_prior_launch(
        self, launch_id: Optional[str], quantity: int
    ) -> List[NodeSpec]:
        """Idempotent re-issue (a restarted controller replaying the same
        batch): instances the first attempt already bought are ADOPTED —
        re-delivered through the callback with their original NodeSpec —
        and only the shortfall is purchased. Instances terminated since
        (e.g. GC'd) are dropped from the replay and re-bought."""
        if launch_id is None:
            return []
        with self._lock:
            prior = self._launches.get(launch_id, [])
            # Deep copies: the registration path mutates the NodeSpec it
            # receives, and the stored record must stay pristine (like a
            # fresh DescribeInstances conversion would be).
            return [
                copy.deepcopy(node) for node in prior
                if node.provider_id in self.instances
            ][:quantity]

    @staticmethod
    def _rank_candidates(
        instance_types, pool_options, allowed_zones, allowed_capacity
    ) -> List[Tuple]:
        """(sort_key, instance_type, offering) rows honoring constraints —
        pinned price-ranked pools in priority order when given, else
        lowest-price-first across offered types (the fleet-API behavior the
        reference delegates to EC2)."""
        candidates: List[Tuple] = []
        if pool_options:
            for rank, pool in enumerate(pool_options):
                if not allowed_zones.contains(pool.zone):
                    continue
                for offering in pool.instance_type.offerings:
                    if offering.zone != pool.zone:
                        continue
                    if not allowed_capacity.contains(offering.capacity_type):
                        continue
                    candidates.append((rank, pool.instance_type, offering))
        else:
            for it in instance_types:
                for offering in it.offerings:
                    if not allowed_zones.contains(offering.zone):
                        continue
                    if not allowed_capacity.contains(offering.capacity_type):
                        continue
                    candidates.append((offering.price, it, offering))
        candidates.sort(key=lambda c: c[0])
        return candidates

    def _buy(self, it: InstanceType, offering: Offering, launch_id) -> NodeSpec:
        """Commit one purchase: mint the instance + NodeSpec and record both.
        The purchase is committed HERE — before any callback runs — exactly
        like CreateFleet returning instance ids: a crash between this point
        and node registration leaks the instance until the GC reaps it or a
        restart adopts it."""
        sequence = next(_node_counter)
        instance_id = f"fi-{sequence:08d}"
        # Unique per instance (like aws:///zone/id), so the leaked-
        # capacity GC can join instances against Nodes.
        provider_id = f"fake:///{offering.zone}/{instance_id}"
        node = NodeSpec(
            name=f"fake-node-{sequence}",
            labels={
                wellknown.INSTANCE_TYPE_LABEL: it.name,
                wellknown.ZONE_LABEL: offering.zone,
                wellknown.CAPACITY_TYPE_LABEL: offering.capacity_type,
                wellknown.ARCH_LABEL: it.architecture,
                wellknown.OS_LABEL: sorted(it.operating_systems)[0],
            },
            capacity=dict(it.capacity),
            instance_type=it.name,
            zone=offering.zone,
            capacity_type=offering.capacity_type,
            provider_id=provider_id,
        )
        with self._lock:
            self.instances[provider_id] = CloudInstance(
                instance_id=instance_id,
                provider_id=provider_id,
                instance_type=it.name,
                zone=offering.zone,
                capacity_type=offering.capacity_type,
                launched_at=self._now(),
            )
            if launch_id is not None:
                self._launches.setdefault(launch_id, []).append(
                    copy.deepcopy(node)
                )
        return node

    def create(
        self,
        constraints: Constraints,
        instance_types: Sequence[InstanceType],
        quantity: int,
        callback: Callable[[NodeSpec], None],
        pool_options: Optional[Sequence] = None,
        launch_id: Optional[str] = None,
    ) -> List[Exception]:
        self.create_calls.append(
            (constraints, [it.name for it in instance_types], quantity)
        )
        adopted = self._adopt_prior_launch(launch_id, quantity)
        errors: List[Exception] = []
        launched_nodes: List[NodeSpec] = []
        requirements = constraints.effective_requirements()
        allowed_zones = requirements.allowed(wellknown.ZONE_LABEL)
        allowed_capacity = requirements.allowed(wellknown.CAPACITY_TYPE_LABEL)
        # Loop-invariant: candidates depend only on the call's inputs (ICE
        # feedback is checked per pool below, against the live set).
        candidates = self._rank_candidates(
            instance_types, pool_options, allowed_zones, allowed_capacity
        )
        for _ in range(quantity - len(adopted)):
            launched = False
            last_error: Optional[Exception] = None
            for _, it, offering in candidates:
                pool = (it.name, offering.zone, offering.capacity_type)
                if pool in self.insufficient_capacity_pools:
                    last_error = InsufficientCapacityError(*pool)
                    self.cache_unavailable(*pool)
                    continue
                launched_nodes.append(self._buy(it, offering, launch_id))
                launched = True
                break
            if not launched:
                errors.append(
                    last_error
                    or RuntimeError("no offering satisfies constraints")
                )
        self.launch_log.append(
            {
                "launch_id": launch_id,
                "quantity": quantity,
                "adopted": [n.provider_id for n in adopted],
                "launched": [n.provider_id for n in launched_nodes],
            }
        )
        # The capacity is bought; the node objects don't exist yet. This is
        # the canonical leak window.
        crashpoint("cloud.after-create-fleet")
        for node in adopted + launched_nodes:
            callback(node)
        return errors

    def delete(self, node: NodeSpec) -> None:
        self.deleted_nodes.append(node.name)
        with self._lock:
            self.instances.pop(node.provider_id, None)

    def list_instances(self) -> List[CloudInstance]:
        with self._lock:
            return list(self.instances.values())

    def terminate_instance(self, instance: CloudInstance) -> None:
        with self._lock:
            removed = self.instances.pop(instance.provider_id, None)
            if removed is not None:
                self.terminated_instances.append(instance.instance_id)

    def default(self, provisioner: Provisioner) -> None:
        """Default capacity-type to on-demand if unconstrained
        (vendor-defaulting parity with aws/apis/v1alpha1/provider_defaults.go)."""
        requirements = provisioner.spec.constraints.requirements
        if requirements.capacity_types() is None:
            from karpenter_tpu.api.requirements import Requirement

            provisioner.spec.constraints.requirements = requirements.add(
                Requirement.in_(
                    wellknown.CAPACITY_TYPE_LABEL,
                    [wellknown.CAPACITY_TYPE_ON_DEMAND, wellknown.CAPACITY_TYPE_SPOT],
                )
            )
