"""Cloud-provider abstraction.

Ref: pkg/cloudprovider/types.go:29-75 — CloudProvider, InstanceType and
Offering. We extend Offering with a price so the solver can optimize projected
$/hr (the reference delegates price choice to EC2 Fleet's lowest-price
allocation strategy; surfacing it lets the TPU solver make the cost tradeoff
jointly with packing).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, Iterable, List, Optional, Sequence

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import Constraints, Provisioner
from karpenter_tpu.api.resources import ResourceList, parse_resource_list

ARCH_AMD64 = "amd64"
ARCH_ARM64 = "arm64"
OS_LINUX = "linux"


@dataclass(frozen=True)
class Offering:
    """One purchasable (zone, capacity-type) combination for an instance type.

    `consolidatable` is the provider's hint that capacity bought from this
    pool may be voluntarily deprovisioned by the consolidation controller —
    False marks commitments (reserved capacity, capacity blocks) where
    shedding the node saves nothing because the bill keeps running."""

    zone: str
    capacity_type: str = wellknown.CAPACITY_TYPE_ON_DEMAND
    price: float = 0.0  # $/hr; 0.0 = unknown
    consolidatable: bool = True


@dataclass
class InstanceType:
    """Ref: cloudprovider.InstanceType interface (types.go:44-63)."""

    name: str
    capacity: ResourceList
    overhead: ResourceList = field(default_factory=dict)
    architecture: str = ARCH_AMD64
    operating_systems: FrozenSet[str] = frozenset({OS_LINUX})
    offerings: List[Offering] = field(default_factory=list)

    def __post_init__(self):
        self.capacity = parse_resource_list(self.capacity)
        self.overhead = parse_resource_list(self.overhead)

    def zones(self) -> FrozenSet[str]:
        return frozenset(offering.zone for offering in self.offerings)

    def capacity_types(self) -> FrozenSet[str]:
        return frozenset(offering.capacity_type for offering in self.offerings)

    def get(self, resource: str) -> float:
        return self.capacity.get(resource, 0.0)

    def min_price(
        self,
        zones: Optional[Iterable[str]] = None,
        capacity_types: Optional[Iterable[str]] = None,
    ) -> float:
        """Cheapest offering price within the allowed zones/capacity types."""
        zones = None if zones is None else set(zones)
        capacity_types = None if capacity_types is None else set(capacity_types)
        prices = [
            o.price
            for o in self.offerings
            if (zones is None or o.zone in zones)
            and (capacity_types is None or o.capacity_type in capacity_types)
        ]
        return min(prices) if prices else float("inf")


# --- Interruption events ----------------------------------------------------
#
# Ref: the reference ecosystem's AWS interruption controller consumes the
# EventBridge streams for EC2 spot-interruption-warning, rebalance-
# recommendation, and instance-state-change through an SQS queue. We surface
# the same three kinds through a provider-neutral poll/ack pair so the
# interruption controller can react inside the reclaim window.

INTERRUPTION_SPOT = "spot-interruption"  # hard: capacity dies at the deadline
INTERRUPTION_REBALANCE = "rebalance-recommendation"  # soft: elevated risk only
INTERRUPTION_STOPPING = "instance-stopping"  # hard: provider is stopping it

# Kinds that carry (or imply) a reclaim deadline; the drain escalates as it
# approaches. Soft kinds drain politely and never override PDBs.
HARD_INTERRUPTION_KINDS = frozenset({INTERRUPTION_SPOT, INTERRUPTION_STOPPING})

# EC2 gives two minutes of warning before a spot reclaim; events that name no
# explicit deadline get this window from their observation time.
DEFAULT_INTERRUPTION_DEADLINE_SECONDS = 120.0


@dataclass(frozen=True)
class InterruptionEvent:
    """One provider notice that an instance is about to lose its capacity.

    `instance_id` is the provider-side join key (events rarely carry the
    zone, so `provider_id` is best-effort — the controller matches either).
    `deadline` is epoch seconds in the provider's clock domain; None = soft
    (no hard reclaim time). `event_id` is the at-least-once ack token
    (`ack_interruption`): the SQS receipt handle for EC2, the fake's queue
    key for tests — an event stays re-deliverable until acked, so a
    controller that dies between observing and recording it sees it again."""

    kind: str
    instance_id: str
    provider_id: str = ""
    deadline: Optional[float] = None
    event_id: str = ""
    detail: str = ""

    def is_hard(self) -> bool:
        return self.kind in HARD_INTERRUPTION_KINDS


@dataclass(frozen=True)
class CloudInstance:
    """A provider-side instance carrying this cluster's ownership tag, as
    returned by `CloudProvider.list_instances`. This is the GC controller's
    view of "what we are paying for": `provider_id` is the join key against
    Nodes, `launched_at` (0.0 = unknown) is observability for leak triage."""

    instance_id: str
    provider_id: str
    instance_type: str = ""
    zone: str = ""
    capacity_type: str = ""
    state: str = "running"
    launched_at: float = 0.0


@dataclass
class NodeSpec:
    """A launched (or to-be-launched) node as the control plane sees it."""

    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    taints: List = field(default_factory=list)
    capacity: ResourceList = field(default_factory=dict)
    instance_type: str = ""
    zone: str = ""
    capacity_type: str = ""
    provider_id: str = ""
    ready: bool = False
    unschedulable: bool = False
    finalizers: List[str] = field(default_factory=list)
    created_at: float = 0.0
    deletion_timestamp: Optional[float] = None
    # Last time the kubelet reported status; None = never joined.
    status_reported_at: Optional[float] = None


class CloudProviderError(Exception):
    pass


class InsufficientCapacityError(CloudProviderError):
    """The provider could not fulfill an offering (ref: aws/errors.go
    InsufficientInstanceCapacity). Carries the failed offering so callers can
    blackout-cache it."""

    def __init__(self, instance_type: str, zone: str, capacity_type: str):
        super().__init__(
            f"insufficient capacity for {instance_type} ({capacity_type}) in {zone}"
        )
        self.instance_type = instance_type
        self.zone = zone
        self.capacity_type = capacity_type


class CloudProvider(abc.ABC):
    """Ref: pkg/cloudprovider/types.go:29-42. `create` is synchronous per node
    packing here (the reference's async channel-per-node is replaced by the
    controller's own worker pool)."""

    @abc.abstractmethod
    def create(
        self,
        constraints: Constraints,
        instance_types: Sequence[InstanceType],
        quantity: int,
        callback: Callable[[NodeSpec], None],
        pool_options: Optional[Sequence] = None,
        launch_id: Optional[str] = None,
    ) -> List[Exception]:
        """Launch `quantity` nodes satisfying constraints, choosing among the
        offered instance_types; invoke callback per launched node. Returns
        per-node errors (empty = full success).

        `pool_options` (ops.ffd.PoolOption rows, cheapest first) pins the
        launch request to specific price-ranked (type, zone) pools — the
        cost-aware plan's override rows. None = derive rows from
        instance_types x offerings (reference semantics,
        ref: instance.go getOverrides:173-207).

        `launch_id` is the caller's stable identity for this logical launch
        (the provisioning worker derives it from the batch content). A
        provider that supports idempotent launches MUST treat a repeated
        launch_id as the same purchase: re-deliver the instances the first
        attempt bought (adoption) instead of buying again, and derive any
        wire-level idempotency token (EC2 ClientToken) from it so a retried
        or crash-re-issued call is a server-side no-op. None = every call is
        a fresh purchase (legacy behavior)."""

    @abc.abstractmethod
    def delete(self, node: NodeSpec) -> None:
        ...

    def list_instances(self) -> List[CloudInstance]:
        """Every live instance carrying this cluster's ownership tag,
        whether or not a Node exists for it — the ground truth the leaked-
        capacity GC (controllers/instancegc.py) reconciles Nodes against.
        Providers that cannot enumerate owned capacity return [] (the GC is
        then inert for them)."""
        return []

    def terminate_instance(self, instance: CloudInstance) -> None:
        """Terminate a (possibly Node-less) instance by provider identity.
        Not-found must be success: the GC races normal termination."""
        raise NotImplementedError(
            f"{type(self).__name__} cannot terminate untracked instances"
        )

    def poll_interruptions(self) -> List[InterruptionEvent]:
        """Pending interruption notices for this cluster's capacity,
        at-least-once: an event stays re-deliverable until `ack_interruption`
        confirms it was durably recorded (the SQS visibility model). Providers
        without an interruption feed return [] (the controller is then inert
        for them)."""
        return []

    def ack_interruption(self, event: InterruptionEvent) -> None:
        """Confirm an event was recorded (annotated onto its Node); the
        provider stops re-delivering it. Unknown/already-acked events are
        success — acks race re-deliveries."""

    def blackout_offering(
        self, instance_type: str, zone: str, capacity_type: str
    ) -> None:
        """Temporarily exclude one (type, zone, capacity-type) pool from
        `get_instance_types` — the interruption controller calls this for a
        reclaimed pool so replacement capacity re-solves AWAY from it (the
        same cache the ICE blackout feeds). Default: no-op."""

    def poll_market_events(self, after_seq: int = 0) -> List:
        """Spot-market ticks (karpenter_tpu.market.feed.MarketTick) with
        seq > after_seq, strictly seq-ordered and REPLAYABLE from 0: a
        restarted controller re-folds the whole history to reconstruct its
        PriceBook (state AND generation) — there is no ack protocol; the
        feed is the durable cursorless history, the way
        DescribeSpotPriceHistory is on EC2. Providers without a market feed
        return [] (the market controller is then inert for them)."""
        return []

    def attach_market(self, book) -> None:
        """Give the provider the controller's PriceBook so ADVERTISED spot
        offering prices track the live market (get_instance_types applies
        the book's per-pool discount; ICE-closed pools drop their spot
        offerings). Default: no-op — static catalogs stay static."""

    def instance_drifted(self, node: NodeSpec) -> Optional[str]:
        """Provider-side drift verdict for one live node: a short human
        reason string when the cloud says the instance no longer matches
        what the provisioner would launch today (launch-template/AMI
        generation moved, offering no longer advertised), else None. The
        drift sweep treats any non-None return as drift kind "provider".
        Must be read-only and cheap enough to call per node per sweep.
        Providers without drift detection return None (the drift controller
        is then spec-hash-only for them)."""
        return None

    @abc.abstractmethod
    def get_instance_types(self, constraints: Optional[Constraints] = None) -> List[InstanceType]:
        ...

    def default(self, provisioner: Provisioner) -> None:
        """Vendor defaulting hook (ref: types.go Default)."""

    def validate(self, provisioner: Provisioner) -> None:
        """Vendor validation hook (ref: types.go Validate)."""
