"""Cloud-provider abstraction.

Ref: pkg/cloudprovider/types.go:29-75 — CloudProvider, InstanceType and
Offering. We extend Offering with a price so the solver can optimize projected
$/hr (the reference delegates price choice to EC2 Fleet's lowest-price
allocation strategy; surfacing it lets the TPU solver make the cost tradeoff
jointly with packing).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, Iterable, List, Optional, Sequence

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import Constraints, Provisioner
from karpenter_tpu.api.resources import ResourceList, parse_resource_list

ARCH_AMD64 = "amd64"
ARCH_ARM64 = "arm64"
OS_LINUX = "linux"


@dataclass(frozen=True)
class Offering:
    """One purchasable (zone, capacity-type) combination for an instance type."""

    zone: str
    capacity_type: str = wellknown.CAPACITY_TYPE_ON_DEMAND
    price: float = 0.0  # $/hr; 0.0 = unknown


@dataclass
class InstanceType:
    """Ref: cloudprovider.InstanceType interface (types.go:44-63)."""

    name: str
    capacity: ResourceList
    overhead: ResourceList = field(default_factory=dict)
    architecture: str = ARCH_AMD64
    operating_systems: FrozenSet[str] = frozenset({OS_LINUX})
    offerings: List[Offering] = field(default_factory=list)

    def __post_init__(self):
        self.capacity = parse_resource_list(self.capacity)
        self.overhead = parse_resource_list(self.overhead)

    def zones(self) -> FrozenSet[str]:
        return frozenset(offering.zone for offering in self.offerings)

    def capacity_types(self) -> FrozenSet[str]:
        return frozenset(offering.capacity_type for offering in self.offerings)

    def get(self, resource: str) -> float:
        return self.capacity.get(resource, 0.0)

    def min_price(
        self,
        zones: Optional[Iterable[str]] = None,
        capacity_types: Optional[Iterable[str]] = None,
    ) -> float:
        """Cheapest offering price within the allowed zones/capacity types."""
        zones = None if zones is None else set(zones)
        capacity_types = None if capacity_types is None else set(capacity_types)
        prices = [
            o.price
            for o in self.offerings
            if (zones is None or o.zone in zones)
            and (capacity_types is None or o.capacity_type in capacity_types)
        ]
        return min(prices) if prices else float("inf")


@dataclass
class NodeSpec:
    """A launched (or to-be-launched) node as the control plane sees it."""

    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    taints: List = field(default_factory=list)
    capacity: ResourceList = field(default_factory=dict)
    instance_type: str = ""
    zone: str = ""
    capacity_type: str = ""
    provider_id: str = ""
    ready: bool = False
    unschedulable: bool = False
    finalizers: List[str] = field(default_factory=list)
    created_at: float = 0.0
    deletion_timestamp: Optional[float] = None
    # Last time the kubelet reported status; None = never joined.
    status_reported_at: Optional[float] = None


class CloudProviderError(Exception):
    pass


class InsufficientCapacityError(CloudProviderError):
    """The provider could not fulfill an offering (ref: aws/errors.go
    InsufficientInstanceCapacity). Carries the failed offering so callers can
    blackout-cache it."""

    def __init__(self, instance_type: str, zone: str, capacity_type: str):
        super().__init__(
            f"insufficient capacity for {instance_type} ({capacity_type}) in {zone}"
        )
        self.instance_type = instance_type
        self.zone = zone
        self.capacity_type = capacity_type


class CloudProvider(abc.ABC):
    """Ref: pkg/cloudprovider/types.go:29-42. `create` is synchronous per node
    packing here (the reference's async channel-per-node is replaced by the
    controller's own worker pool)."""

    @abc.abstractmethod
    def create(
        self,
        constraints: Constraints,
        instance_types: Sequence[InstanceType],
        quantity: int,
        callback: Callable[[NodeSpec], None],
        pool_options: Optional[Sequence] = None,
    ) -> List[Exception]:
        """Launch `quantity` nodes satisfying constraints, choosing among the
        offered instance_types; invoke callback per launched node. Returns
        per-node errors (empty = full success).

        `pool_options` (ops.ffd.PoolOption rows, cheapest first) pins the
        launch request to specific price-ranked (type, zone) pools — the
        cost-aware plan's override rows. None = derive rows from
        instance_types x offerings (reference semantics,
        ref: instance.go getOverrides:173-207)."""

    @abc.abstractmethod
    def delete(self, node: NodeSpec) -> None:
        ...

    @abc.abstractmethod
    def get_instance_types(self, constraints: Optional[Constraints] = None) -> List[InstanceType]:
        ...

    def default(self, provisioner: Provisioner) -> None:
        """Vendor defaulting hook (ref: types.go Default)."""

    def validate(self, provisioner: Provisioner) -> None:
        """Vendor validation hook (ref: types.go Validate)."""
