"""Spot-market model + EC2 Fleet allocation-strategy simulator.

The reference delegates the final (instance-type, zone, capacity-type) pool
choice to EC2 CreateFleet (ref: pkg/cloudprovider/aws/instance.go:116-133):

  * on-demand -> `lowest-price`: the cheapest offered pool wins.
  * spot      -> `capacity-optimized-prioritized`: EC2 picks the pool with the
    deepest spare capacity, honoring the caller-supplied priority order only
    "on a best-effort basis". The reference sets priority = the option's index
    in its ascending-size window (instance.go:173-207) — price-blind.

So a packing plan's realized $/hr depends on the allocation strategy and on
the spot market's (price, depth) state per pool — not just on the cheapest
offered price. This module models both so that plans from *any* solver are
priced by identical, reproducible fleet semantics:

  * `SpotMarket`: per-(type, zone) spot discount and capacity depth with
    configurable family/zone structure and price<->depth anti-correlation
    (deep pools trend cheap, with idiosyncratic noise — the real spot market's
    loose coupling).
  * `allocate`: one fleet launch decision under either strategy.
  * `simulate_plan_cost`: total realized $/hr for a PackResult.

Nothing here is used to *train* the solver against hidden state: solvers see
only offering prices; the market's depth state is revealed only through the
allocation simulator, exactly as EC2 reveals it only through fulfilment.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api import wellknown

Pool = Tuple[str, str]  # (instance_type_name, zone)

# EC2 honors spot priorities "on a best-effort basis" while optimizing for
# capacity: model that as "any pool within DEPTH_SLACK of the deepest offered
# pool is capacity-equivalent; the highest-priority pool among those wins".
DEPTH_SLACK = 0.25


@dataclass
class SpotMarket:
    """Per-pool spot price fraction (of on-demand) and capacity depth."""

    discount: Dict[Pool, float] = field(default_factory=dict)  # spot/od price ratio
    depth: Dict[Pool, float] = field(default_factory=dict)  # relative spare capacity

    def spot_price(self, pool: Pool, on_demand_price: float) -> float:
        return on_demand_price * self.discount.get(pool, 1.0)

    def pool_depth(self, pool: Pool) -> float:
        return self.depth.get(pool, 1.0)


def generate_market(
    type_names: Sequence[str],
    zones: Sequence[str],
    seed: int = 0,
    *,
    price_depth_correlation: float = 0.4,
    family_sigma: float = 0.25,
    zone_sigma: float = 0.2,
    noise_sigma: float = 0.12,
    min_discount: float = 0.25,
    max_discount: float = 0.95,
) -> SpotMarket:
    """A structured spot market: depth factors by family and zone (capacity is
    bought per family per AZ), pool-level noise, and discounts that trend
    inversely with depth (deep pool => cheap) but only loosely
    (`price_depth_correlation` in [0, 1]; 0 = independent)."""
    rng = np.random.default_rng(seed)
    families = sorted({name.split(".")[0] for name in type_names})
    family_depth = {f: float(rng.lognormal(0.0, family_sigma)) for f in families}
    zone_depth = {z: float(rng.lognormal(0.0, zone_sigma)) for z in zones}

    market = SpotMarket()
    depths = {}
    for name in type_names:
        family = name.split(".")[0]
        for zone in zones:
            pool = (name, zone)
            depths[pool] = (
                family_depth[family]
                * zone_depth[zone]
                * float(rng.lognormal(0.0, noise_sigma))
            )
    values = np.array(list(depths.values()))
    lo, hi = values.min(), values.max()
    span = max(hi - lo, 1e-9)
    for pool, depth in depths.items():
        normalized = (depth - lo) / span  # [0, 1]
        market.depth[pool] = float(depth)
        # Cheapness rises with depth by `price_depth_correlation`; the rest is
        # idiosyncratic.
        base = 1.0 - price_depth_correlation * normalized
        noise = float(rng.uniform(-1.0, 1.0)) * (1.0 - price_depth_correlation) * 0.35
        discount = np.clip(
            min_discount + (max_discount - min_discount) * (base + noise - 0.35),
            min_discount,
            max_discount,
        )
        market.discount[pool] = float(discount)
    return market


@dataclass
class PoolOffer:
    """One CreateFleet override row (ref: instance.go:173-207)."""

    instance_type: str
    zone: str
    price: float  # $/hr for this pool at the launch's capacity type
    priority: int  # lower = preferred (spot best-effort only)


def allocate(
    offers: Sequence[PoolOffer],
    capacity_type: str,
    market: Optional[SpotMarket] = None,
    excluded: Iterable[Pool] = (),
    depth_slack: float = DEPTH_SLACK,
) -> Optional[PoolOffer]:
    """One node's pool under the reference's fleet strategies
    (instance.go:129-132): lowest-price for on-demand;
    capacity-optimized-prioritized for spot. depth_slack parameterizes how
    "best-effort" EC2's priority honoring is (0 = pure capacity-optimized,
    ignore priorities entirely unless depths tie; 1 = pure priority order) —
    the bench sweeps it to show the cost win isn't an artifact of one
    assumed value."""
    excluded = set(excluded)
    usable = [o for o in offers if (o.instance_type, o.zone) not in excluded]
    if not usable:
        return None
    if capacity_type != wellknown.CAPACITY_TYPE_SPOT or market is None:
        return min(usable, key=lambda o: (o.price, o.priority))
    deepest = max(market.pool_depth((o.instance_type, o.zone)) for o in usable)
    equivalent = [
        o
        for o in usable
        if market.pool_depth((o.instance_type, o.zone)) >= deepest * (1.0 - depth_slack)
    ]
    return min(equivalent, key=lambda o: o.priority)


def plan_offers(
    packing,
    zones: Sequence[str],
    capacity_type: str,
    market: Optional[SpotMarket],
) -> List[PoolOffer]:
    """Override rows for one Packing: option order IS the priority order
    (the reference's ascending-size window / this framework's price ranking),
    crossed with the allowed zones (instance.go:173-207). A packing that pins
    pool-level rows (`pool_options`) supplies them directly — per-pool
    priorities instead of per-type."""
    if getattr(packing, "pool_options", None):
        offers = []
        for pool in packing.pool_options:
            if zones and pool.zone not in zones:
                continue
            price = pool.price
            if capacity_type == wellknown.CAPACITY_TYPE_SPOT and market is not None:
                price = market.spot_price(
                    (pool.instance_type.name, pool.zone),
                    _on_demand_price(pool.instance_type, pool.zone),
                )
            offers.append(
                PoolOffer(
                    instance_type=pool.instance_type.name,
                    zone=pool.zone,
                    price=price,
                    priority=pool.priority,
                )
            )
        return offers
    offers: List[PoolOffer] = []
    for index, instance_type in enumerate(packing.instance_type_options):
        for offering in instance_type.offerings:
            if offering.capacity_type != capacity_type:
                continue
            if zones and offering.zone not in zones:
                continue
            price = offering.price
            if capacity_type == wellknown.CAPACITY_TYPE_SPOT and market is not None:
                price = market.spot_price(
                    (instance_type.name, offering.zone),
                    _on_demand_price(instance_type, offering.zone),
                )
            offers.append(
                PoolOffer(
                    instance_type=instance_type.name,
                    zone=offering.zone,
                    price=price,
                    priority=index,
                )
            )
    return offers


def _on_demand_price(instance_type, zone: str) -> float:
    for offering in instance_type.offerings:
        if (
            offering.zone == zone
            and offering.capacity_type == wellknown.CAPACITY_TYPE_ON_DEMAND
        ):
            return offering.price
    return instance_type.min_price(
        capacity_types=[wellknown.CAPACITY_TYPE_ON_DEMAND]
    )


def capacity_type_for(constraints, instance_types) -> str:
    """Spot iff allowed by constraints and offered by any candidate type
    (ref: instance.go getCapacityType:281-292)."""
    allowed = constraints.effective_requirements().allowed(
        wellknown.CAPACITY_TYPE_LABEL
    )
    if allowed.contains(wellknown.CAPACITY_TYPE_SPOT):
        for instance_type in instance_types:
            if wellknown.CAPACITY_TYPE_SPOT in instance_type.capacity_types():
                return wellknown.CAPACITY_TYPE_SPOT
    return wellknown.CAPACITY_TYPE_ON_DEMAND


def simulate_plan_cost(
    result,
    constraints,
    market: Optional[SpotMarket] = None,
    zones: Sequence[str] = (),
    depth_slack: float = DEPTH_SLACK,
    excluded: Iterable[Pool] = (),
) -> float:
    """Total realized $/hr of a PackResult when every node is bought through
    the reference's fleet strategies against one shared market state.
    `excluded` pools (ICE'd / blacked-out mid-storm) are unpurchasable for
    the allocation AND the infeasible fallback below."""
    allowed_zones = constraints.effective_requirements().allowed(wellknown.ZONE_LABEL)
    zone_filter = [z for z in zones if allowed_zones.contains(z)] if zones else []
    excluded = set(excluded)
    total = 0.0
    for packing in result.packings:
        capacity_type = capacity_type_for(constraints, packing.instance_type_options)
        offers = plan_offers(packing, zone_filter, capacity_type, market)
        chosen = allocate(
            offers, capacity_type, market, excluded=excluded,
            depth_slack=depth_slack,
        )
        if chosen is None:
            # No purchasable pool: price at the best advertised offering that
            # is still purchasable, so an infeasible plan costs rather than
            # silently zeroes. Excluded pools don't advertise — a packing
            # whose every pool is blacked out prices at inf (pricing it at
            # the best ADVERTISED offering silently under-reported storm-
            # time cost).
            chosen_price = min(
                (
                    offering.price
                    for it in packing.instance_type_options
                    for offering in it.offerings
                    if (it.name, offering.zone) not in excluded
                ),
                default=float("inf"),
            )
            total += packing.node_quantity * chosen_price
            continue
        total += packing.node_quantity * chosen.price
    return total
