"""Tensor kernels and solver primitives: spec encoding, greedy FFD baseline,
JAX pack kernels, batched scoring + LP relaxation, topology masks."""
