"""Batched scoring + LP-relaxation solver.

The cost-optimal packing problem: choose per-type node counts n_t and pod
assignments minimizing sum_t n_t * price_t. The reference never optimizes
this — FFD picks by max-pods-packed (packer.go:163-189) and leaves price to
EC2 Fleet. We solve the continuous relaxation on TPU:

    x[g,t]  >= 0   pods of group g assigned to type t  (sum_t x = c_g)
    n_t     ~  max_r (sum_g x[g,t] * v[g,r]) / K[t,r]  (fractional nodes)
    minimize sum_t price_t * n_t

parameterized as x = c * softmax(logits) over feasible types, optimized with
Adam under lax.scan — pure matmul/elementwise work that maps straight onto
the MXU, and the same step function shards over a device mesh for large
problems (parallel/sharded_solver.py). Integerization (largest-remainder) and
per-type greedy fills turn the relaxed plan into real nodes; the caller
compares the result against greedy and keeps the cheaper packing, so the LP
path can only improve on the baseline.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


class LPResult(NamedTuple):
    assignment: jnp.ndarray  # [G, T] float — relaxed pod counts
    fractional_nodes: jnp.ndarray  # [T] float
    objective: jnp.ndarray  # [] float — relaxed $/hr (lower bound-ish)


def feasibility_mask(
    vectors: jnp.ndarray, capacity: jnp.ndarray, valid_types, allow=None
) -> jnp.ndarray:
    """[G, T] bool — can one pod of group g fit an empty node of type t.

    `allow` is an optional [G, T] constraint mask (one level of the
    constraint compiler's [L, G, T] tensor — see constraints/compiler.py):
    a (group, type) pair the active relaxation level forbids is infeasible
    regardless of fit, so the LP's assignment mass never lands on it."""
    fits = jnp.all(vectors[:, None, :] <= capacity[None, :, :] + 1e-6, axis=-1)
    mask = fits & valid_types[None, :]
    if allow is not None:
        mask = mask & allow
    return mask


def lp_objective(
    logits: jnp.ndarray,  # [G, T]
    vectors: jnp.ndarray,  # [G, R]
    counts: jnp.ndarray,  # [G] float
    capacity: jnp.ndarray,  # [T, R]
    prices: jnp.ndarray,  # [T]
    feasible: jnp.ndarray,  # [G, T] bool
    sharpness: float = 20.0,
) -> jnp.ndarray:
    # -1e9, not -inf: a row with no feasible type (count 0 after the caller
    # strips unschedulable groups) must softmax to finite garbage that the
    # count-multiply zeroes, not NaN-poison the whole objective.
    masked = jnp.where(feasible, logits, -1e9)
    x = counts[:, None] * jax.nn.softmax(masked, axis=1)  # [G, T]
    x = jnp.where(feasible, x, 0.0)
    demand = jnp.einsum("gt,gr->tr", x, vectors)  # [T, R]
    frac = demand / jnp.maximum(capacity, 1e-3)  # [T, R]
    # Smooth max over resource dims keeps gradients flowing to every binding
    # dimension; jnp.max alone starves the non-binding ones.
    nodes = jax.nn.logsumexp(frac * sharpness, axis=1) / sharpness  # [T]
    return jnp.sum(prices * nodes)


def lp_relax_body(
    vectors,  # [G, R] f32
    counts,  # [G] i32/f32
    capacity,  # [T, R] f32
    valid_types,  # [T] bool
    prices,  # [T] f32
    steps: int = 300,
    constrain=None,
) -> LPResult:
    """Traceable LP-relaxation body. `constrain` is an optional hook applied
    to every [G, T] tensor (feasibility mask, logits init, the scan carry,
    and the final assignment): the multi-chip path passes
    `lax.with_sharding_constraint(·, P("groups", "types"))` so GSPMD shards
    the big tensors over the mesh while this math stays topology-agnostic
    (parallel/sharded_solver.py; SURVEY.md §2.7)."""
    gt = (lambda x: x) if constrain is None else constrain
    counts_f = counts.astype(jnp.float32)
    feasible = gt(feasibility_mask(vectors, capacity, valid_types))
    # Initialize biased toward price-efficient types: -price per unit of the
    # type's bottleneck capacity.
    density = prices / jnp.maximum(jnp.max(capacity, axis=1), 1.0)
    logits0 = gt(
        jnp.broadcast_to(-jnp.log(density + 1e-9), feasible.shape).astype(
            jnp.float32
        )
    )

    optimizer = optax.adam(0.25)
    opt_state = optimizer.init(logits0)
    grad_fn = jax.grad(lp_objective)

    def step(carry, _):
        logits, opt_state = carry
        grads = grad_fn(logits, vectors, counts_f, capacity, prices, feasible)
        updates, opt_state = optimizer.update(grads, opt_state, logits)
        return (gt(optax.apply_updates(logits, updates)), opt_state), ()

    (logits, _), _ = jax.lax.scan(step, (logits0, opt_state), None, length=steps)

    masked = jnp.where(feasible, logits, -1e9)
    x = counts_f[:, None] * jax.nn.softmax(masked, axis=1)
    x = gt(jnp.where(feasible, x, 0.0))
    demand = jnp.einsum("gt,gr->tr", x, vectors)
    nodes = jnp.max(demand / jnp.maximum(capacity, 1e-3), axis=1)
    return LPResult(
        assignment=x,
        fractional_nodes=nodes,
        objective=jnp.sum(prices * nodes),
    )


@functools.partial(jax.jit, static_argnames=("steps",))
def lp_relax_solve(
    vectors,  # [G, R] f32
    counts,  # [G] i32/f32
    capacity,  # [T, R] f32
    valid_types,  # [T] bool
    prices,  # [T] f32
    steps: int = 300,
) -> LPResult:
    return lp_relax_body(vectors, counts, capacity, valid_types, prices, steps)


def round_assignment(assignment: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """Largest-remainder rounding of [G, T] relaxed assignment so each group's
    row sums exactly to counts[g]. Returns int64 [G, T]."""
    assignment = np.asarray(assignment, dtype=np.float64)  # vet: host-array(host rounding pass)
    counts = np.asarray(counts, dtype=np.int64)  # vet: host-array(host rounding pass)
    out = np.floor(assignment).astype(np.int64)
    for g in range(assignment.shape[0]):
        deficit = int(counts[g] - out[g].sum())
        if deficit <= 0:
            # Over-assignment can only come from float error; trim greedily
            # from the smallest fractional cells.
            while out[g].sum() > counts[g]:
                candidates = np.nonzero(out[g] > 0)[0]
                out[g, candidates[np.argmin(assignment[g, candidates])]] -= 1
            continue
        remainders = assignment[g] - np.floor(assignment[g])
        order = np.argsort(-remainders)
        for t in order[:deficit]:
            out[g, t] += 1
    return out
