"""Pallas TPU kernels for the solver's hot tensor ops.

The one op in the solve that actually scales super-linearly is the
capacity-dominance price reduction: effective[t] = min over t' of price[t']
where t' dominates t on every resource axis — O(T^2 R) compares + a masked
min, [512, 512, 8] at the padded north-star config. The XLA lowering
materializes the [T, T, R] broadcast; this kernel keeps everything
VMEM-resident and accumulates the dominance mask one resource axis at a time
([T, T] working set, ~1MB at T=512, well inside the ~16MB VMEM budget).

On non-TPU backends (CPU tests, the sidecar without an accelerator) the
kernel runs the identical jnp formulation — pallas interpret mode would also
work, but the jnp path is faster off-TPU and keeps the fallback codepath
exercised.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_EPS = 1e-6


def _dominance_prices_ref(capacity: jnp.ndarray, prices: jnp.ndarray) -> jnp.ndarray:
    """Reference (XLA) formulation — also the non-TPU fallback.

    capacity: [T, R] usable capacity; prices: [T] with invalid rows +inf.
    Returns [T] effective prices (min price over dominating types)."""
    dominates = (
        capacity[None, :, :] >= capacity[:, None, :] - _EPS
    ).all(axis=2)
    return jnp.where(dominates, prices[None, :], jnp.inf).min(axis=1)


def _dominance_kernel(capacity_ref, capacity_t_ref, prices_ref, out_ref):
    """Single-block kernel: the whole problem lives in VMEM.

    All operands stay 2D (Mosaic lowers 1D slices/transposes through costly
    relayouts — the host passes capacity both [T, R] and pre-transposed
    [R, T] so column AND row vectors are plain 2D slices). The dominance
    mask accumulates one resource axis at a time, so the biggest
    intermediate is [T', T], not [T, T, R].

    domT[t', t] = all_r capacity[t', r] >= capacity[t, r] - eps; the output
    row is min over t' of prices[t'] where domT."""
    capacity = capacity_ref[:]  # [T, R] f32
    capacity_t = capacity_t_ref[:]  # [R, T] f32
    prices_col = prices_ref[:]  # [T, 1] f32
    num_types, dims = capacity.shape
    dominates_t = jnp.ones((num_types, num_types), dtype=jnp.bool_)
    for r in range(dims):  # static unroll: R is 8
        cap_col = capacity[:, r : r + 1]  # [T', 1] — values at t'
        cap_row = capacity_t[r : r + 1, :]  # [1, T] — values at t
        dominates_t &= cap_col >= cap_row - _EPS
    effective = jnp.min(
        jnp.where(dominates_t, prices_col, jnp.inf), axis=0, keepdims=True
    )  # [1, T]
    out_ref[:] = effective


@jax.jit
def _dominance_prices_pallas(
    capacity: jnp.ndarray, prices: jnp.ndarray
) -> jnp.ndarray:
    from jax.experimental import pallas as pl

    num_types = capacity.shape[0]
    out = pl.pallas_call(
        _dominance_kernel,
        out_shape=jax.ShapeDtypeStruct((1, num_types), capacity.dtype),
    )(capacity, capacity.T, prices.reshape(num_types, 1))
    return out.reshape(num_types)


# Mosaic-lowering probe result: None = not yet probed.
_pallas_usable_cache = None


def _in_active_trace() -> bool:
    """True while jax is tracing — everything staged here becomes part of
    the outer jaxpr, so an eager probe is impossible in this state."""
    try:
        from jax._src import core as _core

        return not _core.trace_state_clean()
    except Exception:  # noqa: BLE001 — private API moved; fall back to probe
        return isinstance(jnp.zeros(()), jax.core.Tracer)


def ensure_probed() -> bool:
    """Probe the Pallas/Mosaic lowering ONCE, eagerly, at the north-star
    padded shape ([512, 8]). dominance_prices is traced inside the fused
    solve kernel, so a lowering failure there would surface as a compile
    error propagating out of CostSolver.solve with no way to catch it at
    trace time — this probe runs outside any trace (dispatch sites call it
    before invoking their jitted kernels) and permanently routes dominance
    pricing through the XLA formulation if the kernel doesn't compile on
    this backend/generation.

    Called while tracing, it does NOT probe (the ops would stage into the
    outer jaxpr and "succeed" untested) — it reports unusable for that
    compile and leaves the cache unset so a later eager call still probes."""
    global _pallas_usable_cache
    if _pallas_usable_cache is None:
        if _in_active_trace():
            return False
        try:
            probe = jax.block_until_ready(
                _dominance_prices_pallas(
                    jnp.ones((512, 8), jnp.float32), jnp.ones((512,), jnp.float32)
                )
            )
            _pallas_usable_cache = bool(probe.shape == (512,))
        except Exception as err:  # noqa: BLE001 — any lowering failure
            from karpenter_tpu.utils import logging as klog

            klog.named("pallas").warning(
                "pallas dominance kernel unusable on %s (%s); "
                "using the XLA formulation",
                jax.default_backend(),
                err,
            )
            _pallas_usable_cache = False
    return _pallas_usable_cache


def dominance_prices(capacity: jnp.ndarray, prices: jnp.ndarray) -> jnp.ndarray:
    """Effective (dominance-minimum) prices: Pallas on TPU when the lowering
    probe passes, XLA formulation elsewhere. The branch is trace-time Python,
    so this is safe to call under an outer jit — dispatch sites should call
    ensure_probed() eagerly first, or the first compile conservatively bakes
    the XLA path."""
    if jax.default_backend() == "tpu" and ensure_probed():
        return _dominance_prices_pallas(capacity, prices)
    return _dominance_prices_ref(capacity, prices)
