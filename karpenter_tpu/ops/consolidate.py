"""Batched consolidation counterfactuals — the deprovisioning solve.

The provisioning kernels answer "what capacity should be BOUGHT for these
pending pods"; this module answers the inverse question the consolidation
controller asks about capacity already RUNNING: for every candidate node,
what happens to the cluster if the node were gone?

Two counterfactual actions are scored for all candidates in ONE batched
dispatch per sweep (the Go reference simulates candidates one at a time;
evaluating the [C, N] / [C, T] tensors together is exactly the shape the
batched solver was built for):

- **delete** — the candidate's pods are first-fit-decreasing packed into the
  free headroom of the remaining nodes ([C, N, R] fill, victim row masked
  out per candidate). Feasible iff every pod places; savings = the node's
  whole offering price.
- **replace** — the candidate's pods move onto ONE fresh node of a cheaper
  type. For a single receiving node, multi-dimensional feasibility is exact
  additivity: total demand <= usable capacity, which is score_kernel's
  `feasibility_mask` with the [C, R] demand matrix standing in for the group
  axis. Savings = node price minus the cheapest feasible type's price.

Per-candidate masking carries the envelope differences between candidates
(`bin_mask` excludes the victim and ineligible receivers per candidate;
`type_valid` carries per-candidate accelerator anti-waste), so heterogeneous
candidates still share the single dispatch. Shapes are bucketed to powers of
two (ops.pack_kernel.bucket_size) so repeat sweeps hit the jit cache, and
the eager fetch is SMALL: the [C] scalar verdict columns plus the on-device
argmax winner's [G, N] plan row — the full [C, G, N] plan tensor stays
device-resident behind lazy accessors (docs/design/device-residency.md).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.ops.pack_kernel import (
    bucket_size,
    device_resident,
    fetch_bytes,
    pad_to,
)
from karpenter_tpu.ops.score_kernel import feasibility_mask

ACTION_NONE = 0
ACTION_DELETE = 1
ACTION_REPLACE = 2

# Savings below this ($/hr) are noise, not a reason to disrupt a node.
MIN_SAVINGS_DOLLARS = 1e-6


@dataclass
class ConsolidationProblem:
    """Dense inputs for one batched counterfactual solve.

    pod_vectors/pod_counts are the candidates' replaceable pods grouped by
    identical request vector (ops.encode.group_pods order: FFD-sorted desc),
    zero-padded to a common group axis. headroom is the free USABLE capacity
    of every live receiver node; bin_mask[c, j] says node j may receive
    candidate c's pods (False on the victim's own row and on ineligible
    receivers). type_capacity/type_prices densify the replacement fleet
    (build_fleet output: usable capacity, cheapest allowed offering price);
    type_valid[c, t] carries per-candidate masking (accelerator anti-waste).
    """

    pod_vectors: np.ndarray  # [C, G, R] float32
    pod_counts: np.ndarray  # [C, G] int32
    headroom: np.ndarray  # [N, R] float32
    bin_mask: np.ndarray  # [C, N] bool
    node_prices: np.ndarray  # [C] float64 — candidate's current offering $/hr
    type_capacity: np.ndarray  # [T, R] float32
    type_prices: np.ndarray  # [T] float32
    type_valid: np.ndarray  # [C, T] bool

    @property
    def num_candidates(self) -> int:
        return int(self.pod_vectors.shape[0])


@dataclass
class ConsolidationVerdicts:
    """Per-candidate scores, one row per ConsolidationProblem candidate.

    The [C, G, N] delete-plan tensor stays DEVICE-RESIDENT: the eager fetch
    carries only the [C] scalar columns plus the argmax winner's [G, N] row
    (prefetched on device — the only plan the common one-action sweep ever
    decodes). take_row lazily fetches other candidates' rows on demand;
    the delete_take property fetches the whole tensor (tests, tooling)."""

    delete_ok: np.ndarray  # [C] bool — every pod placed into headroom
    replace_type: np.ndarray  # [C] int32 — cheapest feasible type (by index)
    replace_price: np.ndarray  # [C] float — inf when no feasible type
    savings: np.ndarray  # [C] float — $/hr shed by the best action (-inf none)
    action: np.ndarray  # [C] int8 — ACTION_NONE | ACTION_DELETE | ACTION_REPLACE
    _takes: object = None  # [Cp, Gp, Np] int32 device array (padded)
    _shape: Tuple[int, int, int] = (0, 0, 0)  # real (C, G, N)
    _rows: Dict[int, np.ndarray] = field(default_factory=dict)
    _takes_host: Optional[np.ndarray] = None

    def best(self) -> int:
        """Index of the best cost-positive candidate, or -1."""
        if self.savings.size == 0:
            return -1
        index = int(np.argmax(self.savings))
        if self.action[index] == ACTION_NONE:
            return -1
        return index

    def take_row(self, candidate: int) -> np.ndarray:
        """One candidate's [G, N] delete plan. The device-argmax winner's
        row arrived with the eager fetch; any other row is a tiny staged
        device-side slice fetch, paid only when a sweep actually executes
        more than the best action."""
        row = self._rows.get(candidate)
        if row is None:
            _, num_groups, num_bins = self._shape
            row = np.asarray(  # vet: host-array(_fetch returns numpy)
                _fetch(self._takes[candidate])
            )[:num_groups, :num_bins]
            self._rows[candidate] = row
        return row

    @property
    def delete_take(self) -> np.ndarray:
        """The full [C, G, N] plan tensor, fetched on first use — test and
        tooling convenience, NOT the sweep hot path."""
        if self._takes_host is None:
            num_candidates, num_groups, num_bins = self._shape
            self._takes_host = np.asarray(  # vet: host-array(_fetch returns numpy)
                _fetch(self._takes)
            )[:num_candidates, :num_groups, :num_bins]
        return self._takes_host


def _counterfactual_body(
    pod_vectors, pod_counts, headroom, bin_mask, type_capacity, type_prices,
    type_valid, node_prices, cand_valid,
):
    """The fused counterfactual math — one traced computation per shape
    bucket. Delete leg: batched first-fit-decreasing fill of the [C, N, R]
    masked headroom (groups arrive FFD-sorted; per group the cumulative-sum
    cutoff distributes the count across bins in row order — first-fit
    without a per-pod loop). Replace leg: score_kernel.feasibility_mask
    over the [C, R] total demand. Tail post-pass: the same savings/action
    scoring the host applies (float32 here; the host re-derives it in
    float64 as the authoritative copy) drives an ON-DEVICE argmax so the
    winning candidate's [G, N] delete plan can be gathered and fetched
    without transferring the full [C, G, N] tensor."""
    counts = pod_counts.astype(jnp.float32)
    room = jnp.where(bin_mask[:, :, None], headroom[None, :, :], 0.0)

    def place(carry, g):
        vec = pod_vectors[:, g, :]  # [C, R]
        cnt = counts[:, g]  # [C]
        positive = vec > 0
        ratio = jnp.where(
            positive[:, None, :],
            carry / jnp.maximum(vec[:, None, :], 1e-9),
            jnp.inf,
        )  # [C, N, R]
        fit = jnp.floor(jnp.min(ratio, axis=2) + 1e-6)  # [C, N]
        # A group with an all-zero vector (padded rows) fits anywhere.
        fit = jnp.where(jnp.isinf(fit), cnt[:, None], fit)
        fit = jnp.maximum(fit, 0.0)
        before = jnp.cumsum(fit, axis=1) - fit
        take = jnp.clip(cnt[:, None] - before, 0.0, fit)  # [C, N]
        carry = carry - take[:, :, None] * vec[:, None, :]
        return carry, take

    _, takes = jax.lax.scan(place, room, jnp.arange(pod_vectors.shape[1]))
    takes = jnp.transpose(takes, (1, 0, 2))  # [C, G, N]
    placed = takes.sum(axis=2)  # [C, G]
    delete_ok = jnp.all(placed >= counts - 0.5, axis=1)

    demand = (pod_vectors * counts[:, :, None]).sum(axis=1)  # [C, R]
    fits = feasibility_mask(
        demand, type_capacity, jnp.ones(type_capacity.shape[0], bool)
    )  # [C, T]
    fits = fits & type_valid
    priced = jnp.where(fits, type_prices[None, :], jnp.inf)
    replace_price = priced.min(axis=1)
    replace_type = jnp.argmin(priced, axis=1)

    # On-device best-candidate selection (mirrors the host scoring below;
    # padded candidates are masked out via cand_valid). Ties between the
    # device float32 argmax and the host float64 re-derivation are resolved
    # by the host — solve_candidates falls back to a lazy row fetch when
    # the two disagree, so the prefetched row is an optimization, never the
    # authority.
    savings_delete = jnp.where(
        delete_ok & cand_valid, node_prices, -jnp.inf
    )
    margin = node_prices - replace_price
    savings_replace = jnp.where(
        jnp.isfinite(replace_price)
        & (margin > MIN_SAVINGS_DOLLARS)
        & cand_valid,
        margin,
        -jnp.inf,
    )
    best = jnp.argmax(jnp.maximum(savings_delete, savings_replace))
    best_take = takes[best]  # [G, N]
    return (
        takes.astype(jnp.int32),
        delete_ok,
        replace_type.astype(jnp.int32),
        replace_price,
        best.astype(jnp.int32),
        best_take.astype(jnp.int32),
    )


# Per-sweep operands donated (nothing reads them after dispatch); the type
# catalog arrays (argnums 4, 5) are NOT — they ride device_resident handles
# reused across sweeps, and donation would kill them after one call.
_counterfactual_kernel = jax.jit(
    _counterfactual_body, donate_argnums=(0, 1, 2, 3, 6, 7, 8)
)


def _fetch(tree):
    """THE single raw device->host fetch site of this module (everything
    else — the eager scalar columns, lazy plan rows, the full-tensor test
    convenience — routes through here; tools/vet's fetch-discipline checker
    pins that)."""
    return jax.device_get(tree)


# Eager fetch payload (bytes) of the most recent solve_candidates call —
# published by bench.py as the consolidation path's fetch_bytes. Plain
# module state, written by the (single-threaded per sweep) solve path.
LAST_FETCH_BYTES = 0


def _padded(problem: ConsolidationProblem) -> Tuple:
    """Bucket-pad every axis to powers of two so repeat sweeps reuse the
    compiled kernel. Padded candidates carry zero counts, padded bins a
    False mask, padded types a False validity column. The type-catalog
    arrays ride device_resident handles: back-to-back sweeps (and the
    provision solve they follow) reuse the same encoded fleet content
    without a fresh host->device transfer."""
    c_pad = bucket_size(max(problem.num_candidates, 1))
    g_pad = bucket_size(max(int(problem.pod_vectors.shape[1]), 1))
    n_pad = bucket_size(max(int(problem.headroom.shape[0]), 1))
    t_pad = bucket_size(max(int(problem.type_capacity.shape[0]), 1))
    cand_valid = np.zeros(c_pad, dtype=bool)
    cand_valid[: problem.num_candidates] = True
    return (
        pad_to(pad_to(problem.pod_vectors.astype(np.float32), c_pad), g_pad, axis=1),
        pad_to(pad_to(problem.pod_counts.astype(np.int32), c_pad), g_pad, axis=1),
        pad_to(problem.headroom.astype(np.float32), n_pad),
        pad_to(pad_to(problem.bin_mask.astype(bool), c_pad), n_pad, axis=1),
        device_resident(pad_to(problem.type_capacity.astype(np.float32), t_pad)),
        device_resident(pad_to(problem.type_prices.astype(np.float32), t_pad)),
        pad_to(pad_to(problem.type_valid.astype(bool), c_pad), t_pad, axis=1),
        pad_to(problem.node_prices.astype(np.float32), c_pad),
        cand_valid,
    )


def solve_candidates(problem: ConsolidationProblem) -> ConsolidationVerdicts:
    """Score every candidate's delete and replace counterfactuals in one
    batched dispatch + one SMALL device->host fetch — the [C] scalar
    columns plus the on-device-argmax winner's [G, N] plan row; the full
    [C, G, N] plan tensor stays device-resident behind lazy accessors.
    Action selection is re-derived host-side in float64 (authoritative;
    delete preferred on ties — it sheds the whole node instead of trading
    it)."""
    global LAST_FETCH_BYTES
    num_candidates = problem.num_candidates
    num_groups = int(problem.pod_vectors.shape[1])
    num_bins = int(problem.headroom.shape[0])
    padded = _padded(problem)
    takes_dev, delete_ok_d, replace_type_d, replace_price_d, best_d, best_take_d = (
        _counterfactual_kernel(*padded)
    )
    eager = (delete_ok_d, replace_type_d, replace_price_d, best_d, best_take_d)
    LAST_FETCH_BYTES = fetch_bytes(eager)
    delete_ok, replace_type, replace_price, device_best, best_take = _fetch(eager)
    delete_ok = delete_ok[:num_candidates]
    replace_type = replace_type[:num_candidates]
    replace_price = np.asarray(  # vet: host-array(_fetch returns numpy)
        replace_price, dtype=np.float64
    )[:num_candidates]

    node_prices = problem.node_prices.astype(np.float64)
    savings_delete = np.where(delete_ok, node_prices, -np.inf)
    replace_margin = node_prices - replace_price
    savings_replace = np.where(
        np.isfinite(replace_price) & (replace_margin > MIN_SAVINGS_DOLLARS),
        replace_margin,
        -np.inf,
    )
    action = np.full(num_candidates, ACTION_NONE, dtype=np.int8)
    action[savings_replace > MIN_SAVINGS_DOLLARS] = ACTION_REPLACE
    # Delete wins ties: shedding a node beats trading it at equal savings.
    action[
        (savings_delete > MIN_SAVINGS_DOLLARS) & (savings_delete >= savings_replace)
    ] = ACTION_DELETE
    savings = np.where(
        action == ACTION_DELETE,
        savings_delete,
        np.where(action == ACTION_REPLACE, savings_replace, -np.inf),
    )
    verdicts = ConsolidationVerdicts(
        delete_ok=delete_ok,
        replace_type=replace_type,
        replace_price=replace_price,
        savings=savings,
        action=action,
        _takes=takes_dev,
        _shape=(num_candidates, num_groups, num_bins),
    )
    # Seed the row cache with the device winner's prefetched plan. The host
    # float64 scoring is authoritative: if it disagrees with the device's
    # float32 argmax (a tie at the precision boundary), take_row simply
    # fetches the right row lazily instead.
    if int(device_best) < num_candidates:
        verdicts._rows[int(device_best)] = best_take[:num_groups, :num_bins]
    return verdicts


def delete_assignment(
    verdicts: ConsolidationVerdicts, candidate: int, members: List[List]
) -> List[Tuple[object, int]]:
    """Decode one candidate's delete plan into (pod, bin index) pairs.
    `members` is the candidate's PodGroups.members (group-major, the order
    the counts were encoded in); pods are consumed group-cursor style like
    models.solver._decode_rounds."""
    plan: List[Tuple[object, int]] = []
    take = verdicts.take_row(candidate)
    for g, group_members in enumerate(members):
        cursor = 0
        for j in np.nonzero(take[g] > 0)[0]:
            n = int(take[g, j])
            for pod in group_members[cursor : cursor + n]:
                plan.append((pod, int(j)))
            cursor += n
    return plan
