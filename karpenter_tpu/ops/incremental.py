"""Masked scatter / gather kernels for the incremental cluster encode.

The device-resident cluster tensors (models/cluster_state.py) are slot
arrays: a row per pod-group or node, holes where slots were freed. Between
sweeps the host accumulates which slots changed; a flush applies ALL of a
sweep's churn in one jitted masked-scatter dispatch per array — O(delta)
device work, never O(cluster). Compaction and the per-sweep sorted view are
gathers over a host-computed permutation.

Shape discipline: delta sizes and permutation lengths are bucketed to
powers of two (ops.pack_kernel.bucket_size) so repeat flushes hit the jit
cache; padding indices point one past the array (``mode="drop"`` scatters
discard them, gather fills read back zeros), so padded lanes are inert.

Donation: NONE of these kernels donates. The slot arrays are long-lived
generations that lagging consumers may still hold a handle to (the epoch
protocol detects staleness — it must be able to do so by *reading* the old
generation, not by segfaulting on a donated buffer). The per-sweep sorted
gather outputs are fresh temporaries and MAY be donated downstream by the
solve kernels (models/solver), which is exactly where PR 6's donation rules
put the boundary: donation lives only on top-level dispatch kernels, and
incremental buffers are never what they donate.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.ops.pack_kernel import bucket_size, pad_to


@functools.partial(jax.jit, donate_argnums=())
def _scatter_rows(dst, idx, rows):
    # Out-of-range padding indices are dropped, not clamped: a clamped index
    # would silently overwrite the last live row.
    return dst.at[idx].set(rows, mode="drop")


@functools.partial(jax.jit, donate_argnums=())
def _scatter_vals(dst, idx, vals):
    return dst.at[idx].set(vals, mode="drop")


@functools.partial(jax.jit, donate_argnums=())
def _gather_rows(src, perm):
    # Padding indices read back as zeros — a padded lane is an empty group
    # (count 0) / an invalid node row, inert in every downstream kernel.
    return jnp.take(src, perm, axis=0, mode="fill", fill_value=0)


def pad_indices(idx: np.ndarray, sentinel: int, minimum: int = 8) -> np.ndarray:
    """Bucket-pad an int32 index vector with an out-of-range sentinel so the
    jitted scatters/gathers compile once per bucket, not once per delta
    size."""
    idx = np.asarray(idx, dtype=np.int32)  # vet: host-array(callers pass host-built delta indices)
    return pad_to(idx, bucket_size(len(idx), minimum=minimum), value=sentinel)


def scatter_rows(dst, idx: np.ndarray, rows: np.ndarray):
    """dst[idx] = rows on device, O(len(idx)); idx pre-padded via
    pad_indices, rows padded to match (padded rows are dropped)."""
    rows = pad_to(np.asarray(rows), len(idx))  # vet: host-array(delta rows are host mirror copies)
    return _scatter_rows(dst, idx, rows)


def scatter_vals(dst, idx: np.ndarray, vals: np.ndarray):
    vals = pad_to(np.asarray(vals), len(idx))  # vet: host-array(delta values are host mirror copies)
    return _scatter_vals(dst, idx, vals)


def gather_rows(src, perm: np.ndarray):
    """src[perm] on device — the compaction / sorted-view gather. perm is
    bucket-padded (pad_indices) with sentinel = src.shape[0]; padded rows
    read back as zeros."""
    return _gather_rows(src, perm)


def device_slots(array: np.ndarray):
    """Move a freshly (re)built slot mirror onto the device — one transfer,
    used only on rebuild, compaction, and capacity growth (all epoch
    bumps). Steady-state flushes go through the scatters above."""
    return jax.device_put(array)
