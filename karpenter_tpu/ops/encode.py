"""Densify pod / instance-type specs into the arrays every kernel consumes.

The tensor layout (karpenter_tpu.api.wellknown.RESOURCE_DIMS) uses millicores
and MiB so float32 stays exact across realistic magnitudes (float32 integers
are exact to 2^24: 16M millicores / 16 TiB in MiB).

Pods with identical request vectors are collapsed into *groups*: real batches
contain a handful of distinct shapes (deployments replicate pods), so the
solver works on [G] groups instead of [P] pods — the same trick that makes the
greedy baseline O(nodes×types×G) instead of the reference's
O(nodes×types×P) inner loop (ref: binpacking/packable.go:113-132).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider import InstanceType


# Content-keyed memo for resource_vector: pod batches repeat a handful of
# request shapes thousands of times (a 50k-pod batch has ~16 distinct
# shapes), so the dict→vector conversion runs once per distinct content
# instead of once per pod. Entries are read-only so sharing is safe; the
# bound guards a long-running controller against unbounded distinct shapes.
_VEC_MEMO: Dict[Tuple, np.ndarray] = {}
_VEC_MEMO_MAX = 65536


def resource_vector(resources: Mapping[str, float]) -> np.ndarray:
    """ResourceList -> dense [R] float32 vector in kernel units.

    Returns a cached READ-ONLY array shared across calls with equal content —
    copy before mutating."""
    key = tuple(sorted(resources.items()))
    vec = _VEC_MEMO.get(key)
    if vec is not None:
        return vec
    vec = np.zeros(wellknown.NUM_RESOURCE_DIMS, dtype=np.float32)
    for name, value in resources.items():
        index = wellknown.RESOURCE_DIM_INDEX.get(name)
        if index is None:
            continue  # ephemeral-storage etc. — not packed dimensions
        if name == wellknown.RESOURCE_CPU:
            value = value * wellknown.CPU_SCALE
        elif name == wellknown.RESOURCE_MEMORY:
            value = value * wellknown.MEMORY_SCALE
        vec[index] = value
    vec.flags.writeable = False
    if len(_VEC_MEMO) >= _VEC_MEMO_MAX:
        _VEC_MEMO.clear()
    _VEC_MEMO[key] = vec
    return vec


@dataclass
class PodGroups:
    """Pods collapsed by identical request vector, sorted FFD-style
    (desc cpu, then desc memory — ref: binpacking/packer.go:96-104,
    with the remaining dims as deterministic tiebreak)."""

    vectors: np.ndarray  # [G, R] float32
    counts: np.ndarray  # [G] int32
    members: List[List[PodSpec]]  # pods per group, original objects

    @property
    def num_groups(self) -> int:
        return int(self.vectors.shape[0])

    @property
    def num_pods(self) -> int:
        return int(self.counts.sum())


_CPU_INDEX = wellknown.RESOURCE_DIM_INDEX[wellknown.RESOURCE_CPU]
_MEM_INDEX = wellknown.RESOURCE_DIM_INDEX[wellknown.RESOURCE_MEMORY]


def group_sort_key(vector: np.ndarray):
    """THE FFD group ordering (desc cpu, then desc memory, then the full
    vector for determinism) — shared by group_pods and the incremental
    encoder's sorted view (models/cluster_state.py) so the two paths produce
    bit-identical group tensors."""
    return (
        -vector[_CPU_INDEX],
        -vector[_MEM_INDEX],
        tuple(-x for x in vector.tolist()),
    )


def group_pods(pods: Sequence[PodSpec]) -> PodGroups:
    # One dict holding (vector, members) per distinct request shape: this
    # loop runs once per pod of a 50k batch, so it carries exactly one dict
    # probe and one append per pod.
    groups: Dict[bytes, Tuple[np.ndarray, List[PodSpec]]] = {}
    lookup = groups.get
    for pod in pods:
        # The cache is populated at PodSpec construction
        # (api/pods._dense_request_cache — one definition of the format);
        # the fallback covers only detached copies built without __post_init__.
        cached = pod.dense_vector
        if cached is None:  # pragma: no cover — defensive
            from karpenter_tpu.api.pods import _dense_request_cache

            pod.dense_vector = cached = _dense_request_cache(pod.requests)
        entry = lookup(cached[1])
        if entry is None:
            groups[cached[1]] = (cached[0], [pod])
        else:
            entry[1].append(pod)
    # Desc by cpu, then memory, then the full vector for determinism
    # (group_sort_key — shared with the incremental encoder).
    entries = sorted(
        groups.values(), key=lambda entry: group_sort_key(entry[0])
    )
    return PodGroups(
        vectors=np.stack([vec for vec, _ in entries])
        if entries
        else np.zeros((0, wellknown.NUM_RESOURCE_DIMS), np.float32),
        counts=np.array([len(members) for _, members in entries], dtype=np.int32),
        members=[members for _, members in entries],
    )


@dataclass
class InstanceFleet:
    """Candidate instance types densified for the kernels, already filtered to
    the constraint envelope and sorted ascending (ref: packable.go:76-91)."""

    instance_types: List[InstanceType]
    capacity: np.ndarray  # [T, R] usable capacity (total - overhead - daemons)
    total: np.ndarray  # [T, R] raw capacity (node allocatable before daemons)
    prices: np.ndarray  # [T] cheapest feasible offering $/hr
    # Launch envelope implied by the schedule's constraints: the zones pools
    # may come from (empty = unconstrained) and the capacity type a launch
    # would use (ref: instance.go getCapacityType:281-292).
    allowed_zones: List[str] = field(default_factory=list)
    capacity_type: str = wellknown.CAPACITY_TYPE_ON_DEMAND

    @property
    def num_types(self) -> int:
        return len(self.instance_types)


_ACCEL_INDEXES = [
    wellknown.RESOURCE_DIM_INDEX[r]
    for r in wellknown.ACCELERATOR_RESOURCES
    if r in wellknown.RESOURCE_DIM_INDEX
]
_POD_ENI_INDEX = wellknown.RESOURCE_DIM_INDEX[wellknown.RESOURCE_AWS_POD_ENI]


def _passes_constraint_filters(
    instance_type: InstanceType, constraints: Constraints
) -> bool:
    """Zone/type/arch/OS/capacity-type envelope filters
    (ref: packable.go:177-218)."""
    requirements = constraints.effective_requirements()
    checks = [
        (wellknown.INSTANCE_TYPE_LABEL, {instance_type.name}),
        (wellknown.ARCH_LABEL, {instance_type.architecture}),
        (wellknown.OS_LABEL, set(instance_type.operating_systems)),
        (wellknown.ZONE_LABEL, set(instance_type.zones())),
        (wellknown.CAPACITY_TYPE_LABEL, set(instance_type.capacity_types())),
    ]
    for key, offered in checks:
        allowed = requirements.allowed(key)
        if not any(allowed.contains(value) for value in offered):
            return False
    return True


def _passes_accelerator_filters(
    capacity_vec: np.ndarray, pods_need: np.ndarray
) -> bool:
    """Accelerators must match demand in both directions: required -> present,
    absent demand -> absent hardware (anti-waste; ref: packable.go:220-248).
    Pod-ENI is one-directional: only required -> present (ref: :250-262)."""
    for index in _ACCEL_INDEXES:
        if pods_need[index] > 0 and capacity_vec[index] == 0:
            return False
        if pods_need[index] == 0 and capacity_vec[index] > 0:
            return False
    if pods_need[_POD_ENI_INDEX] > 0 and capacity_vec[_POD_ENI_INDEX] == 0:
        return False
    return True


def _slow_kept(
    instance_types: Sequence[InstanceType],
    constraints: Constraints,
    pods_need: np.ndarray,
    daemon_groups: PodGroups,
    allowed_zones,
    allowed_capacity,
) -> List[Tuple[InstanceType, np.ndarray, np.ndarray, float]]:
    """Per-type walk for constrained envelopes / daemon overhead — the
    general path (_fast_kept handles the unconstrained hot shape)."""
    kept: List[Tuple[InstanceType, np.ndarray, np.ndarray, float]] = []
    for instance_type in instance_types:
        if not _passes_constraint_filters(instance_type, constraints):
            continue
        total = resource_vector(instance_type.capacity)
        if not _passes_accelerator_filters(total, pods_need):
            continue
        usable = total - resource_vector(instance_type.overhead)
        if (usable < 0).any():
            continue  # overhead exceeds capacity (ref: packable.go:64-68)
        usable = _greedy_fill(usable, daemon_groups)
        if usable is None:
            continue  # daemons don't fit (ref: packable.go:69-73)
        price = instance_type.min_price(
            zones=[z for z in instance_type.zones() if allowed_zones.contains(z)],
            capacity_types=[
                c for c in instance_type.capacity_types() if allowed_capacity.contains(c)
            ],
        )
        kept.append((instance_type, usable, total, price))
    return kept


def _greedy_fill(remaining: np.ndarray, groups: PodGroups) -> Optional[np.ndarray]:
    """Pack daemons-style: every pod of every group must fit, else None."""
    remaining = remaining.copy()
    for g in range(groups.num_groups):
        need = groups.vectors[g] * groups.counts[g]
        remaining -= need
        if (remaining < 0).any():
            return None
    return remaining


_ENVELOPE_KEYS = (
    wellknown.INSTANCE_TYPE_LABEL,
    wellknown.ARCH_LABEL,
    wellknown.OS_LABEL,
    wellknown.ZONE_LABEL,
    wellknown.CAPACITY_TYPE_LABEL,
)


def _fast_kept(
    instance_types: Sequence[InstanceType], pods_need: np.ndarray
) -> List[Tuple[InstanceType, np.ndarray, np.ndarray, float]]:
    """Vectorized filter for the hot shape — unconstrained envelope, no
    daemons: the accelerator anti-waste and overhead checks collapse to
    [T, R] array masks, and every type's price is its unrestricted
    cheapest offering. Bit-identical kept set to the per-type walk."""
    if not instance_types:
        return []
    total = np.stack([resource_vector(it.capacity) for it in instance_types])
    usable = total - np.stack(
        [resource_vector(it.overhead) for it in instance_types]
    )
    mask = (usable >= 0).all(axis=1)
    # Offering-less types are unlaunchable (no zone/capacity-type to match);
    # the per-type walk drops them because any() over an empty offered set
    # is False even under an unconstrained envelope.
    mask &= np.array([bool(it.offerings) for it in instance_types])
    for index in _ACCEL_INDEXES:
        if pods_need[index] > 0:
            mask &= total[:, index] > 0
        else:
            mask &= total[:, index] == 0
    if pods_need[_POD_ENI_INDEX] > 0:
        mask &= total[:, _POD_ENI_INDEX] > 0
    return [
        (instance_types[i], usable[i], total[i], instance_types[i].min_price())
        for i in np.nonzero(mask)[0]
    ]


def build_fleet(
    instance_types: Sequence[InstanceType],
    constraints: Constraints,
    pods: Sequence[PodSpec],
    daemons: Sequence[PodSpec] = (),
    pods_need: Optional[np.ndarray] = None,
) -> InstanceFleet:
    """Filter + densify instance types for one schedule's constraints
    (ref: PackablesFor packable.go:45-93): constraint envelope filters,
    accelerator anti-waste, kubelet overhead reservation, daemonset overhead
    packing, then ascending sort by (accelerators, cpu, memory).

    pods_need is the [R] elementwise max of the pods' request vectors; pass
    it when the caller already grouped the pods (Solver.solve does) so the
    50k-pod batch isn't re-walked here."""
    if pods_need is None:
        pods_need = (
            np.max([resource_vector(p.requests) for p in pods], axis=0)
            if pods
            else np.zeros(wellknown.NUM_RESOURCE_DIMS, np.float32)
        )
    daemon_groups = group_pods(list(daemons))

    requirements = constraints.effective_requirements()
    allowed_zones = requirements.allowed(wellknown.ZONE_LABEL)
    allowed_capacity = requirements.allowed(wellknown.CAPACITY_TYPE_LABEL)

    unconstrained = daemon_groups.num_groups == 0 and all(
        requirements.allowed(key).is_any() for key in _ENVELOPE_KEYS
    )
    if unconstrained:
        kept = _fast_kept(instance_types, pods_need)
    else:
        kept = _slow_kept(
            instance_types, constraints, pods_need, daemon_groups,
            allowed_zones, allowed_capacity,
        )

    cpu = wellknown.RESOURCE_DIM_INDEX[wellknown.RESOURCE_CPU]
    mem = wellknown.RESOURCE_DIM_INDEX[wellknown.RESOURCE_MEMORY]
    kept.sort(
        key=lambda item: (
            tuple(item[2][i] for i in _ACCEL_INDEXES),
            item[2][cpu],
            item[2][mem],
        )
    )
    # Launch envelope: the offered zones that survive the constraint set
    # (offered zones are finite, so NotIn/complement requirements filter
    # correctly — finite_values() alone would drop them), and spot iff
    # allowed and offered by any kept type (ref: instance.go:281-292).
    zone_values = sorted(
        {
            zone
            for item in kept
            for zone in item[0].zones()
            if allowed_zones.contains(zone)
        }
    )
    capacity_type = wellknown.CAPACITY_TYPE_ON_DEMAND
    if allowed_capacity.contains(wellknown.CAPACITY_TYPE_SPOT):
        for item in kept:
            if wellknown.CAPACITY_TYPE_SPOT in item[0].capacity_types():
                capacity_type = wellknown.CAPACITY_TYPE_SPOT
                break
    if not kept:
        empty = np.zeros((0, wellknown.NUM_RESOURCE_DIMS), np.float32)
        return InstanceFleet(
            [], empty, empty.copy(), np.zeros((0,), np.float32),
            allowed_zones=zone_values,
            capacity_type=capacity_type,
        )
    prices = np.array([item[3] for item in kept], dtype=np.float32)
    prices = _forecast_penalized(prices, kept, allowed_zones, capacity_type)
    return InstanceFleet(
        instance_types=[item[0] for item in kept],
        capacity=np.stack([item[1] for item in kept]),
        total=np.stack([item[2] for item in kept]),
        prices=prices,
        allowed_zones=zone_values,
        capacity_type=capacity_type,
    )


def _forecast_penalized(
    prices: np.ndarray, kept, allowed_zones, capacity_type: str
) -> np.ndarray:
    """Interruption-forecast penalty on the [T] price column (spot fleets
    only): prices += prices * risk * weight, computed host-side in float32
    BEFORE dispatch so the device kernel and every numpy mirror consume the
    same bits (karpenter_tpu/market/forecast.py). A fleet with no active
    PriceBook — or one whose every pool is calm — is returned untouched,
    bit-identical to the pre-market behavior."""
    if capacity_type != wellknown.CAPACITY_TYPE_SPOT:
        return prices
    from karpenter_tpu.market.pricebook import active_book

    book = active_book()
    if book is None or not book.has_risk():
        return prices
    from karpenter_tpu.market import forecast

    risks = forecast.type_risks(
        [item[0].name for item in kept],
        forecast.fleet_zone_lists(kept, allowed_zones),
        book,
    )
    return forecast.penalize_prices(prices, risks)
