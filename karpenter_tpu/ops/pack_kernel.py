"""Batched bin-packing kernel on TPU (JAX).

Reformulates the reference's sequential FFD loop
(ref: pkg/controllers/provisioning/binpacking/packer.go:82-189) as static-shape
tensor rounds:

  * pods are pre-collapsed into G groups of identical request vectors
    (ops.encode.group_pods); G is small (tens) even for 50k-pod batches.
  * one *round* fills a candidate node of every instance type at once —
    a lax.scan over groups, vmapped over the T types.
  * the chosen node fill is **replicated** k = min_{g: p_g>0} floor(c_g / p_g)
    times in one step. Replication is exact for greedy FFD: every one of those
    k nodes would have received an identical fill (the capacity ledger resets
    per node and group counts stay >= the fill). This collapses the reference's
    O(#nodes) sequential loop — 50k pods of one shape solve in one round.
  * rounds run under lax.while_loop with preallocated output buffers, so the
    whole solve is one XLA computation with static shapes (no recompiles
    across batches after bucketing).

Two selection modes:
  * mode="ffd": parity with the reference — the largest type sets the
    max-pods bound, the smallest type achieving it wins, and with quirk=True
    the fits()-early-exit quirk (packable.go:147-157, Cmp >= 0 rejecting exact
    fits) is reproduced bit-for-bit for cross-checking.
  * mode="cost": price-aware — each round picks the type minimizing
    $/(weighted work packed); used by the cost solver to beat greedy $/hr.

All shapes padded: G -> groups (counts 0), T -> types (valid_types mask).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-4
_INF = jnp.inf


class PackRounds(NamedTuple):
    """Kernel output: up to MR rounds of (type, per-group fill, replication)."""

    round_type: jnp.ndarray  # [MR] int32 — chosen instance-type index
    round_fill: jnp.ndarray  # [MR, G] int32 — pods of each group per node
    round_repl: jnp.ndarray  # [MR] int32 — identical nodes this round
    num_rounds: jnp.ndarray  # [] int32
    unschedulable: jnp.ndarray  # [G] int32 — pods set aside per group
    overflow: jnp.ndarray  # [] bool — round budget exhausted (never expected)


def max_rounds(num_groups: int) -> int:
    # Every two rounds exhaust at least one group (replication drops the
    # binding group below its fill), so 2G+8 is a safe static budget.
    return 2 * num_groups + 8


def _fill_one_node(capacity, total, vectors, counts, *, quirk: bool):
    """Greedy-fill one node of one type. Returns packed count per group.

    Mirrors packable.go:113-132: groups scanned largest→smallest; a first
    active group that can't place one pod aborts the whole fill (the caller
    interprets an all-zero fill as "largest pod fits nowhere" for this type);
    with quirk=True, a failed placement stops the scan early once remaining
    capacity falls to/below the smallest active pod on any tracked dimension.
    """
    num_groups = vectors.shape[0]
    active = counts > 0
    any_active = jnp.any(active)
    first_active = jnp.argmax(active)
    last_active = num_groups - 1 - jnp.argmax(active[::-1])
    smallest = vectors[last_active]

    def step(carry, g):
        remaining, stopped, abort = carry
        vec = vectors[g]
        cnt = counts[g]
        ratio = jnp.where(vec > 0, remaining / jnp.where(vec > 0, vec, 1.0), _INF)
        n_fit = jnp.floor(jnp.min(ratio) + _EPS)
        n_fit = jnp.maximum(n_fit, 0.0).astype(jnp.int32)
        allowed = (cnt > 0) & ~stopped & ~abort
        n = jnp.where(allowed, jnp.minimum(cnt, n_fit), 0)
        abort = abort | ((g == first_active) & (cnt > 0) & (n == 0))
        remaining = remaining - n.astype(vectors.dtype) * vec
        failed = allowed & (n < cnt)
        if quirk:
            essentially_full = jnp.any((total > 0) & (remaining <= smallest + _EPS))
            stopped = stopped | (failed & essentially_full)
        return (remaining, stopped, abort), n

    (_, _, abort), packed = jax.lax.scan(
        step,
        (capacity, jnp.asarray(False), jnp.asarray(False)),
        jnp.arange(num_groups),
    )
    packed = jnp.where(abort | ~any_active, 0, packed)
    return packed


class _LoopState(NamedTuple):
    counts: jnp.ndarray
    round_type: jnp.ndarray
    round_fill: jnp.ndarray
    round_repl: jnp.ndarray
    num_rounds: jnp.ndarray
    unschedulable: jnp.ndarray
    iters: jnp.ndarray


@functools.partial(
    jax.jit, static_argnames=("quirk", "mode")
)
def pack_kernel(
    vectors,  # [G, R] f32 — group request vectors, FFD-sorted desc
    counts,  # [G] i32 — pods per group
    capacity,  # [T, R] f32 — usable capacity per type (asc-sorted fleet)
    total,  # [T, R] f32 — raw capacity per type (for the quirk check)
    valid_types,  # [T] bool — padding mask
    prices,  # [T] f32 — $/hr per type (cost mode)
    *,
    quirk: bool = False,
    mode: str = "ffd",
) -> PackRounds:
    num_groups = vectors.shape[0]
    num_types = capacity.shape[0]
    mr = max_rounds(num_groups)

    # Weight per group for cost mode: the max utilization fraction across the
    # largest valid type's dimensions — "how much node does one pod consume".
    largest_valid = num_types - 1 - jnp.argmax(valid_types[::-1])
    ref_cap = jnp.maximum(capacity[largest_valid], 1.0)
    group_weight = jnp.max(vectors / ref_cap, axis=1)  # [G]

    def body(state: _LoopState) -> _LoopState:
        fills = jax.vmap(
            lambda cap, tot: _fill_one_node(
                cap, tot, vectors, state.counts, quirk=quirk
            )
        )(capacity, total)  # [T, G]
        fills = jnp.where(valid_types[:, None], fills, 0)
        sums = fills.sum(axis=1)  # [T]
        packs_any = (sums > 0) & valid_types

        if mode == "ffd":
            bound = sums[largest_valid]
            achieves = (sums == bound) & valid_types & (bound > 0)
            t_sel = jnp.argmax(achieves)  # first (smallest) achieving type
            have_pack = bound > 0
        elif mode == "cost":
            weighted = fills.astype(jnp.float32) @ group_weight  # [T]
            score = jnp.where(packs_any, prices / jnp.maximum(weighted, 1e-9), _INF)
            t_sel = jnp.argmin(score)
            have_pack = jnp.any(packs_any)
        else:
            raise ValueError(f"unknown mode {mode!r}")

        fill = fills[t_sel]  # [G]
        if quirk:
            # Replication must preserve each group's partial/full packing
            # status: once a partially-packed group's count drops to exactly
            # its fill, the "failed reserve" disappears and the fits()
            # early-exit no longer fires, changing later groups' packing
            # (observed in the reference when the last 1.5-pod pairs with a
            # 0.5-pod). So a partial group only replicates while count stays
            # strictly above fill: floor((c-1)/p); a fully-packed group
            # (p == c) exhausts and allows exactly 1.
            safe = jnp.where(
                fill == state.counts,
                1,
                jnp.maximum((state.counts - 1) // jnp.maximum(fill, 1), 1),
            )
        else:
            # Pure greedy: identical fills while counts stay >= fill.
            safe = state.counts // jnp.maximum(fill, 1)
        repl_per_group = jnp.where(fill > 0, safe, jnp.iinfo(jnp.int32).max)
        repl = jnp.maximum(jnp.min(repl_per_group), 1).astype(jnp.int32)

        # Pack branch.
        counts_packed = state.counts - repl * fill
        round_type = state.round_type.at[state.num_rounds].set(t_sel.astype(jnp.int32))
        round_fill = state.round_fill.at[state.num_rounds].set(fill.astype(jnp.int32))
        round_repl = state.round_repl.at[state.num_rounds].set(repl)

        # Unschedulable branch: retire the first group with pods remaining
        # (ref: packer.go:120-124 sets aside the largest pod; identical pods
        # fail identically, so the whole group retires at once).
        first_active = jnp.argmax(state.counts > 0)
        unsched = state.unschedulable.at[first_active].add(
            jnp.where(have_pack, 0, state.counts[first_active])
        )
        counts_unsched = state.counts.at[first_active].set(
            jnp.where(have_pack, state.counts[first_active], 0)
        )

        return _LoopState(
            counts=jnp.where(have_pack, counts_packed, counts_unsched),
            round_type=jnp.where(have_pack, round_type, state.round_type),
            round_fill=jnp.where(have_pack, round_fill, state.round_fill),
            round_repl=jnp.where(have_pack, round_repl, state.round_repl),
            num_rounds=state.num_rounds + jnp.where(have_pack, 1, 0),
            unschedulable=unsched,
            iters=state.iters + 1,
        )

    def cond(state: _LoopState):
        return (state.counts.sum() > 0) & (state.iters < mr + num_groups)

    init = _LoopState(
        counts=counts.astype(jnp.int32),
        round_type=jnp.zeros((mr,), jnp.int32),
        round_fill=jnp.zeros((mr, num_groups), jnp.int32),
        round_repl=jnp.zeros((mr,), jnp.int32),
        num_rounds=jnp.asarray(0, jnp.int32),
        unschedulable=jnp.zeros((num_groups,), jnp.int32),
        iters=jnp.asarray(0, jnp.int32),
    )
    final = jax.lax.while_loop(cond, body, init)
    # num_rounds can exceed the static mr budget (the 2G+8 bound is
    # heuristic): jax clamps the out-of-bounds scatter into the last slot,
    # silently corrupting it while num_rounds keeps counting. Surface that
    # as overflow — the candidate is unusable and scoring must skip it —
    # and clamp the reported count so hosts never read past the buffer.
    return PackRounds(
        round_type=final.round_type,
        round_fill=final.round_fill,
        round_repl=final.round_repl,
        num_rounds=jnp.minimum(final.num_rounds, mr),
        unschedulable=final.unschedulable,
        overflow=(final.counts.sum() > 0) | (final.num_rounds > mr),
    )


def pad_to(array: np.ndarray, size: int, axis: int = 0, value=0) -> np.ndarray:
    pad = size - array.shape[axis]
    if pad <= 0:
        return array
    widths = [(0, 0)] * array.ndim
    widths[axis] = (0, pad)
    return np.pad(array, widths, constant_values=value)


def bucket_size(n: int, minimum: int = 8) -> int:
    """Next power of two >= n — shape bucketing to avoid recompile storms
    (SURVEY.md §7 hard parts: dynamic shapes)."""
    size = minimum
    while size < n:
        size *= 2
    return size
